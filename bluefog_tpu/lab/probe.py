"""Per-rank convergence probe: debiased consensus error as an observable.

The probe turns the quantity the BlueFog paper's convergence story is
*about* — how fast the fleet's debiased push-sum estimates agree — into
a per-round, per-rank number cheap enough to stream always-on into the
telemetry registry and the seqlock'd status page.

**Definition.**  After round ``t``'s combine, rank ``r`` holds the
debiased estimate ``z_t = x_t / p_t``.  The probe reports

    ``e_r(t) = || S(z_t) - S(z_{t-1}) ||_inf``

where ``S`` is a fixed subsample of at most ``sample_cap`` elements,
taken as a handful of contiguous chunks spread across the tensor (NOT
one element per stride: a whole-buffer strided gather touches a
different cache line per element — ~100 µs of DRAM misses per round on
a 4 MB payload, which alone busted the < 2% overhead gate; contiguous
chunks read the same element count through a handful of
hardware-prefetched streamed regions).  For
linear gossip ``x_{t+1} = W x_t`` the successive difference is
``(W - I)`` applied to the disagreement component, so ``e_r(t)``
contracts at the same asymptotic per-round rate ``|λ₂(W)|`` as the
true consensus error ``||z_t - z̄||`` — but unlike the true error it
needs NO global knowledge: one subtraction over a bounded sample of
rank-local state.

**Cost model.**  The probe tick always runs cache-COLD: the combine it
follows just streamed the whole payload through the core, evicting
numpy's code pages along with the data, so the FIRST entry into each
distinct numpy call path costs ~10 µs on the bench box (the identical
call repeated immediately costs ~2 µs).  Per-round exact math (gather,
subtract, two reductions = four cold entries) therefore has a ~40 µs
floor no micro-optimization can cross.  The probe instead gathers one
row per round (a single cold ``take``) into a small block and defers
the subtract/reductions to one VECTORIZED flush every ``flush_every``
rounds — every round still gets its exact ``e_r(t)``, just computed up
to ``flush_every - 1`` rounds late.  That batching is what keeps the
probe inside the < 2% ``lab_probe_overhead_pct`` bench gate.

Pure numpy, no jax, no transport: the same class drives the islands
hot path (gated off-path like tracing/statuspage), the fake-clock unit
tests, and the sweep driver's fits.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

__all__ = ["ConvergenceProbe", "probe_enabled", "DEFAULT_SAMPLE_CAP",
           "DEFAULT_FLUSH_EVERY"]

#: Upper bound on the elements one observation touches; overridable via
#: ``BFTPU_LAB_SAMPLE`` (documented in docs/OBSERVABILITY.md).
DEFAULT_SAMPLE_CAP = 1024

#: Rounds batched per flush on the islands hot path (``BFTPU_LAB_FLUSH``).
#: The class default is 1 (exact, compute-on-observe) — only the
#: islands tick opts into batching, via :func:`flush_every_env`.
DEFAULT_FLUSH_EVERY = 8

#: History entries kept per probe (a sweep cell runs tens of rounds;
#: a week-long training job must not grow without bound).
_HISTORY_CAP = 4096

#: Elements per contiguous sample chunk.  The sample is
#: ``sample_cap // _CHUNK_ELEMS`` such chunks spread evenly, so the
#: per-round DRAM-region count is bounded by chunks, not elements —
#: and within a chunk the hardware prefetcher streams the sequential
#: lines, so longer-but-fewer chunks beat many short ones.
_CHUNK_ELEMS = 256


def probe_enabled() -> bool:
    """Whether ``BFTPU_LAB_PROBE`` asks for the probe (off by default —
    the PR-4/PR-9 off-path convention: observability is opt-in and its
    disabled cost is one env-cached boolean)."""
    return os.environ.get("BFTPU_LAB_PROBE", "0").lower() in (
        "1", "true", "yes", "on")


def _sample_cap() -> int:
    try:
        cap = int(os.environ.get("BFTPU_LAB_SAMPLE", DEFAULT_SAMPLE_CAP))
    except ValueError:
        cap = DEFAULT_SAMPLE_CAP
    return max(1, cap)


def flush_every_env() -> int:
    """``BFTPU_LAB_FLUSH`` (default :data:`DEFAULT_FLUSH_EVERY`) — the
    hot-path batching factor the islands tick constructs probes with."""
    try:
        k = int(os.environ.get("BFTPU_LAB_FLUSH", DEFAULT_FLUSH_EVERY))
    except ValueError:
        k = DEFAULT_FLUSH_EVERY
    return max(1, k)


class ConvergenceProbe:
    """One window's convergence observable on one rank.

    ``observe`` is the per-round entry point: feed it the post-combine
    tensor and the associated push-sum weight.  With the default
    ``flush_every=1`` it returns the current consensus-error sample
    (NaN until two rounds have been seen — a difference needs a
    predecessor).  With ``flush_every=K > 1`` it returns the most
    recently COMPUTED sample, up to ``K-1`` rounds behind; every
    round's exact value still lands in ``history`` (and
    ``last_err``/``last_round``) at the next flush — call
    :meth:`flush_pending` to force the stragglers out before reading.
    """

    def __init__(self, sample_cap: Optional[int] = None,
                 flush_every: int = 1):
        self.sample_cap = int(sample_cap if sample_cap is not None
                              else _sample_cap())
        self.flush_every = max(1, int(flush_every))
        self.rounds = 0            # observes seen
        self.last_err = float("nan")
        self.last_round = 0        # round of the last COMPUTED err
        #: ``(round, err)`` pairs, oldest first, capped at _HISTORY_CAP.
        self.history: List[Tuple[int, float]] = []
        # hot-path state, (re)built on first observe / shape change
        self._idx: Optional[np.ndarray] = None
        self._idx_size = -1
        self._dtype: Optional[np.dtype] = None
        self._block: Optional[np.ndarray] = None  # (K+1, n) sample rows
        self._diff: Optional[np.ndarray] = None   # (K, n) flush scratch
        self._ps: Optional[np.ndarray] = None     # (K,) debias weights
        self._pos = 0              # pending (unflushed) rows in _block
        self._any_p = False        # any pending row needs dividing
        self._prev_valid = False   # _block[0] holds round rounds-_pos

    def _rebuild(self, flat: np.ndarray) -> None:
        if flat.size <= self.sample_cap:
            self._idx = None  # small tensor: observe every element
            n = flat.size
        else:
            chunk = min(_CHUNK_ELEMS, self.sample_cap)
            nchunks = max(1, self.sample_cap // chunk)
            span = flat.size // nchunks
            starts = np.arange(nchunks, dtype=np.int64) * span
            idx = (starts[:, None]
                   + np.arange(chunk, dtype=np.int64)[None, :]).ravel()
            self._idx = idx[idx < flat.size]
            n = self._idx.size
        # work in the tensor's own float dtype: the subtraction of two
        # nearby same-dtype values is exact (Sterbenz), so a float64
        # round-trip would cost a cast dispatch per round and buy no
        # precision the floor-truncated fits could see
        dt = flat.dtype if flat.dtype.kind == "f" else np.dtype(np.float64)
        k = self.flush_every
        self._idx_size = flat.size
        self._dtype = flat.dtype
        self._block = np.empty((k + 1, n), dtype=dt)
        self._diff = np.empty((k, n), dtype=dt)
        self._ps = np.ones(k, dtype=np.float64)
        self._pos = 0
        self._any_p = False
        self._prev_valid = False

    def _flush(self) -> None:
        k = self._pos
        if k == 0:
            return
        blk = self._block
        if self._any_p:
            # debias in place: rows stay debiased, so the carried-over
            # predecessor row is always already divided
            np.divide(blk[1:k + 1], self._ps[:k, None], out=blk[1:k + 1])
            self._ps[:k] = 1.0
            self._any_p = False
        d = np.subtract(blk[1:k + 1], blk[:k], out=self._diff[:k])
        hi = d.max(axis=1)
        lo = d.min(axis=1)
        base = self.rounds - k
        hist = self.history
        for i in range(k):
            if i == 0 and not self._prev_valid:
                err = float("nan")  # a difference needs a predecessor
            else:
                err = float(max(hi[i], -lo[i]))
            self.last_err = err
            self.last_round = base + i + 1
            if len(hist) < _HISTORY_CAP:
                hist.append((self.last_round, err))
        np.copyto(blk[0], blk[k])
        self._pos = 0
        self._prev_valid = True

    def flush_pending(self) -> None:
        """Compute any rounds still sitting in the block (reads of
        ``history``/``last_err`` want the stragglers out first)."""
        self._flush()

    def observe(self, tensor: np.ndarray, p: float = 1.0) -> float:
        """Record round ``t``'s debiased sample; return the latest
        computed ``e`` (this round's, when ``flush_every == 1``).

        Every numpy entry here costs ~10 µs in situ (see the module
        docstring's cost model), so the per-round body is ONE gather
        plus plain-python bookkeeping; the math happens in
        :meth:`_flush`.
        """
        if isinstance(tensor, np.ndarray) and tensor.ndim == 1:
            flat = tensor
        else:
            flat = np.asarray(tensor).ravel()
        if self._idx_size != flat.size or self._dtype != flat.dtype:
            if self._pos:
                self._flush()  # don't drop rounds pending under the old shape
            self._rebuild(flat)
        row = self._block[self._pos + 1]
        if self._idx is None:
            np.copyto(row, flat, casting="unsafe")
        elif row.dtype == flat.dtype:
            np.take(flat, self._idx, out=row, mode="clip")
        else:  # non-float tensor: gather then cast (rare, cold path)
            np.copyto(row, flat.take(self._idx), casting="unsafe")
        if p > 0.0 and p != 1.0:
            self._ps[self._pos] = p
            self._any_p = True
        self.rounds += 1
        self._pos += 1
        if self._pos >= self.flush_every:
            self._flush()
        return self.last_err
