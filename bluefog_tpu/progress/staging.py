"""Zero-copy device→host staging for the progress worker.

Every island win op stages its payload to host numpy before touching
the shm wire.  The historical spelling — ``np.asarray(tensor)`` — is a
full device→host copy on accelerator backends, paid INSIDE the training
step.  On the worker thread that copy is avoidable: a ``jax.Array``
(or any dlpack exporter) can hand numpy a read-only view of its host
buffer via ``np.from_dlpack``, and the shm deposit reads straight out
of it — the staging copy the ROADMAP names simply disappears.  The
``progress.staging_bytes_saved`` telemetry counter measures exactly the
bytes that took the view path instead of a copy.

The view path is gated to the engine worker thread (``worker_scope``):
a view aliases the producing array's buffer, which is only safe under
the engine's documented contract that callers must not donate/delete
in-flight arrays (the same contract the overlap optimizer always had).
Synchronous callers keep the copying behavior bit-for-bit.

When the exporter refuses (non-CPU buffer and no host view, torch
tensors requiring grad, older numpy without ``from_dlpack``) we fall
back to the plain copy — staging never fails because zero-copy did.
"""

from __future__ import annotations

import contextlib
import threading

import numpy as np

from bluefog_tpu.telemetry import registry as _telemetry

_tls = threading.local()


def in_worker() -> bool:
    """Whether the current thread is inside a progress-worker scope."""
    return bool(getattr(_tls, "active", False))


@contextlib.contextmanager
def worker_scope():
    """Mark the current thread as a progress worker: staging inside the
    scope may return zero-copy dlpack views."""
    prev = getattr(_tls, "active", False)
    _tls.active = True
    try:
        yield
    finally:
        _tls.active = prev


def _dlpack_view(tensor):
    """Read-only host view of a dlpack exporter, or None."""
    from_dlpack = getattr(np, "from_dlpack", None)
    if from_dlpack is None or not hasattr(tensor, "__dlpack__"):
        return None
    try:
        v = from_dlpack(tensor)
    except Exception:  # noqa: BLE001 - any refusal means "copy instead"
        return None
    return v if isinstance(v, np.ndarray) else None


def stage(tensor) -> np.ndarray:
    """Host ndarray for ``tensor`` — a zero-copy view when staged on the
    worker thread and the producer exports dlpack, a copy otherwise."""
    if isinstance(tensor, np.ndarray):
        return tensor
    if in_worker():
        v = _dlpack_view(tensor)
        if v is not None:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("progress.staging_bytes_saved").add(int(v.nbytes))
            return v
    if hasattr(tensor, "detach"):  # torch.Tensor (cpu)
        tensor = tensor.detach().cpu().numpy()
    return np.asarray(tensor)
