"""The per-rank progress engine: worker thread, op queue, fusion.

One engine per island rank.  ``submit`` enqueues an op and returns a
:class:`~bluefog_tpu.progress.handles.WinHandle`; the worker thread
drains the queue in FIFO order, coalescing runs of compatible deposits
(same window, same kind, same weights) into one wire op — the
reference's tensor-fusion idea, bounded by ``BFTPU_PROGRESS_FUSION_MB``.
While the queue is idle the worker prefetches in-edge mailboxes so the
caller's next collect runs warm.

Queue state machine (model-checked by the ``progress`` verifier family,
``analysis/progress_rules.py``)::

    SUBMITTED --pop--> EXECUTING --ok--> DONE (handle resolved)
        ^                  |
        |   quiesce/epoch  | requeue (epoch changed under the op)
        +------------------+

Invariants: every submitted op resolves its handle exactly once; ops on
one window execute in submission order; a quiesce (membership-epoch
switch) parks the worker AFTER the in-flight op completes and leaves the
queue intact, so nothing is lost or double-executed across the segment
rebind.

The engine executes ops through a duck-typed ``backend``:

- ``execute(kind, window, payload, weights, kwargs)`` — run one op;
- ``fuse(kind, window, payloads)`` — coalesce deposit payloads
  (optional; default: last-write-wins for ``put``);
- ``prefetch(windows)`` — idle-time mailbox warm-read (optional);
- ``epoch()`` — current membership epoch (optional; enables requeue
  detection when an op fails because the epoch moved under it).

Tests drive the engine in **manual mode** (``start_worker=False``):
no thread is spawned and :meth:`ProgressEngine.step` processes one
batch synchronously — that, plus the injectable ``clock``, makes the
queue/fusion/handle machinery deterministic under test.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from bluefog_tpu.progress.handles import WinHandle
from bluefog_tpu.telemetry import registry as _telemetry

KINDS = ("put", "accumulate", "update")

#: deposits are retried at most this many times across epoch switches
#: before their handle fails — a backstop, not a steady state
MAX_REQUEUES = 3


class Op:
    """One queued window op (internal; callers hold the handle)."""

    __slots__ = ("kind", "window", "payload", "weights", "kwargs",
                 "handle", "seq", "epoch", "submit_ts", "nbytes",
                 "requeues")

    def __init__(self, kind: str, window: str, payload=None, weights=None,
                 kwargs: Optional[Dict[str, Any]] = None, nbytes: int = 0):
        self.kind = kind
        self.window = window
        self.payload = payload
        self.weights = weights
        self.kwargs = dict(kwargs or {})
        self.handle = WinHandle()
        self.seq = -1
        self.epoch = -1
        self.submit_ts = 0.0
        self.nbytes = int(nbytes)
        self.requeues = 0


class ProgressEngine:
    """Background progress engine for one rank (see module docstring)."""

    def __init__(self, backend, *, queue_depth: Optional[int] = None,
                 fusion_bytes: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "progress", idle_poll_s: float = 0.002,
                 start_worker: bool = True):
        from bluefog_tpu import progress as _progress

        self._backend = backend
        self._depth = (_progress.queue_depth() if queue_depth is None
                       else max(1, int(queue_depth)))
        self._fusion_bytes = (_progress.fusion_bytes() if fusion_bytes is None
                              else max(0, int(fusion_bytes)))
        self._clock = clock
        self.name = str(name)
        self._idle_poll_s = float(idle_poll_s)
        self._start_worker = bool(start_worker)

        self._q: Deque[Op] = collections.deque()
        self._cv = threading.Condition()
        self._parked = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._quiesced = False
        self._inflight: Optional[str] = None  # "kind:window" while executing
        self._seq = 0

        # plain-int stats (GIL-atomic bumps; mirrored to telemetry)
        self.submitted = 0
        self.executed = 0
        self.fused_batches = 0
        self.fused_ops = 0
        self.requeued = 0
        self.prefetches = 0
        self.queued_s_total = 0.0
        self.windows_seen: set = set()

    # -- submission ------------------------------------------------------

    def submit(self, kind: str, window: str, payload=None, weights=None,
               nbytes: int = 0, **kwargs) -> WinHandle:
        """Enqueue one op; returns its handle.  Blocks (backpressure)
        while the queue is at ``BFTPU_PROGRESS_QUEUE_DEPTH`` — bounded
        memory under a producer that outruns the wire.  ``payload`` may
        be a zero-arg callable: it is materialized on the worker thread,
        which is where a device→host stage belongs."""
        if kind not in KINDS:
            raise ValueError(f"unknown op kind {kind!r}; expected {KINDS}")
        op = Op(kind, window, payload=payload, weights=weights,
                kwargs=kwargs, nbytes=nbytes)
        with self._cv:
            if self._stopping:
                raise RuntimeError("progress engine is stopped")
            # backpressure only in threaded mode: a manual-mode engine
            # has nobody to drain the queue while we wait
            while (self._thread is not None and len(self._q) >= self._depth
                   and not self._stopping):
                self._cv.wait(0.05)
            if self._stopping:
                raise RuntimeError("progress engine is stopped")
            op.seq = self._seq
            self._seq += 1
            op.submit_ts = self._clock()
            op.epoch = self._backend_epoch()
            self._q.append(op)
            self.submitted += 1
            self.windows_seen.add(window)
            self._cv.notify_all()
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("progress.submitted", kind=kind).inc()
            reg.gauge("progress.queue_depth").set(len(self._q))
        if self._start_worker:
            self._ensure_worker()
        return op.handle

    def _backend_epoch(self) -> int:
        fn = getattr(self._backend, "epoch", None)
        if fn is None:
            return -1
        try:
            return int(fn())
        except Exception:  # noqa: BLE001 - epoch is advisory
            return -1

    def _ensure_worker(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"bftpu-progress:{self.name}")
        self._thread = t
        t.start()

    # -- quiesce / resume (membership-epoch integration) -----------------

    def quiesce(self, timeout: float = 60.0) -> int:
        """Park the worker: the in-flight op completes, queued ops stay
        queued.  Called by the epoch switch BEFORE the old epoch's shm
        segments close — and by the ORPHAN transition on quorum loss
        (islands._enter_orphan), where no :meth:`resume` follows until
        ``merge_orphan`` re-admits the rank under a fresh epoch.
        Returns the number of ops that will re-execute against the new
        epoch's windows after :meth:`resume`."""
        with self._cv:
            self._quiesced = True
            pending = len(self._q)
            self._cv.notify_all()
        if self._thread is not None and self._thread.is_alive():
            self._parked.wait(timeout)
        if pending:
            self.requeued += pending
        reg = _telemetry.get_registry()
        if reg.enabled:
            if pending:
                reg.counter("progress.requeued").add(pending)
            reg.journal("progress_quiesce", pending=pending,
                        inflight=self._inflight or "")
        return pending

    def resume(self) -> None:
        """Unpark after an epoch switch: queued ops resolve their window
        by NAME at execution time, so they land in the new epoch's
        segments with no payload rewrite."""
        with self._cv:
            self._quiesced = False
            self._parked.clear()
            self._cv.notify_all()
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.journal("progress_resume", pending=len(self._q))

    # -- draining / shutdown ---------------------------------------------

    def drain(self, window: Optional[str] = None,
              timeout: Optional[float] = None) -> bool:
        """Wait until no op for ``window`` (all windows when None) is
        queued or in flight.  Manual-mode engines step inline."""
        deadline = None if timeout is None else self._clock() + timeout

        def busy_locked() -> bool:
            if any(window is None or op.window == window for op in self._q):
                return True
            return (self._inflight is not None
                    and (window is None
                         or self._inflight.endswith(f":{window}")))

        while True:
            with self._cv:
                if not busy_locked():
                    return True
                threaded = self._thread is not None and self._thread.is_alive()
                if threaded:
                    self._cv.wait(0.01)
            if not threaded:
                if not self.step():
                    return not self._q
            if deadline is not None and self._clock() > deadline:
                return False

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Shut the engine down.  ``drain=True`` executes the remaining
        queue first; otherwise queued handles fail with RuntimeError."""
        dropped: List[Op] = []
        with self._cv:
            if not drain:
                dropped = list(self._q)
                self._q.clear()
            self._stopping = True
            self._quiesced = False
            self._parked.clear()
            self._cv.notify_all()
        for op in dropped:
            if not op.handle.done():
                op.handle._fail(RuntimeError("progress engine stopped"))
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if drain:
            while self._q:  # manual mode (or a worker that never started)
                if not self.step():
                    break

    @property
    def stopped(self) -> bool:
        return self._stopping

    # -- worker ----------------------------------------------------------

    def _run(self) -> None:
        while True:
            batch: Optional[List[Op]] = None
            prefetch = False
            with self._cv:
                while True:
                    if self._quiesced and not self._stopping:
                        self._parked.set()
                        self._cv.wait()
                        continue
                    self._parked.clear()
                    if self._q:
                        batch = self._pop_batch_locked()
                        break
                    if self._stopping:
                        return
                    timed_out = not self._cv.wait(self._idle_poll_s)
                    if timed_out and not self._q and not self._stopping \
                            and not self._quiesced:
                        prefetch = True
                        break
            if prefetch:
                self._do_prefetch()
                continue
            self._execute(batch)

    def step(self) -> int:
        """Manual mode: process one batch on the calling thread.
        Returns the number of ops processed (0 = queue empty or
        quiesced)."""
        with self._cv:
            if not self._q or self._quiesced:
                return 0
            batch = self._pop_batch_locked()
        self._execute(batch)
        return len(batch)

    def _pop_batch_locked(self) -> List[Op]:
        first = self._q.popleft()
        batch = [first]
        # put always fuses (last-write-wins needs no backend help);
        # accumulate only when the backend can actually sum payloads
        fusable = (first.kind == "put"
                   or (first.kind == "accumulate"
                       and getattr(self._backend, "fuse", None) is not None))
        if fusable and self._fusion_bytes > 0:
            budget = self._fusion_bytes - max(first.nbytes, 0)
            while self._q:
                nxt = self._q[0]
                # fuse only a CONTIGUOUS run of compatible ops: stopping
                # at the first mismatch is what preserves per-window
                # submission order (progress.fusion-order rule)
                if (nxt.kind != first.kind or nxt.window != first.window
                        or nxt.weights != first.weights
                        or nxt.kwargs != first.kwargs
                        or nxt.nbytes > budget):
                    break
                budget -= nxt.nbytes
                batch.append(self._q.popleft())
        self._inflight = f"{first.kind}:{first.window}"
        return batch

    def _fuse_payloads(self, kind: str, window: str, payloads: List[Any]):
        fuse = getattr(self._backend, "fuse", None)
        if fuse is not None:
            return fuse(kind, window, payloads)
        # last-write-wins is always correct for put (each deposit
        # overwrites the slot); accumulate NEEDS a backend fuse, so
        # without one we refuse to coalesce (callers see per-op results)
        if kind == "put":
            return payloads[-1]
        raise TypeError("backend has no fuse(); cannot coalesce "
                        f"{len(payloads)} {kind} ops")

    def _execute(self, batch: List[Op]) -> None:
        from bluefog_tpu.progress import staging
        from bluefog_tpu.tracing import tracer as _tracing

        first = batch[0]
        tr = _tracing.get_tracer()
        ttok = (tr.begin(f"progress.{first.kind}", window=first.window)
                if tr.enabled else None)
        reg = _telemetry.get_registry()
        try:
            with staging.worker_scope():
                payloads = [op.payload() if callable(op.payload)
                            else op.payload for op in batch]
                if first.kind == "update":
                    payload = None
                elif len(payloads) == 1:
                    payload = payloads[0]
                else:
                    payload = self._fuse_payloads(first.kind, first.window,
                                                  payloads)
                result = self._backend.execute(
                    first.kind, first.window, payload, first.weights,
                    first.kwargs)
        except Exception as e:  # noqa: BLE001 - resolved via handle/requeue
            if self._maybe_requeue(batch):
                if ttok is not None:
                    tr.end(ttok)
                return
            for op in batch:
                if not op.handle.done():
                    op.handle._fail(e)
        else:
            now = self._clock()
            for op in batch:
                self.queued_s_total += max(0.0, now - op.submit_ts)
                if not op.handle.done():
                    op.handle._complete(result)
            self.executed += len(batch)
            if reg.enabled:
                reg.counter("progress.executed",
                            kind=first.kind).add(len(batch))
                if len(batch) > 1:
                    reg.counter("progress.fused_batches").inc()
                    reg.counter("progress.fused_ops").add(len(batch) - 1)
            if len(batch) > 1:
                self.fused_batches += 1
                self.fused_ops += len(batch) - 1
        finally:
            if ttok is not None:
                tr.end(ttok)
            with self._cv:
                self._inflight = None
                self._cv.notify_all()
            if reg.enabled:
                reg.gauge("progress.queue_depth").set(len(self._q))

    def _maybe_requeue(self, batch: List[Op]) -> bool:
        """An op that failed because the membership epoch moved under it
        (quiesce raced the submit) goes back to the FRONT of the queue —
        same per-window order — up to MAX_REQUEUES times."""
        ep = self._backend_epoch()
        if ep < 0:
            return False
        stale = [op for op in batch if op.epoch != ep]
        if not stale or any(op.requeues >= MAX_REQUEUES for op in batch):
            return False
        for op in batch:
            op.requeues += 1
            op.epoch = ep
        with self._cv:
            self._q.extendleft(reversed(batch))
            self._cv.notify_all()
        self.requeued += len(batch)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("progress.requeued").add(len(batch))
        return True

    def _do_prefetch(self) -> None:
        fn = getattr(self._backend, "prefetch", None)
        if fn is None or not self.windows_seen:
            return
        try:
            n = int(fn(tuple(sorted(self.windows_seen))) or 0)
        except Exception:  # noqa: BLE001 - prefetch must never kill the worker
            n = 0
        if n:
            self.prefetches += n
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("progress.prefetch_reads").add(n)

    # -- introspection ----------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """Live stats for the status page / ``bftpu-top``."""
        return {
            "queue_depth": len(self._q),
            "inflight": self._inflight,
            "submitted": self.submitted,
            "executed": self.executed,
            "fused_batches": self.fused_batches,
            "fused_ops": self.fused_ops,
            "requeued": self.requeued,
            "prefetches": self.prefetches,
            "queued_s_total": self.queued_s_total,
        }
