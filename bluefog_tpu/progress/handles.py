"""WinHandle — the future returned by the async window ops.

A handle moves through exactly three states::

    PENDING --_complete(result)--> DONE(result)
    PENDING --_fail(exc)---------> DONE(exc)

and never leaves DONE: completing (or failing) a handle twice raises,
which is the lifecycle invariant the ``progress.handle-lifecycle``
verifier rule checks.  Handles are plain condition-free futures — one
``threading.Event`` each — because exactly one thread (the engine
worker, or the submitting thread in the engine-off synchronous
fallback) ever resolves them.
"""

from __future__ import annotations

import threading
from typing import Any, Optional


class WinHandle:
    """Completion future for one submitted async window op.

    ``wait(timeout)`` returns whether the op finished; ``result()``
    blocks then returns the op's value (``True`` for deposits, the
    combined tensor/pytree for ``win_update_async``) or re-raises the
    op's failure; ``done()`` never blocks.
    """

    __slots__ = ("_ev", "_result", "_exc")

    def __init__(self):
        self._ev = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None

    # -- consumer side --------------------------------------------------

    def done(self) -> bool:
        return self._ev.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._ev.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._ev.wait(timeout):
            raise TimeoutError("window op still in flight")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout: Optional[float] = None) \
            -> Optional[BaseException]:
        if not self._ev.wait(timeout):
            raise TimeoutError("window op still in flight")
        return self._exc

    # -- engine side ----------------------------------------------------

    def _complete(self, result: Any) -> None:
        if self._ev.is_set():
            raise RuntimeError("WinHandle resolved twice")
        self._result = result
        self._ev.set()

    def _fail(self, exc: BaseException) -> None:
        if self._ev.is_set():
            raise RuntimeError("WinHandle resolved twice")
        self._exc = exc
        self._ev.set()

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        if not self._ev.is_set():
            state = "pending"
        elif self._exc is not None:
            state = f"failed({type(self._exc).__name__})"
        else:
            state = "done"
        return f"<WinHandle {state}>"


def completed(result: Any) -> "WinHandle":
    """An already-resolved handle — the engine-off synchronous fallback
    (``BFTPU_PROGRESS=0``) and the SPMD-emulation parity wrappers return
    these so callers can use one API shape everywhere."""
    h = WinHandle()
    h._complete(result)
    return h
