"""bluefog_tpu.progress — the per-rank background progress engine.

Upstream BlueFog (a Horovod descendant) hides every one-sided window op
behind a C++ background communication thread with tensor fusion; that
overlap is what lets asynchronous decentralized SGD beat the synchronous
baseline in wall clock (PAPER.md §0).  This package is the JAX twin:
each island rank owns ONE :class:`~bluefog_tpu.progress.engine
.ProgressEngine` — a dedicated worker thread draining a bounded op
queue — and ``islands.win_put_async`` / ``win_accumulate_async`` /
``win_update_async`` return a :class:`~bluefog_tpu.progress.handles
.WinHandle` future instead of blocking the training step.

The engine:

- **fuses** consecutive same-window deposits (``BFTPU_PROGRESS_FUSION_MB``
  caps the coalesced bytes; per-window submission order is preserved —
  the ``progress`` verifier family model-checks this);
- **stages zero-copy**: payloads materialized on the worker thread go
  through :mod:`~bluefog_tpu.progress.staging`, which exports
  ``jax.Array`` leaves via dlpack into a read-only host view instead of
  a device→host copy whenever the backend allows (counted by the
  ``progress.staging_bytes_saved`` telemetry counter);
- **prefetches** in-edge mailboxes while idle so the caller's next
  collect runs over cache-warm pages;
- **quiesces and requeues** across membership-epoch switches: the
  in-flight op completes, queued ops survive the segment rebind and
  re-execute against the new epoch's windows — no committed mass is
  lost (``resilience`` integration; docs/RESILIENCE.md).

``BFTPU_PROGRESS=0`` disables the engine entirely: the async API then
executes synchronously at the call site and returns already-completed
handles — bit-for-bit today's blocking semantics, no extra thread.

The engine is transport-agnostic: it executes ops through a small
backend object (:class:`bluefog_tpu.islands._ProgressBackend` in
production, a fake in the unit tests), so this package never imports
:mod:`bluefog_tpu.islands`.
"""

from __future__ import annotations

import os

from bluefog_tpu.progress import staging
from bluefog_tpu.progress.engine import (KINDS, MAX_REQUEUES, Op,
                                         ProgressEngine)
from bluefog_tpu.progress.handles import WinHandle, completed

__all__ = [
    "KINDS",
    "Op",
    "ProgressEngine",
    "WinHandle",
    "completed",
    "enabled",
    "queue_depth",
    "fusion_bytes",
    "staging",
]

#: default bound on queued (not yet executing) ops before submit blocks
DEFAULT_QUEUE_DEPTH = 256
#: default cap on bytes coalesced into one fused deposit batch (8 MiB)
DEFAULT_FUSION_MB = 8.0


def enabled() -> bool:
    """Whether the background engine is on (``BFTPU_PROGRESS``, default
    on; ``0``/``false``/``off`` disable it)."""
    return os.environ.get("BFTPU_PROGRESS", "1").lower() not in (
        "0", "false", "off")


def queue_depth() -> int:
    """Submission-queue bound (``BFTPU_PROGRESS_QUEUE_DEPTH``)."""
    try:
        return max(1, int(os.environ.get("BFTPU_PROGRESS_QUEUE_DEPTH",
                                         DEFAULT_QUEUE_DEPTH)))
    except ValueError:
        return DEFAULT_QUEUE_DEPTH


def fusion_bytes() -> int:
    """Fused-batch byte cap (``BFTPU_PROGRESS_FUSION_MB``; 0 disables
    fusion — every batch is a single op)."""
    try:
        mb = float(os.environ.get("BFTPU_PROGRESS_FUSION_MB",
                                  DEFAULT_FUSION_MB))
    except ValueError:
        mb = DEFAULT_FUSION_MB
    return max(0, int(mb * 1024 * 1024))
