"""Timeline: named activity spans + chrome-trace output.

TPU-native sibling of the reference's ``bluefog/common/timeline.h/.cc`` [U]
(SURVEY.md §5.1): the reference's background loop stamps per-tensor activity
spans into a Chrome-tracing JSON file when ``BLUEFOG_TIMELINE=<path>`` is
set.  Here spans wrap op dispatch on the controller thread and are emitted
two ways at once:

- ``jax.profiler.TraceAnnotation`` so spans show up inside XLA/TPU profiles
  (the idiomatic TPU path — device-side timing comes from ``jax.profiler``).
- a Chrome-tracing JSON file (same format the reference emits) when
  ``BLUEFOG_TIMELINE`` is set, written by the native C++ writer
  (``cbluefog`` — sibling of ``timeline.cc``) with a pure-Python fallback.

``timeline_start_activity`` / ``timeline_end_activity`` mirror the
reference's custom-span toggles [U].
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import signal
import threading
import time
from typing import Optional

import jax.profiler

from bluefog_tpu.common.logging_util import logger

__all__ = [
    "timeline_start_activity",
    "timeline_end_activity",
    "timeline_context",
    "TimelineWriter",
]


class TimelineWriter:
    """Chrome-tracing JSON writer (reference ``TimelineWriter`` [U]).

    Prefers the native C++ writer from :mod:`bluefog_tpu.native`; falls back
    to a buffered pure-Python implementation.  Thread-safe.
    """

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._events = []
        self._counter_events = []
        self._t0 = time.perf_counter_ns()
        self._native = None
        try:
            from bluefog_tpu.native import timeline_native

            self._native = timeline_native.NativeTimelineWriter(path)
        except Exception:  # pragma: no cover - native lib optional
            self._native = None
        atexit.register(self.flush)
        self._install_sigterm()

    def _install_sigterm(self) -> None:
        # atexit never runs under SIGTERM's default disposition, and
        # launchers kill islands with SIGTERM — flush the buffer first,
        # then chain to whatever handler was installed before us
        try:
            prev = signal.getsignal(signal.SIGTERM)
        except (ValueError, TypeError):  # pragma: no cover - odd runtimes
            return

        def _on_term(signum, frame):
            try:
                self.flush()
            except Exception:  # noqa: BLE001 - dying anyway
                pass
            if callable(prev):
                prev(signum, frame)
            else:
                try:
                    signal.signal(signal.SIGTERM, signal.SIG_DFL)
                except (ValueError, TypeError):
                    pass
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, _on_term)
        except (ValueError, TypeError):
            # non-main thread: atexit still covers graceful exits
            pass

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    def now_us(self) -> float:
        """Current timestamp on this writer's clock (µs since creation).
        Public so other layers (telemetry counter sampling) can stamp
        events onto the same timebase as the spans."""
        return self._now_us()

    def record_counter(self, name: str, ts_us: float, value: float) -> None:
        """Emit a chrome-trace counter sample (``"ph": "C"``).  Telemetry
        counters land on the same profile as the activity spans."""
        if self._native is not None and hasattr(self._native, "counter"):
            self._native.counter(name, ts_us, value)
            return
        with self._lock:
            self._counter_events.append(
                {
                    "name": name,
                    "ph": "C",
                    "ts": ts_us,
                    "pid": os.getpid(),
                    "args": {"value": value},
                }
            )

    def record(self, name: str, start_us: float, dur_us: float, tid: int = 0) -> None:
        if self._native is not None:
            self._native.record(name, start_us, dur_us, tid)
            return
        with self._lock:
            self._events.append(
                {
                    "name": name,
                    "ph": "X",
                    "ts": start_us,
                    "dur": dur_us,
                    "pid": os.getpid(),
                    "tid": tid,
                }
            )

    def flush(self) -> None:
        if self._native is not None:
            self._native.flush()
            # Counter events buffered python-side (native lib without
            # bf_timeline_counter) merge into the native-written file.
            with self._lock:
                extra, self._counter_events = self._counter_events, []
            if extra:
                try:
                    with open(self.path, "r") as f:
                        doc = json.load(f)
                    doc.setdefault("traceEvents", []).extend(extra)
                    with open(self.path, "w") as f:
                        json.dump(doc, f)
                except (OSError, ValueError) as e:  # pragma: no cover
                    logger.warning("timeline counter merge failed: %s", e)
            return
        with self._lock:
            if not self._events and not self._counter_events:
                return
            try:
                with open(self.path, "w") as f:
                    json.dump(
                        {"traceEvents": self._events + self._counter_events},
                        f)
            except OSError as e:  # pragma: no cover
                logger.warning("timeline flush failed: %s", e)


_writer: Optional[TimelineWriter] = None
_open_spans = {}


def _get_writer() -> Optional[TimelineWriter]:
    global _writer
    if _writer is None:
        path = os.environ.get("BLUEFOG_TIMELINE")
        if path:
            _writer = TimelineWriter(path)
    return _writer


def timeline_start_activity(name: str, category: str = "custom") -> bool:
    """Open a named span (reference ``bf.timeline_start_activity`` [U])."""
    w = _get_writer()
    _open_spans[(name, category)] = time.perf_counter_ns()
    return w is not None


def timeline_end_activity(name: str, category: str = "custom") -> bool:
    """Close a span opened by :func:`timeline_start_activity`."""
    start = _open_spans.pop((name, category), None)
    w = _get_writer()
    if start is None:
        return False
    if w is not None:
        t0_us = (start - w._t0) / 1e3
        dur_us = (time.perf_counter_ns() - start) / 1e3
        w.record(f"{category}/{name}", t0_us, dur_us)
    return w is not None


@contextlib.contextmanager
def timeline_context(name: str):
    """Span around an op dispatch; also a ``jax.profiler`` annotation so the
    span is visible in TPU traces.

    Spans record with the CALLING THREAD's id as the chrome-trace tid, so
    background work (e.g. the overlap optimizer's gossip thread) renders
    on its own track, visually parallel to main-thread spans."""
    start = time.perf_counter_ns()
    with jax.profiler.TraceAnnotation(f"bluefog/{name}"):
        yield
    w = _get_writer()
    if w is not None:
        t0_us = (start - w._t0) / 1e3
        dur_us = (time.perf_counter_ns() - start) / 1e3
        w.record(name, t0_us, dur_us,
                 tid=threading.get_ident() & 0x7FFFFFFF)
