"""Ring attention: sequence-parallel exact attention over a mesh axis.

No sibling in the reference (it predates long-context work — SURVEY.md
§5.7); this is the long-context capability the rebuild adds so the gossip
data parallelism composes with sequence sharding on TPU.  The algorithm is
the public blockwise ring attention (Liu et al., arXiv:2310.01889): each
device holds one sequence block of Q, K, V; K/V blocks rotate around the
ring one ``lax.ppermute`` hop per step (riding exactly the wraparound ICI
links, see ``parallel/ici_map``) while each device accumulates its queries'
attention with the online-softmax recurrence — compute overlaps the
neighbor transfer, and no device ever materializes the full sequence.

Layout: per-device ``q, k, v: [B, T_local, H, D]``; the global sequence is
``axis_size * T_local`` in rank order along ``axis_name``.  Exactness (vs a
single-device softmax over the full sequence) is tested to fp32 tolerance.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.parallel._util import resolve_axis_size, vma_full

__all__ = [
    "ring_attention",
    "ring_flash_attention",
    "make_ring_attention_fn",
    "stripe_blocks",
    "unstripe_blocks",
    "striped_positions",
]


def stripe_blocks(x, n: int, axis: int = 1):
    """Permute a global sequence so contiguous shard ``r`` of the result
    holds global positions ``r, r+n, r+2n, ...`` — the *striped* layout.

    Striping balances causal ring attention: with contiguous blocks, hop
    ``s`` is fully masked on devices ``idx < s`` but SPMD lock-step still
    waits for the devices computing full hops, so block-level skipping
    saves no wall-clock; striped, every hop is a near-triangular half-load
    on every device (~2x wall-clock for long causal sequences; same idea
    as striped attention, arXiv:2311.09431).  Apply before sharding; undo
    with :func:`unstripe_blocks`.
    """
    t = x.shape[axis]
    if t % n:
        raise ValueError(f"sequence length {t} not divisible by {n}")
    x = jnp.moveaxis(x, axis, 0)
    x = x.reshape((t // n, n) + x.shape[1:])  # [L, n, ...]: in[i*n + r]
    x = jnp.swapaxes(x, 0, 1).reshape((t,) + x.shape[2:])  # out[r*L + i]
    return jnp.moveaxis(x, 0, axis)


def unstripe_blocks(x, n: int, axis: int = 1):
    """Inverse of :func:`stripe_blocks`."""
    t = x.shape[axis]
    if t % n:
        raise ValueError(f"sequence length {t} not divisible by {n}")
    x = jnp.moveaxis(x, axis, 0)
    x = x.reshape((n, t // n) + x.shape[1:])  # [n, L, ...]: in[r*L + i]
    x = jnp.swapaxes(x, 0, 1).reshape((t,) + x.shape[2:])  # out[i*n + r]
    return jnp.moveaxis(x, 0, axis)


def striped_positions(t_local: int, axis_name: str):
    """Global positions of this device's striped shard (``i*n + idx``) —
    feed to rotary/positional encodings when training striped."""
    n = resolve_axis_size(axis_name, None)
    return jnp.arange(t_local) * n + lax.axis_index(axis_name)


def _causal_hop_dispatch(step, idx, diag_fn, visible_fn, masked_fn, ops):
    """Hop-level causal dispatch, shared by both ring variants: with square
    blocks, the block held at ring step ``s`` has global index ``j = (idx -
    s) mod n``, so ``j == idx`` iff ``s == 0`` (the diagonal, needs element
    masking) and ``j > idx`` iff ``s > idx`` (fully masked — skip the
    compute); every other hop is fully visible (mask-free).  The classic
    halve-the-work fix for causal ring attention."""
    if step == 0:
        return diag_fn(ops)
    return lax.cond(step > idx, masked_fn, visible_fn, ops)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    *,
    causal: bool = True,
    striped: bool = False,
) -> jnp.ndarray:
    """Exact blockwise attention across sequence shards on ``axis_name``.

    q, k, v: [B, T_local, H, D] (this device's sequence block; the
    :func:`stripe_blocks` layout when ``striped=True`` — see its docstring
    for why striping balances the causal load).
    Returns [B, T_local, H, D] in q's dtype.
    """
    n = resolve_axis_size(axis_name, axis_size)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale = 1.0 / math.sqrt(D)
    idx = lax.axis_index(axis_name)

    if striped and causal and Tq != Tk:
        raise ValueError(
            f"striped causal ring attention needs equal q/k shard lengths "
            f"(got {Tq} vs {Tk}); the striped layout has no contiguous-"
            f"block fallback"
        )
    qf = q.astype(jnp.float32)
    m = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)
    o = jnp.zeros((B, Tq, H, D), jnp.float32)
    perm = tuple((i, (i + 1) % n) for i in range(n))

    def fold_block(m, l, o, kb, vb, valid):
        """Online-softmax update of (m, l, o) with one key block."""
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, kb) * scale
        m_new = jnp.maximum(
            m, jnp.max(jnp.where(valid, scores, -jnp.inf), axis=-1)
        )
        # keep m finite where nothing has been seen yet (fully masked rows)
        m_new = jnp.where(jnp.isfinite(m_new), m_new, m)
        alpha = jnp.where(jnp.isfinite(m), jnp.exp(m - m_new), 0.0)  # [B,H,Tq]
        p = jnp.where(valid, jnp.exp(scores - m_new[..., None]), 0.0)
        p = jnp.where(jnp.isfinite(m_new)[..., None], p, 0.0)
        l = l * alpha + jnp.sum(p, axis=-1)
        o = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, vb
        )
        return m_new, l, o

    all_valid = jnp.ones((1, 1, Tq, Tk), bool)
    tri = (jnp.arange(Tk)[None, :] <= jnp.arange(Tq)[:, None])[None, None]
    tri_strict = (jnp.arange(Tk)[None, :] < jnp.arange(Tq)[:, None])[None, None]
    kv = (k.astype(jnp.float32), v.astype(jnp.float32))
    for step in range(n):
        kb, vb = kv
        j = (idx - step) % n  # which global block this device holds now
        if striped and causal and Tq == Tk:
            # striped layout: key stripe j visible up to/including the
            # diagonal iff j <= our stripe index (see stripe_blocks); a
            # mask select beats lax.cond here — both "branches" would run
            # the identical fold, differing only in a constant mask
            valid = tri if step == 0 else jnp.where(j <= idx, tri, tri_strict)
            m, l, o = fold_block(m, l, o, kb, vb, valid)
        elif causal and Tq == Tk:
            m, l, o = _causal_hop_dispatch(
                step, idx,
                lambda ops: fold_block(*ops, tri),
                lambda ops: fold_block(*ops, all_valid),
                lambda ops: ops[:3],
                (m, l, o, kb, vb),
            )
        else:
            if causal:
                gq = idx * Tq + jnp.arange(Tq)  # global query positions
                gk = j * Tk + jnp.arange(Tk)  # global key positions
                valid = (gk[None, :] <= gq[:, None])[None, None]
            else:
                valid = all_valid
            m, l, o = fold_block(m, l, o, kb, vb, valid)
        if step != n - 1:
            kv = lax.ppermute(kv, axis_name, perm)

    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return (o / denom).astype(q.dtype)


def ring_flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    *,
    causal: bool = True,
    striped: bool = False,
    block_q: Optional[int] = None,  # None: per-shard sequence-adaptive
    block_k: Optional[int] = None,  # (kernels._default_blocks)
    interpret: bool = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Ring attention with blockwise flash attention as the per-hop compute.

    ``striped=True`` assumes the :func:`stripe_blocks` layout (shard ``r``
    holds global positions ``i*n + r``): every causal hop then reduces to a
    (near-)triangular mask with static offsets — delta 0 when the key
    shard's stripe index is <= ours, else delta 1 — so the work is balanced
    across devices instead of diagonal-heavy (see :func:`stripe_blocks`).

    Same semantics/layout as :func:`ring_attention`, but each hop runs
    :func:`bluefog_tpu.kernels.flash_attention_with_lse` — MXU-blocked,
    O(T_local·block) memory instead of materializing the [Tq, Tk] score
    matrix — and hops merge by the logsumexp rule.  ``impl`` selects the
    per-hop implementation (default "auto" = the Pallas kernel; "xla"
    selects the blockwise-XLA forward, measured 13x slower in end-to-end
    training — see the flash_attention module docstring).  Differentiable
    end to end (the kernel's VJP carries the lse cotangent the merge
    needs).

    Note: when running the kernel in *interpret mode* (CPU testing), the
    Pallas HLO interpreter is not vma-aware, so the enclosing
    ``jax.shard_map`` needs ``check_vma=False``; compiled TPU execution has
    no such restriction.
    """
    from bluefog_tpu.kernels import flash_attention_with_lse

    n = resolve_axis_size(axis_name, axis_size)
    tq, tk = q.shape[1], k.shape[1]
    if striped and causal and tq != tk:
        raise ValueError(
            f"striped causal ring attention needs equal q/k shard lengths "
            f"(got {tq} vs {tk}); the striped layout has no contiguous-"
            f"block fallback"
        )
    idx = lax.axis_index(axis_name)
    perm = tuple((i, (i + 1) % n) for i in range(n))

    def flash(q_, kb_, vb_, *, q_start, k_start, causal_):
        q_start = jnp.asarray(q_start, jnp.float32).reshape(1)
        k_start = jnp.asarray(k_start, jnp.float32).reshape(1)
        return flash_attention_with_lse(
            q_, kb_, vb_, q_start=q_start, k_start=k_start, causal=causal_,
            block_q=block_q, block_k=block_k, interpret=interpret, impl=impl,
        )

    def masked_hop(ops):
        # sentinels vma-typed like the compute branches' outputs
        q_, _, _ = ops
        b, t, h, _ = q_.shape
        return (vma_full(q_, q_.shape, q_.dtype),
                vma_full(q_, (b, h, t), jnp.float32, -1e30))

    def diag_hop(ops):
        # q_start == k_start: relative masking suffices, and static zero
        # offsets unlock the aligned triangular fast paths
        return flash(*ops, q_start=0, k_start=0, causal_=True)

    def visible_hop(ops):
        return flash(*ops, q_start=0, k_start=0, causal_=False)

    o = None
    lse = None
    kv = (k, v)
    for step in range(n):
        kb, vb = kv
        j = (idx - step) % n  # global index of the key block held this step
        if striped and causal and tq == tk:
            # striped layout: token (i, stripe j) has global pos i*n + j,
            # so visibility vs our stripe idx depends only on j <= idx.
            # One flash call with a traced 0/1 key offset instead of a
            # lax.cond between two static-offset calls: the cond's
            # transpose hoists the branches' scalar offset constants to
            # the shard_map boundary, where their (zero) cotangents fail
            # jax-0.4.x's rep checking — the same class of failure the
            # tp/pipeline blocks hit (docs/STATUS.md rounds 11-12)
            delta = 0 if step == 0 else jnp.where(j <= idx, 0, 1)
            o_s, lse_s = flash(q, kb, vb, q_start=0, k_start=delta,
                               causal_=True)
        elif causal and tq == tk:
            o_s, lse_s = _causal_hop_dispatch(
                step, idx, diag_hop, visible_hop, masked_hop, (q, kb, vb)
            )
        else:
            o_s, lse_s = flash(
                q, kb, vb, q_start=idx * tq, k_start=j * tk, causal_=causal
            )
        o_s = o_s.astype(jnp.float32)
        if o is None:
            o, lse = o_s, lse_s
        else:
            m = jnp.maximum(lse, lse_s)
            w_old = jnp.exp(lse - m)  # [B, H, T]
            w_new = jnp.exp(lse_s - m)
            denom = w_old + w_new  # >= 1 (or 2 for all-masked rows)
            align = lambda w: w.transpose(0, 2, 1)[..., None]  # -> [B,T,H,1]
            o = (align(w_old) * o + align(w_new) * o_s) / align(denom)
            lse = m + jnp.log(denom)
        if step != n - 1:
            kv = lax.ppermute(kv, axis_name, perm)
    return o.astype(q.dtype)


def make_ring_attention_fn(axis_name: str, axis_size: int, causal: bool = True,
                           *, flash: bool = False, striped: bool = False,
                           **flash_kwargs) -> Callable:
    """attention_fn for ``models.transformer.LlamaLM``: plugs sequence-
    parallel ring attention into the decoder blocks (``flash=True`` selects
    the blockwise flash hop compute; ``striped=True`` the load-balanced
    :func:`stripe_blocks` layout — pair with :func:`striped_positions`)."""
    if flash:
        return partial(
            ring_flash_attention, axis_name=axis_name, axis_size=axis_size,
            causal=causal, striped=striped, **flash_kwargs
        )
    return partial(
        ring_attention, axis_name=axis_name, axis_size=axis_size,
        causal=causal, striped=striped,
    )
