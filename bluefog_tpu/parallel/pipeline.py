"""Pipeline parallelism over a dedicated mesh axis (GPipe-style).

No sibling in the reference — it is a decentralized data-parallel framework
with replicated models (SURVEY.md §2.3: PP honestly absent upstream).  Like
:mod:`.tensor_parallel`, this is a composition bonus: a ``pp`` mesh axis
holding one *stage* (a contiguous slice of layers) per device, designed to
compose with the gossip axis on a ``("bf_nodes", "pp")`` mesh.

TPU-first design: the whole schedule is one ``lax.scan`` inside
``shard_map`` — no host round-trips, no per-tick dispatch.  Microbatches
stream stage-to-stage via single-hop ``lax.ppermute`` (nearest-neighbor on
the ICI torus), the classic GPipe fill/drain bubble of ``pp - 1`` ticks at
each end.  The scan is differentiable end-to-end (``ppermute`` transposes
to the reverse permutation), so backward is the mirrored pipeline for free
— XLA handles activation storage; wrap ``stage_fn`` in ``jax.checkpoint``
for rematerialized long pipelines.

Layout: every device holds ITS stage's parameters (stacked ``[pp, ...]``
outside, ``in_specs P("pp")``).  The per-stage function must map
``(stage_params, activation) -> activation`` with one signature for every
stage (the usual homogeneous-transformer assumption).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.parallel._util import pvary, resolve_axis_size
from bluefog_tpu.parallel.tensor_parallel import (
    copy_to_tp_region,
    reduce_from_tp_region,
)

__all__ = ["pipeline_apply", "stack_stage_params", "PP_AXIS"]

PP_AXIS = "pp"


def stack_stage_params(per_stage_params):
    """List of per-stage parameter pytrees -> stacked ``[pp, ...]`` leaves
    for ``shard_map`` ``in_specs P("pp")`` (use ``leaf[0]`` inside)."""
    return jax.tree_util.tree_map(lambda *ls: jnp.stack(ls), *per_stage_params)


def pipeline_apply(
    stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    stage_params,
    x,
    axis_name: str = PP_AXIS,
    *,
    num_microbatches: int,
    axis_size: Optional[int] = None,
):
    """Run the pipeline: ``x [num_micro * mb, ...]`` -> same shape.

    Called inside ``shard_map``; ``stage_params`` is this device's stage's
    parameter pytree.  Every device passes the same (replicated) ``x`` and
    receives the same (replicated) output — the input is logically consumed
    by stage 0 and the output produced by the last stage, with a masked
    ``psum`` replicating it back (so the result composes with downstream
    replicated compute, e.g. a loss).

    The schedule runs ``num_micro + pp - 1`` ticks; microbatch ``m`` is
    injected at tick ``m``, transformed by stage ``s`` at tick ``m + s``,
    and collected after its last-stage tick.
    """
    n = int(resolve_axis_size(axis_name, axis_size))
    idx = lax.axis_index(axis_name)
    # the replicated batch enters the pp-varying region through the f
    # operator (identity/pvary forward, psum backward): each stage's
    # transpose contributes only its masked share of the input cotangent
    # (zero off stage 0), and the psum reassembles a statically
    # replicated dx — without it, shard_map's rep checker cannot infer
    # replication for a grad-of-pipeline output typed P()
    x = copy_to_tp_region(x, axis_name)
    total = x.shape[0]
    if total % num_microbatches:
        raise ValueError(
            f"batch {total} not divisible by num_microbatches={num_microbatches}"
        )
    mb = total // num_microbatches
    micro = x.reshape((num_microbatches, mb) + x.shape[1:])
    ticks = num_microbatches + n - 1
    fwd_perm = [(i, i + 1) for i in range(n - 1)]

    def tick(carry, t):
        state, outs = carry
        # stage 0 swallows the next microbatch (zeros once drained)
        inject = jnp.where(
            t < num_microbatches,
            lax.dynamic_index_in_dim(
                micro, jnp.minimum(t, num_microbatches - 1), keepdims=False
            ),
            jnp.zeros_like(state),
        )
        state = jnp.where(idx == 0, inject, state)
        state = stage_fn(stage_params, state)
        # the last stage banks microbatch m at tick m + n - 1
        m = t - (n - 1)
        valid = (idx == n - 1) & (m >= 0)
        outs = jnp.where(
            valid,
            lax.dynamic_update_index_in_dim(
                outs, state.astype(outs.dtype), jnp.maximum(m, 0), axis=0
            ),
            outs,
        )
        # stream every in-flight activation one stage forward
        state = lax.ppermute(state, axis_name, fwd_perm)
        return (state, outs), None

    # scan carries become pp-varying; type the inits to match
    state0 = pvary(jnp.zeros_like(micro[0]), axis_name)
    outs0 = pvary(jnp.zeros_like(micro), axis_name)
    (_, outs), _ = lax.scan(tick, (state0, outs0), jnp.arange(ticks))
    # replicate the last stage's collected outputs to every stage.  The
    # masked psum must be the g operator (identity backward): a raw psum
    # would transpose to another psum and scale every stage's gradients by
    # pp under a replicated downstream loss (see tensor_parallel).
    outs = reduce_from_tp_region(
        jnp.where(idx == n - 1, outs, jnp.zeros_like(outs)), axis_name
    )
    return outs.reshape((total,) + x.shape[1:])
