"""Expert parallelism: Switch-style mixture-of-experts over an ``ep`` axis.

No sibling in the reference (SURVEY.md §2.3: EP honestly absent upstream) —
the last of the composition bonuses (see :mod:`.tensor_parallel`,
:mod:`.pipeline`).  Experts shard over the ``ep`` mesh axis; tokens live
sharded over the same axis (each device routes its own token shard), and
dispatch/return ride a single ``lax.all_to_all`` pair — the canonical
TPU MoE wire pattern (Fedus et al., arXiv:2101.03961; Lepikhin et al.,
arXiv:2006.16668).

TPU-first choices: routing is the dense one-hot dispatch/combine einsum
formulation (everything stays MXU-shaped — no gather/scatter, no dynamic
shapes), capacity is static (``capacity_factor``), overflow tokens pass
through the residual untouched (standard Switch behavior).  Everything is
differentiable, including the router (gate probability scales the expert
output, the straight-through-free Switch estimator).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.parallel._util import resolve_axis_size

__all__ = ["switch_moe", "init_moe_params", "EP_AXIS"]

EP_AXIS = "ep"


def init_moe_params(key, d_model: int, d_ff: int, num_experts: int,
                    dtype=jnp.float32):
    """Full (unsharded) MoE params: router [d, E] (replicated), expert
    stacks wi [E, d, f] / wo [E, f, d] (shard axis 0 over ep: pass
    ``leaf.reshape(ep, E//ep, ...)`` stacked, or use ``in_specs
    P("ep")`` directly on the expert axis)."""
    kr, ki, ko = jax.random.split(key, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_ff)
    return {
        "router": (jax.random.normal(kr, (d_model, num_experts), jnp.float32)
                   * 0.02).astype(dtype),
        "wi": (jax.random.normal(ki, (num_experts, d_model, d_ff), jnp.float32)
               * scale_in).astype(dtype),
        "wo": (jax.random.normal(ko, (num_experts, d_ff, d_model), jnp.float32)
               * scale_out).astype(dtype),
    }


def switch_moe(
    x,
    params,
    axis_name: str = EP_AXIS,
    *,
    capacity_factor: float = 1.25,
    axis_size: Optional[int] = None,
    activation=jax.nn.gelu,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Top-1 (Switch) MoE layer; call inside ``shard_map``.

    ``x [T_local, d]`` — this device's token shard.  ``params``: ``router
    [d, E]`` replicated; ``wi [E_local, d, f]`` / ``wo [E_local, f, d]`` —
    this device's expert shard (``E = ep * E_local``).

    Returns ``(out [T_local, d], aux_loss)`` where ``aux_loss`` is the
    Switch load-balancing term (mean over devices), already ``pmean``-ed.
    """
    n = int(resolve_axis_size(axis_name, axis_size))
    e_local = params["wi"].shape[0]
    E = n * e_local
    if params["router"].shape[1] != E:
        raise ValueError(
            f"router is {params['router'].shape[1]} experts wide but "
            f"ep={n} x {e_local} local experts = {E}; pass this device's "
            f"[E/ep, ...] expert shard, not the full stack"
        )
    T = x.shape[0]
    # per-device, per-expert slot budget (ceil: capacity_factor headroom
    # must yield slots even when T/E is small)
    cap = max(1, math.ceil(capacity_factor * T / E))

    logits = jnp.einsum("td,de->te", x, params["router"],
                        preferred_element_type=jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)  # [T, E] fp32
    expert = jnp.argmax(probs, axis=-1)  # [T]
    gate = jnp.max(probs, axis=-1)  # [T]

    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's slots (this device's view)
    pos = (jnp.cumsum(onehot, axis=0) * onehot - 1.0).astype(jnp.int32)
    # one_hot zeroes out-of-range rows, so it IS the keep mask: pos == -1
    # (inactive pair) and pos >= cap (overflow) both yield all-zero slots
    dispatch = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # [T, E, cap] 0/1
    combine = dispatch * gate[:, None, None]  # gradient flows to the router

    wdt = x.dtype
    # gather tokens into expert slots: [E, cap, d]
    xin = jnp.einsum("td,tec->ecd", x, dispatch.astype(wdt))
    # ship slots to their expert's device: [E_local, n * cap, d]
    xin = lax.all_to_all(xin, axis_name, split_axis=0, concat_axis=1, tiled=True)
    h = activation(jnp.einsum("ecd,edf->ecf", xin, params["wi"],
                              preferred_element_type=jnp.float32).astype(wdt))
    y = jnp.einsum("ecf,efd->ecd", h, params["wo"],
                   preferred_element_type=jnp.float32).astype(wdt)
    # return slots to their source device: [E, cap, d]
    y = lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0, tiled=True)
    out = jnp.einsum("ecd,tec->td", y, combine.astype(wdt))

    # Switch aux loss: E * <fraction routed to e> . <mean router prob e>,
    # averaged over devices
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = lax.pmean(E * jnp.sum(frac * mean_prob), axis_name)
    return out, aux
