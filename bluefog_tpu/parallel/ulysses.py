"""Ulysses sequence parallelism: all-to-all head/sequence re-sharding.

No sibling in the reference (it predates long-context work — SURVEY.md
§5.7); together with ``parallel.ring_attention`` this gives the rebuild
both public long-context strategies.  The algorithm is DeepSpeed-Ulysses
(Jacobs et al., arXiv:2309.14509): inputs arrive sharded over the
*sequence* (each device holds ``[B, T_local, H, D]``); one
``lax.all_to_all`` per operand re-shards them over *heads*
(``[B, T_global, H_local, D]``), every device then runs ordinary full-
sequence attention on its own head slice, and one final ``all_to_all``
restores sequence sharding.

Trade-off vs ring attention (why both exist):

- Ulysses: 4 all-to-alls moving ``O(B·T·H·D / n)`` per device total —
  bandwidth *decreases* with mesh size and the attention itself is a
  single dense/flash call (best MXU utilization).  But the head count must
  be divisible by the axis size, and peak activation memory holds the full
  sequence for ``H/n`` heads.
- Ring: ``n-1`` neighbor hops riding single ICI links, O(T_local) memory,
  any head count — but the per-hop blockwise compute is smaller and the
  softmax runs as an online recurrence.

Short sequences / many heads → Ulysses; extreme lengths / few heads →
ring.  Both plug into the model family via the same ``attention_fn`` slot.

Layout: per-device ``q, k, v: [B, T_local, H, D]``; the global sequence is
``axis_size * T_local`` in rank order along ``axis_name`` (identical to
``ring_attention``, so they are drop-in interchangeable).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax.numpy as jnp
from jax import lax

from bluefog_tpu.parallel._util import resolve_axis_size

__all__ = ["ulysses_attention", "make_ulysses_attention_fn"]


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    axis_name: str,
    axis_size: int,
    *,
    causal: bool = True,
    flash: bool = False,
    block_q: Optional[int] = None,  # None: per-shard sequence-adaptive
    block_k: Optional[int] = None,  # (kernels._default_blocks)
    interpret: bool = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Exact attention across sequence shards via head re-sharding.

    q, k, v: [B, T_local, H, D] (this device's sequence block); H must be
    divisible by ``axis_size``.  Returns [B, T_local, H, D] in q's dtype.
    ``flash=True`` runs the Pallas flash kernel on the gathered sequence.
    """
    n = resolve_axis_size(axis_name, axis_size)
    H = q.shape[2]
    if H % n != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({H}) divisible by the "
            f"sequence axis size ({n}); use ring_attention otherwise"
        )

    # [B, T_local, H, D] -> [B, T_global, H/n, D].  all_to_all concatenates
    # received blocks in rank order along the sequence axis, which IS the
    # global order because rank i holds sequence block i.  When q/k/v agree
    # in shape and dtype (the training hot path) they ride ONE stacked
    # collective (axes shift by one under the leading stack axis); otherwise
    # (e.g. causal=False cross-attention with Tk != Tq, or narrower k/v
    # dtypes) each reshards independently.
    if q.shape == k.shape == v.shape and q.dtype == k.dtype == v.dtype:
        qkv = lax.all_to_all(
            jnp.stack((q, k, v)), axis_name=axis_name,
            split_axis=3, concat_axis=2, tiled=True,
        )
        qg, kg, vg = qkv
    else:
        reshard = partial(lax.all_to_all, axis_name=axis_name,
                          split_axis=2, concat_axis=1, tiled=True)
        qg, kg, vg = reshard(q), reshard(k), reshard(v)

    if flash:
        from bluefog_tpu.kernels import flash_attention

        out = flash_attention(
            qg, kg, vg, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret, impl=impl,
        )
    else:
        from bluefog_tpu.models.transformer import dense_attention

        out = dense_attention(qg, kg, vg, causal=causal, dtype=q.dtype)

    # [B, T_global, H/n, D] -> [B, T_local, H, D]
    return lax.all_to_all(
        out.astype(q.dtype), axis_name=axis_name,
        split_axis=1, concat_axis=2, tiled=True,
    )


def make_ulysses_attention_fn(axis_name: str, axis_size: int,
                              causal: bool = True, *, flash: bool = False,
                              **flash_kwargs) -> Callable:
    """attention_fn for ``models.transformer.LlamaLM``: plugs Ulysses
    sequence parallelism into the decoder blocks (same slot and layout as
    ``make_ring_attention_fn`` — interchangeable)."""
    return partial(
        ulysses_attention, axis_name=axis_name, axis_size=axis_size,
        causal=causal, flash=flash, **flash_kwargs
    )
