"""ZeRO-1-style sharded optimizer state composing with machine-axis gossip.

BEYOND PARITY: the reference has no optimizer-state sharding — its 8B-class
configs assume enough HBM per rank for full f32 state.  On a 16 GB v5e,
1.05B params is the replicated-state ceiling (3 f32 copies = 12.6 GB,
measured round 2); going past it needs the state split across chips.  This
module is the TPU-native composition of two axes of the hierarchical mesh
(``core.basics.hier_mesh``):

- ``bf_local`` (intra-machine, ICI): data-parallel grads are
  ``psum_scatter``-ed so each chip keeps only 1/local_size of the f32
  master weights + optimizer state (the ZeRO-1 partition; Rajbhandari et
  al. 2020), and the working bf16 params are ``all_gather``-ed per step.
- ``bf_machines`` (inter-machine, DCN): the updated master SHARDS gossip
  with the neighbor-weighted combine over the machine topology — shard i
  only ever mixes with shard i, so decentralized averaging commutes with
  the partition and each machine pays 1/local_size of the gossip bytes.

Everything runs inside ONE jitted ``shard_map`` over the hierarchical mesh:
all_gather + fwd/bwd + psum_scatter + shard update + gossip ppermutes are
scheduled together by XLA (SURVEY.md §3.2's controller dissolved into the
compiled program).

Elementwise optimizers (SGD+momentum, AdamW) act identically on a packed
flat vector as on the tree, so the state lives as ONE padded f32 vector
per replica — the same fusion idea as the window packing.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu.core.basics import LOCAL_AXIS, MACHINES_AXIS
from bluefog_tpu.core.plan import CommPlan
from bluefog_tpu import ops_spmd
from bluefog_tpu.training import apply_accepts_labels

__all__ = [
    "make_zero_gossip_train_step",
    "make_fsdp_gossip_train_step",
    "fsdp_act_constraint",
    "fsdp_onehot_constraint",
    "fsdp_param_io_constraint",
    "fsdp_count_struct",
    "fsdp_state_struct",
    "packed_layout",
    "unpack_params",
]


def fsdp_act_constraint(hier_mesh: "Mesh"):
    """Activation constraint for models running under
    :func:`make_fsdp_gossip_train_step` (e.g. ``LlamaLM.act_constraint``).

    Pins the leading (batch) dim of every block-boundary activation to
    ``bf_local`` — the GSPMD FSDP recipe's load-bearing half.  Weights are
    sharded over ``bf_local`` on their largest dim, so an unconstrained
    ``x @ W`` lets propagation choose between gathering W (FSDP, what we
    want) and gathering x's batch (tensor-parallel-style, locally cheaper
    because x is the smaller operand).  Without this pin the 8B compile
    measured the latter everywhere: full-batch f32 temps ~2.5 GB/layer and
    zero reduce-scatters.  Runs inside the machines-vmap, so the spec
    covers the per-machine view; ``spmd_axis_name=MACHINES_AXIS`` on the
    vmap supplies the machines dim."""

    def constrain(x):
        spec = P(LOCAL_AXIS, *([None] * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(hier_mesh, spec))

    return constrain


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _marked_read(w, fwd_sh, grad_sh, grad_dtype):
    return lax.with_sharding_constraint(w, fwd_sh)


def _marked_read_fwd(w, fwd_sh, grad_sh, grad_dtype):
    return lax.with_sharding_constraint(w, fwd_sh), None


def _marked_read_bwd(fwd_sh, grad_sh, grad_dtype, _, g):
    if grad_dtype is not None:
        g = g.astype(grad_dtype)
    return (lax.with_sharding_constraint(g, grad_sh),)


_marked_read.defvjp(_marked_read_fwd, _marked_read_bwd)


def fsdp_onehot_constraint(hier_mesh: "Mesh"):
    """Pins the one-hot embedding operand ``[B, T, vocab]`` vocab-sharded
    (``LlamaLM.onehot_constraint``): the embedding dot then partitions on
    its CONTRACTING dim — each device contracts its vocab shard and the
    [B, T, d] partials reduce — instead of GSPMD's default, which
    all-gathers the f32 table (2.1 GB/device at 128k vocab, measured on
    the 8B compile)."""

    def constrain(oh):
        spec = P(*([None] * (oh.ndim - 1) + [LOCAL_AXIS]))
        return lax.with_sharding_constraint(
            oh, NamedSharding(hier_mesh, spec))

    return constrain


def fsdp_param_io_constraint(hier_mesh: "Mesh", grad_dtype=None):
    """Per-read FSDP marker for model weights (``LlamaLM.weight_constraint``).

    Forward: re-pins the leaf (or, in a scanned model, the per-layer
    SLICE) to its own FSDP shard spec — an identity that stops sharding
    propagation from re-resolving the read toward a replicated layout.
    A "gather here" (replicated-forward) marker was measured strictly
    worse: under ``nn.scan`` GSPMD hoists the resulting gather to the
    WHOLE stacked leaf ahead of the loop (37.5 GB of temps at
    8B/32-layer).

    Backward: the custom VJP pins the cotangent to the same shard spec AT
    ITS PRODUCTION SITE — without it the 128k-vocab head/embedding
    gradients accumulate replicated in f32 (measured ~2.1 GB per buffer,
    the largest single temps item of the 8B compile) — and optionally
    rounds it to ``grad_dtype`` (bf16 = the standard bf16-gradient
    contract; halves gradient liveness).

    The rounding must be ONE-SHOT per leaf: a scan-sliced block weight's
    cotangent is that layer's gradient alone (no cross-layer sum), but a
    leaf read INSIDE a loop body — the chunked LM head reads its kernel
    once per chunk — would have each per-read cotangent rounded and then
    summed in ``grad_dtype`` by the scan transpose.  For such sites use
    the attached ``.sharding_only`` variant (same sharding pin, no cast)
    inside the loop and apply the full marker once outside, so the chunk
    cotangents accumulate in f32 and round once
    (``LlamaLM.weight_constraint`` does this wiring)."""
    _, local = hier_mesh.devices.shape

    def _make(cast_dtype):
        def constrain(w):
            i = _shard_dim(w.shape, local)
            parts = [None] * w.ndim
            if i is not None:
                parts[i] = LOCAL_AXIS
            sh = NamedSharding(hier_mesh, P(*parts))
            return _marked_read(w, sh, sh, cast_dtype)

        return constrain

    constrain = _make(grad_dtype)
    constrain.sharding_only = _make(None)
    return constrain


class _Layout(NamedTuple):
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    treedef: Any
    total: int      # unpadded element count
    padded: int     # total padded to a multiple of local_size


def packed_layout(params, local_size: int) -> _Layout:
    """Works on real arrays AND ShapeDtypeStructs (the 8B lower-only
    feasibility path builds the layout without materializing buffers)."""
    flat, treedef = jax.tree_util.tree_flatten(params)
    shapes = tuple(tuple(getattr(l, "shape", None) or np.shape(l))
                   for l in flat)
    sizes = tuple(int(np.prod(s, dtype=np.int64)) if s else 1 for s in shapes)
    total = int(sum(sizes))
    padded = ((total + local_size - 1) // local_size) * local_size
    return _Layout(shapes, sizes, treedef, total, padded)


def _pack(flat, layout: _Layout, dtype=jnp.float32):
    vec = jnp.concatenate(
        [jnp.ravel(l).astype(dtype) for l in flat]
    )
    return jnp.pad(vec, (0, layout.padded - layout.total))


def unpack_params(vec, layout: _Layout, dtype):
    """Padded flat vector -> the params tree in ``dtype``."""
    leaves = []
    off = 0
    for shape, size in zip(layout.shapes, layout.sizes):
        leaves.append(vec[off:off + size].reshape(shape).astype(dtype))
        off += size
    return jax.tree_util.tree_unflatten(layout.treedef, leaves)


def _make_update_rule(optimizer: str, lr: float, momentum: float,
                      weight_decay: float):
    """Elementwise update rule on a flat f32 shard (identical math to the
    tree form — the partition is invisible to elementwise optimizers).

    Returns ``(init, update)``: ``init(zeros_f32, zeros_i32)`` builds the
    state tuple from the two zero-factories; ``update(g, state, w) ->
    (delta, state)``.  "sgdm": state (mu,), ``momentum`` is the momentum
    coefficient, ``weight_decay`` is L2 folded into the gradient.
    "adamw": state (mu, nu, count); ``momentum`` maps to b1 and
    ``weight_decay`` is DECOUPLED (applied to w, not g) per Loshchilov &
    Hutter — with weight_decay=0 this is exactly ``optax.adam``.
    """
    wd = float(weight_decay)
    if optimizer == "sgdm":
        mom = float(momentum)

        def init(zeros_f32, zeros_i32):
            del zeros_i32
            return (zeros_f32(),)

        def update(g, state, w):
            (mu,) = state
            if wd:
                g = g + wd * w
            # accumulate in f32, store at the state's dtype: with a bf16
            # momentum buffer (momentum_dtype=bf16, the 134M/1B bench
            # configs' choice) this is optax's accumulator_dtype contract —
            # halves the optimizer shard, identical math at f32 state
            mu_f = mom * mu.astype(jnp.float32) + g
            return -lr * mu_f, (mu_f.astype(mu.dtype),)

        return init, update
    if optimizer == "adamw":
        b1, b2, eps = float(momentum), 0.999, 1e-8

        def init(zeros_f32, zeros_i32):
            # nu is pinned f32 REGARDLESS of the caller's accumulator
            # dtype: its EMA decays by (1-b2) = 0.1%/step, below bf16's
            # ~0.39% ulp — a bf16 nu can never decay and freezes at
            # early-training values (mu's 10%/step increments survive
            # bf16 fine, so mu honors the caller's dtype)
            return (zeros_f32(), zeros_f32(jnp.float32), zeros_i32())

        def update(g, state, w):
            mu, nu, count = state
            count = count + 1
            # f32-accumulate, store at the state's dtype (same contract as
            # sgdm above — without the cast-back, momentum_dtype=bf16
            # state silently drifts to f32 after the first step)
            mu_f = b1 * mu.astype(jnp.float32) + (1 - b1) * g
            nu_f = b2 * nu.astype(jnp.float32) + (1 - b2) * g * g
            c = count.astype(jnp.float32)
            mu_hat = mu_f / (1 - b1 ** c)
            nu_hat = nu_f / (1 - b2 ** c)
            delta = -lr * (mu_hat / (jnp.sqrt(nu_hat) + eps) + wd * w)
            return delta, (mu_f.astype(mu.dtype), nu_f.astype(nu.dtype),
                           count)

        return init, update
    raise ValueError(f"optimizer must be 'sgdm' or 'adamw', got {optimizer!r}")


def make_zero_gossip_train_step(
    apply_fn: Callable,
    loss_fn: Callable,
    hier_mesh: Mesh,
    machine_plan: Optional[CommPlan],
    *,
    learning_rate: float = 1e-3,
    momentum: float = 0.9,
    optimizer: str = "sgdm",
    weight_decay: float = 0.0,
    compute_dtype=jnp.bfloat16,
):
    """Build ``(init_fn, step_fn, params_of)`` for ZeRO-1 + gossip training.

    ``init_fn(params)`` -> state with the f32 master and every optimizer
    slot (``optimizer="sgdm"``: momentum; ``"adamw"``: mu/nu/count) as
    ``[machines, local, padded/local]`` arrays sharded over BOTH mesh
    axes (each chip stores exactly its shard — the ZeRO-1 partition
    covers Adam's second moment too, the case the 8B table needs).

    ``step_fn(state, batch, labels) -> (state, mean_loss)`` — batch/labels
    lead with ``[machines, local, ...]``.

    ``params_of(state)`` -> full params tree in ``compute_dtype`` (machine
    0's replica) for eval/checkpoint.
    """
    machines, local = hier_mesh.devices.shape
    lr = float(learning_rate)
    _takes_labels = apply_accepts_labels(apply_fn)
    opt_init, opt_update = _make_update_rule(
        optimizer, lr, momentum, weight_decay)
    layout_box = {}

    def _layout_for(params):
        if "l" not in layout_box:
            layout_box["l"] = packed_layout(params, local)
        return layout_box["l"]

    def init_fn(params):
        layout = _layout_for(params)
        flat = jax.tree_util.tree_leaves(params)
        vec = _pack(flat, layout)                       # [padded] f32
        shard_len = layout.padded // local
        # every machine starts from the same point (consistent-start
        # idiom); each (machine, local) device stores one shard
        grid = jnp.broadcast_to(
            vec.reshape(local, shard_len)[None], (machines, local, shard_len)
        )
        sharding = NamedSharding(hier_mesh, P(MACHINES_AXIS, LOCAL_AXIS))
        master = jax.device_put(grid, sharding)
        opt = opt_init(
            lambda dtype=None: jax.device_put(
                jnp.zeros_like(grid, dtype=dtype), sharding),
            # per-replica step counter as [machines, local, 1] int32 so
            # every state leaf shares the (machines, local) spec
            lambda: jax.device_put(
                jnp.zeros((machines, local, 1), jnp.int32), sharding),
        )
        return {"master": master, "opt": opt}

    def _step(master, opt, batch, labels, layout):
        # shard_map body: master [1, 1, shard_len], opt leaves [1, 1, *]
        shard = master[0, 0]
        full = lax.all_gather(shard, LOCAL_AXIS, tiled=True)  # [padded] f32
        params = unpack_params(full, layout, compute_dtype)

        def local_loss(p):
            if _takes_labels:
                out = apply_fn(p, batch[0, 0], labels=labels[0, 0])
            else:
                out = apply_fn(p, batch[0, 0])
            return loss_fn(out, labels[0, 0])

        loss, grads = jax.value_and_grad(local_loss)(params)
        g = _pack(jax.tree_util.tree_leaves(grads), layout)
        # mean over the data-parallel (intra-machine) axis, scattered so
        # each chip keeps only its shard of the gradient
        g_shard = lax.psum_scatter(
            g, LOCAL_AXIS, scatter_dimension=0, tiled=True
        ) / local
        delta, opt_new = opt_update(
            g_shard, tuple(o[0, 0] for o in opt), shard)
        shard = shard + delta
        # decentralized averaging across machines, PER SHARD: shard i of
        # machine m mixes with shard i of its machine-topology neighbors
        if machine_plan is not None and machines > 1:
            shard = ops_spmd.neighbor_allreduce(
                shard, machine_plan, MACHINES_AXIS
            )
        loss = lax.pmean(lax.pmean(loss, LOCAL_AXIS), MACHINES_AXIS)
        return (shard[None, None],
                tuple(o[None, None] for o in opt_new), loss)

    def step_fn_factory(layout):
        body = functools.partial(_step, layout=layout)
        sharded = jax.shard_map(
            body,
            mesh=hier_mesh,
            in_specs=(P(MACHINES_AXIS, LOCAL_AXIS),
                      P(MACHINES_AXIS, LOCAL_AXIS),
                      P(MACHINES_AXIS, LOCAL_AXIS),
                      P(MACHINES_AXIS, LOCAL_AXIS)),
            out_specs=(P(MACHINES_AXIS, LOCAL_AXIS),
                       P(MACHINES_AXIS, LOCAL_AXIS), P()),
        )
        return jax.jit(sharded, donate_argnums=(0, 1))

    step_box = {}

    def _layout():
        if "l" not in layout_box:
            raise RuntimeError(
                "call init_fn(params) first: the packed layout "
                "(shapes/offsets) comes from the params tree — when "
                "restoring state from a checkpoint, still call init_fn "
                "with a matching params tree to rebuild it"
            )
        return layout_box["l"]

    def step_fn(state, batch, labels):
        layout = _layout()
        if "f" not in step_box:
            step_box["f"] = step_fn_factory(layout)
        master, opt, loss = step_box["f"](
            state["master"], state["opt"], batch, labels
        )
        return {"master": master, "opt": opt}, loss

    def params_of(state):
        layout = _layout()
        grid = state["master"]
        vec = jnp.reshape(grid[0], (-1,))  # machine 0's replica
        return unpack_params(vec, layout, compute_dtype)

    return init_fn, step_fn, params_of


# ---------------------------------------------------------------------------
# FSDP-style variant: per-leaf sharding via GSPMD (the 8B memory path)
# ---------------------------------------------------------------------------


def _shard_dim(shape, local_size: int):
    """The dimension to partition over ``bf_local``: the largest one
    divisible by local_size (None -> replicate the leaf; only tiny leaves
    like norms fall through)."""
    best = None
    for i, d in enumerate(shape):
        if d % local_size == 0 and d >= local_size and (
            best is None or d > shape[best]
        ):
            best = i
    return best


def _fsdp_spec(shape, local_size: int) -> P:
    """The PartitionSpec a ``[machines, *shape]`` state leaf gets under
    :func:`make_fsdp_gossip_train_step` — the single source of truth used
    by both ``init_fn`` and AOT callers (``fsdp_state_struct``)."""
    parts = [MACHINES_AXIS] + [None] * len(shape)
    i = _shard_dim(shape, local_size)
    if i is not None:
        parts[i + 1] = LOCAL_AXIS
    return P(*parts)


def fsdp_count_struct(leaf, hier_mesh: Mesh):
    """ShapeDtypeStruct for an adamw per-leaf step counter with EXACTLY
    ``init_fn``'s layout ([machines, 1, ...] int32, machines-sharded) —
    the AOT twin of the count factory in ``make_fsdp_gossip_train_step``
    so feasibility checks cannot drift from the runtime state."""
    machines, _ = hier_mesh.devices.shape
    return jax.ShapeDtypeStruct(
        (machines,) + (1,) * len(leaf.shape), jnp.int32,
        sharding=NamedSharding(hier_mesh, P(MACHINES_AXIS)))


def fsdp_state_struct(leaf, hier_mesh: Mesh, dtype=jnp.float32):
    """ShapeDtypeStruct for one master/momentum leaf with the EXACT
    sharding ``init_fn`` would give it — lets feasibility checks lower
    the step without materializing any buffer (benchmarks/zero_8b.py).
    ``dtype``: f32 for master leaves; pass the builder's ``momentum_dtype``
    for momentum structs."""
    machines, local = hier_mesh.devices.shape
    shape = tuple(leaf.shape)
    sh = NamedSharding(hier_mesh, _fsdp_spec(shape, local))
    return jax.ShapeDtypeStruct((machines,) + shape, dtype,
                                sharding=sh)


def make_fsdp_gossip_train_step(
    apply_fn: Callable,
    loss_fn: Callable,
    hier_mesh: Mesh,
    machine_plan: Optional[CommPlan],
    *,
    learning_rate: float = 1e-3,
    momentum: float = 0.9,
    optimizer: str = "sgdm",
    weight_decay: float = 0.0,
    compute_dtype=jnp.bfloat16,
    momentum_dtype=jnp.float32,
):
    """FSDP-style ZeRO + gossip: per-LEAF sharding under GSPMD.

    Unlike :func:`make_zero_gossip_train_step` (one packed vector, whole
    gradient materialized before the scatter), this keeps every leaf of
    the f32 master + momentum sharded over ``bf_local`` on its largest
    divisible dimension and lets XLA insert the per-use all-gathers in
    the forward and reduce-scatters on the gradients (the standard GSPMD
    FSDP recipe) — peak transient memory is per-OPERAND, not per-model,
    which is what closes the memory math at 8B (docs/STATUS.md round 3).

    Decentralized semantics: each MACHINE holds its own replica (leaves
    gain a leading ``[machines]`` axis, sharded over ``bf_machines``);
    after the local update the replicas mix with the machine topology via
    the shift-class plan — ``ops_spmd.neighbor_allreduce`` inside a
    machines-manual/local-auto ``shard_map``, one ppermute per class
    (exactly ``CommPlan.mixing_matrix`` by construction; the earlier
    dense-W einsum spelling all-gathered every leaf's f32 shard over the
    machines axis, which broke the 8B memory budget — see the mix-site
    comment).

    ``batch``/``labels``: ``[machines, per_machine_batch, ...]``.
    """
    machines, local = hier_mesh.devices.shape
    lr = float(learning_rate)
    _takes_labels = apply_accepts_labels(apply_fn)
    opt_init, opt_update = _make_update_rule(
        optimizer, lr, momentum, weight_decay)
    do_mix = machine_plan is not None and machines > 1

    def _sharding(shape):
        return NamedSharding(hier_mesh, _fsdp_spec(shape, local))

    def init_fn(params):
        def place(leaf):
            leaf = jnp.asarray(leaf, jnp.float32)
            stacked = jnp.broadcast_to(leaf[None], (machines,) + leaf.shape)
            return jax.device_put(stacked, _sharding(leaf.shape))

        master = jax.tree_util.tree_map(place, params)
        opt = opt_init(
            lambda dtype=None: jax.tree_util.tree_map(
                lambda a: jnp.zeros_like(a, dtype=dtype or momentum_dtype),
                master),
            # per-replica, per-leaf step counter: [machines, 1, ...]
            # int32, broadcastable against its leaf
            lambda: jax.tree_util.tree_map(
                lambda a: jax.device_put(
                    jnp.zeros((machines,) + (1,) * (a.ndim - 1), jnp.int32),
                    NamedSharding(hier_mesh, P(MACHINES_AXIS))),
                master),
        )
        return {"master": master, "opt": opt}

    data_sharding_box = {}

    def step_fn(state, batch, labels):
        if "f" not in data_sharding_box:
            data_sharding_box["f"] = _build_step()
        return data_sharding_box["f"](state, batch, labels)

    def lower_step(state, batch, labels):
        """AOT-lower the step on ShapeDtypeStructs — the 8B feasibility
        check traces/lowers the full program with real dims but never
        materializes a buffer (benchmarks/zero_8b.py)."""
        if "f" not in data_sharding_box:
            data_sharding_box["f"] = _build_step()
        return data_sharding_box["f"].lower(state, batch, labels)

    step_fn.lower = lower_step

    def _build_step():
        data_spec = NamedSharding(hier_mesh, P(MACHINES_AXIS, LOCAL_AXIS))

        def step(state, batch, labels):
            master, opt = state["master"], state["opt"]

            def total_loss(master):
                p = jax.tree_util.tree_map(
                    lambda a: a.astype(compute_dtype), master)

                def one(pm, bm, lm):
                    if _takes_labels:
                        return loss_fn(apply_fn(pm, bm, labels=lm), lm)
                    return loss_fn(apply_fn(pm, bm), lm)

                # spmd_axis_name: inside the vmap, sharding constraints
                # (fsdp_act_constraint in the model) see the UNBATCHED
                # per-machine shapes; the batched machines dim is pinned
                # to MACHINES_AXIS here so the two compose into the full
                # P(machines, local, ...) layout
                losses = jax.vmap(one, spmd_axis_name=MACHINES_AXIS)(
                    p, batch, labels)
                return jnp.sum(losses), losses

            (_, losses), grads = jax.value_and_grad(
                total_loss, has_aux=True)(master)
            # force the reduce-scatter: gradient leaves live in the same
            # per-leaf partition as the master they update
            grads = jax.tree_util.tree_map(
                lambda g, m: lax.with_sharding_constraint(
                    g, _sharding(m.shape[1:])), grads, master)
            # the elementwise update rule, leaf by leaf (state slots are
            # trees shaped like master; the count slot broadcasts)
            m_leaves, tdef = jax.tree_util.tree_flatten(master)
            g_leaves = jax.tree_util.tree_leaves(grads)
            o_leaves = [jax.tree_util.tree_leaves(o) for o in opt]
            new_m, new_o = [], [[] for _ in opt]
            for i, (w, g) in enumerate(zip(m_leaves, g_leaves)):
                delta, o_new = opt_update(
                    g, tuple(ol[i] for ol in o_leaves), w)
                new_m.append(w + delta)
                for slot, val in zip(new_o, o_new):
                    slot.append(val)
            master = jax.tree_util.tree_unflatten(tdef, new_m)
            opt = tuple(jax.tree_util.tree_unflatten(tdef, slot)
                        for slot in new_o)
            if do_mix:
                # gossip combine via the shift-class plan (ONE ppermute per
                # class inside a machines-manual/local-auto shard_map), NOT
                # the dense-W einsum: the einsum's lowering all-gathers the
                # machines axis of every leaf's f32 shard — machines× the
                # whole state as temps, measured 16 of the 18 GB/device
                # that broke the 8B/32-layer budget.  ppermute keeps one
                # in-flight shard + accumulator per leaf.  Same W by
                # construction (machine_plan IS the matrix's source).
                # FULLY manual over both mesh axes (the local shard rides
                # through untouched — permute + weighted sum is
                # elementwise-linear, so permuting each local shard
                # independently IS the leaf permute).  A machines-manual/
                # local-auto spelling leaves the partitioner to rewrite
                # the region, and its reshard of a collective operand
                # between manual-subgroup and auto shardings is broken on
                # the CPU backend (CHECK in spmd_partitioner.cc); the
                # machine index rides in as a sharded iota rather than
                # lax.axis_index for the same reason (partition-id).
                def _mix_body(t, midx):
                    sq = jax.tree_util.tree_map(lambda a: a[0], t)
                    mixed = ops_spmd.neighbor_allreduce(
                        sq, plan=machine_plan, axis_name=MACHINES_AXIS,
                        rank_index=midx[0])
                    return jax.tree_util.tree_map(lambda a: a[None], mixed)

                midx = jnp.arange(machines, dtype=jnp.int32)
                mix_specs = jax.tree_util.tree_map(
                    lambda a: _fsdp_spec(a.shape[1:], local), master)
                master = jax.shard_map(
                    _mix_body, mesh=hier_mesh,
                    in_specs=(mix_specs, P(MACHINES_AXIS)),
                    out_specs=mix_specs,
                    axis_names=frozenset({MACHINES_AXIS, LOCAL_AXIS}))(
                        master, midx)
                master = jax.tree_util.tree_map(
                    lambda a: lax.with_sharding_constraint(
                        a, _sharding(a.shape[1:])), master)
            return {"master": master, "opt": opt}, jnp.mean(losses)

        return jax.jit(
            step,
            in_shardings=(None, data_spec, data_spec),
            donate_argnums=(0,),
        )

    def params_of(state):
        return jax.tree_util.tree_map(
            lambda a: a[0].astype(compute_dtype), state["master"])

    return init_fn, step_fn, params_of
