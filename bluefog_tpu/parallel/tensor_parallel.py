"""Megatron-style tensor parallelism over a dedicated mesh axis.

No sibling in the reference — it is a decentralized *data*-parallel
framework with every model replicated per rank (SURVEY.md §2.3: TP honestly
absent upstream).  This module is the promised composition bonus: a ``tp``
mesh axis that shards feature/head dimensions, designed to compose with the
framework's gossip axis — a ``("bf_nodes", "tp")`` mesh runs decentralized
neighbor averaging *between* model-sharded replicas, with every collective
riding ICI (TP's ``psum`` on the minor axis, gossip's ``ppermute`` on the
major one; the scaling-book recipe of shard-then-let-XLA-insert-collectives).

Layout follows Megatron (Shoeybi et al., arXiv:1909.08053): attention QKV
and MLP-in are **column-parallel** (output features sharded, no
communication), attention-out and MLP-out are **row-parallel** (input
features sharded, one ``psum``) — two collectives per transformer block.

All functions here are *functional* and meant to run inside ``shard_map``
(or the models' jit with sharding constraints): they take the per-shard
parameter pytree directly.  :func:`shard_tp_params` turns a full (unsharded)
parameter tree into the stacked ``[tp, ...]`` layout for ``in_specs
P("tp")``.
"""

from __future__ import annotations

import functools
import math
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.parallel._util import pvary as _util_pvary

__all__ = [
    "copy_to_tp_region",
    "reduce_from_tp_region",
    "column_parallel_dense",
    "row_parallel_dense",
    "tp_mlp",
    "tp_self_attention",
    "tp_transformer_block",
    "init_tp_block_params",
    "TP_BLOCK_SHARD_AXES",
    "shard_tp_params",
    "split_tp_params",
    "merge_tp_params",
    "unshard_tp_params",
]

TP_AXIS = "tp"


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp_region(x, axis_name: str = TP_AXIS):
    """Megatron's **g** operator: ``psum`` forward, *identity* backward.

    A raw ``lax.psum`` transposes to another ``psum`` — correct for
    device-varying losses, but here the downstream loss is replicated over
    tp, so the raw transpose would multiply every cotangent by the axis
    size.  The identity backward hands each shard the (already replicated)
    cotangent once, making sharded-weight gradients the exact shard of the
    full gradient.

    Megatron's conjugate **f** operator lives in
    :func:`copy_to_tp_region` — apply it where the replicated stream
    enters the tp region (done by :func:`tp_mlp` /
    :func:`tp_self_attention` internally).
    """
    return lax.psum(x, axis_name)


def _reduce_fwd(x, axis_name):
    return lax.psum(x, axis_name), None


def _reduce_bwd(axis_name, _, g):
    # the primal input is tp-varying; re-type the (replicated) cotangent to
    # match under shard_map's varying-manual-axes checking
    return (_util_pvary(g, axis_name),)


reduce_from_tp_region.defvjp(_reduce_fwd, _reduce_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp_region(x, axis_name: str = TP_AXIS):
    """Megatron's **f** operator: identity forward, ``psum`` backward.

    Where a tp-*replicated* activation (a norm output, an embedding
    lookup) enters the tp-sharded region, each shard's transpose produces
    only its partial contribution to the activation cotangent; the psum
    backward assembles the full (and hence again replicated) cotangent, so
    gradients of replicated leaves upstream — norm scales, embeddings —
    come out exact and statically inferable as replicated under
    ``shard_map``'s rep checking.  On older JAX (no varying-manual-axes
    typing) the forward is a plain identity; on newer JAX it is
    ``pvary``, typing the output tp-varying so no implicit cast is needed.
    """
    return _util_pvary(x, axis_name)


def _copy_fwd(x, axis_name):
    return _util_pvary(x, axis_name), None


def _copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


copy_to_tp_region.defvjp(_copy_fwd, _copy_bwd)


def column_parallel_dense(x, kernel, bias=None):
    """``x [..., in] @ kernel [in, out_shard]`` — output features sharded,
    zero communication (Megatron's f in the f/g conjugate pair)."""
    y = jnp.einsum("...i,io->...o", x, kernel,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    if bias is not None:
        y = y + bias
    return y


def row_parallel_dense(x, kernel, bias=None, axis_name: str = TP_AXIS):
    """``psum_tp(x [..., in_shard] @ kernel [in_shard, out])`` — input
    features sharded, one ``psum`` to assemble the output (Megatron's g)."""
    y = jnp.einsum("...i,io->...o", x, kernel,
                   preferred_element_type=jnp.float32).astype(x.dtype)
    y = reduce_from_tp_region(y, axis_name)
    if bias is not None:
        y = y + bias  # bias replicated: add once, after the reduction
    return y


def tp_mlp(x, params, axis_name: str = TP_AXIS,
           activation: Callable = jax.nn.gelu):
    """Column-parallel up-projection, activation, row-parallel down."""
    x = copy_to_tp_region(x, axis_name)
    h = activation(column_parallel_dense(x, params["wi"]))
    return row_parallel_dense(h, params["wo"], axis_name=axis_name)


def tp_self_attention(
    x,
    params,
    axis_name: str = TP_AXIS,
    *,
    causal: bool = False,
    attention_fn: Optional[Callable] = None,
):
    """Self-attention with heads sharded over ``axis_name``.

    ``params``: ``wq/wk/wv [d_model, H_shard, Dh]`` (column-parallel),
    ``wo [H_shard, Dh, d_model]`` (row-parallel).  ``attention_fn(q, k, v)``
    defaults to fp32-softmax dense attention on the local heads; plug in the
    flash kernel or ring attention for long sequences (head sharding and
    sequence sharding compose — different axes).
    """
    dtype = x.dtype
    x = copy_to_tp_region(x, axis_name)
    q = jnp.einsum("btm,mhd->bthd", x, params["wq"]).astype(dtype)
    k = jnp.einsum("btm,mhd->bthd", x, params["wk"]).astype(dtype)
    v = jnp.einsum("btm,mhd->bthd", x, params["wv"]).astype(dtype)
    if attention_fn is None:
        from bluefog_tpu.models.transformer import dense_attention

        att = dense_attention(q, k, v, causal=causal, dtype=dtype)
    else:
        att = attention_fn(q, k, v)
    out = jnp.einsum("bthd,hdm->btm", att, params["wo"],
                     preferred_element_type=jnp.float32).astype(dtype)
    return reduce_from_tp_region(out, axis_name)


def _rms_norm(x, scale, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    y = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def tp_transformer_block(
    x,
    params,
    axis_name: str = TP_AXIS,
    *,
    causal: bool = True,
    attention_fn: Optional[Callable] = None,
):
    """Pre-norm block: x + attn(norm(x)); x + mlp(norm(x)).  Two psums."""
    h = x + tp_self_attention(
        _rms_norm(x, params["norm1"]), params["attn"], axis_name,
        causal=causal, attention_fn=attention_fn,
    )
    return h + tp_mlp(_rms_norm(h, params["norm2"]), params["mlp"], axis_name)


# --------------------------------------------------------------------------
# Parameter construction / (un)sharding
# --------------------------------------------------------------------------

#: For each block parameter: the axis of the *full* tensor that TP shards,
#: or None for replicated leaves.
TP_BLOCK_SHARD_AXES: Dict[str, Any] = {
    "attn": {"wq": 1, "wk": 1, "wv": 1, "wo": 0},  # heads axis
    "mlp": {"wi": 1, "wo": 0},  # dff axis
    "norm1": None,
    "norm2": None,
}


def init_tp_block_params(key, d_model: int, num_heads: int, dff: int,
                         dtype=jnp.bfloat16):
    """Full (unsharded) transformer-block parameters; pair with
    :func:`shard_tp_params` + ``TP_BLOCK_SHARD_AXES``."""
    dh = d_model // num_heads
    ks = jax.random.split(key, 6)

    def dense_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                / math.sqrt(fan_in)).astype(dtype)

    return {
        "attn": {
            "wq": dense_init(ks[0], (d_model, num_heads, dh), d_model),
            "wk": dense_init(ks[1], (d_model, num_heads, dh), d_model),
            "wv": dense_init(ks[2], (d_model, num_heads, dh), d_model),
            "wo": dense_init(ks[3], (num_heads, dh, d_model), d_model),
        },
        "mlp": {
            "wi": dense_init(ks[4], (d_model, dff), d_model),
            "wo": dense_init(ks[5], (dff, d_model), dff),
        },
        "norm1": jnp.ones((d_model,), jnp.float32),
        "norm2": jnp.ones((d_model,), jnp.float32),
    }


def _tree_map_with_axes(fn, params, axes):
    """Map ``fn(leaf, shard_axis_or_None)`` over params following the
    ``axes`` spec tree (dict/list mirroring params; a None or int spec at a
    subtree applies to every leaf under it)."""
    if isinstance(params, dict):
        if isinstance(axes, dict):
            missing = set(params) - set(axes)
            if missing:
                raise ValueError(
                    f"axes spec is missing keys {sorted(missing)}; list every "
                    f"key explicitly (use None for replicated leaves)"
                )
            return {
                k: _tree_map_with_axes(fn, v, axes[k]) for k, v in params.items()
            }
        return {k: _tree_map_with_axes(fn, v, axes) for k, v in params.items()}
    if isinstance(params, (list, tuple)):
        sub = axes if isinstance(axes, (list, tuple)) else [axes] * len(params)
        if len(sub) != len(params):
            raise ValueError(
                f"axes list length {len(sub)} != params list length {len(params)}"
            )
        out = [_tree_map_with_axes(fn, p, a) for p, a in zip(params, sub)]
        if isinstance(params, tuple):
            # namedtuples take fields positionally
            return type(params)(*out) if hasattr(params, "_fields") else tuple(out)
        return out
    return fn(params, axes)


def shard_tp_params(params, axes, tp: int):
    """Full params -> stacked ``[tp, ...]`` leaves (replicated leaves tiled),
    ready for ``shard_map`` ``in_specs P("tp")`` (use ``leaf[0]`` inside).

    Tiling replicated leaves is fine for *inference/forward* use; for
    training, route them around the tp axis instead via
    :func:`split_tp_params` (see its docstring for why)."""

    def shard(leaf, ax):
        if leaf is None:  # placeholder from split_tp_params
            return None
        leaf = jnp.asarray(leaf)
        if ax is None:
            return jnp.broadcast_to(leaf[None], (tp,) + leaf.shape)
        if leaf.shape[ax] % tp:
            raise ValueError(
                f"axis {ax} of size {leaf.shape[ax]} not divisible by tp={tp}"
            )
        return jnp.moveaxis(
            leaf.reshape(
                leaf.shape[:ax] + (tp, leaf.shape[ax] // tp) + leaf.shape[ax + 1:]
            ),
            ax, 0,
        )

    return _tree_map_with_axes(shard, params, axes)


def split_tp_params(params, axes):
    """Split a full parameter tree into ``(replicated, sharded)`` subtrees
    by the axes spec (``None`` = replicated), with ``None`` placeholders at
    the other tree's positions.

    **This split is the correct-training layout rule.**  Sharded leaves go
    through :func:`shard_tp_params` and enter ``shard_map`` tp-varying
    (``P(..., "tp")``); replicated leaves must enter tp-*invariant*
    (``P()``, or ``P("bf_nodes")`` when stacked over a gossip axis) — then
    :func:`copy_to_tp_region` (Megatron's f operator, applied by the block
    functions at region entry) transposes the replicated→varying boundary
    into a psum, and every gradient (including norms/embeddings) comes out
    correct with no manual sync.
    Feeding replicated leaves through the stacked tp layout instead types
    them varying: their backward then mixes full (replicated-path) and
    partial (sharded-path) contributions per shard, which no uniform
    psum/identity rule can repair.
    """
    repl = _tree_map_with_axes(lambda l, ax: l if ax is None else None, params, axes)
    shard = _tree_map_with_axes(lambda l, ax: None if ax is None else l, params, axes)
    return repl, shard


def merge_tp_params(replicated, sharded):
    """Inverse of :func:`split_tp_params`: fill each ``None`` placeholder
    from the other tree."""
    return jax.tree_util.tree_map(
        lambda a, b: b if a is None else a,
        replicated, sharded,
        is_leaf=lambda x: x is None,
    )


def unshard_tp_params(params, axes):
    """Inverse of :func:`shard_tp_params` (stacked ``[tp, ...]`` -> full)."""

    def unshard(leaf, ax):
        if leaf is None:  # placeholder from split_tp_params
            return None
        leaf = jnp.asarray(leaf)
        if ax is None:
            return leaf[0]
        tp = leaf.shape[0]
        moved = jnp.moveaxis(leaf, 0, ax)  # [..., tp, shard, ...]
        return moved.reshape(
            moved.shape[:ax] + (tp * moved.shape[ax + 1],) + moved.shape[ax + 2:]
        )

    return _tree_map_with_axes(unshard, params, axes)
