"""ICI-aware topology layout: map virtual gossip graphs onto the TPU torus.

This module has no sibling in the reference — it is the TPU-native
replacement for what MPI gave the reference for free: `mpirun` rank
placement + `MPI_Dist_graph_create_adjacent` letting the MPI implementation
reorder ranks for the physical network (SURVEY.md §2.4).  On TPU the
physical network is an ICI torus with wraparound links, and *we* choose the
rank→chip assignment: a gossip edge between torus-adjacent chips costs one
hop; a random assignment makes every edge a multi-hop route through other
chips' routers, eating the bandwidth the gossip win depends on (SURVEY.md
§7 hard part #3).

Strategy: order devices along a *snake (boustrophedon) Hamiltonian cycle*
of the torus.  Consecutive snake positions are torus-adjacent, so:

- ``RingGraph`` edges ride exactly one ICI hop each;
- ``ExponentialTwoGraph``'s 2^k-shift edges stay short: a +s shift along
  the snake is at most ``ceil(s / X) + min(s mod X, X - s mod X)`` hops on
  an X-wide torus (row-major snake), i.e. O(s/X) instead of O(s);
- hop costs are measurable per plan via :func:`plan_hop_cost`, which bench
  and tests use to compare layouts.

TPU device objects expose physical ``coords`` (x, y, z); on CPU test
meshes synthetic coords are provided by the caller.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.core.plan import CommPlan

Coord = Tuple[int, ...]

__all__ = [
    "snake_order",
    "device_coords",
    "order_devices_for_ring",
    "order_devices_for_topology",
    "hop_distance",
    "plan_hop_cost",
    "assignment_from_coords",
    "optimize_assignment",
]


def snake_order(shape: Sequence[int]) -> List[Coord]:
    """Boustrophedon visit order of an N-D torus grid.

    Consecutive entries differ by one unit step in exactly one dimension
    (torus-adjacent); for even leading dimensions the cycle also closes
    (last adjacent to first via a wraparound link).
    """
    shape = tuple(int(s) for s in shape)
    if not shape:
        return [()]
    if len(shape) == 1:
        return [(i,) for i in range(shape[0])]
    inner = snake_order(shape[1:])
    out: List[Coord] = []
    for i in range(shape[0]):
        layer = inner if i % 2 == 0 else inner[::-1]
        out.extend((i,) + c for c in layer)
    return out


def device_coords(devices) -> Optional[List[Coord]]:
    """Physical coords for TPU devices (None when unavailable, e.g. CPU)."""
    coords = []
    for d in devices:
        c = getattr(d, "coords", None)
        if c is None:
            return None
        coords.append(tuple(int(v) for v in c))
    return coords


def assignment_from_coords(
    coords: Sequence[Coord], torus_shape: Sequence[int]
) -> List[int]:
    """Rank order (device indices) following the snake cycle of the torus.

    ``coords[i]`` is device i's physical coordinate; returns a permutation
    ``order`` such that rank r should be device ``order[r]``.
    """
    pos = {tuple(c): i for i, c in enumerate(coords)}
    order = []
    for c in snake_order(torus_shape):
        if c in pos:
            order.append(pos[c])
    if len(order) != len(coords):
        raise ValueError(
            f"coords do not tile the torus {tuple(torus_shape)}: "
            f"{len(order)} of {len(coords)} matched"
        )
    return order


def order_devices_for_ring(devices, torus_shape: Optional[Sequence[int]] = None):
    """Reorder ``devices`` so consecutive ranks are torus-adjacent.

    Pass the result to ``bluefog_tpu.init(devices=...)`` before installing a
    ring/exp-2 topology.  Falls back to the given order when physical coords
    are unavailable (CPU simulation) — the mapping is then logical only.
    """
    coords = device_coords(devices)
    if coords is None:
        return list(devices)
    if torus_shape is None:
        torus_shape = tuple(max(c[d] for c in coords) + 1 for d in range(len(coords[0])))
    order = assignment_from_coords(coords, torus_shape)
    return [devices[i] for i in order]


def _topology_edges(topo):
    """Directed non-self edges + weights of a networkx digraph."""
    edges, weights = [], []
    for s, d, data in topo.edges(data=True):
        if s == d:
            continue
        edges.append((int(s), int(d)))
        weights.append(float(data.get("weight", 1.0)))
    return edges, weights


def optimize_assignment(
    topo,
    coords: Sequence[Coord],
    torus_shape: Sequence[int],
    *,
    iters: int = 20000,
    seed: int = 0,
):
    """Annealed rank→position assignment for an arbitrary weighted digraph.

    Seeds the search with the snake order (so the result is never worse than
    the heuristic) and runs the native simulated annealer
    (``native/layout_optimizer.cc``; pure-Python twin as fallback) to
    minimize Σ weight·hops over the topology's edges.  Returns
    ``(order, cost)`` where ``order[r]`` indexes ``coords``.
    """
    from bluefog_tpu.native.layout_native import anneal_layout

    try:
        init = assignment_from_coords(coords, torus_shape)
    except ValueError:
        init = None  # coords don't tile the torus; start from identity
    edges, weights = _topology_edges(topo)
    return anneal_layout(
        coords, torus_shape, edges, weights, init=init, iters=iters, seed=seed
    )


def order_devices_for_topology(
    devices,
    topo,
    torus_shape: Optional[Sequence[int]] = None,
    *,
    iters: int = 20000,
    seed: int = 0,
):
    """Reorder ``devices`` to minimize the topology's weighted ICI hop cost.

    The general-graph sibling of :func:`order_devices_for_ring`: pass the
    result to ``bluefog_tpu.init(devices=...)`` before ``set_topology``.
    Falls back to the given order when physical coords are unavailable
    (CPU simulation).
    """
    coords = device_coords(devices)
    if coords is None:
        return list(devices)
    if torus_shape is None:
        torus_shape = tuple(
            max(c[d] for c in coords) + 1 for d in range(len(coords[0]))
        )
    order, _ = optimize_assignment(
        topo, coords, torus_shape, iters=iters, seed=seed
    )
    return [devices[i] for i in order]


def hop_distance(a: Coord, b: Coord, torus_shape: Sequence[int]) -> int:
    """Torus Manhattan distance (wraparound-aware) between two coords."""
    dist = 0
    for x, y, s in zip(a, b, torus_shape):
        d = abs(x - y)
        dist += min(d, s - d)
    return dist


def plan_hop_cost(
    plan: CommPlan,
    rank_coords: Sequence[Coord],
    torus_shape: Sequence[int],
) -> Dict[str, float]:
    """Hop statistics of a compiled plan under a rank→coord assignment.

    total_hops drives link-bandwidth use; max_edge_hops is the latency
    critical path of one gossip round.
    """
    hops = [
        hop_distance(rank_coords[s], rank_coords[d], torus_shape)
        for cls in plan.classes
        for s, d in cls.perm
    ]
    if not hops:
        return {"total_hops": 0.0, "max_edge_hops": 0.0, "mean_edge_hops": 0.0}
    return {
        "total_hops": float(np.sum(hops)),
        "max_edge_hops": float(np.max(hops)),
        "mean_edge_hops": float(np.mean(hops)),
    }
