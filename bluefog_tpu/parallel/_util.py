"""Shared helpers for the parallel strategies."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def vma_full(ref, shape, dtype, fill=0.0):
    """A constant array carrying ``ref``'s varying-manual-axes type.

    The safe way to build sentinels/inits inside ``shard_map``: fresh
    ``jnp.full`` constants are unvarying-typed and fail vma checks against
    compute branches, while operand arithmetic (``ref * 0.0``) propagates
    NaN whenever ``ref`` contains inf.  Outside a trace (or on pre-vma
    JAX) this is just ``jnp.full``.
    """
    z = jnp.full(shape, fill, dtype)
    try:
        vma = tuple(jax.typeof(ref).vma)
    except (AttributeError, TypeError):
        return z
    if not vma:
        return z
    if hasattr(lax, "pcast"):
        return lax.pcast(z, vma, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(z, vma)
    return z


def pvary(x, axis_name):
    """Re-type a replicated value as varying over ``axis_name`` under
    shard_map's varying-manual-axes checking, across JAX versions
    (``pcast`` is current, ``pvary`` its deprecated predecessor, pre-vma
    JAX needs nothing)."""
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, axis_name)
    return x


def resolve_axis_size(axis_name: str, axis_size) -> int:
    """Validate ``axis_size`` against the mesh axis it names.

    Inside a shard_map/pmap trace the bound axis size is authoritative: a
    stale ``axis_size`` argument would otherwise produce silently wrong
    causal masks (ring) or an opaque XLA dimension error (ulysses
    all_to_all).  Outside a trace the axis is unbound and the passed value
    is all we have.  ``axis_size=None`` means "no caller claim": allowed
    inside a trace, an error outside one.
    """
    try:
        # lax.axis_size is current jax; psum of a literal constant-folds
        # to the bound axis size as a Python int on versions without it
        n = (lax.axis_size(axis_name) if hasattr(lax, "axis_size")
             else lax.psum(1, axis_name))
    except NameError:
        if axis_size is None:
            raise
        return axis_size
    if axis_size is not None and axis_size != n:
        raise ValueError(
            f"axis_size={axis_size} does not match the actual size of mesh "
            f"axis {axis_name!r} ({n})"
        )
    return n
