"""bluefog_tpu.serve — live weight publication to inference replicas.

The read side of "a system that serves while it trains" (ROADMAP item
5, docs/SERVING.md): a gossip-training island *publishes* consistent
versioned weight snapshots — the debiased push-sum estimate, fenced at
an epoch boundary and quorum-gated so an ORPHAN minority can never
publish — into a double-buffered seqlock'd snapshot region; a fleet of
inference replica processes *subscribes* and hot-swaps with zero
downtime.

- :mod:`bluefog_tpu.serve.snapshot` — the region: the double-buffer
  publish protocol, the seqlock + crc read protocol, and the
  mid-publish death matrix.
- :mod:`bluefog_tpu.serve.replica` — the subscriber: atomic-flip
  hot-swap, bounded full-jitter retry, and the
  ``BFTPU_SERVE_MAX_LAG`` staleness policy.
- :mod:`bluefog_tpu.serve.loadgen` — the open-loop load generator
  (Poisson / fixed-rate arrivals, coordinated-omission-safe latency)
  and the ``BFTPU_SERVE_SLO_MS`` / ``BFTPU_SERVE_SLO_STALENESS``
  violation-window monitor.
- ``python -m bluefog_tpu.serve`` — one replica process (what
  ``bftpu-run --serve-replicas K`` spawns K of).

The publisher entry point lives with the training loop:
``islands.serve_publish(name)``.
"""

from bluefog_tpu.serve.replica import (
    REPLICA_RANK_BASE,
    Replica,
    ShmSource,
    StaleSnapshotError,
    full_jitter,
    serve_max_lag,
    serve_stale_policy,
)
from bluefog_tpu.serve.loadgen import (
    LoadGenerator,
    LoadReport,
    SLOMonitor,
    serve_slo_ms,
    serve_slo_staleness,
)
from bluefog_tpu.serve.snapshot import (
    SERVE_SCHEMA,
    SnapshotRegion,
    SnapshotUnavailable,
    TornSnapshotError,
    read_committed,
    region_path,
)

__all__ = [
    "SERVE_SCHEMA",
    "SnapshotRegion",
    "SnapshotUnavailable",
    "TornSnapshotError",
    "read_committed",
    "region_path",
    "REPLICA_RANK_BASE",
    "Replica",
    "ShmSource",
    "StaleSnapshotError",
    "full_jitter",
    "serve_max_lag",
    "serve_stale_policy",
    "LoadGenerator",
    "LoadReport",
    "SLOMonitor",
    "serve_slo_ms",
    "serve_slo_staleness",
]
