"""One inference replica process: ``python -m bluefog_tpu.serve``.

``bftpu-run --serve-replicas K`` spawns K of these next to the training
island.  The loop is deliberately boring — poll, maybe swap, serve —
because every interesting behavior (retry, staleness, chaos) lives in
:class:`bluefog_tpu.serve.replica.Replica` where tests can reach it.

``--remote host:port`` (or ``BFTPU_SERVE_REMOTE``) attaches through
the snapshot distribution tree instead of the local shm region: the
replica joins the publisher's coordinator, feeds off its assigned
parent, and relays to its own children — the cross-host read path
(docs/SERVING.md, "Cross-host distribution").
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from bluefog_tpu.serve.replica import Replica, StaleSnapshotError
from bluefog_tpu.serve.snapshot import SnapshotUnavailable


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.serve",
        description="Run one inference replica against a job's "
                    "snapshot region.")
    ap.add_argument("--job", required=True, help="job name to subscribe to")
    ap.add_argument("--replica-id", type=int, default=0)
    ap.add_argument("--remote", default=os.environ.get(
        "BFTPU_SERVE_REMOTE", ""),
        help="attach over TCP through the distribution tree "
        "(publisher's host:port) instead of the local shm region")
    ap.add_argument("--no-relay", action="store_true",
                    help="remote mode: never relay to children "
                    "(leaf-only subscriber)")
    ap.add_argument("--poll-s", type=float, default=0.02,
                    help="seconds between region polls")
    ap.add_argument("--steps", type=int, default=0,
                    help="exit after N serve steps (0 = run until killed)")
    ap.add_argument("--duration-s", type=float, default=0.0,
                    help="exit after this many seconds (0 = no limit)")
    args = ap.parse_args(argv)

    source = None
    if args.remote:
        from bluefog_tpu.serve.distrib import TcpSource

        source = TcpSource(args.remote, replica_id=args.replica_id,
                           relay=not args.no_relay)
    rep = Replica(args.job, args.replica_id, source=source)
    t_end = time.monotonic() + args.duration_s if args.duration_s else None
    try:
        while True:
            try:
                rep.poll_swap()
                rep.serve_step()
            except SnapshotUnavailable:
                pass  # nothing committed yet — keep polling
            except StaleSnapshotError as e:
                print(f"[serve r{args.replica_id}] refusing: {e}",
                      file=sys.stderr)
            if args.steps and rep.serve_steps >= args.steps:
                break
            if t_end is not None and time.monotonic() >= t_end:
                break
            time.sleep(args.poll_s)
    finally:
        print(f"[serve r{args.replica_id}] version={rep.version} "
              f"swaps={rep.swaps} steps={rep.serve_steps} lag={rep.lag}")
        if source is not None:
            source.close()
        rep.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
