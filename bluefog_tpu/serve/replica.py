"""The inference replica: subscribe, hot-swap, never serve torn bytes.

A replica keeps exactly one in-memory snapshot (the A buffer in its own
address space) and polls the job's snapshot region; a newer committed
version is read to the side (B fills while A serves) and installed by a
single reference flip, so there is no serve-path downtime and no
intermediate state — a SIGKILL between the read and the flip (the chaos
hook drives exactly that) just means the next incarnation re-reads the
same committed version.

The served version is **strictly monotone per replica**: a region
re-read can only move the replica forward, and a publisher handoff
cannot regress it because the committed word itself is monotone
(:mod:`bluefog_tpu.serve.snapshot`).

Degradation contract (docs/SERVING.md):

- transient trouble (region missing, torn bracket, `PeerTimeoutError`,
  `OrphanedError` from a quiesced publisher) → bounded full-jitter
  retry, then keep serving the current snapshot;
- lag beyond ``BFTPU_SERVE_MAX_LAG`` → policy-selectable via
  ``BFTPU_SERVE_STALE_POLICY``: ``warn`` serves stale and journals,
  ``refuse`` raises :class:`StaleSnapshotError` so the caller can shed
  load instead of serving ancient weights.
"""

from __future__ import annotations

import os
import random
import time
from typing import Optional, Tuple

import numpy as np

from bluefog_tpu import telemetry as _telemetry
from bluefog_tpu.serve import snapshot as _snap
from bluefog_tpu.serve.snapshot import (SnapshotUnavailable,
                                        TornSnapshotError)

__all__ = [
    "Replica",
    "ShmSource",
    "StaleSnapshotError",
    "full_jitter",
    "serve_max_lag",
    "serve_stale_policy",
    "REPLICA_RANK_BASE",
]

#: replicas publish status pages as ranks >= this offset, so one
#: ``bftpu-top`` attach shows the training island and the serving fleet
#: side by side without rank collisions (islands are bounded well below)
REPLICA_RANK_BASE = 1000


def serve_max_lag() -> int:
    """``BFTPU_SERVE_MAX_LAG``: how many committed versions a replica
    may trail before the stale policy kicks in (0 = unbounded)."""
    try:
        return max(0, int(os.environ.get("BFTPU_SERVE_MAX_LAG", "0")))
    except ValueError:
        return 0


def serve_stale_policy() -> str:
    """``BFTPU_SERVE_STALE_POLICY``: ``warn`` (serve stale, journal) or
    ``refuse`` (raise so the caller sheds load)."""
    v = os.environ.get("BFTPU_SERVE_STALE_POLICY", "warn")
    return v if v in ("warn", "refuse") else "warn"


def serve_retries() -> int:
    try:
        return max(1, int(os.environ.get("BFTPU_SERVE_RETRIES", "5")))
    except ValueError:
        return 5


def serve_backoff_s() -> float:
    try:
        return float(os.environ.get("BFTPU_SERVE_BACKOFF_S", "0.05"))
    except ValueError:
        return 0.05


def full_jitter(attempt: int, base: float, cap: float = 2.0,
                rng: Optional[random.Random] = None) -> float:
    """Full-jitter backoff: ``uniform(0, min(cap, base * 2**attempt))``.

    The deterministic ``base * 2**attempt`` schedule resynchronizes a
    fleet (every replica that lost the publisher at the same instant
    retries at the same instant — a thundering herd); sampling the whole
    interval decorrelates them.  Same shape as the TCP reconnect
    backoff (``tcp_transport._backoff``)."""
    bound = min(float(cap), float(base) * (2 ** max(0, int(attempt))))
    r = rng if rng is not None else random
    return r.uniform(0.0, bound) if bound > 0 else 0.0


class StaleSnapshotError(RuntimeError):
    """Served lag exceeded ``BFTPU_SERVE_MAX_LAG`` under the ``refuse``
    policy."""

    def __init__(self, msg: str, lag: int = -1, max_lag: int = -1):
        super().__init__(msg)
        self.lag = int(lag)
        self.max_lag = int(max_lag)


def _kill_replica() -> int:
    """Chaos: replica id whose Nth swap is killed mid-flight (-1 off)."""
    try:
        return int(os.environ.get("BFTPU_CHAOS_SERVE_KILL_REPLICA", "-1"))
    except ValueError:
        return -1


def _kill_swap() -> int:
    """Chaos: the swap ordinal at which the kill fires (default 1)."""
    try:
        return int(os.environ.get("BFTPU_CHAOS_SERVE_KILL_SWAP", "1"))
    except ValueError:
        return 1


class ShmSource:
    """The single-host source: the job's seqlock'd snapshot region."""

    def __init__(self, job: str):
        self.job = str(job)

    def poll(self) -> Tuple[int, int, int, np.ndarray]:
        return _snap.read_committed(self.job)


#: exception classes the bounded-backoff retry treats as transient; the
#: TCP source's PeerTimeoutError and a quiesced publisher's
#: OrphanedError are appended lazily (keeps this module importable
#: without the native transport stack)
def _transient_errors() -> tuple:
    errs = [SnapshotUnavailable, TornSnapshotError, OSError]
    try:
        from bluefog_tpu.native.tcp_transport import PeerTimeoutError
        errs.append(PeerTimeoutError)
    except Exception:
        pass
    try:
        from bluefog_tpu.resilience.quorum import OrphanedError
        errs.append(OrphanedError)
    except Exception:
        pass
    return tuple(errs)


class Replica:
    """One serving process: poll → side-read → atomic flip → serve."""

    def __init__(self, job: str, replica_id: int = 0, *,
                 source=None, rng: Optional[random.Random] = None,
                 publish_page: bool = True):
        self.job = str(job)
        self.replica_id = int(replica_id)
        self.source = source if source is not None else ShmSource(job)
        self._rng = rng if rng is not None else random.Random()
        # the A buffer: (version, epoch, step, tensor) flipped as one ref
        self._current: Optional[Tuple[int, int, int, np.ndarray]] = None
        #: newest committed version observed at the region, even when
        #: the swap was skipped — the lag denominator
        self.published_version = 0
        self.swaps = 0
        self.serve_steps = 0
        self.stale_served = 0
        self.retries = 0
        self._page = None
        if publish_page:
            from bluefog_tpu.introspect.statuspage import StatusPage
            self._page = StatusPage(job, REPLICA_RANK_BASE + self.replica_id)
            self._publish_page("attach")

    # -- observability -----------------------------------------------------

    @property
    def version(self) -> int:
        """The version this replica is serving (0 = nothing yet)."""
        return self._current[0] if self._current is not None else 0

    @property
    def lag(self) -> int:
        return max(0, self.published_version - self.version)

    def _publish_page(self, op: str) -> None:
        if self._page is None:
            return
        cur = self._current
        # a distribution-tree source (serve.distrib.TcpSource) exposes
        # its slot/parent — surfaced on the page so bftpu-top draws
        # the tree (slot -1 = shm-attached, not in the tree)
        slot = getattr(self.source, "slot", None)
        parent = getattr(self.source, "parent_slot", -1)
        self._page.publish(
            nranks=0, step=self.serve_steps,
            epoch=cur[1] if cur else 0, op_id=self.swaps,
            last_op=op, serve_version=self.version, serve_lag=self.lag,
            distrib_slot=-1 if slot is None else int(slot),
            distrib_parent=int(parent))

    # -- subscribe / swap --------------------------------------------------

    def _poll_with_retry(self) -> Tuple[int, int, int, np.ndarray]:
        reg = _telemetry.get_registry()
        errs = _transient_errors()
        base, cap = serve_backoff_s(), 2.0
        last: Optional[Exception] = None
        for attempt in range(serve_retries()):
            try:
                return self.source.poll()
            except errs as e:
                last = e
                self.retries += 1
                delay = full_jitter(attempt, base, cap, self._rng)
                if reg.enabled:
                    reg.counter("serve.retries",
                                replica=str(self.replica_id)).inc()
                    reg.journal("serve_retry", replica=self.replica_id,
                                attempt=attempt + 1, backoff_s=delay,
                                error=type(e).__name__)
                time.sleep(delay)
        assert last is not None
        raise last

    def poll_swap(self) -> bool:
        """One subscribe cycle.  Reads the committed snapshot (bounded
        jittered retries on transient errors), and hot-swaps iff it is
        strictly newer than what we serve.  Returns True on a swap.

        Raises the last transient error only when we have NOTHING to
        serve yet; once a snapshot is installed, poll trouble degrades
        to serving the current version (the zero-downtime contract)."""
        reg = _telemetry.get_registry()
        t0 = time.monotonic()
        try:
            version, epoch, step, arr = self._poll_with_retry()
        except _transient_errors():
            if self._current is None:
                raise
            return False
        self.published_version = max(self.published_version, version)
        if self._current is not None and version <= self._current[0]:
            return False  # monotone: never regress, never re-swap
        # B is filled (arr lives only in this frame); chaos kills the
        # replica exactly here — mid-swap, after the read, before the
        # flip — and the e2e asserts A kept serving until the kill
        if (self.replica_id == _kill_replica()
                and self.swaps + 1 == _kill_swap()):
            from bluefog_tpu.resilience import chaos as _chaos
            _chaos.kill_self()
        self._current = (version, epoch, step, arr)  # the atomic flip
        self.swaps += 1
        if reg.enabled:
            reg.counter("serve.swaps", replica=str(self.replica_id)).inc()
            reg.gauge("serve.version",
                      replica=str(self.replica_id)).set(version)
            reg.gauge("serve.lag", replica=str(self.replica_id)).set(self.lag)
            reg.histogram("serve.swap_s").observe(time.monotonic() - t0)
            reg.journal("serve_swap", replica=self.replica_id,
                        version=version, epoch=epoch, step=step,
                        lag=self.lag)
        self._publish_page("swap")
        return True

    # -- serve -------------------------------------------------------------

    def serve_step(self, x: Optional[np.ndarray] = None):
        """One inference step against the installed snapshot.

        Returns ``(version, y)`` where ``y`` is ``snapshot @ x`` (or the
        snapshot itself when ``x`` is None — zero-copy).  Never reads
        the region: swap and serve are decoupled, which is what makes
        mid-swap death a non-event for in-flight requests."""
        cur = self._current
        if cur is None:
            raise SnapshotUnavailable(
                f"replica {self.replica_id}: nothing committed yet")
        version, _epoch, _step, arr = cur
        lag, max_lag = self.lag, serve_max_lag()
        reg = _telemetry.get_registry()
        if max_lag and lag > max_lag:
            if serve_stale_policy() == "refuse":
                if reg.enabled:
                    reg.counter("serve.refused",
                                replica=str(self.replica_id)).inc()
                raise StaleSnapshotError(
                    f"replica {self.replica_id} is {lag} versions behind "
                    f"(BFTPU_SERVE_MAX_LAG={max_lag}, policy=refuse)",
                    lag=lag, max_lag=max_lag)
            self.stale_served += 1
            if reg.enabled:
                reg.counter("serve.stale_served",
                            replica=str(self.replica_id)).inc()
                reg.journal("serve_stale", replica=self.replica_id,
                            version=version, lag=lag, max_lag=max_lag)
        self.serve_steps += 1
        if reg.enabled:
            reg.counter("serve.steps", replica=str(self.replica_id)).inc()
        if x is None:
            return version, arr
        return version, arr.reshape(-1) @ np.asarray(x).reshape(-1)

    def close(self, unlink: bool = False) -> None:
        if self._page is not None:
            self._page.close(unlink)
            self._page = None
