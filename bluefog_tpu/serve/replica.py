"""The inference replica: subscribe, hot-swap, never serve torn bytes.

A replica keeps exactly one in-memory snapshot (the A buffer in its own
address space) and polls the job's snapshot region; a newer committed
version is read to the side (B fills while A serves) and installed by a
single reference flip, so there is no serve-path downtime and no
intermediate state — a SIGKILL between the read and the flip (the chaos
hook drives exactly that) just means the next incarnation re-reads the
same committed version.

The served version is **strictly monotone per replica**: a region
re-read can only move the replica forward, and a publisher handoff
cannot regress it because the committed word itself is monotone
(:mod:`bluefog_tpu.serve.snapshot`).

Degradation contract (docs/SERVING.md):

- transient trouble (region missing, torn bracket, `PeerTimeoutError`,
  `OrphanedError` from a quiesced publisher) → bounded full-jitter
  retry, then keep serving the current snapshot;
- lag beyond ``BFTPU_SERVE_MAX_LAG`` → policy-selectable via
  ``BFTPU_SERVE_STALE_POLICY``: ``warn`` serves stale and journals,
  ``refuse`` raises :class:`StaleSnapshotError` so the caller can shed
  load instead of serving ancient weights.
"""

from __future__ import annotations

import os
import random
import time
from collections import deque
from typing import Optional, Tuple

import numpy as np

from bluefog_tpu import telemetry as _telemetry
from bluefog_tpu.serve import snapshot as _snap
from bluefog_tpu.serve.snapshot import (SnapshotUnavailable,
                                        TornSnapshotError)

__all__ = [
    "Replica",
    "ShmSource",
    "StaleSnapshotError",
    "full_jitter",
    "serve_max_lag",
    "serve_stale_policy",
    "REPLICA_RANK_BASE",
]

#: replicas publish status pages as ranks >= this offset, so one
#: ``bftpu-top`` attach shows the training island and the serving fleet
#: side by side without rank collisions (islands are bounded well below)
REPLICA_RANK_BASE = 1000


def serve_max_lag() -> int:
    """``BFTPU_SERVE_MAX_LAG``: how many committed versions a replica
    may trail before the stale policy kicks in (0 = unbounded)."""
    try:
        return max(0, int(os.environ.get("BFTPU_SERVE_MAX_LAG", "0")))
    except ValueError:
        return 0


def serve_stale_policy() -> str:
    """``BFTPU_SERVE_STALE_POLICY``: ``warn`` (serve stale, journal) or
    ``refuse`` (raise so the caller sheds load)."""
    v = os.environ.get("BFTPU_SERVE_STALE_POLICY", "warn")
    return v if v in ("warn", "refuse") else "warn"


def serve_retries() -> int:
    try:
        return max(1, int(os.environ.get("BFTPU_SERVE_RETRIES", "5")))
    except ValueError:
        return 5


def serve_backoff_s() -> float:
    try:
        return float(os.environ.get("BFTPU_SERVE_BACKOFF_S", "0.05"))
    except ValueError:
        return 0.05


def full_jitter(attempt: int, base: float, cap: float = 2.0,
                rng: Optional[random.Random] = None) -> float:
    """Full-jitter backoff: ``uniform(0, min(cap, base * 2**attempt))``.

    The deterministic ``base * 2**attempt`` schedule resynchronizes a
    fleet (every replica that lost the publisher at the same instant
    retries at the same instant — a thundering herd); sampling the whole
    interval decorrelates them.  Same shape as the TCP reconnect
    backoff (``tcp_transport._backoff``)."""
    bound = min(float(cap), float(base) * (2 ** max(0, int(attempt))))
    r = rng if rng is not None else random
    return r.uniform(0.0, bound) if bound > 0 else 0.0


class StaleSnapshotError(RuntimeError):
    """Served lag exceeded ``BFTPU_SERVE_MAX_LAG`` under the ``refuse``
    policy."""

    def __init__(self, msg: str, lag: int = -1, max_lag: int = -1):
        super().__init__(msg)
        self.lag = int(lag)
        self.max_lag = int(max_lag)


def _kill_replica() -> int:
    """Chaos: replica id whose Nth swap is killed mid-flight (-1 off)."""
    try:
        return int(os.environ.get("BFTPU_CHAOS_SERVE_KILL_REPLICA", "-1"))
    except ValueError:
        return -1


def _kill_swap() -> int:
    """Chaos: the swap ordinal at which the kill fires (default 1)."""
    try:
        return int(os.environ.get("BFTPU_CHAOS_SERVE_KILL_SWAP", "1"))
    except ValueError:
        return 1


class ShmSource:
    """The single-host source: the job's seqlock'd snapshot region."""

    def __init__(self, job: str):
        self.job = str(job)

    def poll(self) -> Tuple[int, int, int, np.ndarray]:
        return _snap.read_committed(self.job)


#: exception classes the bounded-backoff retry treats as transient; the
#: TCP source's PeerTimeoutError and a quiesced publisher's
#: OrphanedError are appended lazily (keeps this module importable
#: without the native transport stack)
def _transient_errors() -> tuple:
    errs = [SnapshotUnavailable, TornSnapshotError, OSError]
    try:
        from bluefog_tpu.native.tcp_transport import PeerTimeoutError
        errs.append(PeerTimeoutError)
    except Exception:
        pass
    try:
        from bluefog_tpu.resilience.quorum import OrphanedError
        errs.append(OrphanedError)
    except Exception:
        pass
    return tuple(errs)


class _RequestStats:
    """Rolling-window request latencies for the statuspage.

    Keeps the last ``window_s`` of (done_mono, latency_ms) completions;
    the page publishes window QPS and p50/p99 so ``bftpu-top`` shows
    *current* traffic, not lifetime averages that smear a stall."""

    __slots__ = ("window_s", "_buf")

    def __init__(self, window_s: float = 5.0):
        self.window_s = float(window_s)
        self._buf: deque = deque()

    def note(self, done_mono: float, latency_ms: float) -> None:
        self._buf.append((float(done_mono), float(latency_ms)))
        cut = done_mono - self.window_s
        while self._buf and self._buf[0][0] < cut:
            self._buf.popleft()

    def snapshot(self, now: float) -> Tuple[float, float, float]:
        """(qps, p50_ms, p99_ms) over the window; (-1,-1,-1) when empty."""
        cut = now - self.window_s
        while self._buf and self._buf[0][0] < cut:
            self._buf.popleft()
        if not self._buf:
            return -1.0, -1.0, -1.0
        lat = sorted(l for _, l in self._buf)
        span = max(0.05, min(self.window_s, now - self._buf[0][0]))
        n = len(lat)

        def q(p: float) -> float:
            pos = p * (n - 1)
            lo = int(pos)
            hi = min(lo + 1, n - 1)
            return lat[lo] + (lat[hi] - lat[lo]) * (pos - lo)

        return n / span, q(0.50), q(0.99)


class Replica:
    """One serving process: poll → side-read → atomic flip → serve."""

    def __init__(self, job: str, replica_id: int = 0, *,
                 source=None, rng: Optional[random.Random] = None,
                 publish_page: bool = True):
        self.job = str(job)
        self.replica_id = int(replica_id)
        self.source = source if source is not None else ShmSource(job)
        self._rng = rng if rng is not None else random.Random()
        # the A buffer: (version, epoch, step, tensor) flipped as one ref
        self._current: Optional[Tuple[int, int, int, np.ndarray]] = None
        #: newest committed version observed at the region, even when
        #: the swap was skipped — the lag denominator
        self.published_version = 0
        self.swaps = 0
        self.serve_steps = 0
        self.stale_served = 0
        self.retries = 0
        self._req_stats: Optional[_RequestStats] = None
        self._slo = None  # lazy loadgen.slo.SLOMonitor
        self._page_throttle_t = 0.0
        self._page = None
        if publish_page:
            from bluefog_tpu.introspect.statuspage import StatusPage
            self._page = StatusPage(job, REPLICA_RANK_BASE + self.replica_id)
            self._publish_page("attach")

    # -- observability -----------------------------------------------------

    @property
    def version(self) -> int:
        """The version this replica is serving (0 = nothing yet)."""
        return self._current[0] if self._current is not None else 0

    @property
    def lag(self) -> int:
        return max(0, self.published_version - self.version)

    def _publish_page(self, op: str) -> None:
        if self._page is None:
            return
        cur = self._current
        # a distribution-tree source (serve.distrib.TcpSource) exposes
        # its slot/parent — surfaced on the page so bftpu-top draws
        # the tree (slot -1 = shm-attached, not in the tree)
        slot = getattr(self.source, "slot", None)
        parent = getattr(self.source, "parent_slot", -1)
        qps = p50 = p99 = -1.0
        if self._req_stats is not None:
            qps, p50, p99 = self._req_stats.snapshot(time.monotonic())
        self._page.publish(
            nranks=0, step=self.serve_steps,
            epoch=cur[1] if cur else 0, op_id=self.swaps,
            last_op=op, serve_version=self.version, serve_lag=self.lag,
            distrib_slot=-1 if slot is None else int(slot),
            distrib_parent=int(parent),
            qps=qps, p50_ms=p50, p99_ms=p99,
            slo_state=self._slo.state if self._slo is not None else -1)

    # -- subscribe / swap --------------------------------------------------

    def _poll_with_retry(self) -> Tuple[int, int, int, np.ndarray]:
        reg = _telemetry.get_registry()
        errs = _transient_errors()
        base, cap = serve_backoff_s(), 2.0
        last: Optional[Exception] = None
        for attempt in range(serve_retries()):
            try:
                return self.source.poll()
            except errs as e:
                last = e
                self.retries += 1
                delay = full_jitter(attempt, base, cap, self._rng)
                if reg.enabled:
                    reg.counter("serve.retries",
                                replica=str(self.replica_id)).inc()
                    reg.journal("serve_retry", replica=self.replica_id,
                                attempt=attempt + 1, backoff_s=delay,
                                error=type(e).__name__)
                time.sleep(delay)
        assert last is not None
        raise last

    def poll_swap(self) -> bool:
        """One subscribe cycle.  Reads the committed snapshot (bounded
        jittered retries on transient errors), and hot-swaps iff it is
        strictly newer than what we serve.  Returns True on a swap.

        Raises the last transient error only when we have NOTHING to
        serve yet; once a snapshot is installed, poll trouble degrades
        to serving the current version (the zero-downtime contract)."""
        reg = _telemetry.get_registry()
        t0 = time.monotonic()
        try:
            version, epoch, step, arr = self._poll_with_retry()
        except _transient_errors():
            if self._current is None:
                raise
            return False
        self.published_version = max(self.published_version, version)
        if self._current is not None and version <= self._current[0]:
            return False  # monotone: never regress, never re-swap
        # B is filled (arr lives only in this frame); chaos kills the
        # replica exactly here — mid-swap, after the read, before the
        # flip — and the e2e asserts A kept serving until the kill
        if (self.replica_id == _kill_replica()
                and self.swaps + 1 == _kill_swap()):
            from bluefog_tpu.resilience import chaos as _chaos
            _chaos.kill_self()
        self._current = (version, epoch, step, arr)  # the atomic flip
        self.swaps += 1
        if reg.enabled:
            reg.counter("serve.swaps", replica=str(self.replica_id)).inc()
            reg.gauge("serve.version",
                      replica=str(self.replica_id)).set(version)
            reg.gauge("serve.lag", replica=str(self.replica_id)).set(self.lag)
            reg.histogram("serve.swap_s").observe(time.monotonic() - t0)
            reg.journal("serve_swap", replica=self.replica_id,
                        version=version, epoch=epoch, step=step,
                        lag=self.lag)
        self._publish_page("swap")
        return True

    # -- serve -------------------------------------------------------------

    def serve_step(self, x: Optional[np.ndarray] = None):
        """One inference step against the installed snapshot.

        Returns ``(version, y)`` where ``y`` is ``snapshot @ x`` (or the
        snapshot itself when ``x`` is None — zero-copy).  Never reads
        the region: swap and serve are decoupled, which is what makes
        mid-swap death a non-event for in-flight requests."""
        cur = self._current
        if cur is None:
            raise SnapshotUnavailable(
                f"replica {self.replica_id}: nothing committed yet")
        version, _epoch, _step, arr = cur
        lag, max_lag = self.lag, serve_max_lag()
        reg = _telemetry.get_registry()
        if max_lag and lag > max_lag:
            if serve_stale_policy() == "refuse":
                if reg.enabled:
                    reg.counter("serve.refused",
                                replica=str(self.replica_id)).inc()
                raise StaleSnapshotError(
                    f"replica {self.replica_id} is {lag} versions behind "
                    f"(BFTPU_SERVE_MAX_LAG={max_lag}, policy=refuse)",
                    lag=lag, max_lag=max_lag)
            self.stale_served += 1
            if reg.enabled:
                reg.counter("serve.stale_served",
                            replica=str(self.replica_id)).inc()
                reg.journal("serve_stale", replica=self.replica_id,
                            version=version, lag=lag, max_lag=max_lag)
        self.serve_steps += 1
        if reg.enabled:
            reg.counter("serve.steps", replica=str(self.replica_id)).inc()
        if x is None:
            return version, arr
        return version, arr.reshape(-1) @ np.asarray(x).reshape(-1)

    # -- request-level telemetry -------------------------------------------

    def note_request(self, send_mono: float, done_mono: float, *,
                     version: int = 0, outcome: str = "ok",
                     start_mono: Optional[float] = None) -> bool:
        """Record one completed request (open-loop latency basis).

        ``send_mono`` is the *scheduled* send time, so the latency
        charged here includes any queueing the request suffered before
        ``serve_step`` ran (the loadgen's coordinated-omission fix).
        Feeds the ``serve.request_latency`` histogram, the per-replica
        SLO monitor, and — throttled to ~4 Hz — the statuspage QPS /
        p50 / p99 / SLO columns.  Returns True iff the request violated
        an armed SLO."""
        from bluefog_tpu.serve.loadgen.slo import SLOMonitor
        if self._req_stats is None:
            self._req_stats = _RequestStats()
        if self._slo is None:
            self._slo = SLOMonitor(self.replica_id)
        latency_ms = max(0.0, (float(done_mono) - float(send_mono)) * 1e3)
        self._req_stats.note(done_mono, latency_ms)
        violated = self._slo.note(send_mono, done_mono, lag=self.lag)
        reg = _telemetry.get_registry()
        if reg.enabled:
            rid = str(self.replica_id)
            reg.counter("serve.requests", replica=rid,
                        outcome=str(outcome)).inc()
            reg.histogram(
                "serve.request_latency",
                buckets=_telemetry.SERVE_LATENCY_BUCKETS_S,
                replica=rid).observe(latency_ms / 1e3)
            if violated:
                reg.counter("serve.slo_violations", replica=rid).inc()
            reg.journal(
                "serve_request", replica=self.replica_id,
                send_mono=float(send_mono),
                start_mono=float(send_mono if start_mono is None
                                 else start_mono),
                done_mono=float(done_mono), latency_ms=latency_ms,
                version=int(version), lag=self.lag, outcome=str(outcome))
        now = time.monotonic()
        if now - self._page_throttle_t >= 0.25:
            self._page_throttle_t = now
            if reg.enabled:
                qps, _, _ = self._req_stats.snapshot(now)
                if qps >= 0:
                    reg.gauge("serve.qps", replica=str(self.replica_id)
                              ).set(qps)
            self._publish_page("serve")
        return violated

    def close_slo(self) -> None:
        """Flush the SLO monitor's open violation window (teardown)."""
        if self._slo is not None:
            self._slo.close()
            self._publish_page("slo-flush")

    def close(self, unlink: bool = False) -> None:
        if self._slo is not None:
            self._slo.close()
            self._slo = None
        if self._page is not None:
            self._page.close(unlink)
            self._page = None
