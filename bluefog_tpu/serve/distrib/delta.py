"""Delta publication: per-chunk dirty tracking over the wire codec.

The publisher chunks the committed snapshot, encodes every chunk with
the PR-11 wire codec (``BFTPU_WIRE_DTYPE``: f32 | bf16 | int8) and
keeps, per chunk, the version that last changed its **decoded** bytes
— the dirty map.  A subscriber at version ``v`` receives only chunks
whose last-modified version exceeds ``v``; one whose lag exceeds the
dirty-map horizon (``BFTPU_DISTRIB_HORIZON``) degrades to a
full-buffer resync instead of a near-total delta.

Lossy codecs stay honest the same way the gossip path does: the
quantization error folds into the next publish (the error-feedback
residual, held per chunk on the publisher), so repeated deltas are
lossless-in-the-limit.  What the fleet distributes is therefore the
**canonical wire-state** ``W = decode(encode(x + residual))`` — every
node that applies a delta holds bit-identical decoded bytes, and the
commit frame carries a CRC32 of the full canonical buffer so a
subscriber proves bit-identity before flipping.  Relays never
re-encode: they store and forward the encoded chunk payloads, so the
canonical bytes are decided exactly once, at the publisher.

``ChunkStore`` is the one datastructure every node holds — publisher,
relay, leaf.  Its state is a single atomically-swapped reference
(meta + chunk map), so a relay's feed threads serve a committed
generation while the subscriber side stages the next one.
"""

from __future__ import annotations

import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from bluefog_tpu.native import wire_codec as _wc

__all__ = [
    "ChunkMeta",
    "ChunkStore",
    "DeltaEncoder",
    "distrib_fanout",
    "distrib_horizon",
    "distrib_chunk_kb",
    "distrib_timeout_s",
    "distrib_retries",
]


def distrib_fanout() -> int:
    """``BFTPU_DISTRIB_FANOUT``: max children per tree node (>=1)."""
    try:
        return max(1, int(os.environ.get("BFTPU_DISTRIB_FANOUT", "4")))
    except ValueError:
        return 4


def distrib_horizon() -> int:
    """``BFTPU_DISTRIB_HORIZON``: max versions of lag served as a
    delta; beyond it the subscriber gets a full-buffer resync."""
    try:
        return max(1, int(os.environ.get("BFTPU_DISTRIB_HORIZON", "8")))
    except ValueError:
        return 8


def distrib_chunk_kb() -> int:
    """``BFTPU_DISTRIB_CHUNK_KB``: dirty-tracking granularity."""
    try:
        return max(1, int(os.environ.get("BFTPU_DISTRIB_CHUNK_KB", "64")))
    except ValueError:
        return 64


def distrib_timeout_s() -> float:
    """``BFTPU_DISTRIB_TIMEOUT_S``: per-socket-op timeout on feed
    edges (parent death is detected as timeouts, then re-parented)."""
    try:
        return float(os.environ.get("BFTPU_DISTRIB_TIMEOUT_S", "5.0"))
    except ValueError:
        return 5.0


def distrib_retries() -> int:
    """``BFTPU_DISTRIB_RETRIES``: full-jitter attempts against the
    current parent before requesting a re-parent."""
    try:
        return max(1, int(os.environ.get("BFTPU_DISTRIB_RETRIES", "3")))
    except ValueError:
        return 3


class ChunkMeta(tuple):
    """Immutable commit metadata: one committed generation of the
    store.  A plain tuple subclass so it hashes/compares structurally
    and rides queues without pickling surprises."""

    __slots__ = ()

    def __new__(cls, version: int, epoch: int, step: int, nchunks: int,
                shape: Tuple[int, ...], dtype: str, crc: int):
        return tuple.__new__(cls, (int(version), int(epoch), int(step),
                                   int(nchunks), tuple(shape),
                                   str(dtype), int(crc)))

    def __getnewargs__(self):
        return (self[0], self[1], self[2], self[3], self[4], self[5],
                self[6])

    version = property(lambda s: s[0])
    epoch = property(lambda s: s[1])
    step = property(lambda s: s[2])
    nchunks = property(lambda s: s[3])
    shape = property(lambda s: s[4])
    dtype = property(lambda s: s[5])
    crc = property(lambda s: s[6])


#: one stored chunk: (lastmod version, wire code, payload bytes, scale)
Chunk = Tuple[int, int, bytes, float]


class ChunkStore:
    """Every node's copy of the canonical wire-state, one atomically
    swapped ``(meta, chunks)`` reference — feed threads snapshot it,
    the subscriber installs a fully staged generation on top."""

    def __init__(self):
        self._snap: Tuple[Optional[ChunkMeta], Dict[int, Chunk]] = \
            (None, {})
        self._decoded: Tuple[int, Optional[np.ndarray]] = (0, None)

    # -- readers (feed threads, replica) ------------------------------------

    def snap(self) -> Tuple[Optional[ChunkMeta], Dict[int, Chunk]]:
        return self._snap

    @property
    def version(self) -> int:
        meta, _ = self._snap
        return meta.version if meta is not None else 0

    def delta_since(self, have: int, horizon: Optional[int] = None
                    ) -> Tuple[bool, List[Tuple[int, Chunk]], ChunkMeta]:
        """What a subscriber at version ``have`` needs to reach the
        head: ``(full, [(idx, chunk)...], meta)``.  ``full`` is True
        on the resync path — subscriber at 0, ahead of us (a previous
        publisher incarnation's head), or lagging past the horizon."""
        meta, chunks = self._snap
        if meta is None:
            raise ValueError("store holds no committed generation")
        h = distrib_horizon() if horizon is None else max(1, int(horizon))
        full = (have <= 0 or have > meta.version
                or meta.version - have > h)
        if not full and have == meta.version:
            return False, [], meta
        items = sorted(chunks.items())
        if not full:
            items = [(i, c) for i, c in items if c[0] > have]
        return full, items, meta

    def decode(self) -> Tuple[ChunkMeta, np.ndarray]:
        """The canonical array for the committed generation (cached
        per version — decode is deterministic, so every node's bytes
        for a version are identical by construction)."""
        meta, chunks = self._snap
        if meta is None:
            raise ValueError("store holds no committed generation")
        ver, arr = self._decoded
        if arr is not None and ver == meta.version:
            return meta, arr
        arr = decode_store(meta, chunks)
        self._decoded = (meta.version, arr)
        return meta, arr

    # -- writer (subscriber / publisher) ------------------------------------

    def install(self, meta: ChunkMeta, chunks: Dict[int, Chunk], *,
                full: bool, verify: bool = True) -> np.ndarray:
        """Stage + flip one generation.  ``chunks`` is the delta (or
        the whole buffer when ``full``); the staged map is checked
        against ``meta`` (chunk count and canonical CRC) BEFORE the
        flip, so a bad generation never becomes servable."""
        _, cur = self._snap
        staged = dict(chunks) if full else {**cur, **chunks}
        if len(staged) != meta.nchunks:
            raise ValueError(
                f"staged generation v{meta.version} has {len(staged)} "
                f"chunks, commit says {meta.nchunks} — "
                f"{'full' if full else 'delta'} stream incomplete")
        arr = decode_store(meta, staged)
        if verify:
            crc = zlib.crc32(arr.tobytes()) & 0xFFFFFFFF
            if crc != meta.crc:
                raise ValueError(
                    f"canonical CRC mismatch at v{meta.version}: "
                    f"got {crc:#010x}, commit says {meta.crc:#010x}")
        self._snap = (meta, staged)
        self._decoded = (meta.version, arr)
        return arr


def _payload_elems(code: int, payload: bytes, dtype: np.dtype) -> int:
    """Element count a chunk's encoded payload carries."""
    if code == _wc.WIRE_BF16:
        return len(payload) // 2
    if code == _wc.WIRE_INT8:
        return len(payload)
    return len(payload) // max(1, dtype.itemsize)


def decode_store(meta: ChunkMeta, chunks: Dict[int, Chunk]) -> np.ndarray:
    """Concatenate-decode a full chunk map back to the canonical
    array (deterministic: payload + code + scale decide every byte).

    The chunk granularity is derived from chunk 0's own payload, NOT
    from this host's ``BFTPU_DISTRIB_CHUNK_KB`` — the publisher
    decides the geometry, and a subscriber with a drifted env must
    still decode the stream it was sent."""
    dtype = np.dtype(meta.dtype)
    total = int(np.prod(meta.shape)) if meta.shape else 1
    per = total
    if meta.nchunks > 1:
        _, code0, payload0, _ = chunks[0]
        per = max(1, _payload_elems(code0, payload0, dtype))
    parts = []
    for i in range(meta.nchunks):
        lastmod, code, payload, scale = chunks[i]
        count = min(per, total - i * per)
        parts.append(_wc.decode_chunk(payload, code, scale, dtype, count))
    flat = np.concatenate(parts) if parts else np.empty(0, dtype)
    return flat.reshape(meta.shape)


def _chunk_elems(dtype: np.dtype) -> int:
    return max(1, (distrib_chunk_kb() * 1024) // max(1, dtype.itemsize))


class DeltaEncoder:
    """Publisher-side: snapshot in, dirty-tracked canonical chunks out.

    Holds the per-chunk error-feedback residuals (sender-side, exactly
    like the gossip edges) and the previous canonical bytes per chunk
    so an unchanged chunk keeps its last-modified version — the dirty
    map.  ``publish()`` installs the new generation into ``store``."""

    def __init__(self, store: Optional[ChunkStore] = None):
        self.store = store if store is not None else ChunkStore()
        self._residual: Dict[int, np.ndarray] = {}
        self.published = 0

    def publish(self, version: int, epoch: int, step: int,
                arr: np.ndarray) -> ChunkMeta:
        x = np.ascontiguousarray(arr)
        flat = x.reshape(-1)
        dtype = flat.dtype
        per = _chunk_elems(dtype)
        n = max(1, -(-flat.size // per)) if flat.size else 1
        code = _wc.wire_code()
        _, prev = self.store.snap()
        chunks: Dict[int, Chunk] = {}
        dirty = 0
        for i in range(n):
            seg = flat[i * per:(i + 1) * per]
            if dtype.kind == "f" and code != _wc.WIRE_RAW:
                r = self._residual.get(i)
                buf = seg + r if r is not None else seg.copy()
            else:
                buf = seg
            used, payload, scale = _wc.encode_chunk(buf, code)
            payload = bytes(payload)
            if dtype.kind == "f" and code != _wc.WIRE_RAW:
                dec = _wc.decode_chunk(payload, used, scale, dtype,
                                       seg.size)
                self._residual[i] = buf - dec
            old = prev.get(i)
            if (old is not None and old[1] == used and old[3] == scale
                    and old[2] == payload):
                chunks[i] = old  # clean: keep its lastmod version
            else:
                chunks[i] = (int(version), used, payload, float(scale))
                dirty += 1
        for i in list(self._residual):
            if i >= n:
                del self._residual[i]
        crc_arr = decode_store(
            ChunkMeta(version, epoch, step, n, x.shape, dtype.str, 0),
            chunks)
        crc = zlib.crc32(crc_arr.tobytes()) & 0xFFFFFFFF
        meta = ChunkMeta(version, epoch, step, n, x.shape, dtype.str,
                         crc)
        self.store.install(meta, chunks, full=True, verify=False)
        self.published += 1
        self.last_dirty = dirty
        return meta
