"""Snapshot distribution plane: delta fan-out trees for replica fleets.

One quorum-fenced publisher feeds hundreds of cross-host replicas:

- :mod:`.delta` — per-chunk dirty tracking over the PR-11 wire codec
  (bf16/int8 with error-feedback residuals; canonical wire-state with
  a CRC-checked bit-identity contract; horizon-bounded deltas with
  full-buffer resync beyond it);
- :mod:`.tree` — pure bounded-degree tree placement/repair math,
  shared verbatim by the production coordinator, the sim model, and
  ``analysis/distrib_rules.py``;
- :mod:`.feed` — feed servers (publisher and relays), the tree
  coordinator, the ``_OP_CHUNK``/``_OP_COMMIT`` delta framing;
- :mod:`.sub` — :class:`~.sub.TcpSource`, the TCP-backed region twin
  a :class:`~bluefog_tpu.serve.replica.Replica` attaches by
  ``host:port``.

See docs/SERVING.md ("Cross-host distribution") for the protocol and
the death matrix.
"""

from bluefog_tpu.serve.distrib.delta import (ChunkMeta, ChunkStore,  # noqa: F401
                                             DeltaEncoder,
                                             distrib_chunk_kb,
                                             distrib_fanout,
                                             distrib_horizon,
                                             distrib_retries,
                                             distrib_timeout_s)
from bluefog_tpu.serve.distrib.feed import (DistribPublisher,  # noqa: F401
                                            FeedServer, parse_addr)
from bluefog_tpu.serve.distrib.sub import TcpSource  # noqa: F401
from bluefog_tpu.serve.distrib import tree  # noqa: F401

__all__ = [
    "ChunkMeta",
    "ChunkStore",
    "DeltaEncoder",
    "DistribPublisher",
    "FeedServer",
    "TcpSource",
    "parse_addr",
    "tree",
    "distrib_fanout",
    "distrib_horizon",
    "distrib_chunk_kb",
    "distrib_timeout_s",
    "distrib_retries",
]
