"""The fan-out tree: pure placement/repair math, model-checkable.

Subscriber slots organize into a bounded-degree distribution tree so
one publisher feeds R replicas with O(fanout) sockets and O(log R)
relay depth (the serve-path analog of the exponential-2 gossip graph:
sparse edges, logarithmic diameter).  Parent ``-1`` is the publisher.

The canonical placement is the array heap shape: slot ``k``'s parent
is the publisher for ``k < fanout`` and slot ``k // fanout - 1``
otherwise, which gives every interior slot at most ``fanout`` children
and depth ``floor(log_fanout(k)) + 1``.

Repair is greedy re-attachment: an orphaned slot re-parents to the
shallowest live slot with spare capacity that is not inside its own
subtree (cycles are structurally impossible that way), falling back to
the publisher as root of last resort — the publisher accepts the
orphan even above its own fanout, because a reachable-but-hot root
beats an unreachable subtree.

Everything here is side-effect free over plain ints/dicts: the sim's
distribution-tree model and ``analysis/distrib_rules.py`` exhaust
kill/re-parent sequences against these exact functions, and the
production coordinator (:mod:`.feed`) calls the same code — one
algorithm, three consumers.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

__all__ = [
    "PUBLISHER",
    "parent_of",
    "depth_of",
    "tree_depth",
    "children_of",
    "subtree_of",
    "choose_parent",
    "reassign",
    "tree_valid",
]

#: the parent id meaning "fed directly by the publisher"
PUBLISHER = -1


def parent_of(k: int, fanout: int) -> int:
    """Canonical (pre-fault) parent of slot ``k``: heap shape."""
    f = max(1, int(fanout))
    return PUBLISHER if k < f else (k // f) - 1


def depth_of(k: int, parents: Dict[int, int]) -> int:
    """Hops from slot ``k`` to the publisher (1 = fed directly).
    Returns -1 on a cycle or a dangling parent (invalid tree)."""
    seen = set()
    d, cur = 0, k
    while cur != PUBLISHER:
        if cur in seen or cur not in parents:
            return -1
        seen.add(cur)
        d, cur = d + 1, parents[cur]
    return d


def tree_depth(parents: Dict[int, int]) -> int:
    """Max depth over all slots (0 for an empty tree, -1 if any slot
    is cyclic/dangling)."""
    depths = [depth_of(k, parents) for k in parents]
    if any(d < 0 for d in depths):
        return -1
    return max(depths, default=0)


def children_of(parents: Dict[int, int]) -> Dict[int, List[int]]:
    """Parent -> sorted children (``PUBLISHER`` key = publisher-fed)."""
    out: Dict[int, List[int]] = {}
    for k in sorted(parents):
        out.setdefault(parents[k], []).append(k)
    return out


def subtree_of(k: int, parents: Dict[int, int]) -> set:
    """``k`` plus every slot that (transitively) feeds from it."""
    kids = children_of(parents)
    out, frontier = {k}, [k]
    while frontier:
        nxt = []
        for p in frontier:
            for c in kids.get(p, ()):
                if c not in out:
                    out.add(c)
                    nxt.append(c)
        frontier = nxt
    return out


def choose_parent(k: int, parents: Dict[int, int], fanout: int,
                  dead: Iterable[int] = (), *,
                  degree_cap: bool = True) -> int:
    """Greedy repair/join placement for slot ``k``.

    Candidates are live slots outside ``k``'s own subtree, preferred
    shallowest-first (then lowest id) while they have fewer than
    ``fanout`` children; the publisher is the root of last resort and
    is chosen even when its direct-feed count already hit ``fanout``.
    ``degree_cap=False`` is the seeded-fixture knob (the
    ``distrib-degree-overflow`` bug): it picks the shallowest live
    slot regardless of load, which the tree-validity invariant must
    catch."""
    deadset = set(dead)
    avoid = subtree_of(k, parents) if k in parents else {k}
    kids = children_of(parents)
    cands = []
    for c in sorted(parents):
        if c in deadset or c in avoid:
            continue
        load = len([x for x in kids.get(c, ())
                    if x not in deadset and x not in avoid])
        d = depth_of(c, parents)
        if d < 0:
            continue
        if degree_cap and load >= max(1, int(fanout)):
            continue
        cands.append((d, c))
    if not cands:
        return PUBLISHER
    if degree_cap:
        # publisher stays preferred while it has direct-feed capacity
        pub_load = len([x for x in kids.get(PUBLISHER, ())
                        if x not in deadset and x not in avoid])
        if pub_load < max(1, int(fanout)):
            return PUBLISHER
    return min(cands)[1]


def reassign(parents: Dict[int, int], dead: int, fanout: int, *,
             degree_cap: bool = True) -> Dict[int, int]:
    """New parent map after slot ``dead`` dies: ``dead`` leaves the
    tree and each of its direct children re-parents greedily (their
    own subtrees ride along unchanged)."""
    out = {k: p for k, p in parents.items() if k != dead}
    orphans = sorted(k for k, p in parents.items()
                     if p == dead and k != dead)
    for k in orphans:
        out[k] = choose_parent(k, out, fanout, dead=(dead,),
                               degree_cap=degree_cap)
    return out


def tree_valid(parents: Dict[int, int], fanout: int,
               root_cap: Optional[int] = None) -> Optional[str]:
    """The standing tree invariant: ``None`` when the map is a
    connected, acyclic, degree-capped tree rooted at the publisher;
    otherwise a description of the violation.

    Every slot must reach ``PUBLISHER`` (connected + acyclic in one
    walk), and no slot may feed more than ``fanout`` children.  The
    publisher's own degree is capped only when ``root_cap`` is given —
    it is the root of last resort, allowed to run hot after repair."""
    f = max(1, int(fanout))
    for k in sorted(parents):
        if parents[k] == k:
            return f"slot {k} is its own parent"
        if depth_of(k, parents) < 0:
            return (f"slot {k} cannot reach the publisher "
                    f"(cycle or dangling parent in {parents})")
    for p, kids in sorted(children_of(parents).items()):
        if p == PUBLISHER:
            if root_cap is not None and len(kids) > root_cap:
                return (f"publisher feeds {len(kids)} slots "
                        f"> cap {root_cap}")
            continue
        if len(kids) > f:
            return (f"slot {p} feeds {len(kids)} children "
                    f"> fanout {f}: {kids}")
    return None
