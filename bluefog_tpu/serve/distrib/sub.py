"""The subscriber: a TCP-backed region twin for :class:`~..replica.Replica`.

``TcpSource`` joins the tree through the publisher's coordinator, gets
a slot and a parent feed address, and polls that parent over one
persistent socket.  Applied deltas stage beside the committed
generation and land with a single reference flip — the same
death-matrix shape as the shm region: a kill mid-delta leaves the
previous version serving, and the CRC check before the flip makes
served bytes bit-identical to a committed canonical snapshot.

Every subscriber is also (by default) a **relay**: it runs its own
:class:`~.feed.FeedServer` over its committed store and reports that
address at join, so the coordinator can hang children off it.  The
store flips at commit time — before the owning replica's own
``poll_swap`` — so a relay feeds its children the new generation no
later than it starts serving it.

Parent death shows up as socket errors/timeouts; after
``BFTPU_DISTRIB_RETRIES`` full-jitter attempts the subscriber asks the
coordinator to re-place it (``OP_PARENT``), falling back to the
publisher as root of last resort.  A subscriber that slept past the
dirty-map horizon simply receives the full-resync stream — same code
path, one flag.
"""

from __future__ import annotations

import json
import random
import socket
import time
from typing import Optional, Tuple

import numpy as np

from bluefog_tpu import telemetry as _telemetry
from bluefog_tpu.native.tcp_transport import _HDR, _BufReader, _send_msg
from bluefog_tpu.serve.distrib import feed as _feed
from bluefog_tpu.serve.distrib import tree as _tree
from bluefog_tpu.serve.distrib.delta import (ChunkStore,
                                             distrib_timeout_s)
from bluefog_tpu.serve.snapshot import SnapshotUnavailable

__all__ = ["TcpSource"]


def _chaos_kill(var: str) -> Tuple[int, int]:
    """Parse ``"replica_id:n"`` chaos vars (-1 = off)."""
    import os

    v = os.environ.get(var, "")
    if not v:
        return -1, 0
    try:
        rid, _, n = v.partition(":")
        return int(rid), int(n or "1")
    except ValueError:
        return -1, 0


class TcpSource:
    """``source=`` twin for :class:`bluefog_tpu.serve.replica.Replica`:
    attach by ``host:port`` instead of shm name."""

    def __init__(self, addr: str, *, replica_id: int = 0,
                 relay: bool = True, relay_host: str = "127.0.0.1",
                 rng: Optional[random.Random] = None,
                 fanout: Optional[int] = None):
        self.coord_addr = _feed.parse_addr(addr)
        self.replica_id = int(replica_id)
        self.store = ChunkStore()
        self._rng = rng if rng is not None else random.Random()
        self.slot: Optional[int] = None
        self.parent_slot = _tree.PUBLISHER
        self._parent_addr: Optional[Tuple[str, int]] = None
        self._sock: Optional[socket.socket] = None
        self._rd: Optional[_BufReader] = None
        self.syncs = 0
        self.resyncs = 0
        self.reparents = 0
        self.relay_server: Optional[_feed.FeedServer] = None
        if relay:
            self.relay_server = _feed.FeedServer(self.store, relay_host,
                                                 0, fanout=fanout)

    # -- control plane (transient coordinator connections) -------------------

    def _control(self, op: int, req: dict) -> dict:
        s = socket.create_connection(self.coord_addr,
                                     timeout=distrib_timeout_s())
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(s, op, payload=json.dumps(req).encode())
            rd = _BufReader(s)
            hdr = _HDR.unpack(rd.read_exact(_HDR.size))
            payload = rd.read_exact(hdr[4]) if hdr[4] else b""
            if hdr[0] != _feed.OP_ASSIGN:
                raise ConnectionError(f"coordinator replied op {hdr[0]}")
            return json.loads(payload.decode())
        finally:
            try:
                s.close()
            except OSError:
                pass

    def _join(self) -> None:
        req = {"slot": self.slot}
        if self.relay_server is not None:
            req["relay"] = list(self.relay_server.addr)
        rep = self._control(_feed.OP_JOIN, req)
        self._adopt_assignment(rep)

    def _reparent(self, dead_slot: int) -> None:
        rep = self._control(_feed.OP_PARENT,
                            {"slot": self.slot, "dead": dead_slot})
        self._adopt_assignment(rep)
        self.reparents += 1
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("distrib.sub_reparents",
                        replica=str(self.replica_id)).inc()

    def _adopt_assignment(self, rep: dict) -> None:
        self.slot = int(rep["slot"])
        self.parent_slot = int(rep["parent"])
        if self.parent_slot >= 0:
            self._parent_addr = (rep["host"], int(rep["port"]))
        else:
            self._parent_addr = self.coord_addr
        self._disconnect()

    # -- the persistent feed socket ------------------------------------------

    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock, self._rd = None, None

    def _connect(self) -> None:
        if self.slot is None:
            self._join()
        assert self._parent_addr is not None
        s = socket.create_connection(self._parent_addr,
                                     timeout=distrib_timeout_s())
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock, self._rd = s, _BufReader(s)

    def _poll_once(self) -> Tuple[int, int, int, np.ndarray]:
        from bluefog_tpu.serve.distrib.delta import distrib_retries
        from bluefog_tpu.serve.replica import full_jitter

        last: Optional[Exception] = None
        for attempt in range(distrib_retries()):
            try:
                if self._sock is None:
                    self._connect()
                return self._sync()
            except (OSError, ConnectionError) as e:
                last = e
                self._disconnect()
                time.sleep(full_jitter(attempt, 0.02, 0.5, self._rng))
        # parent presumed dead: re-place through the coordinator (the
        # publisher itself being down surfaces as the next failure,
        # which the Replica's own retry loop owns)
        dead = self.parent_slot
        self._reparent(dead)
        self._connect()
        return self._sync()

    def poll(self) -> Tuple[int, int, int, np.ndarray]:
        """The Replica source contract: newest committed snapshot as
        ``(version, epoch, step, arr)``; transient trouble raises
        OSError-family so the replica's jittered retry owns policy."""
        try:
            return self._poll_once()
        except (ConnectionError, json.JSONDecodeError) as e:
            raise OSError(str(e)) from e

    def _sync(self) -> Tuple[int, int, int, np.ndarray]:
        assert self._sock is not None and self._rd is not None
        # chaos instrumentation: a schedule_suspend() here SIGSTOPs
        # the subscriber between syncs — sleeping past the dirty-map
        # horizon is exactly how the full-resync path gets exercised
        from bluefog_tpu.resilience import chaos as _chaos
        from bluefog_tpu.serve.replica import REPLICA_RANK_BASE
        _chaos.checkpoint(REPLICA_RANK_BASE + self.replica_id,
                          "distrib_sync")
        have = self.store.version
        _send_msg(self._sock, _feed.OP_POLL, trace=have)
        meta, chunks, full, head = _feed.recv_delta(self._rd)
        reg = _telemetry.get_registry()
        if meta is None:
            # NOCHANGE: serve what we hold (nothing yet -> the replica
            # treats SnapshotUnavailable as transient and retries)
            if reg.enabled:
                reg.counter("distrib.nochange",
                            replica=str(self.replica_id)).inc()
            if self.store.version == 0:
                raise SnapshotUnavailable(
                    f"distrib slot {self.slot}: upstream head is "
                    f"v{head}, nothing committed here yet")
            m, arr = self.store.decode()
            return m.version, m.epoch, m.step, arr
        kill_id, kill_n = _chaos_kill("BFTPU_CHAOS_DISTRIB_KILL_SYNC")
        if kill_id == self.replica_id and self.syncs + 1 == kill_n:
            # chaos: die mid-delta, AFTER receiving the stream but
            # BEFORE the flip — previous generation must keep serving
            from bluefog_tpu.resilience import chaos as _chaos
            _chaos.kill_self()
        try:
            arr = self.store.install(meta, chunks, full=full)
        except (ValueError, KeyError):
            # torn/incomplete generation (e.g. shape changed under a
            # delta): drop state and take the full-resync path
            self.store = ChunkStore() if self.relay_server is None \
                else self._reset_relay_store()
            raise ConnectionError(
                f"distrib slot {self.slot}: staged generation "
                f"v{meta.version} failed verification; resyncing")
        self.syncs += 1
        if full:
            self.resyncs += 1
        if reg.enabled:
            reg.counter("distrib.sub_resyncs" if full
                        else "distrib.sub_syncs",
                        replica=str(self.replica_id)).inc()
            reg.gauge("distrib.sub_version",
                      replica=str(self.replica_id)).set(meta.version)
            reg.journal("distrib_resync" if full else "distrib_sync",
                        replica=self.replica_id, slot=self.slot,
                        version=meta.version, chunks=len(chunks),
                        parent=self.parent_slot)
        kill_id, kill_n = _chaos_kill("BFTPU_CHAOS_DISTRIB_KILL_RELAY")
        if kill_id == self.replica_id and self.syncs == kill_n:
            # chaos: the relay dies mid-fanout — after its store flip
            # (children may already have pulled v) but before its own
            # replica swap; the e2e asserts the subtree re-parents
            from bluefog_tpu.resilience import chaos as _chaos
            _chaos.kill_self()
        return meta.version, meta.epoch, meta.step, arr

    def _reset_relay_store(self) -> ChunkStore:
        # the relay server holds a reference to the store object, so a
        # reset must keep the identity: swap the internal state instead
        self.store._snap = (None, {})
        self.store._decoded = (0, None)
        return self.store

    def close(self) -> None:
        self._disconnect()
        if self.relay_server is not None:
            self.relay_server.close()
            self.relay_server = None
