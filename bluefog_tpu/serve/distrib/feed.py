"""The distribution wire: feed servers, the coordinator, the publisher.

Every node in the tree — publisher and relaying subscriber alike —
runs a :class:`FeedServer`: a thread-per-connection TCP server (the
same shape as ``tcp_transport._Server``) answering **pull** requests
out of its :class:`~.delta.ChunkStore`.  A child holds ONE persistent
socket to its parent and polls; the publisher therefore keeps at most
``fanout`` persistent feed sockets no matter how many replicas the
tree holds, plus short-lived control connections for join/re-parent.

Frames reuse the PR-11 chunked header (``tcp_transport._HDR``) and the
``_OP_CHUNK``/``_OP_COMMIT`` state machine:

====================  ==================================================
``OP_POLL`` (20)      child → parent; ``trace`` = version the child has
``OP_NOCHANGE`` (21)  parent → child; ``trace`` = parent's head version
``_OP_CHUNK`` (14)    one encoded chunk; ``win_id`` = chunk index,
                      ``mode`` = ``(wire_code << 1) | full_flag``,
                      ``p`` = int8 scale, ``trace`` = chunk lastmod
``_OP_COMMIT`` (15)   seals the stream; payload = :data:`_COMMIT`
                      (version/epoch/step, chunk counts, shape, dtype,
                      canonical CRC32, full flag)
``OP_JOIN`` (22)      joiner → coordinator; payload = relay addr JSON
``OP_PARENT`` (23)    child → coordinator: my parent died, re-place me
``OP_ASSIGN`` (24)    coordinator → child; ``slot`` = tree slot,
                      payload = parent feed address JSON ({} = feed
                      straight from the publisher)
====================  ==================================================

The coordinator (join/re-parent handling) runs only on the publisher's
feed server and drives the SAME pure placement code the sim and the
analysis family model-check (:mod:`.tree`).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from bluefog_tpu import telemetry as _telemetry
from bluefog_tpu.native.tcp_transport import (_HDR, _OP_CHUNK, _OP_COMMIT,
                                              _BufReader, _send_msg)
from bluefog_tpu.serve.distrib import tree as _tree
from bluefog_tpu.serve.distrib.delta import (ChunkMeta, ChunkStore,
                                             distrib_fanout,
                                             distrib_timeout_s)

__all__ = [
    "OP_POLL",
    "OP_NOCHANGE",
    "OP_JOIN",
    "OP_PARENT",
    "OP_ASSIGN",
    "FeedServer",
    "DistribPublisher",
    "parse_addr",
]

OP_POLL = 20
OP_NOCHANGE = 21
OP_JOIN = 22
OP_PARENT = 23
OP_ASSIGN = 24

#: commit payload: version, epoch, step (u64); nchunks, nsent, ndim
#: (u32); dims[4] (u32); dtype str (8s); canonical crc32 (u32);
#: flags (u32, bit 0 = full resync)
_COMMIT = struct.Struct("<QQQIII4I8sII")
_FLAG_FULL = 1


def parse_addr(addr: str) -> Tuple[str, int]:
    """``host:port`` -> tuple (the ``--serve-remote`` argument)."""
    host, _, port = str(addr).rpartition(":")
    return (host or "127.0.0.1"), int(port)


def pack_commit(meta: ChunkMeta, nsent: int, full: bool) -> bytes:
    dims = list(meta.shape[:4]) + [0] * (4 - min(4, len(meta.shape)))
    return _COMMIT.pack(meta.version, meta.epoch, meta.step,
                        meta.nchunks, nsent, len(meta.shape),
                        *[int(d) for d in dims],
                        meta.dtype.encode()[:8].ljust(8, b"\x00"),
                        meta.crc, _FLAG_FULL if full else 0)


def unpack_commit(payload: bytes) -> Tuple[ChunkMeta, int, bool]:
    (ver, epoch, step, nchunks, nsent, ndim, d0, d1, d2, d3, dt, crc,
     flags) = _COMMIT.unpack(payload)
    shape = tuple(int(d) for d in (d0, d1, d2, d3)[:ndim])
    meta = ChunkMeta(ver, epoch, step, nchunks, shape,
                     dt.rstrip(b"\x00").decode(), crc)
    return meta, int(nsent), bool(flags & _FLAG_FULL)


def send_delta(sock: socket.socket, store: ChunkStore,
               have: int) -> Tuple[bool, int, int]:
    """Answer one POLL out of ``store``: NOCHANGE, a delta, or a full
    resync.  Returns ``(full, chunks_sent, payload_bytes)``."""
    meta, _ = store.snap()
    if meta is None or (have == meta.version and have > 0):
        head = meta.version if meta is not None else 0
        _send_msg(sock, OP_NOCHANGE, trace=head)
        return False, 0, 0
    full, items, meta = store.delta_since(have)
    sent_bytes = 0
    for idx, (lastmod, code, payload, scale) in items:
        _send_msg(sock, _OP_CHUNK, win_id=idx,
                  mode=(code << 1) | (1 if full else 0),
                  p=scale, payload=payload, trace=lastmod)
        sent_bytes += len(payload)
    _send_msg(sock, _OP_COMMIT, payload=pack_commit(meta, len(items),
                                                    full))
    return full, len(items), sent_bytes


def recv_delta(rd: "_BufReader") -> Tuple[Optional[ChunkMeta],
                                          Dict[int, tuple], bool, int]:
    """Read one POLL answer: ``(meta, chunks, full, head)``.  ``meta``
    is None on NOCHANGE (``head`` then carries the server's version).
    Raises ``ConnectionError`` on a stream that dies mid-delta."""
    chunks: Dict[int, tuple] = {}
    full = False
    while True:
        op, win_id, slot, mode, nbytes, p, trace = _HDR.unpack(
            rd.read_exact(_HDR.size))
        payload = rd.read_exact(nbytes) if nbytes else b""
        if op == OP_NOCHANGE:
            return None, {}, False, int(trace)
        if op == _OP_CHUNK:
            full = full or bool(mode & 1)
            chunks[int(win_id)] = (int(trace), int(mode) >> 1,
                                   bytes(payload), float(p))
            continue
        if op == _OP_COMMIT:
            meta, nsent, cfull = unpack_commit(payload)
            if nsent != len(chunks):
                raise ConnectionError(
                    f"delta stream torn: commit says {nsent} chunks, "
                    f"received {len(chunks)}")
            return meta, chunks, full or cfull, meta.version
        raise ConnectionError(f"unexpected feed op {op}")


class FeedServer:
    """Serve deltas out of a store; on the publisher, also place
    joiners into the tree and repair it when a relay dies."""

    def __init__(self, store: ChunkStore, host: str = "127.0.0.1",
                 port: int = 0, *, coordinator: bool = False,
                 fanout: Optional[int] = None):
        self.store = store
        self.coordinator = bool(coordinator)
        self.fanout = int(fanout) if fanout else distrib_fanout()
        self._lsock = socket.create_server((host, int(port)))
        self.addr = self._lsock.getsockname()[:2]
        self._lock = threading.Lock()
        # coordinator state: slot -> parent slot (the live tree, the
        # exact map tree_valid() checks) and slot -> relay feed addr
        # (None = leaf that cannot relay)
        self.parents: Dict[int, int] = {}
        self.relay_addr: Dict[int, Optional[Tuple[str, int]]] = {}
        self._next_slot = 0
        self.reparents = 0
        self.feeds = 0  # persistent feed conns accepted (lifetime)
        self._live = 0  # persistent feed conns open right now
        self._conns: set = set()
        self._stop = threading.Event()
        self._thr = threading.Thread(target=self._accept_loop,
                                     daemon=True)
        self._thr.start()

    # -- coordinator placement ----------------------------------------------

    def _assign(self, slot: int, *, dead: Optional[int] = None) -> dict:
        with self._lock:
            if dead is not None and dead in self.parents:
                self.parents = _tree.reassign(self.parents, dead,
                                              self.fanout)
                self.relay_addr.pop(dead, None)
                self.reparents += 1
            if slot not in self.parents:
                self.parents[slot] = _tree.choose_parent(
                    slot, self.parents, self.fanout)
            parent = self.parents[slot]
            # a parent that cannot relay (leaf-only subscriber) or has
            # no known address feeds the child from the publisher
            addr = self.relay_addr.get(parent) \
                if parent != _tree.PUBLISHER else None
            if parent != _tree.PUBLISHER and addr is None:
                parent = self.parents[slot] = _tree.PUBLISHER
            err = _tree.tree_valid(self.parents, self.fanout)
        if err:
            raise RuntimeError(f"coordinator built an invalid tree: "
                               f"{err}")
        out = {"slot": slot, "parent": parent}
        if parent != _tree.PUBLISHER:
            out["host"], out["port"] = addr
        return out

    def handle_join(self, relay: Optional[Tuple[str, int]],
                    slot: Optional[int] = None) -> dict:
        with self._lock:
            if slot is None:
                slot = self._next_slot
                self._next_slot += 1
            else:
                self._next_slot = max(self._next_slot, slot + 1)
            self.relay_addr[slot] = relay
        rep = self._assign(slot)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("distrib.joins").inc()
            reg.journal("distrib_join", slot=slot,
                        parent=rep["parent"])
        return rep

    def handle_reparent(self, slot: int, dead: int) -> dict:
        with self._lock:
            self.parents.pop(slot, None)  # re-place, subtree intact
        rep = self._assign(slot, dead=dead if dead >= 0 else None)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("distrib.reparents").inc()
            reg.journal("distrib_reparent", slot=slot, dead=dead,
                        parent=rep["parent"])
        return rep

    # -- server loop ---------------------------------------------------------

    def _accept_loop(self) -> None:
        self._lsock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self._conns.add(conn)
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        reg = _telemetry.get_registry()
        counted = False
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(distrib_timeout_s())
            rd = _BufReader(conn)
            while not self._stop.is_set():
                op, win_id, slot, mode, nbytes, p, trace = _HDR.unpack(
                    rd.read_exact(_HDR.size))
                payload = rd.read_exact(nbytes) if nbytes else b""
                if op == OP_POLL:
                    if not counted:
                        counted = True
                        self.feeds += 1
                        with self._lock:
                            self._live += 1
                    full, n, nbytes_out = send_delta(conn, self.store,
                                                     int(trace))
                    if reg.enabled and n:
                        reg.counter("distrib.resyncs" if full
                                    else "distrib.syncs").inc()
                        reg.counter("distrib.full_bytes" if full else
                                    "distrib.delta_bytes").add(nbytes_out)
                elif op in (OP_JOIN, OP_PARENT) and self.coordinator:
                    req = json.loads(payload.decode() or "{}")
                    relay = req.get("relay")
                    if op == OP_JOIN:
                        rep = self.handle_join(
                            tuple(relay) if relay else None,
                            req.get("slot"))
                    else:
                        rep = self.handle_reparent(int(req["slot"]),
                                                   int(req.get("dead",
                                                               -1)))
                    _send_msg(conn, OP_ASSIGN, slot=rep["slot"],
                              payload=json.dumps(rep).encode())
                else:
                    raise ConnectionError(f"unexpected op {op} "
                                          f"(coordinator="
                                          f"{self.coordinator})")
        except (OSError, ConnectionError, ValueError, struct.error):
            pass
        finally:
            with self._lock:
                if counted:
                    self._live -= 1
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    @property
    def live_feeds(self) -> int:
        """Persistent feed sockets open right now — the acceptance
        bound: a publisher's stays <= fanout however many replicas
        the tree holds."""
        return self._live

    def close(self) -> None:
        """Stop accepting AND sever live feed conns — process-death
        semantics, so a child's next read fails fast instead of
        pulling stale generations from a zombie thread."""
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        self._thr.join(timeout=2.0)


class DistribPublisher:
    """The tree root: encode committed snapshots into the store and
    coordinate the tree.  Feed it from the job's shm
    ``SnapshotRegion`` (:meth:`pump`) or directly (:meth:`publish` —
    tests and the bench)."""

    def __init__(self, job: str = "distrib", host: str = "127.0.0.1",
                 port: int = 0, *, fanout: Optional[int] = None):
        from bluefog_tpu.serve.distrib.delta import DeltaEncoder

        self.job = str(job)
        self.encoder = DeltaEncoder()
        self.store = self.encoder.store
        self.server = FeedServer(self.store, host, port,
                                 coordinator=True, fanout=fanout)
        self.addr = self.server.addr

    @property
    def addr_str(self) -> str:
        return f"{self.addr[0]}:{self.addr[1]}"

    def publish(self, version: int, epoch: int, step: int,
                arr: np.ndarray) -> ChunkMeta:
        meta = self.encoder.publish(version, epoch, step, arr)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("distrib.publishes").inc()
            reg.gauge("distrib.version").set(meta.version)
            reg.journal("distrib_publish", version=meta.version,
                        dirty=self.encoder.last_dirty,
                        nchunks=meta.nchunks)
        return meta

    def pump(self) -> bool:
        """Re-encode the region's committed snapshot when it moved;
        returns True when a new version was published to the tree."""
        from bluefog_tpu.serve import snapshot as _snap

        version, epoch, step, arr = _snap.read_committed(self.job)
        if version <= self.store.version:
            return False
        self.publish(version, epoch, step, arr)
        return True

    def close(self) -> None:
        self.server.close()
