"""Seeded arrival-process schedules for the open-loop load generator.

A schedule is a plain list of offsets (seconds from the run start) at
which requests *must* be sent — computed up front, before any request
fires, so a stalled server can never push the next arrival later
(that deferral is exactly the coordinated-omission bug the open loop
exists to avoid).

Two processes:

- ``fixed``: deterministic ``1/rate`` spacing — the constant offered
  load a capacity gate wants;
- ``poisson``: exponential inter-arrival gaps (``rng.expovariate``) —
  the memoryless bursty traffic real serving fleets see, and the same
  process the sim's traffic model replays on the virtual clock.

Both are seeded: the same ``(schedule, rate, duration, seed)`` tuple
yields the same offsets on every run and every host, which is what
makes load-test latency numbers comparable across commits.
"""

from __future__ import annotations

import os
import random
from typing import List

__all__ = [
    "arrival_times",
    "loadgen_rate_hz",
    "loadgen_schedule",
    "loadgen_seed",
    "loadgen_duration_s",
]


def loadgen_rate_hz() -> float:
    """``BFTPU_LOADGEN_RATE_HZ``: offered load per replica (default 100)."""
    try:
        v = float(os.environ.get("BFTPU_LOADGEN_RATE_HZ", "100"))
        return v if v > 0 else 100.0
    except ValueError:
        return 100.0


def loadgen_schedule() -> str:
    """``BFTPU_LOADGEN_SCHEDULE``: ``poisson`` (default) or ``fixed``."""
    v = os.environ.get("BFTPU_LOADGEN_SCHEDULE", "poisson")
    return v if v in ("poisson", "fixed") else "poisson"


def loadgen_seed() -> int:
    """``BFTPU_LOADGEN_SEED``: base seed for the arrival RNG (default 0)."""
    try:
        return int(os.environ.get("BFTPU_LOADGEN_SEED", "0"))
    except ValueError:
        return 0


def loadgen_duration_s() -> float:
    """``BFTPU_LOADGEN_DURATION_S``: run length in seconds (default 5)."""
    try:
        v = float(os.environ.get("BFTPU_LOADGEN_DURATION_S", "5"))
        return v if v > 0 else 5.0
    except ValueError:
        return 5.0


def arrival_times(schedule: str, rate_hz: float, duration_s: float,
                  seed: int = 0, stream: int = 0) -> List[float]:
    """Offsets (s from t=0) at which requests must be sent.

    ``stream`` decorrelates per-replica schedules drawn from one base
    seed — each replica gets an independent but reproducible process
    (the XOR constant keeps stream 0 distinct from seed+0 elsewhere).
    """
    rate = float(rate_hz)
    dur = float(duration_s)
    if rate <= 0 or dur <= 0:
        return []
    out: List[float] = []
    if schedule == "fixed":
        gap = 1.0 / rate
        t = gap  # first arrival one gap in, not a synchronized t=0 burst
        while t < dur:
            out.append(t)
            t += gap
        return out
    rng = random.Random((int(seed) ^ 0x10AD) + 0x9E37 * int(stream))
    t = rng.expovariate(rate)
    while t < dur:
        out.append(t)
        t += rng.expovariate(rate)
    return out
