"""bluefog_tpu.serve.loadgen — open-loop load generation for the
serving fleet.

The serve plane (PR 15 hot-swap, PR 18 distribution trees) is wired
end to end but was never *load-measured*; this package closes the loop
(ROADMAP item 3, tail end): a deterministic arrival-process driver
fires ``serve_step`` requests against K live replicas on independent
timers and records per-request latency into the telemetry journal.

The driver is **open-loop**: the send timestamp of every request is
fixed in advance by the arrival schedule, never by the completion of
the previous request.  A closed-loop generator that waits for each
response before issuing the next silently throttles offered load
whenever the server stalls — a 2 s hot-swap pause shows up as *one*
slow request instead of the hundreds that would have arrived in those
2 s.  That measurement bug has a name — **coordinated omission** — and
charging queueing delay to latency (``done_ts - send_ts``, not
``done_ts - start_ts``) is the fix.

- :mod:`bluefog_tpu.serve.loadgen.arrivals` — seeded Poisson and
  fixed-rate arrival schedules.
- :mod:`bluefog_tpu.serve.loadgen.driver` — the open-loop driver:
  one timer thread per replica, per-request journal records.
- :mod:`bluefog_tpu.serve.loadgen.slo` — the SLO monitor:
  ``BFTPU_SERVE_SLO_MS`` / ``BFTPU_SERVE_SLO_STALENESS`` objectives,
  gap-closed violation windows journaled for cause attribution.
"""

from bluefog_tpu.serve.loadgen.arrivals import (
    arrival_times,
    loadgen_duration_s,
    loadgen_rate_hz,
    loadgen_schedule,
    loadgen_seed,
)
from bluefog_tpu.serve.loadgen.driver import LoadGenerator, LoadReport
from bluefog_tpu.serve.loadgen.slo import (
    SLOMonitor,
    serve_slo_ms,
    serve_slo_staleness,
)

__all__ = [
    "arrival_times",
    "loadgen_rate_hz",
    "loadgen_schedule",
    "loadgen_seed",
    "loadgen_duration_s",
    "LoadGenerator",
    "LoadReport",
    "SLOMonitor",
    "serve_slo_ms",
    "serve_slo_staleness",
]
