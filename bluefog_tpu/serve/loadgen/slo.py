"""The serve SLO monitor: objectives, violation windows, attribution.

Two objectives, both env-armed and off by default:

- ``BFTPU_SERVE_SLO_MS`` — request latency objective in milliseconds
  (``done_ts - send_ts``, the open-loop definition that charges
  queueing delay); 0 disarms.
- ``BFTPU_SERVE_SLO_STALENESS`` — staleness objective in *versions*:
  a request served while the replica lags the committed version by
  more than this violates; 0 = unbounded.

Individual violating requests are noise; what an operator acts on is
the violation **window** — a maximal run of violations whose ends are
less than ``gap_s`` apart.  The monitor journals one ``slo_violation``
event per closed window carrying CLOCK_MONOTONIC bounds, which is what
lets ``python -m bluefog_tpu.telemetry --slo-report`` join windows
against cause events (``serve_publish`` in flight, ``serve_respawn``,
``distrib_reparent``) from other processes' journals: on Linux the
monotonic clock is system-wide, so cross-process mono timestamps are
directly comparable.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

from bluefog_tpu import telemetry as _telemetry

__all__ = ["SLOMonitor", "serve_slo_ms", "serve_slo_staleness"]


def serve_slo_ms() -> float:
    """``BFTPU_SERVE_SLO_MS``: latency objective in ms (0 = disarmed)."""
    try:
        return max(0.0, float(os.environ.get("BFTPU_SERVE_SLO_MS", "0")))
    except ValueError:
        return 0.0


def serve_slo_staleness() -> int:
    """``BFTPU_SERVE_SLO_STALENESS``: max served lag in versions
    (0 = unbounded)."""
    try:
        return max(0, int(os.environ.get("BFTPU_SERVE_SLO_STALENESS", "0")))
    except ValueError:
        return 0


class SLOMonitor:
    """Fold per-request outcomes into gap-closed violation windows.

    One monitor per replica; feed it every completed request via
    :meth:`note` and :meth:`close` it at teardown to flush the open
    window.  Windows are kept in-process (``self.windows``) *and*
    journaled, so tests can assert without a journal and the merge CLI
    can attribute across processes with one.
    """

    def __init__(self, replica_id: int = 0, *,
                 slo_ms: Optional[float] = None,
                 staleness_slo: Optional[int] = None,
                 gap_s: float = 0.25):
        self.replica_id = int(replica_id)
        self.slo_s = (serve_slo_ms() if slo_ms is None
                      else max(0.0, float(slo_ms))) / 1e3
        self.staleness_slo = (serve_slo_staleness() if staleness_slo is None
                              else max(0, int(staleness_slo)))
        self.gap_s = float(gap_s)
        self.requests = 0
        self.violations = 0
        self.windows: List[dict] = []
        self._open: Optional[dict] = None

    @property
    def armed(self) -> bool:
        return self.slo_s > 0 or self.staleness_slo > 0

    @property
    def state(self) -> int:
        """Statuspage encoding: -1 = disarmed or no traffic yet,
        0 = inside the objective, 1 = in an open violation window."""
        if not self.armed or self.requests == 0:
            return -1
        return 1 if self._open is not None else 0

    def note(self, send_mono: float, done_mono: float,
             lag: int = 0) -> bool:
        """Record one completed request; returns True iff it violated."""
        self.requests += 1
        latency_s = max(0.0, float(done_mono) - float(send_mono))
        kinds = []
        if self.slo_s > 0 and latency_s > self.slo_s:
            kinds.append("latency")
        if self.staleness_slo > 0 and int(lag) > self.staleness_slo:
            kinds.append("staleness")
        if not kinds:
            # a compliant completion past the gap closes the window; a
            # compliant completion *inside* the gap does not — requests
            # overlap in flight, so strict alternation would shred one
            # stall into many windows
            if (self._open is not None
                    and done_mono - self._open["t1_mono"] > self.gap_s):
                self._flush()
            return False
        self.violations += 1
        # journal "mono" is registry-relative, so windows carry their
        # own absolute bounds: raw CLOCK_MONOTONIC (system-wide on
        # Linux) plus wall-clock twins — the merge CLI joins cause
        # events by their universal "ts" field
        off = time.time() - time.monotonic()
        w = self._open
        if w is not None and done_mono - w["t1_mono"] <= self.gap_s:
            w["t1_mono"] = max(w["t1_mono"], float(done_mono))
            w["t1_wall"] = w["t1_mono"] + off
            w["requests"] += 1
            w["worst_ms"] = max(w["worst_ms"], latency_s * 1e3)
            for k in kinds:
                if k not in w["kinds"]:
                    w["kinds"].append(k)
        else:
            if w is not None:
                self._flush()
            self._open = {
                "replica": self.replica_id,
                "t0_mono": float(send_mono),
                "t1_mono": float(done_mono),
                "t0_wall": float(send_mono) + off,
                "t1_wall": float(done_mono) + off,
                "requests": 1,
                "worst_ms": latency_s * 1e3,
                "kinds": list(kinds),
            }
        return True

    def _flush(self) -> None:
        w, self._open = self._open, None
        if w is None:
            return
        self.windows.append(w)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("serve.slo_windows",
                        replica=str(self.replica_id)).inc()
            reg.journal("slo_violation", **w)

    def close(self) -> None:
        """Flush the open window (call at loadgen/replica teardown)."""
        self._flush()
