"""The open-loop driver: scheduled sends, latency charged from the
schedule, one timer thread per replica.

The one rule that makes this open-loop: a request's ``send_ts`` is the
*scheduled* arrival time, fixed before the run starts, and is never
re-anchored when the driver falls behind.  If a hot-swap (or the GIL,
or the replica itself) stalls the loop, the backlog of overdue
arrivals fires immediately and each one's latency is measured from
when it *should* have been sent — so a 500 ms stall at 100 Hz shows up
as ~50 requests with up to 500 ms of queueing delay, not as one slow
request and 49 that silently never happened (coordinated omission).

Per-request records go through ``Replica.note_request`` when the
target has one (the real replica: histogram + journal + SLO monitor +
statuspage), with a journal-only fallback for bare targets, so the sim
and the bench share one record schema.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from bluefog_tpu import telemetry as _telemetry
from bluefog_tpu.serve.loadgen import arrivals as _arrivals

__all__ = ["LoadGenerator", "LoadReport"]


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank-with-interpolation quantile of a sorted list."""
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


@dataclass
class LoadReport:
    """Aggregate of one load run (latencies in ms, open-loop basis)."""

    requests: int = 0
    duration_s: float = 0.0
    qps: float = 0.0
    p50_ms: float = float("nan")
    p99_ms: float = float("nan")
    max_ms: float = float("nan")
    outcomes: Dict[str, int] = field(default_factory=dict)
    slo_violations: int = 0
    per_replica: Dict[int, dict] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "requests": self.requests,
            "duration_s": self.duration_s,
            "qps": self.qps,
            "p50_ms": self.p50_ms,
            "p99_ms": self.p99_ms,
            "max_ms": self.max_ms,
            "outcomes": dict(self.outcomes),
            "slo_violations": self.slo_violations,
            "per_replica": {k: dict(v) for k, v in
                            sorted(self.per_replica.items())},
        }


class _ReplicaStats:
    __slots__ = ("latencies_ms", "outcomes", "violations")

    def __init__(self):
        self.latencies_ms: List[float] = []
        self.outcomes: Dict[str, int] = {}
        self.violations = 0


class LoadGenerator:
    """Fire scheduled ``serve_step`` requests at K replicas.

    ``replicas`` is a sequence of targets exposing ``serve_step()``;
    real :class:`bluefog_tpu.serve.Replica` objects additionally get
    their ``note_request`` called per completion (telemetry + SLO).
    All knobs default from the ``BFTPU_LOADGEN_*`` environment so a
    bench or an operator shell can steer a run without code.
    """

    def __init__(self, replicas: Sequence, *,
                 rate_hz: Optional[float] = None,
                 schedule: Optional[str] = None,
                 duration_s: Optional[float] = None,
                 seed: Optional[int] = None):
        self.replicas = list(replicas)
        if not self.replicas:
            raise ValueError("loadgen needs at least one replica")
        self.rate_hz = (_arrivals.loadgen_rate_hz() if rate_hz is None
                        else float(rate_hz))
        self.schedule = (_arrivals.loadgen_schedule() if schedule is None
                         else str(schedule))
        self.duration_s = (_arrivals.loadgen_duration_s()
                           if duration_s is None else float(duration_s))
        self.seed = _arrivals.loadgen_seed() if seed is None else int(seed)
        self._stats = [_ReplicaStats() for _ in self.replicas]
        self._stop = threading.Event()

    def stop(self) -> None:
        """Abort the run early (remaining scheduled arrivals dropped)."""
        self._stop.set()

    # -- per-replica worker ------------------------------------------------

    def _fire(self, idx: int, rep, send_mono: float) -> None:
        st = self._stats[idx]
        start = time.monotonic()
        outcome, version = "ok", 0
        try:
            version, _ = rep.serve_step()
        except Exception as e:  # noqa: BLE001 — outcome-classified below
            outcome = ("stale" if type(e).__name__ == "StaleSnapshotError"
                       else "error")
        done = time.monotonic()
        # the open-loop latency: from the SCHEDULED send, so queueing
        # delay while this worker was behind schedule is charged here
        lat_ms = (done - send_mono) * 1e3
        st.latencies_ms.append(lat_ms)
        st.outcomes[outcome] = st.outcomes.get(outcome, 0) + 1
        note = getattr(rep, "note_request", None)
        if note is not None:
            if note(send_mono, done, version=version, outcome=outcome,
                    start_mono=start):
                st.violations += 1
        else:
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.journal("serve_request", replica=idx,
                            send_mono=send_mono, start_mono=start,
                            done_mono=done, latency_ms=lat_ms,
                            version=version, outcome=outcome)

    def _worker(self, idx: int, rep, offsets: List[float],
                t0: float) -> None:
        for off in offsets:
            target = t0 + off
            while not self._stop.is_set():
                delta = target - time.monotonic()
                if delta <= 0:
                    break
                time.sleep(min(delta, 0.05))
            if self._stop.is_set():
                return
            # NEVER re-anchor: if we are behind, fire immediately with
            # send_ts = target (the scheduled time), not "now"
            self._fire(idx, rep, target)

    # -- the run -----------------------------------------------------------

    def run(self) -> LoadReport:
        reg = _telemetry.get_registry()
        offsets = [
            _arrivals.arrival_times(self.schedule, self.rate_hz,
                                    self.duration_s, self.seed, stream=i)
            for i in range(len(self.replicas))
        ]
        if reg.enabled:
            reg.journal("loadgen_start", replicas=len(self.replicas),
                        schedule=self.schedule, rate_hz=self.rate_hz,
                        duration_s=self.duration_s, seed=self.seed,
                        planned=sum(len(o) for o in offsets))
        t0 = time.monotonic()
        threads = [
            threading.Thread(target=self._worker, name=f"loadgen-{i}",
                             args=(i, rep, offsets[i], t0), daemon=True)
            for i, rep in enumerate(self.replicas)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        for rep in self.replicas:
            close = getattr(rep, "close_slo", None)
            if close is not None:
                close()
        rep_out = self._report(wall)
        if reg.enabled:
            reg.journal("loadgen_done", requests=rep_out.requests,
                        qps=rep_out.qps, p50_ms=rep_out.p50_ms,
                        p99_ms=rep_out.p99_ms,
                        slo_violations=rep_out.slo_violations)
        return rep_out

    def _report(self, wall_s: float) -> LoadReport:
        out = LoadReport(duration_s=wall_s)
        all_lat: List[float] = []
        for i, (rep, st) in enumerate(zip(self.replicas, self._stats)):
            rid = getattr(rep, "replica_id", i)
            all_lat.extend(st.latencies_ms)
            out.requests += len(st.latencies_ms)
            out.slo_violations += st.violations
            for k, v in st.outcomes.items():
                out.outcomes[k] = out.outcomes.get(k, 0) + v
            lat = sorted(st.latencies_ms)
            out.per_replica[int(rid)] = {
                "requests": len(lat),
                "qps": len(lat) / wall_s if wall_s > 0 else 0.0,
                "p50_ms": _quantile(lat, 0.50),
                "p99_ms": _quantile(lat, 0.99),
                "violations": st.violations,
            }
        all_lat.sort()
        out.qps = out.requests / wall_s if wall_s > 0 else 0.0
        out.p50_ms = _quantile(all_lat, 0.50)
        out.p99_ms = _quantile(all_lat, 0.99)
        out.max_ms = all_lat[-1] if all_lat else float("nan")
        return out
