"""The double-buffered seqlock'd snapshot region (docs/SERVING.md).

One mmap file per job (``bf_<job>_serve``) carries the publication
plane between a training island and its inference replicas:

- a **header** — seqlock'd (seq → odd, fields, seq → even, the status
  page idiom) holding the active buffer index and the committed
  ``(version, epoch, step)`` triple;
- **two payload buffers** — each with its own seqlock, a payload crc32,
  and the version it was filled for.

The publish protocol writes the INACTIVE buffer under its buffer
seqlock, then flips the header to point at it.  The two writes are
ordered, so every possible publisher death leaves the region serving
the previous committed snapshot:

- death mid-payload: the standby buffer's seq stays odd, the header
  still names the old buffer — readers never see the torn bytes;
- death after the payload but before the flip: the standby buffer is
  whole but uncommitted — same observable;
- death mid-flip: the header seq stays odd; readers retry, give up,
  and keep serving from their in-memory copy, and the NEXT publisher's
  :meth:`SnapshotRegion.attach` repairs the header from the newest
  whole buffer (rollback to A).

The committed version is persisted in the header, so a successor
publisher (the next-lowest live rank after a heal) continues the
version sequence — **strictly monotone across publisher death**, the
invariant replicas and the sim audit.

Chaos hooks (`BFTPU_CHAOS_SERVE_PUB_KILL_PUBLISH` /
``_PHASE``) SIGKILL the publisher at the exact protocol point the
death matrix above names — the np=4 e2e drives both.
"""

from __future__ import annotations

import os
import struct
import time
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

from bluefog_tpu.native import shm_native

__all__ = [
    "SnapshotRegion",
    "read_committed",
    "region_path",
    "SnapshotUnavailable",
    "TornSnapshotError",
    "SERVE_SCHEMA",
]

SERVE_SCHEMA = "bftpu-serve-region/1"
SERVE_MAGIC = 0x42465356  # "BFSV"
SERVE_LAYOUT = 1

#: header: magic u32, layout u32, seq u64, active u32, pad u32,
#: version u64, epoch u64, step u64, payload_cap u64
_HEAD = struct.Struct("<IIQIIQQQQ")
#: per-buffer meta: seq u64, version u64, nbytes u64, crc32 u32,
#: ndim u32, dims 4*u32, dtype 8s
_BUF = struct.Struct("<QQQII4I8s")
_MAX_DIMS = 4
_HEAD_OFF = 0
_BUF0_OFF = 64
assert _HEAD.size <= _BUF0_OFF


class SnapshotUnavailable(RuntimeError):
    """No snapshot region (or no committed version) yet — retriable."""


class TornSnapshotError(RuntimeError):
    """The region never settled across retries (writer mid-publish or
    dead mid-flip) — the caller keeps its current snapshot."""


def region_path(job: str) -> str:
    return os.path.join(
        shm_native._FALLBACK_DIR,
        shm_native.seg_name(job, "serve")[1:])


def _buf_stride(payload_cap: int) -> int:
    # buffer meta padded to 64, then the payload, padded to 8
    return 64 + ((int(payload_cap) + 7) & ~7)


def _pub_kill_publish() -> int:
    """Chaos: the publish ordinal at which the publisher SIGKILLs
    itself mid-publish (-1 = unarmed)."""
    try:
        return int(os.environ.get("BFTPU_CHAOS_SERVE_PUB_KILL_PUBLISH",
                                  "-1"))
    except ValueError:
        return -1


def _pub_kill_phase() -> str:
    """``payload`` (die with the standby buffer torn) or ``flip`` (die
    with the payload whole but the header not yet flipped)."""
    v = os.environ.get("BFTPU_CHAOS_SERVE_PUB_KILL_PHASE", "payload")
    return v if v in ("payload", "flip") else "payload"


class SnapshotRegion:
    """The writer side: owned by exactly one publisher at a time.

    ``attach`` opens (or creates) the region and repairs a header left
    odd by a publisher that died mid-flip; ``publish`` runs the
    double-buffer protocol and returns the committed version."""

    def __init__(self, job: str, payload_cap: int):
        self.job = str(job)
        self.payload_cap = int(payload_cap)
        stride = _buf_stride(self.payload_cap)
        self._stride = stride
        self._seg = shm_native._FallbackSegment(
            region_path(job), _BUF0_OFF + 2 * stride)
        self._publishes = 0  # this process's publish ordinal (chaos)
        self._attach()

    # -- attach / repair ---------------------------------------------------

    def _attach(self) -> None:
        mm = self._seg._mm
        magic, layout = struct.unpack_from("<II", mm, 0)
        if magic != SERVE_MAGIC:
            # fresh region: no committed version yet
            _HEAD.pack_into(mm, 0, SERVE_MAGIC, SERVE_LAYOUT, 0,
                            0, 0, 0, 0, 0, self.payload_cap)
            return
        if layout != SERVE_LAYOUT:
            raise ValueError(f"serve region layout {layout} "
                             f"(want {SERVE_LAYOUT})")
        cap = struct.unpack_from("<Q", mm, 48)[0]
        if cap != self.payload_cap:
            raise ValueError(
                f"serve region payload capacity {cap} != {self.payload_cap}"
                " (one region, one tensor shape — recreate the job)")
        head_seq = struct.unpack_from("<Q", mm, 8)[0]
        if head_seq % 2 == 1:
            self._repair()

    def _repair(self) -> None:
        """A predecessor died mid-flip: rebuild the header from the
        newest WHOLE buffer (rollback) and make the seq even again."""
        mm = self._seg._mm
        best = None  # (version, index, epoch, step)
        for b in (0, 1):
            off = _BUF0_OFF + b * self._stride
            (seq, ver, nbytes, crc, ndim, d0, d1, d2, d3,
             dt) = _BUF.unpack_from(mm, off)
            if seq % 2 == 1 or ver == 0:
                continue
            if best is None or ver > best[0]:
                best = (ver, b)
        head_seq = struct.unpack_from("<Q", mm, 8)[0] + 1  # -> even
        if best is None:
            _HEAD.pack_into(mm, 0, SERVE_MAGIC, SERVE_LAYOUT, head_seq,
                            0, 0, 0, 0, 0, self.payload_cap)
            return
        ver, b = best
        epoch, step = struct.unpack_from("<QQ", mm, 32)
        struct.pack_into("<Q", mm, 8, head_seq - 1)  # stay odd while...
        struct.pack_into("<IIQ", mm, 16, b, 0, ver)  # ...fields rewrite
        struct.pack_into("<QQ", mm, 32, epoch, step)
        struct.pack_into("<Q", mm, 8, head_seq)

    # -- the committed word ------------------------------------------------

    @property
    def version(self) -> int:
        """The committed version word (0 = nothing published yet)."""
        return struct.unpack_from("<Q", self._seg._mm, 16 + 8)[0]

    # -- publish -----------------------------------------------------------

    def publish(self, tensor: np.ndarray, *, version: Optional[int] = None,
                epoch: int = 0, step: int = 0) -> int:
        """Double-buffered seqlock'd publish; returns the committed
        version.  ``version=None`` continues the persisted sequence
        (strictly monotone across publisher restarts)."""
        from bluefog_tpu.resilience import chaos as _chaos

        mm = self._seg._mm
        arr = np.ascontiguousarray(tensor)
        raw = arr.tobytes()
        if len(raw) > self.payload_cap:
            raise ValueError(f"snapshot {len(raw)} B over the region's "
                             f"payload capacity {self.payload_cap} B")
        if arr.ndim > _MAX_DIMS:
            raise ValueError(f"snapshot ndim {arr.ndim} > {_MAX_DIMS}")
        cur = self.version
        if version is None:
            version = cur + 1
        elif version <= cur:
            raise ValueError(f"version {version} not past the committed "
                             f"{cur} (the word is strictly monotone)")
        self._publishes += 1
        chaos_publish = self._publishes == _pub_kill_publish()
        active = struct.unpack_from("<I", mm, 16)[0]
        b = 1 - (active & 1)
        off = _BUF0_OFF + b * self._stride
        # standby buffer seqlock: odd (a predecessor may have left it
        # odd already — both parities land on odd here)
        bseq = struct.unpack_from("<Q", mm, off)[0]
        bseq += 1 if bseq % 2 == 0 else 2
        struct.pack_into("<Q", mm, off, bseq)
        dims = list(arr.shape) + [0] * (_MAX_DIMS - arr.ndim)
        if chaos_publish and _pub_kill_phase() == "payload":
            # die with the standby buffer torn: half the payload bytes
            # landed, the seq is odd, the header still names the old
            # buffer — every reader stays on the committed version
            mm[off + 64:off + 64 + max(1, len(raw) // 2)] = \
                raw[:max(1, len(raw) // 2)]
            _chaos.kill_self()
        mm[off + 64:off + 64 + len(raw)] = raw
        _BUF.pack_into(mm, off, bseq, version, len(raw),
                       zlib.crc32(raw) & 0xFFFFFFFF, arr.ndim,
                       *dims, str(arr.dtype).encode()[:8])
        struct.pack_into("<Q", mm, off, bseq + 1)  # buffer whole
        if chaos_publish and _pub_kill_phase() == "flip":
            # die between the payload commit and the header flip: the
            # standby buffer is whole but UNCOMMITTED — rollback to A
            _chaos.kill_self()
        hseq = struct.unpack_from("<Q", mm, 8)[0]
        hseq += 1 if hseq % 2 == 0 else 2
        struct.pack_into("<Q", mm, 8, hseq)           # header odd
        struct.pack_into("<IIQ", mm, 16, b, 0, version)
        struct.pack_into("<QQ", mm, 32, int(epoch), int(step))
        struct.pack_into("<Q", mm, 8, hseq + 1)       # header even
        return int(version)

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink)


def _decode_buffer(buf: bytes, off: int, want_version: int
                   ) -> Tuple[np.ndarray, Dict[str, int]]:
    (seq, ver, nbytes, crc, ndim, d0, d1, d2, d3,
     dt) = _BUF.unpack_from(buf, off)
    if seq % 2 == 1:
        raise TornSnapshotError("buffer seq odd (write in flight)")
    if ver != want_version:
        raise TornSnapshotError(
            f"buffer version {ver} != committed {want_version}")
    raw = buf[off + 64:off + 64 + nbytes]
    if len(raw) < nbytes:
        raise TornSnapshotError("buffer payload truncated")
    if zlib.crc32(raw) & 0xFFFFFFFF != crc:
        raise TornSnapshotError("payload crc mismatch (torn mix)")
    dtype = np.dtype(dt.split(b"\0", 1)[0].decode() or "float64")
    dims = [d0, d1, d2, d3][:ndim]
    arr = np.frombuffer(raw, dtype=dtype).reshape(dims).copy()
    return arr, {"seq": seq, "nbytes": nbytes}


def read_committed(job: str, retries: int = 8
                   ) -> Tuple[int, int, int, np.ndarray]:
    """Seqlock reader: returns ``(version, epoch, step, tensor)`` of
    the committed snapshot.  Two whole-region reads bracket the header
    and active-buffer seqs — accept iff both are even and identical
    across the bracket (the status-page protocol, double-buffered).

    Raises :class:`SnapshotUnavailable` when the region does not exist
    or nothing is committed yet, :class:`TornSnapshotError` when it
    never settles (publisher mid-publish — the caller keeps serving
    its in-memory copy)."""
    path = region_path(job)
    err: Optional[Exception] = None
    for _ in range(max(1, retries)):
        try:
            with open(path, "rb") as f:
                buf1 = f.read()
        except OSError:
            raise SnapshotUnavailable(f"no serve region for job {job!r}")
        if len(buf1) < _BUF0_OFF:
            raise SnapshotUnavailable(f"serve region {path} truncated")
        (magic, layout, hseq, active, _pad, version, epoch, step,
         cap) = _HEAD.unpack_from(buf1, 0)
        if magic != SERVE_MAGIC:
            raise SnapshotUnavailable(
                f"not a serve region (magic 0x{magic:08x})")
        if version == 0:
            raise SnapshotUnavailable(
                f"serve region {path}: nothing committed yet")
        if hseq % 2 == 0:
            try:
                stride = _buf_stride(cap)
                off = _BUF0_OFF + (active & 1) * stride
                arr, meta = _decode_buffer(buf1, off, version)
                with open(path, "rb") as f:
                    buf2 = f.read(off + 8)
                hseq2 = struct.unpack_from("<Q", buf2, 8)[0]
                bseq2 = struct.unpack_from("<Q", buf2, off)[0]
                if hseq2 == hseq and bseq2 == meta["seq"]:
                    return int(version), int(epoch), int(step), arr
                err = TornSnapshotError("seq moved across the bracket")
            except TornSnapshotError as e:
                err = e
        else:
            err = TornSnapshotError(f"header seq odd ({hseq})")
        time.sleep(0.001)
    raise TornSnapshotError(
        f"serve region {path} torn across retries: {err}")
