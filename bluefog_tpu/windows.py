"""One-sided window ops — device-memory mailbox emulation.

TPU-native sibling of the reference's RMA window layer
(``bluefog/torch/mpi_win_ops.cc``, ``MPI_Win_create/Put/Get/Accumulate``
paths in ``bluefog/common/mpi_controller.cc`` [U]; SURVEY.md §3.4, §7
stage 5).  The reference gives every rank one registered buffer **per
in-neighbor** per named window so concurrent writers never collide; a
``win_put`` deposits into the writer's dedicated slot at the destination and
``win_update`` locally combines the slots.

XLA has no one-sided RMA, so the same window model is emulated with
rank-major mailbox arrays living in device memory:

- ``win_create(name)`` allocates ``mail[size, max_in_degree, ...]`` — rank
  d's slot k holds the last deposit from its k-th in-neighbor (ascending
  rank order), exactly the reference's per-writer-buffer model.
- ``win_put/win_get/win_accumulate`` lower to one ``lax.ppermute`` per shift
  class of the window's topology, scattering into the destination slots.
- ``win_update`` is the purely local weighted combine, as upstream.

Semantic deviation (documented, by design): deposits are dispatched
asynchronously by the JAX runtime but become visible at the next collective
exchange point, so the execution realizes the *synchronous schedule* of the
asynchronous algorithm (bounded staleness 0).  Every consensus/push-sum
algorithm expressible upstream runs unchanged; what is lost is only
wall-clock desynchronization between ranks.  ``win_mutex`` therefore
degenerates to a no-op shim (SURVEY.md §5.2): there are never concurrent
writers to a slot.

Push-sum support: when associated-p mode is on (reference
``turn_on_win_ops_with_associated_p`` [U]) a scalar weight p rides along
with every deposit and is combined identically, enabling directed-graph
push-sum averaging (x/p debiasing).
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bluefog_tpu.common.logging_util import logger
from bluefog_tpu.core import basics
from bluefog_tpu.core.basics import NODES_AXIS
from bluefog_tpu.core.plan import CommPlan
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.timeline import timeline_context

__all__ = [
    "win_create",
    "win_free",
    "win_put",
    "win_put_nonblocking",
    "win_get",
    "win_get_nonblocking",
    "win_accumulate",
    "win_accumulate_nonblocking",
    "win_put_async",
    "win_accumulate_async",
    "win_update_async",
    "win_update",
    "win_put_update",
    "win_update_then_collect",
    "win_wait",
    "win_poll",
    "win_mutex",
    "get_win_version",
    "win_associated_p",
    "win_set_exposed",
    "turn_on_win_ops_with_associated_p",
    "turn_off_win_ops_with_associated_p",
    "record_win_ops",
    "note_win_op",
    "degraded_update_weights",
]

WeightsArg = Union[None, Sequence[Dict[int, float]]]

# ``record_win_ops`` trace target; None = recording off.  The events come
# from the telemetry op stream (telemetry.note_op) — one bookkeeping path
# shared by this module, the island runtime, and the win_ops.total counter.
_OP_LOG: Optional[List[Tuple[str, str]]] = None


def _op_log_listener(op: str, name: str) -> None:
    log = _OP_LOG
    if log is not None:
        log.append((op, name))


@contextlib.contextmanager
def record_win_ops():
    """Record ``(op, window_name)`` for every public win op in the block,
    yielding the live event list.  The epoch-ordering lint
    (``bluefog_tpu.analysis.epoch_rules.check_trace``) consumes this trace,
    so a real training loop's window usage can be checked against the
    use-before-create / use-after-free / mixed-deposit-epoch rules exactly
    as the analysis CLI checks canned traces.  A thin consumer of the
    telemetry op stream: both this module's SPMD ops and the island
    runtime's publish through ``telemetry.note_op``, so one recorder covers
    both execution modes.  Nested recorders share the outer list;
    ``win_free(None)`` logs with name ``"*"``."""
    global _OP_LOG
    prev = _OP_LOG
    log = [] if prev is None else prev
    _OP_LOG = log
    if prev is None:
        _telemetry.add_op_listener(_op_log_listener)
    try:
        yield log
    finally:
        _OP_LOG = prev
        if prev is None:
            _telemetry.remove_op_listener(_op_log_listener)


def _log_op(op: str, name: Optional[str]) -> None:
    _telemetry.note_op(op, name)


def note_win_op(op: str, name: Optional[str]) -> None:
    """Deprecated shim: window ops from other modules now publish through
    :func:`bluefog_tpu.telemetry.note_op` directly; kept so existing
    callers keep feeding the active ``record_win_ops()`` trace."""
    _telemetry.note_op(op, name)


class _Window:
    """Per-name window state (the reference's window registry entry [U])."""

    def __init__(self, name: str, tensor: jnp.ndarray, plan: CommPlan, zero_init: bool):
        ctx = basics.context()
        self.name = name
        self.plan = plan
        self.shape = tensor.shape  # rank-major [size, ...]
        self.dtype = tensor.dtype
        maxd = max(plan.max_in_degree, 1)
        # Place every buffer with the mesh's rank-major sharding UP FRONT:
        # the exchange jits return mesh-sharded outputs, so an unplaced
        # initial buffer would change the call signature after the first
        # exchange (one wasted recompile) and pay a full reshard on entry.
        shard = NamedSharding(ctx.mesh, P(NODES_AXIS))
        self.self_tensor = jax.device_put(jnp.asarray(tensor), shard)
        init = jnp.zeros((ctx.size, maxd) + tensor.shape[1:], dtype=tensor.dtype)
        if not zero_init:
            # Reference initializes each neighbor buffer with the local
            # tensor value so a pre-put win_update is a no-op average.
            init = init + jnp.expand_dims(jnp.asarray(tensor), 1)
        self.mail = jax.device_put(init, shard)
        self.versions = jax.device_put(
            jnp.zeros((ctx.size, maxd), dtype=jnp.int32), shard)
        # push-sum associated scalars (mailbox follows the tensor-mailbox
        # init convention: zero_init -> empty, else neighbor's initial p=1)
        self.p_self = jax.device_put(
            jnp.ones((ctx.size,), dtype=jnp.float32), shard)
        self.p_mail = jax.device_put(
            jnp.zeros((ctx.size, maxd), dtype=jnp.float32)
            if zero_init
            else jnp.ones((ctx.size, maxd), dtype=jnp.float32), shard)
        # device-resident host constants for the default-weights fused path
        self.default_consts = None


def _ctx():
    return basics.context()


def _win(name: str) -> _Window:
    w = _ctx().windows.get(name)
    if w is None:
        raise KeyError(f"no window named {name!r}; call win_create first")
    return w


def _class_scales(
    plan: CommPlan,
    weights: WeightsArg,
    side: str,
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-class scale + active-edge mask, both [num_classes, size] indexed
    by the *receiving* rank's mask position.

    side='send': scales[c, s] = weight rank s applies to what it sends in
    class c (keyed by that class's destination) — the reference's
    ``dst_weights``.  side='recv': scales[c, d] = weight rank d applies to
    what it receives in class c — the reference's ``src_weights``.

    When a weights sequence is given it also *selects* the edges: an edge
    not listed in the dict does not transfer at all (the reference's
    selective put/get — a put with ``dst_weights={1: w}`` touches only rank
    1's window [U]).  ``active[c, d] = 0`` suppresses the slot update at
    receiver d for that class.
    """
    C = len(plan.classes)
    scales = np.ones((C, plan.size), dtype=np.float32)
    active = np.ones((C, plan.size), dtype=np.float32)
    if weights is None:
        return scales, active
    if len(weights) != plan.size:
        raise ValueError(f"weights must be a length-{plan.size} sequence of dicts")
    for c, cls in enumerate(plan.classes):
        for s, d in cls.perm:
            listed = d in weights[s] if side == "send" else s in weights[d]
            if not listed:
                active[c, d] = 0.0
                scales[c, s if side == "send" else d] = 0.0
            elif side == "send":
                scales[c, s] = float(weights[s][d])
            else:
                scales[c, d] = float(weights[d][s])
    return scales, active


def _exchange_body(plan, accumulate, with_p, x, mail0, ver0, p_self, pm0,
                   scales, active, idx):
    """Per-rank exchange: deposit (scaled) payloads into destination
    mailbox slots — the ppermute lowering of MPI_Put/MPI_Accumulate [U].
    Local shapes: x [1,...], mail0 [maxd,...], ver0 [maxd], p_self [1],
    pm0 [maxd], scales/active [C,1] (sharded by rank)."""
    for c, cls in enumerate(plan.classes):
        wdt = x.dtype if jnp.issubdtype(x.dtype, jnp.inexact) else jnp.float32
        scale = scales[c, 0].astype(wdt)
        payload = (x[0].astype(wdt) * scale).astype(x.dtype)
        recvd = lax.ppermute(payload, NODES_AXIS, cls.perm)
        slot = jnp.asarray(cls.slot_index)[idx]
        valid = jnp.asarray(cls.recv_mask)[idx].astype(bool) & (active[c, 0] > 0)
        slot_c = jnp.maximum(slot, 0)
        cur = lax.dynamic_index_in_dim(mail0, slot_c, axis=0, keepdims=False)
        new = cur + recvd if accumulate else recvd
        mail0 = jnp.where(
            valid, lax.dynamic_update_index_in_dim(mail0, new, slot_c, axis=0), mail0
        )
        ver0 = jnp.where(
            valid,
            lax.dynamic_update_index_in_dim(
                ver0, lax.dynamic_index_in_dim(ver0, slot_c, 0, keepdims=False) + 1,
                slot_c, axis=0,
            ),
            ver0,
        )
        if with_p:
            p_recvd = lax.ppermute(p_self[0] * scales[c, 0], NODES_AXIS, cls.perm)
            p_cur = lax.dynamic_index_in_dim(pm0, slot_c, 0, keepdims=False)
            p_new = p_cur + p_recvd if accumulate else p_recvd
            pm0 = jnp.where(
                valid,
                lax.dynamic_update_index_in_dim(pm0, p_new, slot_c, axis=0),
                pm0,
            )
    return mail0, ver0, pm0


def _build_exchange(plan: CommPlan, accumulate: bool, with_p: bool,
                    donate: bool = True):
    """Jitted rank-major exchange (see :func:`_exchange_body`).

    ``donate=False`` when the result is called from inside another jit
    (donation only applies at the outermost dispatch; the fused-window
    wrappers donate on their own outer jit instead)."""
    ctx = _ctx()

    def spmd(x, mail, versions, p_self, p_mail, scales, active):
        idx = lax.axis_index(NODES_AXIS)
        mail0, ver0, pm0 = _exchange_body(
            plan, accumulate, with_p, x, mail[0], versions[0], p_self,
            p_mail[0], scales, active, idx,
        )
        return mail0[None], ver0[None], pm0[None]

    # mail/versions/p_mail are returned and reassigned by every caller, so
    # the input buffers are dead after the call: donating them lets XLA
    # update in place instead of copying the full mailbox each exchange
    return jax.jit(
        jax.shard_map(
            spmd,
            mesh=ctx.mesh,
            in_specs=(P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS),
                      P(NODES_AXIS), P(None, NODES_AXIS), P(None, NODES_AXIS)),
            out_specs=(P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS)),
        ),
        donate_argnums=(1, 2, 4) if donate else (),
    )


def _build_put_update(plan: CommPlan, accumulate: bool, with_p: bool, wdt,
                      donate: bool = True):
    """One compiled program for put/accumulate + local weighted combine —
    the fused hot path of :func:`win_put_update` (one dispatch instead of
    an exchange jit plus a combine jit; XLA schedules the ppermute rounds
    together with the FMA combine)."""
    ctx = _ctx()

    def spmd(x, mail, versions, p_self, p_mail, scales, active, wmat, swvec):
        idx = lax.axis_index(NODES_AXIS)
        mail0, ver0, pm0 = _exchange_body(
            plan, accumulate, with_p, x, mail[0], versions[0], p_self,
            p_mail[0], scales, active, idx,
        )
        extra = (1,) * (x.ndim - 1)  # x local [1, ...]: payload rank is ndim-1
        w = wmat[0].astype(wdt).reshape(wmat.shape[1:2] + extra)
        sw = swvec[0].astype(wdt)
        combined = sw * x[0].astype(wdt) + (w * mail0.astype(wdt)).sum(axis=0)
        if with_p:
            p_new = swvec[0] * p_self[0] + (wmat[0] * pm0).sum()
        else:
            p_new = p_self[0]
        return (combined.astype(x.dtype)[None], mail0[None], ver0[None],
                pm0[None], p_new[None])

    # mail/versions/p_self/p_mail are returned and reassigned by
    # win_put_update after every call (the input buffers are dead):
    # donation lets XLA update the mailbox state in place
    return jax.jit(
        jax.shard_map(
            spmd,
            mesh=ctx.mesh,
            in_specs=(P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS),
                      P(NODES_AXIS), P(None, NODES_AXIS), P(None, NODES_AXIS),
                      P(NODES_AXIS), P(NODES_AXIS)),
            out_specs=(P(NODES_AXIS), P(NODES_AXIS), P(NODES_AXIS),
                       P(NODES_AXIS), P(NODES_AXIS)),
        ),
        donate_argnums=(1, 2, 3, 4) if donate else (),
    )


def _exchange(
    win: _Window, x, scales: np.ndarray, active: np.ndarray, accumulate: bool
) -> None:
    ctx = _ctx()
    with_p = ctx.win_associated_p_enabled
    key = ("win_exchange", win.plan, accumulate, with_p, win.dtype, win.shape[1:])
    f = ctx.jit_cache(key, lambda: _build_exchange(win.plan, accumulate, with_p))
    mail, versions, p_mail = f(
        _cast_to_window_dtype(win, win.name, x),
        win.mail,
        win.versions,
        win.p_self,
        win.p_mail,
        jnp.asarray(scales),
        jnp.asarray(active),
    )
    # always reassign: the jit donated the old p_mail buffer, so the
    # previous win.p_mail is invalid even when the p machinery is off
    win.mail, win.versions, win.p_mail = mail, versions, p_mail


# --------------------------------------------------------------------------
# Public API
# --------------------------------------------------------------------------


class _FusionMeta:
    """Pack/unpack metadata for a pytree (fused) window: the reference's
    tensor-fusion buffer (``BLUEFOG_FUSION_THRESHOLD`` [U]) as an API-level
    feature — a whole parameter tree rides ONE window, so each gossip round
    is one exchange instead of one per leaf (measured 27x on BERT-base
    through the tunnel's per-dispatch cost; `benchmarks/bert_pushsum.py`)."""

    __slots__ = ("treedef", "shapes", "sizes")

    def __init__(self, treedef, shapes, sizes):
        self.treedef = treedef
        self.shapes = shapes
        self.sizes = sizes


def _fusion_split(tensor):
    """(meta, packed) for a pytree input; (None, tensor) for a bare array."""
    leaves, treedef = jax.tree_util.tree_flatten(tensor)
    if treedef == jax.tree_util.tree_structure(0):
        return None, basics.to_rank_major_global(tensor)
    if not leaves:
        raise ValueError("win_create: empty pytree")
    if isinstance(tensor, (list, tuple)) and all(
        np.ndim(l) == 0 for l in leaves
    ):
        # nested-list-of-scalars spelling of a bare array
        return None, jnp.asarray(tensor)
    ctx = _ctx()
    # multi-host: each leaf may arrive as this process's rank rows; the
    # converter assembles global arrays (single process: plain asarray).
    # One call — a list is a pytree, and per-leaf calls would redo the
    # context/sharding setup per leaf.
    leaves = basics.to_rank_major_global(leaves)
    dts = {jnp.asarray(l).dtype for l in leaves}
    if len(dts) > 1:
        raise ValueError(
            f"fused windows need a uniform leaf dtype, got {sorted(map(str, dts))}; "
            "create one window per dtype group (cf. islands.DistributedWinPutOptimizer)"
        )
    bad = [tuple(np.shape(l)) for l in leaves
           if np.ndim(l) == 0 or np.shape(l)[0] != ctx.size]
    if bad:
        raise ValueError(
            f"every fused-window leaf must be rank-major with leading dim "
            f"{ctx.size}; offending leaf shapes: {bad[:4]}"
        )
    n = ctx.size
    shapes = [tuple(np.shape(l)[1:]) for l in leaves]
    sizes = [int(np.prod(s, dtype=np.int64)) for s in shapes]
    meta = _FusionMeta(treedef, shapes, sizes)
    return meta, _fusion_pack(meta, leaves, n)


def _pack_leaves(meta, leaves, n, dtype=None):
    """Traceable pack body — the ONE place the packed layout is defined."""
    ls = [l.astype(dtype) if dtype is not None else l for l in leaves]
    return jnp.concatenate([l.reshape(n, -1) for l in ls], axis=1)


def _unpack_leaves(meta, packed, n):
    """Traceable unpack body (inverse of :func:`_pack_leaves`)."""
    out, off = [], 0
    for s, sz in zip(meta.shapes, meta.sizes):
        out.append(packed[:, off:off + sz].reshape((n,) + s))
        off += sz
    return out


def _fusion_pack(meta, leaves, n):
    # ONE compiled program per tree structure: eagerly this is ~2 dispatches
    # per leaf, which on dispatch-expensive platforms costs more than the
    # gossip itself (measured 15x on BERT-base through the tunnel)
    f = _ctx().jit_cache(
        ("win_fusion_pack", meta.treedef, tuple(meta.shapes), n),
        lambda: jax.jit(lambda ls: _pack_leaves(meta, ls, n)),
    )
    return f([jnp.asarray(l) for l in leaves])


def _check_fused_leaves(meta, leaves, n):
    bad = [(tuple(np.shape(l)), (n,) + tuple(exp))
           for l, exp in zip(leaves, meta.shapes)
           if tuple(np.shape(l)) != (n,) + tuple(exp)]
    if bad:
        # same-size-different-shape leaves would pack without error and
        # unpack as silently corrupted data
        raise ValueError(f"leaf shapes do not match the window's: {bad[:4]}")


def _fusion_pack_tree(meta, tree, n):
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if treedef != meta.treedef:
        raise ValueError(
            f"pytree structure does not match the window's: {treedef} vs "
            f"{meta.treedef}"
        )
    _check_fused_leaves(meta, leaves, n)
    return _fusion_pack(meta, leaves, n)


def _pack_input(name, tensor):
    """Pack a pytree op input when ``name`` is a fused window."""
    meta = _ctx().win_fusion.get(name)
    if meta is None:
        return tensor
    return _fusion_pack_tree(meta, tensor, _ctx().size)


def _fused_exchange(win, name, meta, tree, scales, active, accumulate):
    """Pack + exchange in ONE compiled program (fused windows): leaves go
    in, the packed exposure comes back alongside the new mailbox state —
    a separate eager pack would cost an extra dispatch per gossip round."""
    ctx = _ctx()
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if treedef != meta.treedef:
        raise ValueError(
            f"pytree structure does not match the window's: {treedef} vs "
            f"{meta.treedef}"
        )
    _check_fused_leaves(meta, leaves, ctx.size)
    with_p = ctx.win_associated_p_enabled
    n = ctx.size
    key = ("win_fused_exchange", meta.treedef, tuple(meta.shapes), win.plan,
           accumulate, with_p, win.dtype)

    def build():
        inner = _build_exchange(win.plan, accumulate, with_p, donate=False)

        def f(ls, mail, versions, p_self, p_mail, scales, active):
            x = _pack_leaves(meta, ls, n, dtype=win.dtype)
            mail, versions, p_mail = inner(
                x, mail, versions, p_self, p_mail, scales, active
            )
            return x, mail, versions, p_mail

        # donate at the outermost jit (nested donation is ignored)
        return jax.jit(f, donate_argnums=(1, 2, 4))

    f = ctx.jit_cache(key, build)
    x, mail, versions, p_mail = f(
        leaves, win.mail, win.versions, win.p_self, win.p_mail,
        jnp.asarray(scales), jnp.asarray(active),
    )
    win.self_tensor = x
    # always reassign (the old p_mail buffer was donated)
    win.mail, win.versions, win.p_mail = mail, versions, p_mail


def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    """Collectively create a named window from a rank-major tensor — or a
    whole rank-major PYTREE, which is fused into one packed window (every
    subsequent op on ``name`` then accepts/returns the same tree structure)
    (reference ``bf.win_create(tensor, name, zero_init)`` [U]; the pytree
    form subsumes its fusion buffer).  The window's neighbor structure
    snapshots the currently-installed topology."""
    _log_op("win_create", name)
    ctx = _ctx()
    # _fusion_split performs the multi-host conversion for both forms
    meta, tensor = _fusion_split(tensor)
    t = jnp.asarray(tensor)
    if t.shape[0] != ctx.size:
        raise ValueError(
            f"win_create expects rank-major tensor with leading dim {ctx.size}"
        )
    if name in ctx.windows:
        return False
    ctx.windows[name] = _Window(name, t, ctx.plan, zero_init)
    if meta is not None:
        ctx.win_fusion[name] = meta
    return True


def win_free(name: Optional[str] = None) -> bool:
    """Free one window, or all when name is None (reference ``bf.win_free`` [U])."""
    _log_op("win_free", name)
    ctx = _ctx()
    if name is None:
        ctx.windows.clear()
        ctx.win_fusion.clear()
        return True
    ctx.win_fusion.pop(name, None)
    return ctx.windows.pop(name, None) is not None


def _cast_to_window_dtype(win, name, tensor):
    """Eager cast with a CLEAR multi-process error.

    In the multi-process non-fused path the input is a global
    non-fully-addressable array; an eager ``convert_element_type`` on it
    raises an opaque JAX error, so detect the case and name the fix
    (the fused path avoids this by casting inside the compiled program).
    """
    t = jnp.asarray(tensor) if not isinstance(tensor, jax.Array) else tensor
    if t.dtype != win.dtype and not getattr(t, "is_fully_addressable", True):
        raise ValueError(
            f"window '{name}' holds {win.dtype} but the input is {t.dtype}: "
            "eager dtype casts on non-fully-addressable (multi-process "
            "global) arrays are not supported — cast the input to the "
            "window dtype before the call, or use a fused (pytree) window"
        )
    return jnp.asarray(t, dtype=win.dtype)


def win_put(tensor, name: str, dst_weights: WeightsArg = None) -> bool:
    """Deposit (optionally dst-scaled) values into this rank's slot at each
    out-neighbor — only the ranks listed in ``dst_weights`` when given
    (reference ``bf.win_put`` — MPI_Put path [U]).

    Also refreshes the window's exposed tensor: upstream the window aliases
    the tensor's memory, so the put value *is* the current exposure.
    """
    with timeline_context("win_put"):
        _log_op("win_put", name)
        win = _win(name)
        tensor = basics.to_rank_major_global(tensor)
        scales, active = _class_scales(win.plan, dst_weights, side="send")
        meta = _ctx().win_fusion.get(name)
        if meta is not None:
            _fused_exchange(win, name, meta, tensor, scales, active,
                            accumulate=False)
        else:
            win.self_tensor = _cast_to_window_dtype(win, name, tensor)
            _exchange(win, tensor, scales, active, accumulate=False)
    return True


@jax.jit
def _completion_probe(mail):
    """A tiny array data-dependent on ``mail``'s producing op — what a
    nonblocking Handle holds.  The mailbox buffers themselves are DONATED
    by the next window op on the same window, which would leave a Handle
    holding a deleted array; the probe is a separate 1-element buffer that
    becomes ready exactly when the exchange completes and is never
    donated."""
    return jnp.ravel(mail)[:1]


def win_put_nonblocking(tensor, name: str, dst_weights: WeightsArg = None):
    from bluefog_tpu.ops import Handle

    win_put(tensor, name, dst_weights)
    return Handle(_completion_probe(_win(name).mail))


def win_accumulate(tensor, name: str, dst_weights: WeightsArg = None) -> bool:
    """Like win_put but adds into the destination slot (reference
    ``bf.win_accumulate`` — MPI_Accumulate path [U])."""
    with timeline_context("win_accumulate"):
        _log_op("win_accumulate", name)
        win = _win(name)
        tensor = basics.to_rank_major_global(tensor)
        scales, active = _class_scales(win.plan, dst_weights, side="send")
        meta = _ctx().win_fusion.get(name)
        if meta is not None:
            _fused_exchange(win, name, meta, tensor, scales, active,
                            accumulate=True)
        else:
            win.self_tensor = _cast_to_window_dtype(win, name, tensor)
            _exchange(win, tensor, scales, active, accumulate=True)
    return True


def win_accumulate_nonblocking(tensor, name: str, dst_weights: WeightsArg = None):
    from bluefog_tpu.ops import Handle

    win_accumulate(tensor, name, dst_weights)
    return Handle(_completion_probe(_win(name).mail))


def win_put_async(tensor, name: str, dst_weights: WeightsArg = None):
    """API parity with :func:`bluefog_tpu.islands.win_put_async`: the
    bulk-synchronous emulation has no background wire, so the op executes
    at the call site and the returned
    :class:`~bluefog_tpu.progress.handles.WinHandle` is already resolved
    — programs written against the async surface run unchanged here."""
    from bluefog_tpu import progress as _progress

    t = tensor() if callable(tensor) else tensor
    return _progress.completed(win_put(t, name, dst_weights))


def win_accumulate_async(tensor, name: str, dst_weights: WeightsArg = None):
    """See :func:`win_put_async` — completed-handle parity wrapper."""
    from bluefog_tpu import progress as _progress

    t = tensor() if callable(tensor) else tensor
    return _progress.completed(win_accumulate(t, name, dst_weights))


def win_update_async(name: str,
                     self_weight=None,
                     neighbor_weights: WeightsArg = None,
                     reset: bool = False):
    """See :func:`win_put_async`; the handle's ``result()`` is the
    combined tensor (``clone`` semantics, matching the island engine)."""
    from bluefog_tpu import progress as _progress

    return _progress.completed(win_update(
        name, self_weight=self_weight, neighbor_weights=neighbor_weights,
        reset=reset, clone=True))


def win_get(name: str, src_weights: WeightsArg = None) -> bool:
    """Pull in-neighbors' exposed tensors into my mailbox slots, optionally
    receiver-scaled (reference ``bf.win_get`` — MPI_Get path [U])."""
    with timeline_context("win_get"):
        _log_op("win_get", name)
        win = _win(name)
        # A get of s's exposed tensor by d == a put of s's tensor to d with
        # receiver-side scaling, under the lockstep schedule.
        send, _ = _class_scales(win.plan, None, side="send")
        recv, active = _class_scales(win.plan, src_weights, side="recv")
        # apply receiver scale post-transfer by folding into sender scale:
        # within a class each (s,d) is unique, so scale at sender by the
        # destination's recv weight.
        for c, cls in enumerate(win.plan.classes):
            for s, d in cls.perm:
                send[c, s] = recv[c, d]
        _exchange(win, win.self_tensor, send, active, accumulate=False)
    return True


def win_get_nonblocking(name: str, src_weights: WeightsArg = None):
    from bluefog_tpu.ops import Handle

    win_get(name, src_weights)
    return Handle(_completion_probe(_win(name).mail))


def _reset_mailbox(win: _Window) -> None:
    win.mail = jnp.zeros_like(win.mail)
    win.p_mail = jnp.zeros_like(win.p_mail)


def _update_weights(win: _Window, self_weight, neighbor_weights):
    """Host-side combine weights: matrix [size, maxd] + self vector [size]
    (the reference ``win_update`` weight convention: default uniform
    1/(in_degree+1); explicit neighbor weights imply self = 1 - sum)."""
    plan = win.plan
    size = plan.size
    maxd = max(plan.max_in_degree, 1)
    wmat = np.zeros((size, maxd), dtype=np.float32)
    swvec = np.zeros((size,), dtype=np.float32)
    for d in range(size):
        nbrs = plan.in_neighbors[d]
        if neighbor_weights is not None:
            for k, s in enumerate(nbrs):
                wmat[d, k] = float(neighbor_weights[d].get(s, 0.0))
        else:
            for k in range(len(nbrs)):
                wmat[d, k] = 1.0 / (len(nbrs) + 1)
        if self_weight is None:
            swvec[d] = (
                1.0 - wmat[d].sum()
                if neighbor_weights is not None
                else 1.0 / (len(nbrs) + 1)
            )
        elif np.isscalar(self_weight):
            swvec[d] = float(self_weight)
        else:
            swvec[d] = float(self_weight[d])
    return wmat, swvec


def degraded_update_weights(plan: CommPlan, dead):
    """Per-rank ``(self_weights, neighbor_weights)`` for :func:`win_update`
    with the ranks in ``dead`` excised from the combine.

    Each survivor drops its dead in-neighbors and ABSORBS their compiled
    plan weight into its own self weight, so every row total is preserved
    exactly: convex rows stay convex and push-sum collect rows stay
    mass-conserving — the island runtime's degraded-combine rule
    (resilience/degraded.py), made available to the SPMD emulation for
    fault-injected gossip.  Dead ranks' own rows are left untouched
    (their state no longer participates)."""
    dead = set(int(r) for r in dead)
    W = plan.mixing_matrix()
    self_w: List[float] = []
    neighbor_w: List[Dict[int, float]] = []
    for d in range(plan.size):
        sw = float(W[d, d])
        nw = {}
        for s in plan.in_neighbors[d]:
            if d not in dead and s in dead:
                sw += float(W[d, s])
            else:
                nw[s] = float(W[d, s])
        self_w.append(sw)
        neighbor_w.append(nw)
    return self_w, neighbor_w


def _combine(self_tensor, mail, p_self, p_mail, wmat, swvec, *, wdt, with_p):
    """Fused local weighted combine (jitted via the context cache)."""
    size, maxd = wmat.shape
    extra = (1,) * (self_tensor.ndim - 1)
    w = wmat.astype(wdt).reshape((size, maxd) + extra)
    sw = swvec.astype(wdt).reshape((size,) + extra)
    combined = sw * self_tensor.astype(wdt) + (w * mail.astype(wdt)).sum(axis=1)
    new_p = swvec * p_self + (wmat * p_mail).sum(axis=1) if with_p else p_self
    return combined.astype(self_tensor.dtype), new_p


def win_update(
    name: str,
    self_weight: Optional[Union[float, Sequence[float]]] = None,
    neighbor_weights: WeightsArg = None,
    reset: bool = False,
    clone: bool = False,
):
    """Local weighted combine of the exposed tensor with mailbox slots,
    storing the result back as the exposed tensor (reference
    ``bf.win_update(name, self_weight, neighbor_weights, reset, clone)``
    [U]).  Default weights: uniform 1/(in_degree+1).  ``reset`` zeroes the
    mailbox (and associated p) after reading — the accumulate idiom.
    """
    with timeline_context("win_update"):
        _log_op("win_update", name)
        ctx = _ctx()
        win = _win(name)
        maxd = max(win.plan.max_in_degree, 1)
        wmat, swvec = _update_weights(win, self_weight, neighbor_weights)
        wdt = win.dtype if jnp.issubdtype(win.dtype, jnp.inexact) else jnp.float32
        with_p = ctx.win_associated_p_enabled
        meta = ctx.win_fusion.get(name)
        # one fused kernel per (shape, dtype, with_p); weights are traced
        # args so every weight value shares the compile.  Fused (pytree)
        # windows get the unpack INSIDE the same program — a separate eager
        # unpack would cost an extra dispatch per round.
        if meta is None:
            key = ("win_update", with_p, win.dtype, win.shape[1:], maxd)
            f = ctx.jit_cache(
                key,
                lambda: jax.jit(_combine, static_argnames=("wdt", "with_p")),
            )
        else:
            key = ("win_update_fused", with_p, win.dtype, win.shape[1:],
                   maxd, meta.treedef, tuple(meta.shapes))

            def build():
                n = ctx.size

                def f(self_t, mail, p_self, p_mail, wmat, swvec):
                    combined, p_new = _combine(
                        self_t, mail, p_self, p_mail, wmat, swvec,
                        wdt=wdt, with_p=with_p,
                    )
                    return combined, p_new, _unpack_leaves(meta, combined, n)

                return jax.jit(f)

            f = ctx.jit_cache(key, build)
        if meta is None:
            combined, p_self = f(
                win.self_tensor,
                win.mail,
                win.p_self,
                win.p_mail,
                jnp.asarray(wmat),
                jnp.asarray(swvec),
                wdt=wdt,
                with_p=with_p,
            )
            leaves = None
        else:
            combined, p_self, leaves = f(
                win.self_tensor,
                win.mail,
                win.p_self,
                win.p_mail,
                jnp.asarray(wmat),
                jnp.asarray(swvec),
            )
        win.self_tensor = combined
        if with_p:
            win.p_self = p_self
        if reset:
            _reset_mailbox(win)
        if meta is not None:
            tree = jax.tree_util.tree_unflatten(meta.treedef, leaves)
            if clone:
                tree = jax.tree_util.tree_map(jnp.array, tree)
            return tree
        out = win.self_tensor
        return jnp.array(out) if clone else out


def win_put_update(
    tensor,
    name: str,
    dst_weights: WeightsArg = None,
    *,
    self_weight: Optional[Union[float, Sequence[float]]] = None,
    neighbor_weights: WeightsArg = None,
    accumulate: bool = False,
    reset: bool = False,
):
    """Fused ``win_put`` (or ``win_accumulate``) + ``win_update`` in ONE
    compiled program — the hot path of :class:`DistributedWinPutOptimizer`
    and the gossip benchmark.  Semantically identical to the two calls in
    sequence; returns the combined tensor.  Not a reference API (upstream's
    put and update run on different sides of an RMA epoch); provided
    because under the mailbox emulation the pair always executes back to
    back, and one dispatch lets XLA schedule the exchange with the combine.
    """
    with timeline_context("win_put_update"):
        _log_op("win_put_update", name)
        ctx = _ctx()
        win = _win(name)
        tensor = basics.to_rank_major_global(tensor)
        meta = ctx.win_fusion.get(name)
        if meta is not None:
            leaves, treedef = jax.tree_util.tree_flatten(tensor)
            if treedef != meta.treedef:
                raise ValueError(
                    f"pytree structure does not match the window's: "
                    f"{treedef} vs {meta.treedef}"
                )
            _check_fused_leaves(meta, leaves, ctx.size)
            t = leaves  # packed inside the compiled program below
        else:
            t = _cast_to_window_dtype(win, name, tensor)
        if dst_weights is None and self_weight is None and neighbor_weights is None:
            # the optimizer hot path: the four weight arrays are constant
            # per window, so build + upload them once
            if win.default_consts is None:
                scales, active = _class_scales(win.plan, None, side="send")
                wmat, swvec = _update_weights(win, None, None)
                win.default_consts = tuple(
                    jnp.asarray(a) for a in (scales, active, wmat, swvec)
                )
            scales_d, active_d, wmat_d, swvec_d = win.default_consts
        else:
            scales, active = _class_scales(win.plan, dst_weights, side="send")
            wmat, swvec = _update_weights(win, self_weight, neighbor_weights)
            scales_d, active_d, wmat_d, swvec_d = (
                jnp.asarray(scales), jnp.asarray(active),
                jnp.asarray(wmat), jnp.asarray(swvec),
            )
        with_p = ctx.win_associated_p_enabled
        wdt = win.dtype if jnp.issubdtype(win.dtype, jnp.inexact) else jnp.float32
        key = ("win_put_update", win.plan, accumulate, with_p, win.dtype,
               win.shape[1:],
               None if meta is None else (meta.treedef, tuple(meta.shapes)))

        def build():
            if meta is None:
                return _build_put_update(win.plan, accumulate, with_p, wdt)
            inner = _build_put_update(win.plan, accumulate, with_p, wdt,
                                      donate=False)
            n = ctx.size

            def f(ls, mail, versions, p_self, p_mail, sc, ac, wm, sw):
                x = _pack_leaves(meta, ls, n, dtype=win.dtype)
                combined, mail, versions, p_mail, p_self = inner(
                    x, mail, versions, p_self, p_mail, sc, ac, wm, sw
                )
                return (combined, mail, versions, p_mail, p_self,
                        _unpack_leaves(meta, combined, n))

            # donate at the outermost jit (nested donation is ignored)
            return jax.jit(f, donate_argnums=(1, 2, 3, 4))

        f = ctx.jit_cache(key, build)
        out = f(
            t, win.mail, win.versions, win.p_self, win.p_mail,
            scales_d, active_d, wmat_d, swvec_d,
        )
        combined, mail, versions, p_mail, p_self = out[:5]
        win.self_tensor = combined
        win.mail, win.versions = mail, versions
        # always reassign: the jit donates the old p buffers, so the
        # previous win.p_mail/p_self are invalid even with with_p off
        # (the returned values are passthroughs in that case)
        win.p_mail, win.p_self = p_mail, p_self
        if reset:
            _reset_mailbox(win)
        if meta is not None:
            return jax.tree_util.tree_unflatten(meta.treedef, out[5])
        return combined


def win_update_then_collect(name: str, require_mutex: bool = False):
    """Collect-style update: self weight 1, every neighbor slot weight 1,
    then reset — the push-sum accumulate-and-drain idiom (reference
    ``bf.win_update_then_collect`` [U]).

    ``require_mutex`` is accepted for parity but has no effect HERE: under
    the bulk-synchronous SPMD emulation the combine and drain execute in
    one compiled program, so no concurrent writer can interleave
    (staleness-0 — the mutex the reference takes is provably redundant).
    The islands runtime, whose writers ARE concurrent, honors the flag
    with a real cross-process mutex (``islands.win_update_then_collect``).
    """
    if require_mutex:
        logger.debug(
            "win_update_then_collect(require_mutex=True): no-op under the "
            "bulk-synchronous emulation (atomic by construction); the "
            "islands runtime takes a real mutex"
        )
    _log_op("win_update_then_collect", name)
    ctx = _ctx()
    win = _win(name)
    ones = [
        {s: 1.0 for s in win.plan.in_neighbors[d]} for d in range(ctx.size)
    ]
    return win_update(name, self_weight=1.0, neighbor_weights=ones, reset=True)


def win_wait(handle) -> bool:
    handle.wait()
    return True


def win_poll(handle) -> bool:
    """Reference ``bf.win_poll`` [U].  May block where the platform has no
    async readiness query (see :meth:`bluefog_tpu.ops.Handle.poll`)."""
    return handle.poll()


@contextlib.contextmanager
def win_mutex(name: str, for_self: bool = False, ranks: Optional[List[int]] = None):
    """No-op shim kept for API parity (reference ``bf.win_mutex`` [U]): the
    mailbox emulation is bulk-synchronous, so slot access is never
    concurrent (SURVEY.md §5.2)."""
    del name, for_self, ranks
    yield


def get_win_version(name: str) -> List[Dict[int, int]]:
    """Per-rank {in_neighbor: deposit_count} (reference
    ``bf.get_win_version`` [U])."""
    win = _win(name)
    ver = np.asarray(win.versions)
    return [
        {s: int(ver[d, k]) for k, s in enumerate(win.plan.in_neighbors[d])}
        for d in range(win.plan.size)
    ]


def win_associated_p(name: str) -> jnp.ndarray:
    """The push-sum associated scalar p per rank (reference
    ``bf.win_associated_p`` [U]).

    Returns a COPY: the window's own p buffer is donated by the next
    window op, so handing out the live reference would leave the caller
    holding a deleted array."""
    return jnp.array(_win(name).p_self)


def win_set_exposed(name: str, tensor, associated_p=None) -> None:
    """Overwrite the window's exposed tensor (and optionally its associated
    p) without a put — the debias-and-restart idiom of push-sum loops, where
    the caller stores x/p back as the new x and resets p to 1.  The reference
    gets this for free because its windows alias the torch tensor [U]; the
    mailbox emulation needs an explicit setter."""
    _log_op("win_set_exposed", name)
    win = _win(name)
    tensor = basics.to_rank_major_global(tensor)
    t = jnp.asarray(_pack_input(name, tensor), dtype=win.dtype)
    if t.shape != win.shape:
        raise ValueError(f"shape {t.shape} != window shape {win.shape}")
    win.self_tensor = t
    if associated_p is not None:
        win.p_self = jnp.broadcast_to(
            jnp.asarray(associated_p, jnp.float32), win.p_self.shape
        )


def turn_on_win_ops_with_associated_p() -> None:
    _ctx().win_associated_p_enabled = True


def turn_off_win_ops_with_associated_p() -> None:
    _ctx().win_associated_p_enabled = False
