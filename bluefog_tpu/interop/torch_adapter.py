"""PyTorch interop: run bluefog_tpu collectives on torch tensors.

Sibling of the reference's second-framework layer (the experimental
``bluefog/tensorflow`` support and the ``bluefog/torch`` adapter that
translates framework tensors to the runtime's tensor abstraction —
SURVEY.md §2.1/§2.2).  Here the translation is zero-copy where possible
(dlpack) and the full eager op surface works on torch CPU tensors: torch in
this environment is CPU-only, so tensors round-trip through the mesh's
device memory around each op.

Usage:
    from bluefog_tpu.interop import torch_adapter as bft
    out = bft.neighbor_allreduce(torch_tensor)   # rank-major torch tensor
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "to_jax",
    "to_torch",
    "allreduce",
    "broadcast",
    "allgather",
    "neighbor_allreduce",
    "neighbor_allgather",
    "hierarchical_neighbor_allreduce",
]


def _torch():
    import torch

    return torch


def to_jax(t) -> jnp.ndarray:
    """torch.Tensor -> jax array.

    Goes through numpy (shares memory with the CPU tensor, one copy to
    device) rather than dlpack: dlpack imports arrive *committed* to a
    single device, which blocks the jit/shard_map resharding the rank-major
    ops rely on.
    """
    torch = _torch()
    if not isinstance(t, torch.Tensor):
        return jnp.asarray(t)
    return jnp.asarray(t.detach().cpu().contiguous().numpy())


def to_torch(a):
    """jax array -> torch.Tensor."""
    torch = _torch()
    try:
        return torch.from_dlpack(a)
    except Exception:
        return torch.from_numpy(np.asarray(a))


def _wrap(op_name: str):
    def fn(tensor, *args, **kwargs):
        from bluefog_tpu import ops

        out = getattr(ops, op_name)(to_jax(tensor), *args, **kwargs)
        return jax.tree_util.tree_map(to_torch, out)

    fn.__name__ = op_name
    fn.__doc__ = f"torch-tensor veneer over bluefog_tpu.ops.{op_name}"
    return fn


allreduce = _wrap("allreduce")
broadcast = _wrap("broadcast")
allgather = _wrap("allgather")
neighbor_allreduce = _wrap("neighbor_allreduce")
neighbor_allgather = _wrap("neighbor_allgather")
hierarchical_neighbor_allreduce = _wrap("hierarchical_neighbor_allreduce")
