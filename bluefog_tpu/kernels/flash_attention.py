"""Flash attention: blockwise XLA forward/backward + a Pallas TPU kernel.

Two interchangeable forwards behind one ``impl`` switch ("auto" default =
the Pallas kernel): a hand Pallas kernel and an online-softmax blockwise
computation in plain XLA (``impl="xla"``).  Forward-only standing (r4
continuation, benchmarks/attention_fwd_ab.py, scan-chain + slope
protocol): the Pallas forward is 4-6x FASTER than the XLA blockwise
forward at 134M/1B/long-context dims (44-82 TF/s vs 9-18; repeatable to
a few % once the slope estimator cancels the constant per-dispatch
tunnel overhead that compressed single-region readings to 1.3-3x).
(The r3-era header claimed the
reverse — XLA ahead 25-35% — measured at 512^2 blocks before the aligned
fast path and packed scalar tiles; the r4 kernel work flipped it, closing
the r3 verdict's "largest known recoverable perf item".)  END-TO-END the
margin is larger still: training with ``impl="xla"`` measured 13x slower
(Llama-134M S=2048: 4.8k vs 63.0k tok/s/chip) — the unrolled blockwise
forward inside the custom-vjp recompute wrecks the backward schedule
under jit — so auto stays Pallas on both lenses.
Both share the custom-VJP blockwise backward and produce identical
(o, lse) contracts; interpret mode always runs the Pallas logic so CPU
tests exercise the kernel.

No sibling in the reference — it has no attention at all (SURVEY.md §2.3) —
but the rebuild's transformer workloads (BERT push-sum fine-tune, Llama
gossip pretraining; BASELINE configs #3/#5) spend their FLOPs here, so the
hot op gets a hand kernel the way the reference hand-codes its hot combine
loops in native code (``nccl_controller.cc`` [U]).

Forward: the standard online-softmax blocking (Dao et al., arXiv:2205.14135;
blockwise form as in Liu et al., arXiv:2310.01889): grid over
``(batch*heads, q_blocks, k_blocks)`` with the k axis innermost, carrying
running max ``m``, normalizer ``l`` and the output accumulator in VMEM
scratch across k iterations — O(T·block) memory instead of O(T²), q/k block
matmuls on the MXU, fp32 accumulation regardless of input dtype.  Causal
masking works on *global* positions: the query/key start offsets ride in as
SMEM scalars, so the same compiled kernel serves the single-device case
(offsets 0) and one hop of ring attention (offsets = rotating block
positions, including fully-masked hops, which predicate away at runtime).

Backward: custom VJP that recomputes per-k-block probabilities from the
saved logsumexp (the flash trick — no O(T²) residuals).  The default is
a PAIR OF PALLAS KERNELS (dK/dV accumulated over q blocks, dQ over k
blocks, probability tiles live only in VMEM): the earlier XLA
``fori_loop`` backward materialized `[BH, T, block_k]` f32 tiles in HBM
per k-block and measured memory-bound — 12.6 ms/block vs ~1 ms
causal-matmul ideal at 134M/S=2048, 79% of block time (STATUS round-3
decomposition); switching to the Pallas backward measured **+15%
end-to-end** on Llama-134M training (72.1k → 83.2k tok/s) and +6% at 1B.
The XLA backward remains behind ``impl="xla"``.  The lse output is
itself differentiable (its cotangent folds into the dS term), which is
what lets ring attention's logsumexp *merge* train end-to-end.

On non-TPU platforms the same kernel runs in Pallas interpret mode (tests
exercise the real kernel logic on the CPU mesh).
"""

from __future__ import annotations

import functools
import math
import os
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from bluefog_tpu.parallel._util import vma_full

__all__ = ["flash_attention", "flash_attention_with_lse", "make_flash_attention_fn"]

_NEG_INF = -1e30  # finite mask sentinel (real scores can never reach it)
_MASK_THRESH = -0.5e30  # "was this entry masked" test after sentinel fill
_LANES = 128
# Total lane width of the per-row-scalar tiles.  The forward's lse
# output uses the full width; the backward packs BOTH scalars (lse, corr)
# into one tile of this width — each gets _SCALAR_LANES/2 lanes — and
# re-reads one such tile per (q-block, k-block) pair.  History (all
# end-to-end interleaved benchmarks/llama.py A/Bs; microbenchmarks
# through this tunnel are useless, spreads >100%):
# - r4, 512^2 blocks: separate 128-lane lse/corr arrays = ~1.8 GB of
#   re-reads per 134M layer (r3 advisor finding); narrowing to 8 lanes
#   measured 3-4% SLOWER (the narrow 512x8 f32 DMA cost more than the
#   fat reads, which fwd+bwd overlap hid); packing both scalars into one
#   128-lane tile (half the bytes, one DMA) measured +1% and shipped.
# - r4 continuation, 1024^2 blocks (the retuned default): the lane
#   conclusion FLIPPED — 8 lanes is +5.1% at 134M (97.7k vs 93.0k tok/s,
#   reproduced 97.8k/97.7k) and +0.9% at 1B (15.60k vs 15.46k): a
#   1024-row scalar tile amortizes the narrow-DMA overhead that the
#   512-row tile could not, and 16x fewer scalar bytes win.  8 ships.
_SCALAR_LANES = int(os.environ.get("BLUEFOG_FLASH_SCALAR_LANES", "8"))
_ALIGNED_ENABLED = os.environ.get("BLUEFOG_FLASH_ALIGNED", "1") != "0"
# Experiment knob (MEASURED NULL, default off): run the kernels' softmax
# recurrences in base-2 (exp2/log2) with scale*log2(e) folded into the q
# operand — the FA2 CUDA trick.  The (o, lse) contract stays natural-log
# (lse converted at kernel finish), so ring merges and the XLA paths are
# unaffected.  Numerics: the folded multiplier is never a power of two, so
# q rounds once in its storage dtype (<= 2^-9 relative on bf16 scores;
# exact-ish on f32/CPU); all CPU-interpret numerics tests pass either way.
# r4 end-to-end A/B (2 interleaved benchmarks/llama.py rounds, 134M,
# 1024^2 blocks): off 92.3/93.0 vs on 92.5/87.6 tok/s — within noise to
# negative; Mosaic's natural exp evidently already lowers to the cheap
# path, so the saved multiply buys nothing on this chip.
_EXP2_ENABLED = os.environ.get("BLUEFOG_FLASH_EXP2", "0") != "0"
# Experiment knob: backward-only block override ("BQxBK", e.g. "512x1024").
# The bwd kernels carry more live VMEM tiles than the forward (p, dp, ds
# alongside q/k/v/do and the packed scalars), so their best block shape
# need not match the forward's; this decouples them for A/B sweeps
# without touching the API.  Empty = backward inherits the forward blocks.
_BWD_BLOCKS = None
if os.environ.get("BLUEFOG_FLASH_BWD_BLOCKS"):
    try:
        _BWD_BLOCKS = tuple(
            int(x) for x in os.environ["BLUEFOG_FLASH_BWD_BLOCKS"].split("x"))
    except ValueError:
        _BWD_BLOCKS = ()  # non-numeric parts get the same diagnostic
    if len(_BWD_BLOCKS) != 2:
        raise ValueError(
            "BLUEFOG_FLASH_BWD_BLOCKS must be 'BQxBK' (e.g. '512x1024'), "
            f"got {os.environ['BLUEFOG_FLASH_BWD_BLOCKS']!r}")
_LOG2E = math.log2(math.e)
_LN2 = math.log(2.0)
_MAX_UNROLL = 64  # triangular fast paths unroll at most this many k blocks


def _kexp(x):
    """exp in the kernel's score space (base-2 when _EXP2_ENABLED)."""
    return jnp.exp2(x) if _EXP2_ENABLED else jnp.exp(x)


def _score_operand(q, dtype, scale):
    """The q matmul operand with the softmax scale folded where possible.

    Returns ``(q_operand, scale_scores)``: under exp2 mode scale*log2(e)
    always folds into q (one D-wide pass; rounds q once in its storage
    dtype); otherwise an exact power-of-two scale folds losslessly; any
    other scale stays on the f32 scores (``scale_scores=True``) —
    shared by the forward and both backward kernels."""
    if _EXP2_ENABLED:
        return q * jnp.asarray(scale * _LOG2E, dtype), False
    if _scale_folds_exactly(scale):
        return q * jnp.asarray(scale, dtype), False
    return q, True


def _lse_in_score_space(lse):
    """Natural-log lse converted to the kernel's score space (base-2
    under exp2 mode) for the backward recompute ``p = exp(s - lse)``."""
    return lse * _LOG2E if _EXP2_ENABLED else lse


def _use_triangular(causal, tri_delta, tq, tk, num_k):
    """Shared gate for the fwd/bwd triangular fast paths: static offsets
    with a small non-negative key-ahead delta (0 = aligned; 1 = the striped
    ring's strict-lower-triangle hops), square shapes, bounded unroll."""
    return (causal and tri_delta is not None and tq == tk
            and num_k <= _MAX_UNROLL)


def _tri_mask(rows, block_k, delta=0):
    """Causal mask for a q-row slice starting exactly at the k block, with
    keys shifted ``delta`` positions ahead (visible iff col + delta <= row)."""
    return jnp.arange(rows)[:, None] >= jnp.arange(block_k)[None, :] + delta


def _default_interpret() -> bool:
    platform = jax.devices()[0].platform
    return platform not in ("tpu", "axon")


def _block_spec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


def _default_blocks(tq, tk, block_q, block_k):
    """Sequence-adaptive block defaults, measured on v5e fwd+bwd.

    History: 512x512 measured fastest at T=2048 in round 2 (12.4->9.8 ms
    vs 256x256) and 1024x1024 won only at T>=8192 (30.1 vs 41.1 ms) — but
    that tuning predates the aligned fast path (interior causal tiles now
    run ZERO mask VPU work), which shifts the balance toward bigger tiles:
    re-measured END-TO-END in round 4 with the aligned path, 1024x1024 at
    T=2048 is +14% on Llama-134M training (81.8k -> 93.2k tok/s, D=64,
    interleaved same-session) and +7% on Llama-1B (14.06k -> 15.03k,
    D=128).  2048x2048 fails to compile (a [2048, 2048] f32 score tile
    plus accumulators exceeds what Mosaic will carry).  So: 1024 whenever
    the sequence admits it, 512 below."""
    big = max(tq, tk) >= 2048
    if block_q is None:
        block_q = 1024 if big else 512
    if block_k is None:
        block_k = 1024 if big else 512
    return block_q, block_k


def _fit_block(t, b):
    """Largest power-of-two shrink of ``b`` that divides sequence length
    ``t`` (capped at ``t`` itself), so default block sizes adapt to short or
    odd shards instead of raising.  Lengths whose largest fitting block is
    degenerate (< 8 sublanes, e.g. odd primes) still raise loudly — a
    near-1-row Pallas grid would be pathologically slow or fail Mosaic
    layout opaquely."""
    b = min(b, t)
    while t % b and b > 1:
        b = max(b // 2, 1)
    if b < 8 and b < t:
        raise ValueError(
            f"no block size >= 8 divides sequence length {t} (best fit {b}); "
            f"pad the sequence/shard to a multiple of 8"
        )
    return b


def _out_struct(shape, dtype, operands):
    """ShapeDtypeStruct whose varying-mesh-axes set is the union of the
    operands' (required under shard_map's vma checking; empty outside)."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in operands))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):  # older jax: no vma tracking
        return jax.ShapeDtypeStruct(shape, dtype)


def _scale_folds_exactly(scale: float) -> bool:
    """True when ``scale`` is a power of two — folding it into a bf16
    operand is then an exact exponent shift (head dim a power of 4, e.g.
    D=64 -> 1/8).  Otherwise folding would round q*scale to bf16 and the
    scale stays on the f32 scores."""
    m, _ = math.frexp(scale)
    return scale > 0 and m == 0.5


def _aligned_mask(s, block_q, block_k, delta):
    """Cheap diagonal-tile causal mask for the aligned (static-offset) fast
    path: one broadcast compare of a [bq,1] row iota against a [1,bk]
    column iota, instead of two full-tile 2D iotas + add + compare.
    Visible iff col + delta <= row (delta 0 = aligned; 1 = the striped
    ring's strict-lower-triangle hops)."""
    row = lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    col = lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    return jnp.where(col + delta <= row, s, _NEG_INF)


def _fwd_kernel(qs_ref, ks_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_ref, l_ref,
                *, scale: float, block_q: int, block_k: int, causal: bool,
                num_k: int, aligned_delta):
    """One (bh, iq, jk) program: fold k-block jk into the online softmax.

    ``aligned_delta`` (static int or None) enables the aligned fast path:
    offsets are statically equal (+delta), so interior tiles (jk < iq) run
    with NO mask VPU work at all, diagonal tiles get the cheap broadcast
    mask, and the sentinel-row fixup exists only when a fully-masked row is
    actually possible (delta > 0).  The earlier uniform-kernel note ("a
    lax.cond skipping the mask measured slower") held for a runtime-offset
    cond inside one body; the static split compiles two bodies and measured
    faster (see module docstring history).
    """
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc[...] = jnp.zeros_like(acc)

    def _body(masked):
        # operands stay in their storage dtype (bf16 on TPU — full-rate MXU
        # passes); fp32 happens only in the accumulator via
        # preferred_element_type.  Casting to fp32 first would force the
        # MXU's slow fp32 path and make the kernel slower than dense XLA.
        # Scale folding: see _score_operand.
        q, scale_scores = _score_operand(q_ref[0], q_ref.dtype, scale)
        k = k_ref[0]  # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k] fp32 (base-2 space under _EXP2_ENABLED)
        if scale_scores:
            s = s * scale
        sentinel_rows = False
        if masked:
            if aligned_delta is None:
                qpos = qs_ref[0, 0] + iq * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                kpos = ks_ref[0, 0] + jk * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1
                )
                s = jnp.where(kpos <= qpos, s, _NEG_INF)
                sentinel_rows = True  # dynamic offsets: fully-masked rows possible
            else:
                s = _aligned_mask(s, block_q, block_k, aligned_delta)
                # delta == 0: every row of a diagonal tile sees >= 1 key,
                # masked entries underflow to 0 through exp(s - m_new)
                sentinel_rows = aligned_delta > 0
        m_prev = m_ref[:, :1]  # [block_q, 1] (replicated columns)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = _kexp(m_prev - m_new)  # [block_q, 1]
        p = _kexp(s - m_new)  # [block_q, block_k]
        if sentinel_rows:
            # fully-masked rows have m_new == sentinel and would otherwise
            # contribute exp(0) == 1 per entry
            p = jnp.where(s > _MASK_THRESH, p, 0.0)
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal and aligned_delta is not None:
        pl.when(jk < iq)(lambda: _body(False))
        pl.when(jk == iq)(lambda: _body(True))
    elif causal:
        # predicate away k blocks entirely above the diagonal (runtime skip:
        # the offsets are dynamic, so this can't prune at compile time)
        first_k = ks_ref[0, 0] + jk * block_k
        last_q = qs_ref[0, 0] + (iq + 1) * block_q - 1
        pl.when(first_k <= last_q)(lambda: _body(True))
    else:
        _body(False)

    @pl.when(jk == num_k - 1)
    def _finish():
        l = l_ref[:, :1]
        o_ref[0] = (acc[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        # lse contract is natural-log regardless of the kernel's score
        # space: base-2 m converts via ln(2)
        m_fin = m_ref[:, :_SCALAR_LANES]
        if _EXP2_ENABLED:
            m_fin = m_fin * _LN2
        lse = m_fin + jnp.log(jnp.maximum(l_ref[:, :_SCALAR_LANES], 1e-30))
        lse_ref[0] = lse.astype(jnp.float32)


def _aligned_or_none(tri_delta, causal, tq, tk, block_q, block_k):
    """The Pallas aligned fast path needs: causal, statically-equal offsets
    (+delta <= 1), square shapes, and equal block sizes (tile (i, j) sits
    exactly on the diagonal iff i == j).  delta <= 1 is load-bearing: the
    path leaves interior tiles (jk < iq) UNMASKED, which is exactly valid
    for delta 0 (aligned) and 1 (the striped ring's strict lower
    triangle); at delta >= 2 the last key of tile iq-1 would be a future
    position for the first row of q block iq.  Larger static deltas fall
    back to the general masked path."""
    if (_ALIGNED_ENABLED and causal and tri_delta is not None
            and tri_delta <= 1 and tq == tk and block_q == block_k):
        return tri_delta
    return None


def _flash_fwd(q, k, v, q_start, k_start, *, scale, causal, block_q, block_k,
               interpret, tri_delta=None):
    """q,k,v: [BH, T, D]; q_start/k_start: int32 scalars (global offsets).

    Returns (o [BH, Tq, D], lse [BH, Tq]).
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q, block_k = _default_blocks(tq, tk, block_q, block_k)
    block_q = _fit_block(tq, block_q)
    block_k = _fit_block(tk, block_k)
    num_q, num_k = tq // block_q, tk // block_k

    qs = jnp.asarray(q_start, jnp.int32).reshape(1, 1)
    ks = jnp.asarray(k_start, jnp.int32).reshape(1, 1)
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        num_k=num_k,
        aligned_delta=_aligned_or_none(tri_delta, causal, tq, tk,
                                       block_q, block_k),
    )
    smem = pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                        memory_space=pltpu.SMEM)
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh, num_q, num_k),
        in_specs=[
            smem,
            smem,
            _block_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _block_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            _block_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            _block_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _block_spec((1, block_q, _SCALAR_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, tq, d), q.dtype, (q, k, v)),
            _out_struct((bh, tq, _SCALAR_LANES), jnp.float32, (q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qs, ks, q, k, v)
    return o, lse[:, :, 0]


def _blockwise_fwd_xla(q, k, v, q_start, k_start, *, scale, causal, block_k,
                       tri_delta):
    """Online-softmax blockwise forward in plain XLA; same math and
    (o, lse) contract as the Pallas kernel.

    Selectable via ``impl="xla"``.  At the r3-era 512^2 blocks it beat
    the hand kernel forward-only by ~25-35%; after the r4 aligned fast
    path + 1024^2 retune the Pallas forward is 4-6x FASTER
    (benchmarks/attention_fwd_ab.py, slope protocol), and inside the custom-vjp's
    backward recompute this path measured 13x slower end-to-end on Llama
    training — so it is NOT the auto default on either lens.  Kept as
    the independent same-contract implementation (numerics cross-check,
    non-Mosaic fallback).
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    _, block_k = _default_blocks(tq, tk, None, block_k)
    block_k = _fit_block(tk, block_k)
    num_k = tk // block_k
    f32 = functools.partial(jnp.einsum, preferred_element_type=jnp.float32)

    if _use_triangular(causal, tri_delta, tq, tk, num_k):
        # triangular unroll: k block j touches only q rows >= j*block_k
        o = vma_full(q, q.shape, jnp.float32)
        m = vma_full(q, (bh, tq, 1), jnp.float32, _NEG_INF)
        l = vma_full(q, (bh, tq, 1), jnp.float32)
        for j in range(num_k):
            r0 = j * block_k
            kb, vb = k[:, r0:r0 + block_k], v[:, r0:r0 + block_k]
            s = f32("bqd,bkd->bqk", q[:, r0:], kb) * scale
            s = jnp.where(_tri_mask(tq - r0, block_k, tri_delta)[None], s,
                          _NEG_INF)
            m_new = jnp.maximum(m[:, r0:], s.max(-1, keepdims=True))
            alpha = jnp.exp(m[:, r0:] - m_new)
            p = jnp.exp(s - m_new)  # masked entries underflow to 0...
            if tri_delta:
                # ...except on fully-masked rows (rows < delta), where
                # m_new is the sentinel and exp(0) would be 1
                p = jnp.where(s > _MASK_THRESH, p, 0.0)
            l = l.at[:, r0:].set(l[:, r0:] * alpha + p.sum(-1, keepdims=True))
            o = o.at[:, r0:].set(
                o[:, r0:] * alpha + f32("bqk,bkd->bqd", p.astype(v.dtype), vb)
            )
            m = m.at[:, r0:].set(m_new)
    else:
        qpos = q_start + jnp.arange(tq)

        def body(j, carry):
            o, m, l = carry
            kb = lax.dynamic_slice_in_dim(k, j * block_k, block_k, axis=1)
            vb = lax.dynamic_slice_in_dim(v, j * block_k, block_k, axis=1)
            s = f32("bqd,bkd->bqk", q, kb) * scale
            if causal:
                kpos = k_start + j * block_k + jnp.arange(block_k)
                s = jnp.where((kpos[None, :] <= qpos[:, None])[None], s,
                              _NEG_INF)
            m_new = jnp.maximum(m, s.max(-1, keepdims=True))
            alpha = jnp.exp(m - m_new)
            p = jnp.exp(s - m_new)
            if causal:
                # fully-masked rows: m_new is the sentinel, exp(0) would be 1
                p = jnp.where(s > _MASK_THRESH, p, 0.0)
            l = l * alpha + p.sum(-1, keepdims=True)
            o = o * alpha + f32("bqk,bkd->bqd", p.astype(v.dtype), vb)
            return o, m_new, l

        o, m, l = lax.fori_loop(
            0, num_k,
            body,
            (vma_full(q, q.shape, jnp.float32),
             vma_full(q, (bh, tq, 1), jnp.float32, _NEG_INF),
             vma_full(q, (bh, tq, 1), jnp.float32)),
        )

    out = (o / jnp.maximum(l, 1e-30)).astype(q.dtype)
    lse = (m + jnp.log(jnp.maximum(l, 1e-30)))[..., 0]
    return out, lse


def _bwd_dkv_kernel(qs_ref, ks_ref, q_ref, g_ref, aux_ref,
                    k_ref, v_ref, dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale: float, block_q: int, block_k: int,
                    causal: bool, num_q: int, aligned_delta, half: int):
    """One (bh, jk, iq) program: fold q-block iq into dK/dV of k-block jk.

    Same recompute-from-lse trick as the XLA backward, but the
    [block_q, block_k] probability/score tiles live and die in VMEM —
    the XLA path materializes them per k-block in HBM, which is why the
    backward measured memory-bound (docs/STATUS.md round-3 decomposition).
    ``aligned_delta``: see :func:`_fwd_kernel`.  ``aux_ref`` packs the two
    per-row scalars in one tile (lse in lanes [:half], corr in [half:]) —
    one scalar DMA per grid step instead of two.
    """
    jk = pl.program_id(1)
    iq = pl.program_id(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _body(masked):
        q = q_ref[0]  # [block_q, D]
        g = g_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]  # [block_k, D]
        lse = _lse_in_score_space(aux_ref[0][:, :1])  # [block_q, 1]
        corr = aux_ref[0][:, half:half + 1]
        qk, scale_scores = _score_operand(q, q_ref.dtype, scale)
        s = jax.lax.dot_general(
            qk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k] fp32
        if scale_scores:
            s = s * scale
        if masked:
            if aligned_delta is None:
                qpos = qs_ref[0, 0] + iq * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kpos = ks_ref[0, 0] + jk * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(kpos <= qpos, s, _NEG_INF)
            else:
                s = _aligned_mask(s, block_q, block_k, aligned_delta)
            # masked entries (and whole sentinel-lse rows) exp to exactly 0
            p = _kexp(jnp.where(s > _MASK_THRESH, s - lse, _NEG_INF))
        else:
            # interior tile: nothing is masked and (aligned path) no
            # sentinel-lse row can appear here — plain recompute
            p = _kexp(s - lse)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # ds stays UNSCALED per tile; scale multiplies the f32 accumulator
        # once at _finish (a [block_k, D] pass instead of a
        # [block_q, block_k] pass per tile — exact, any scale)
        ds = (p * (dp + corr)).astype(q.dtype)
        dk_acc[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal and aligned_delta is not None:
        pl.when(iq > jk)(lambda: _body(False))
        pl.when(iq == jk)(lambda: _body(True))
    elif causal:
        # skip q blocks entirely above the diagonal (they reach no k row)
        last_q = qs_ref[0, 0] + (iq + 1) * block_q - 1
        first_k = ks_ref[0, 0] + jk * block_k
        pl.when(last_q >= first_k)(lambda: _body(True))
    else:
        _body(False)

    @pl.when(iq == num_q - 1)
    def _finish():
        dk_ref[0] = (dk_acc[...] * scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(qs_ref, ks_ref, q_ref, g_ref, aux_ref,
                   k_ref, v_ref, dq_ref, dq_acc,
                   *, scale: float, block_q: int, block_k: int,
                   causal: bool, num_k: int, aligned_delta, half: int):
    """One (bh, iq, jk) program: fold k-block jk into dQ of q-block iq.
    ``aligned_delta``: see :func:`_fwd_kernel`; ``aux_ref``/``half``: see
    :func:`_bwd_dkv_kernel`."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _body(masked):
        q = q_ref[0]
        g = g_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        lse = _lse_in_score_space(aux_ref[0][:, :1])
        corr = aux_ref[0][:, half:half + 1]
        qk, scale_scores = _score_operand(q, q_ref.dtype, scale)
        s = jax.lax.dot_general(
            qk, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if scale_scores:
            s = s * scale
        if masked:
            if aligned_delta is None:
                qpos = qs_ref[0, 0] + iq * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                kpos = ks_ref[0, 0] + jk * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                s = jnp.where(kpos <= qpos, s, _NEG_INF)
            else:
                s = _aligned_mask(s, block_q, block_k, aligned_delta)
            p = _kexp(jnp.where(s > _MASK_THRESH, s - lse, _NEG_INF))
        else:
            p = _kexp(s - lse)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # unscaled ds; scale applied once to the accumulator at _finish
        ds = (p * (dp + corr)).astype(q.dtype)
        dq_acc[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal and aligned_delta is not None:
        pl.when(jk < iq)(lambda: _body(False))
        pl.when(jk == iq)(lambda: _body(True))
    elif causal:
        first_k = ks_ref[0, 0] + jk * block_k
        last_q = qs_ref[0, 0] + (iq + 1) * block_q - 1
        pl.when(first_k <= last_q)(lambda: _body(True))
    else:
        _body(False)

    @pl.when(jk == num_k - 1)
    def _finish():
        dq_ref[0] = (dq_acc[...] * scale).astype(dq_ref.dtype)


def _flash_bwd_pallas(q, k, v, lse, corr, q_start, k_start, g,
                      *, scale, causal, block_q, block_k, interpret,
                      tri_delta=None):
    """dQ/dK/dV via two Pallas kernels; all [BH, T, D].

    ``corr`` is ``g_lse − rowsum(o·g)`` per q row (f32, [BH, Tq]) — the
    dS correction term, precomputed once in XLA.
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q, block_k = _default_blocks(tq, tk, block_q, block_k)
    block_q = _fit_block(tq, block_q)
    block_k = _fit_block(tk, block_k)
    num_q, num_k = tq // block_q, tk // block_k
    aligned = _aligned_or_none(tri_delta, causal, tq, tk, block_q, block_k)

    qs = jnp.asarray(q_start, jnp.int32).reshape(1, 1)
    ks = jnp.asarray(k_start, jnp.int32).reshape(1, 1)
    # per-row scalars ride lane-replicated, PACKED in one array (lse in
    # lanes [:half], corr in [half:]): the packed tile is the SAME width
    # as ONE of the old separate lse/corr tiles, so each (q-block,
    # k-block) grid step reads half the scalar bytes in one DMA instead
    # of two (the separate 128-lane arrays measured ~1.8 GB of re-reads
    # per 134M layer, r3 advisor finding)
    half = max(_SCALAR_LANES // 2, 1)
    aux = jnp.concatenate(
        [jnp.broadcast_to(lse[..., None], (bh, tq, half)),
         jnp.broadcast_to(corr[..., None], (bh, tq, half))], axis=-1)

    smem = pl.BlockSpec((1, 1), lambda *_: (0, 0), memory_space=pltpu.SMEM)

    def rowspec(index):  # q/g/aux blocks, selected by the q index
        return [
            _block_spec((1, block_q, d), lambda b, x, y: (b, index(x, y), 0)),
            _block_spec((1, block_q, d), lambda b, x, y: (b, index(x, y), 0)),
            _block_spec((1, block_q, 2 * half),
                        lambda b, x, y: (b, index(x, y), 0)),
        ]

    def kvspec(index):  # k/v blocks, selected by the k index
        return [
            _block_spec((1, block_k, d), lambda b, x, y: (b, index(x, y), 0)),
            _block_spec((1, block_k, d), lambda b, x, y: (b, index(x, y), 0)),
        ]

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, block_q=block_q, block_k=block_k,
            causal=causal, num_q=num_q, aligned_delta=aligned, half=half),
        grid=(bh, num_k, num_q),
        in_specs=[smem, smem,
                  *rowspec(lambda j, i: i), *kvspec(lambda j, i: j)],
        out_specs=[
            _block_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            _block_spec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            _out_struct((bh, tk, d), k.dtype, (q, k, v, g)),
            _out_struct((bh, tk, d), v.dtype, (q, k, v, g)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qs, ks, q, g, aux, k, v)

    dq, = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, block_q=block_q, block_k=block_k,
            causal=causal, num_k=num_k, aligned_delta=aligned, half=half),
        grid=(bh, num_q, num_k),
        in_specs=[smem, smem,
                  *rowspec(lambda i, j: i), *kvspec(lambda i, j: j)],
        out_specs=[
            _block_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            _out_struct((bh, tq, d), q.dtype, (q, k, v, g)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qs, ks, q, g, aux, k, v)
    return dq, dk, dv


def _blockwise_bwd(q, k, v, o, lse, q_start, k_start, g, g_lse,
                   *, scale, causal, block_k, tri_delta=None):
    """dQ/dK/dV via per-k-block recompute from lse; all [BH, T, D].

    ``g_lse`` is the lse output's cotangent: d lse/d s is the normalized
    probability row, so it folds into dS as ``p * g_lse`` (used by ring
    attention's merge; zeros for plain attention).  ``tri_delta`` (static
    int or None) asserts static offsets with key-ahead delta and tq == tk,
    enabling the triangular fast path.
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    _, block_k = _default_blocks(tq, tk, None, block_k)
    block_k = _fit_block(tk, block_k)  # must cover tk exactly, like forward
    num_k = tk // block_k
    # matmul operands stay in their storage dtype (bf16 on TPU) with fp32
    # accumulators — casting up first would force the MXU's slow fp32 path;
    # only elementwise softmax math runs in fp32
    f32 = functools.partial(jnp.einsum, preferred_element_type=jnp.float32)
    delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, Tq, 1]
    corr = g_lse.astype(jnp.float32)[..., None] - delta  # [BH, Tq, 1]

    if _use_triangular(causal, tri_delta, tq, tk, num_k):
        # Triangular fast path: with zero offsets, k block j only reaches q
        # rows >= j*block_k — static slicing halves the causal bwd FLOPs
        # that the dynamic fori_loop below must spend on fully-masked rows.
        dq = q.astype(jnp.float32) * 0.0
        dks, dvs = [], []
        for j in range(num_k):
            r0 = j * block_k
            kb, vb = k[:, r0:r0 + block_k], v[:, r0:r0 + block_k]
            qj, gj = q[:, r0:], g[:, r0:]
            s = f32("bqd,bkd->bqk", qj, kb) * scale
            s = jnp.where(_tri_mask(tq - r0, block_k, tri_delta)[None], s,
                          _NEG_INF)
            p = jnp.exp(s - lse[:, r0:, None])  # masked entries underflow to 0
            if tri_delta:
                # fully-masked rows have sentinel lse: exp would explode
                p = jnp.where(s > _MASK_THRESH, p, 0.0)
            dvs.append(f32("bqk,bqd->bkd", p.astype(gj.dtype), gj))
            dp = f32("bqd,bkd->bqk", gj, vb)
            ds = (p * (dp + corr[:, r0:]) * scale).astype(q.dtype)
            dq = dq.at[:, r0:].add(f32("bqk,bkd->bqd", ds, kb))
            dks.append(f32("bqk,bqd->bkd", ds, qj))
        dk = jnp.concatenate(dks, axis=1)
        dv = jnp.concatenate(dvs, axis=1)
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    qpos = q_start + jnp.arange(tq)

    def body(j, carry):
        dq, dk, dv = carry
        kb = lax.dynamic_slice_in_dim(k, j * block_k, block_k, axis=1)
        vb = lax.dynamic_slice_in_dim(v, j * block_k, block_k, axis=1)
        s = f32("bqd,bkd->bqk", q, kb) * scale
        if causal:
            kpos = k_start + j * block_k + jnp.arange(block_k)
            mask = kpos[None, :] <= qpos[:, None]
            s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # normalized probs [BH, Tq, block_k]
        if causal:
            p = jnp.where(s[...] > _MASK_THRESH, p, 0.0)
        dvb = f32("bqk,bqd->bkd", p.astype(g.dtype), g)
        dp = f32("bqd,bkd->bqk", g, vb)
        ds = (p * (dp + corr) * scale).astype(q.dtype)
        dq = dq + f32("bqk,bkd->bqd", ds, kb)
        dkb = f32("bqk,bqd->bkd", ds, q)
        dk = lax.dynamic_update_slice_in_dim(dk, dkb, j * block_k, axis=1)
        dv = lax.dynamic_update_slice_in_dim(dv, dvb, j * block_k, axis=1)
        return dq, dk, dv

    # fp32 carries vma-typed like the operands
    init = tuple(vma_full(x, x.shape, jnp.float32) for x in (q, k, v))
    dq, dk, dv = lax.fori_loop(0, num_k, body, init)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _fwd_dispatch(q, k, v, q_start, k_start, *, scale, causal, block_q,
                  block_k, interpret, tri_delta, impl):
    """Choose the forward implementation (static): "pallas", "xla", or
    "auto" (= Pallas kernel; "xla" remains selectable).

    Auto history: at the r3-era 512^2 blocks the XLA blockwise forward
    won a forward-only microbenchmark by ~25-35% and auto briefly
    pointed at it — but END-TO-END TRAINING with it measured 13x slower
    on the Llama-134M S=2048 benchmark (4.8k vs 63.0k tok/s/chip): under
    jit the unrolled per-block forward inside the custom-vjp recompute
    blows up the backward's schedule.  (Post-r4-retune the forward-only
    comparison reversed too — Pallas 4-6x faster,
    benchmarks/attention_fwd_ab.py.)  Training throughput is the
    headline workload, so auto = Pallas; forward-heavy callers can still
    pass impl="xla"."""
    use_xla = impl == "xla"
    if use_xla:
        return _blockwise_fwd_xla(
            q, k, v, q_start, k_start,
            scale=scale, causal=causal, block_k=block_k, tri_delta=tri_delta,
        )
    return _flash_fwd(
        q, k, v, q_start, k_start,
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, tri_delta=tri_delta,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_core(q, k, v, q_start, k_start, scale, causal, block_q, block_k,
                interpret, tri_delta, impl):
    """(o, lse) with offsets as float32 scalars (zero-cotangent slots)."""
    return _fwd_dispatch(
        q, k, v, q_start.astype(jnp.int32), k_start.astype(jnp.int32),
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, tri_delta=tri_delta, impl=impl,
    )


def _flash_core_fwd(q, k, v, q_start, k_start, scale, causal, block_q,
                    block_k, interpret, tri_delta, impl):
    o, lse = _fwd_dispatch(
        q, k, v, q_start.astype(jnp.int32), k_start.astype(jnp.int32),
        scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, tri_delta=tri_delta, impl=impl,
    )
    return (o, lse), (q, k, v, o, lse, q_start, k_start)


def _flash_core_bwd(scale, causal, block_q, block_k, interpret, tri_delta,
                    impl, res, cts):
    q, k, v, o, lse, q_start, k_start = res
    g, g_lse = cts
    if impl == "xla":
        dq, dk, dv = _blockwise_bwd(
            q, k, v, o, lse,
            q_start.astype(jnp.int32), k_start.astype(jnp.int32), g, g_lse,
            scale=scale, causal=causal, block_k=block_k, tri_delta=tri_delta,
        )
    else:
        # Pallas backward (default): probability/score tiles stay in VMEM.
        # The XLA blockwise backward materialized them per k-block in HBM
        # and measured memory-bound — 12.6 ms/block vs ~1 ms causal-matmul
        # ideal at 134M/S=2048, 79% of block time (STATUS round-3
        # decomposition); the "Mosaic backward deprioritized" round-1 note
        # is superseded by that measurement.
        delta = jnp.sum(o.astype(jnp.float32) * g.astype(jnp.float32),
                        axis=-1)  # [BH, Tq]
        corr = g_lse.astype(jnp.float32) - delta
        bwd_bq, bwd_bk = (_BWD_BLOCKS if _BWD_BLOCKS is not None
                          else (block_q, block_k))
        dq, dk, dv = _flash_bwd_pallas(
            q, k, v, lse, corr,
            q_start.astype(jnp.int32), k_start.astype(jnp.int32), g,
            scale=scale, causal=causal, block_q=bwd_bq, block_k=bwd_bk,
            interpret=interpret, tri_delta=tri_delta,
        )
    return dq, dk, dv, jnp.zeros_like(q_start), jnp.zeros_like(k_start)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention_with_lse(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    q_start=0,
    k_start=0,
    causal: bool = True,
    block_q: Optional[int] = None,  # None: sequence-adaptive (see _default_blocks)
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    impl: str = "auto",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(out, lse) for q, k, v of shape ``[B, T, H, D]``; lse ``[B, H, T]``.

    ``q_start``/``k_start`` are *global* sequence offsets (may be traced),
    letting causal masking span sequence shards — one hop of ring attention
    calls this with the rotating key-block offset.  Rows with no visible
    keys return out=0, lse≈-1e30, which merge correctly.

    ``impl``: "auto" (default = the Pallas kernel — see module docstring
    for the measured 13x training-throughput gap vs "xla"), "xla", or
    "pallas".  ``block_q`` only affects the Pallas kernel; the XLA path
    blocks on ``block_k`` alone.
    """
    if impl not in ("auto", "xla", "pallas"):
        raise ValueError(f"impl must be auto/xla/pallas, got {impl!r}")
    if interpret is None:
        interpret = _default_interpret()
    b, tq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def fold(x):  # [B, T, H, D] -> [B*H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    # static offsets with a small key-ahead delta + square shapes unlock
    # the triangular fast paths (delta 0 = aligned; delta 1 = the striped
    # ring's strict-lower-triangle hops)
    tri_delta = None
    if (isinstance(q_start, int) and isinstance(k_start, int)
            and 0 <= k_start - q_start <= 8 and q.shape[1] == k.shape[1]):
        tri_delta = k_start - q_start
    o, lse = _flash_core(
        fold(q), fold(k), fold(v),
        jnp.asarray(q_start, jnp.float32), jnp.asarray(k_start, jnp.float32),
        scale, causal, block_q, block_k, interpret, tri_delta, impl,
    )
    o = o.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
    return o, lse.reshape(b, h, tq)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: Optional[int] = None,  # None: sequence-adaptive (see _default_blocks)
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    impl: str = "auto",
) -> jnp.ndarray:
    """Memory-efficient exact attention; q, k, v: ``[B, T, H, D]``.

    Drop-in for :func:`bluefog_tpu.models.transformer.dense_attention`
    (same layout/semantics, fp32 softmax), O(T·block) memory.
    """
    o, _ = flash_attention_with_lse(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret, impl=impl,
    )
    return o


def make_flash_attention_fn(
    causal: bool = True,
    block_q: Optional[int] = None,  # None: sequence-adaptive (see _default_blocks)
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    impl: str = "auto",
) -> Callable:
    """``attention_fn`` for :class:`bluefog_tpu.models.transformer.LlamaLM`."""
    return functools.partial(
        flash_attention,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        impl=impl,
    )
