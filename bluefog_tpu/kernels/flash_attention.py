"""Flash attention as a Pallas TPU kernel (forward) + blockwise XLA backward.

No sibling in the reference — it has no attention at all (SURVEY.md §2.3) —
but the rebuild's transformer workloads (BERT push-sum fine-tune, Llama
gossip pretraining; BASELINE configs #3/#5) spend their FLOPs here, so the
hot op gets a hand kernel the way the reference hand-codes its hot combine
loops in native code (``nccl_controller.cc`` [U]).

Forward: the standard online-softmax blocking (Dao et al., arXiv:2205.14135;
blockwise form as in Liu et al., arXiv:2310.01889): grid over
``(batch*heads, q_blocks, k_blocks)`` with the k axis innermost, carrying
running max ``m``, normalizer ``l`` and the output accumulator in VMEM
scratch across k iterations — O(T·block) memory instead of O(T²), q/k block
matmuls on the MXU, fp32 accumulation regardless of input dtype.  Causal
grids skip fully-masked k blocks via ``pl.when`` predication.

Backward: custom VJP that recomputes per-k-block probabilities from the
saved logsumexp (the flash trick — no O(T²) residuals) and accumulates
dQ/dK/dV with a ``lax.fori_loop`` of plain XLA matmuls.  Recompute-based
backward keeps memory O(T·block) and lets XLA fuse/schedule; a full Mosaic
backward kernel is a later optimization, not a semantic change.

On non-TPU platforms the same kernel runs in Pallas interpret mode (tests
exercise the real kernel logic on the CPU mesh).
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "make_flash_attention_fn"]

_NEG_INF = -1e30  # finite sentinel: keeps exp() exact zeros without nan traps


def _default_interpret() -> bool:
    platform = jax.devices()[0].platform
    return platform not in ("tpu", "axon")


def _block_spec(shape, index_map):
    return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m_ref, l_ref,
                *, scale: float, block_q: int, block_k: int, causal: bool,
                num_k: int):
    """One (bh, iq, jk) program: fold k-block jk into the online softmax."""
    iq = pl.program_id(1)
    jk = pl.program_id(2)

    @pl.when(jk == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc[...] = jnp.zeros_like(acc)

    def _body():
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        k = k_ref[0].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0].astype(jnp.float32)  # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]
        if causal:
            qpos = iq * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = jk * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(kpos <= qpos, s, _NEG_INF)
        m_prev = m_ref[:, :1]  # [block_q, 1] (replicated columns)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        p = jnp.exp(s - m_new)  # [block_q, block_k]
        l_new = l_ref[:, :1] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc[...] = acc[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # skip k blocks entirely above the diagonal
        pl.when(jk * block_k <= (iq + 1) * block_q - 1)(_body)
    else:
        _body()

    @pl.when(jk == num_k - 1)
    def _finish():
        l = l_ref[:, :1]
        safe_l = jnp.maximum(l, 1e-30)
        o_ref[0] = (acc[...] / safe_l).astype(o_ref.dtype)
        lse = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
        lse_ref[0] = lse.astype(jnp.float32)


def _flash_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """q,k,v: [BH, T, D] -> (o [BH, T, D], lse [BH, T, LANES])."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        raise ValueError(
            f"sequence lengths ({tq}, {tk}) must divide by blocks "
            f"({block_q}, {block_k})"
        )
    num_q, num_k = tq // block_q, tk // block_k
    lanes = 128

    grid = (bh, num_q, num_k)
    kernel = functools.partial(
        _fwd_kernel,
        scale=scale,
        block_q=block_q,
        block_k=block_k,
        causal=causal,
        num_k=num_k,
    )
    scratch = [
        pltpu.VMEM((block_q, d), jnp.float32),
        pltpu.VMEM((block_q, lanes), jnp.float32),
        pltpu.VMEM((block_q, lanes), jnp.float32),
    ]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _block_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _block_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            _block_spec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            _block_spec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _block_spec((1, block_q, lanes), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, lanes), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, k, v)
    return o, lse[:, :, 0]


def _blockwise_bwd(q, k, v, o, lse, g, *, scale, causal, block_k):
    """dQ/dK/dV via per-k-block recompute from lse; all [BH, T, D] fp32."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_k = min(block_k, tk)
    num_k = tk // block_k
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    of, gf = o.astype(jnp.float32), g.astype(jnp.float32)
    delta = jnp.sum(of * gf, axis=-1, keepdims=True)  # [BH, Tq, 1]
    qpos = jnp.arange(tq)

    def body(j, carry):
        dq, dk, dv = carry
        kb = lax.dynamic_slice_in_dim(kf, j * block_k, block_k, axis=1)
        vb = lax.dynamic_slice_in_dim(vf, j * block_k, block_k, axis=1)
        s = jnp.einsum("bqd,bkd->bqk", qf, kb) * scale
        if causal:
            kpos = j * block_k + jnp.arange(block_k)
            mask = kpos[None, :] <= qpos[:, None]
            s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [BH, Tq, block_k]
        dvb = jnp.einsum("bqk,bqd->bkd", p, gf)
        dp = jnp.einsum("bqd,bkd->bqk", gf, vb)
        ds = p * (dp - delta) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kb)
        dkb = jnp.einsum("bqk,bqd->bkd", ds, qf)
        dk = lax.dynamic_update_slice_in_dim(dk, dkb, j * block_k, axis=1)
        dv = lax.dynamic_update_slice_in_dim(dv, dvb, j * block_k, axis=1)
        return dq, dk, dv

    init = (
        jnp.zeros((bh, tq, d), jnp.float32),
        jnp.zeros((bh, tk, d), jnp.float32),
        jnp.zeros((bh, tk, d), jnp.float32),
    )
    dq, dk, dv = lax.fori_loop(0, num_k, body, init)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash_core(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return o


def _flash_core_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(
        q, k, v, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return o, (q, k, v, o, lse)


def _flash_core_bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _blockwise_bwd(
        q, k, v, o, lse, g, scale=scale, causal=causal, block_k=block_k
    )


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Memory-efficient exact attention; q, k, v: ``[B, T, H, D]``.

    Drop-in for :func:`bluefog_tpu.models.transformer.dense_attention`
    (same layout/semantics, fp32 softmax), O(T·block) memory.
    """
    if interpret is None:
        interpret = _default_interpret()
    b, tq, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    def fold(x):  # [B, T, H, D] -> [B*H, T, D]
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    o = _flash_core(
        fold(q), fold(k), fold(v), scale, causal, block_q, block_k, interpret
    )
    return o.reshape(b, h, tq, d).transpose(0, 2, 1, 3)


def make_flash_attention_fn(
    causal: bool = True,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> Callable:
    """``attention_fn`` for :class:`bluefog_tpu.models.transformer.LlamaLM`."""
    return functools.partial(
        flash_attention,
        causal=causal,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
    )
