"""Pallas TPU kernels for the hot ops.

The reference keeps its hot paths in hand-written native code (CUDA stream
combines in ``bluefog/common/nccl_controller.cc`` [U], fused MPI combine
loops in ``mpi_controller.cc`` [U]); the TPU-native analogue is Pallas —
kernels compiled straight to Mosaic for the MXU/VPU, fused with XLA around
them.
"""

from bluefog_tpu.kernels.flash_attention import (
    flash_attention,
    flash_attention_with_lse,
    make_flash_attention_fn,
)

__all__ = [
    "flash_attention",
    "flash_attention_with_lse",
    "make_flash_attention_fn",
]
