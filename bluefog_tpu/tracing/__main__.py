"""CLI: stitch per-rank trace buffers into one Chrome trace.

    python -m bluefog_tpu.tracing [PATHS...] [--out merged.json]
                                  [--critical-path] [--journals] [--check]

Positional arguments are per-rank ``trace-*.json`` files or directories
(directories are globbed; merged outputs and flight dumps are skipped by
schema tag).  With no arguments the default tracing dir
(``$BFTPU_TRACING`` when it names a dir, else /tmp/bftpu_tracing) is
scanned.

``--out`` writes the merged Chrome trace (default
``<dir>/merged-trace.json``; load it in ``chrome://tracing`` or
Perfetto).  ``--critical-path`` additionally prints the per-round
critical-path / straggler-attribution report to stdout.  ``--journals``
folds telemetry event journals from the same directories into the trace
as instant events.  ``--check`` runs the analysis trace rules over the
loaded buffers and exits non-zero on findings.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from bluefog_tpu.tracing.merge import (
    critical_path,
    find_flights,
    find_traces,
    load_trace,
    merge_traces,
)
from bluefog_tpu.tracing.tracer import _DEFAULT_DIR, tracing_dir


def _default_paths() -> List[str]:
    d = tracing_dir() or _DEFAULT_DIR
    return [d] if os.path.isdir(d) else []


def _load_journals(paths: List[str]):
    """Rank → telemetry journal events found beside the trace buffers."""
    import glob
    import re

    from bluefog_tpu.telemetry import read_journal
    from bluefog_tpu.telemetry.registry import journal_paths

    journals = {}
    for p in paths:
        d = p if os.path.isdir(p) else os.path.dirname(p) or "."
        for jp in sorted(glob.glob(
                os.path.join(d, "telemetry-*.events.jsonl"))):
            m = re.search(r"-r(\d+)\.events\.jsonl$", jp)
            if not m:
                continue
            # journal_paths folds in the rotated generation (<path>.1,
            # BFTPU_JOURNAL_MAX_MB) ahead of the live file
            for part in journal_paths(jp):
                events, _bad = read_journal(part)
                journals.setdefault(int(m.group(1)), []).extend(events)
    return journals


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.tracing",
        description="Merge per-rank trace buffers into one Chrome trace "
                    "with cross-rank flow events.")
    ap.add_argument("paths", nargs="*",
                    help="trace-buffer files or directories "
                         "(default: the tracing dir)")
    ap.add_argument("--out", default=None,
                    help="merged Chrome-trace path "
                         "(default: <dir>/merged-trace.json)")
    ap.add_argument("--critical-path", action="store_true",
                    help="print the per-round critical-path / straggler "
                         "report to stdout")
    ap.add_argument("--journals", action="store_true",
                    help="fold telemetry event journals into the trace")
    ap.add_argument("--check", action="store_true",
                    help="run analysis trace rules over the buffers; "
                         "exit non-zero on findings")
    args = ap.parse_args(argv)

    roots = args.paths or _default_paths()
    paths = find_traces(roots)
    traces = []
    for p in paths:
        try:
            tr = load_trace(p)
        except (OSError, ValueError) as e:
            print(f"warning: skipping {p}: {e}", file=sys.stderr)
            continue
        if tr is not None:
            traces.append(tr)
    if not traces:
        print("error: no trace buffers found "
              "(run with BFTPU_TRACING=1, or pass trace paths)",
              file=sys.stderr)
        return 2

    journals = _load_journals(roots) if args.journals else None
    merged = merge_traces(traces, journals=journals)

    out = args.out
    if out is None:
        d = roots[0] if os.path.isdir(roots[0]) else (
            os.path.dirname(paths[0]) or ".")
        out = os.path.join(d, "merged-trace.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(merged, f)
        f.write("\n")
    n_flow = sum(1 for e in merged["traceEvents"] if e.get("ph") == "s")
    print(f"merged {len(traces)} rank buffer(s) "
          f"(ranks {merged['otherData']['ranks']}, {n_flow} flows) -> {out}",
          file=sys.stderr)

    flights = find_flights(roots)
    if flights:
        print(f"flight dumps present: {', '.join(flights)}", file=sys.stderr)

    if args.critical_path:
        report = critical_path(traces)
        print(json.dumps(report, indent=2))

    rc = 0
    if args.check:
        from bluefog_tpu.analysis import trace_rules

        findings = trace_rules.check_trace_corpus(traces)
        for f in findings:
            print(f"CHECK {f.severity}: [{f.rule}] {f.subject}: {f.message}",
                  file=sys.stderr)
        if findings:
            rc = 1
        else:
            print(f"check ok: {len(traces)} buffers", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
