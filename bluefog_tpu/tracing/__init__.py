"""bluefog_tpu.tracing — cross-rank distributed tracing for gossip.

What :mod:`bluefog_tpu.telemetry` (aggregate counters/histograms) cannot
answer — *which* deposit a collect consumed, *which* rank lengthened a
round — this package does, with four pieces:

* **Context propagation**: a u64 ``(round, op_id, origin_rank)`` word
  (:func:`pack_ctx`) rides both transports — an 8-byte sidecar word per
  shm mailbox slot, a header field in the TCP frame — so the producing
  span on one rank and the consuming span on another share an identity.
* **Clock alignment**: a min-RTT offset estimator
  (:class:`~bluefog_tpu.tracing.clock.ClockEstimator`) over the TCP
  coordinator path, re-sampled per heartbeat; same-host shm ranks share
  ``CLOCK_MONOTONIC`` and keep offset 0.
* **Merge CLI**: ``python -m bluefog_tpu.tracing`` stitches per-rank
  buffers (+ telemetry journals) into one Chrome trace with flow arrows
  along gossip edges; ``--critical-path`` extracts each round's longest
  causal chain and a straggler-attribution report.
* **Flight recorder**: a SIGKILL-durable mmap ring of recent spans per
  rank, dumped on SIGTERM / fatal errors / ``PeerTimeoutError`` and
  recovered post-mortem by the spawner for killed ranks.

Enable with ``BFTPU_TRACING=1`` (or ``=<dir>``); unset means
:func:`get_tracer` returns a shared no-op ``NullTracer``.  See
docs/OBSERVABILITY.md.  Stdlib-only: importable without jax, numpy, or
the native library.
"""

from bluefog_tpu.tracing.clock import ClockEstimator
from bluefog_tpu.tracing.merge import (
    MERGED_TRACE_SCHEMA,
    critical_path,
    find_flights,
    find_traces,
    flow_index,
    load_flight,
    load_trace,
    merge_traces,
)
from bluefog_tpu.tracing.tracer import (
    FLIGHT_SCHEMA,
    TRACE_SCHEMA,
    FlightRing,
    NullTracer,
    Tracer,
    convert_flight_rings,
    get_tracer,
    pack_ctx,
    read_flight_ring,
    reset,
    tracing_dir,
    unpack_ctx,
)

__all__ = [
    "TRACE_SCHEMA",
    "FLIGHT_SCHEMA",
    "MERGED_TRACE_SCHEMA",
    "ClockEstimator",
    "FlightRing",
    "Tracer",
    "NullTracer",
    "get_tracer",
    "reset",
    "tracing_dir",
    "pack_ctx",
    "unpack_ctx",
    "read_flight_ring",
    "convert_flight_rings",
    "find_traces",
    "find_flights",
    "load_trace",
    "load_flight",
    "merge_traces",
    "flow_index",
    "critical_path",
]
