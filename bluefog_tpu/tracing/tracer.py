"""Per-rank tracer: causal spans, trace-context words, and a flight ring.

Three cooperating pieces, all stdlib-only (importable without jax or the
native library, like :mod:`bluefog_tpu.telemetry`):

* **Trace-context words** — :func:`pack_ctx` packs ``(round, op_id,
  origin_rank)`` into one u64 that rides the transports (an 8-byte
  sidecar word per shm mailbox slot, a u64 field in the TCP frame).  The
  producing span records the word it *emitted*; the consuming span
  records the word it *collected* — :mod:`bluefog_tpu.tracing.merge`
  joins the two into a Chrome-trace flow arrow.

* **Span buffer** — ``tr.begin(...)`` / ``tr.end(tok, ...)`` append
  closed spans (monotonic ns timestamps) to an in-memory list, written
  as ``trace-<job>-r<rank>.json`` at shutdown/atexit (atomic tmp +
  rename, the telemetry snapshot idiom).

* **Flight ring** — a fixed-size mmap-backed ring of recent begin/end
  records (``trace-<job>-r<rank>.flight.bin``).  mmap writes land in the
  page cache, so the ring survives SIGKILL; the spawner converts a dead
  rank's ring to ``flight-<job>-r<rank>.json`` post-mortem, and the
  tracer itself dumps it in-process on SIGTERM, fatal worker errors and
  ``PeerTimeoutError``.  A ``'B'`` record with no matching ``'E'`` names
  the op that was in flight when the rank died.

Enable with ``BFTPU_TRACING=1`` (or ``=<dir>``); when unset,
:func:`get_tracer` returns a shared ``NullTracer`` whose methods are
no-ops — instrumented call sites cost one attribute load and a falsy
branch, the same contract ``BFTPU_TELEMETRY`` has.
"""

from __future__ import annotations

import atexit
import glob
import json
import mmap
import os
import re
import signal
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from bluefog_tpu.telemetry.registry import (
    _resolve_job,
    _resolve_rank,
    _safe_name,
)
from bluefog_tpu.tracing.clock import ClockEstimator

TRACE_SCHEMA = "bftpu-trace-v1"
FLIGHT_SCHEMA = "bftpu-flight-v1"

_DEFAULT_DIR = "/tmp/bftpu_tracing"

# span-buffer hard cap: ~100 bytes/span keeps worst case ~10 MB/rank;
# overflow increments ``dropped`` instead of growing without bound
_MAX_SPANS = 100_000


def tracing_dir() -> Optional[str]:
    """Directory for trace buffers, or None when tracing is off.

    ``BFTPU_TRACING`` semantics mirror ``BFTPU_TELEMETRY``: unset, empty
    or ``"0"`` → off; ``"1"`` → the default dir; anything else IS the
    directory."""
    v = os.environ.get("BFTPU_TRACING", "")
    if v in ("", "0"):
        return None
    if v == "1":
        return _DEFAULT_DIR
    return v


# ---------------------------------------------------------------------------
# trace-context word: (round, op_id, origin) in one u64
# ---------------------------------------------------------------------------
#
#   bits 32..63  op_id   (per-rank monotone counter, one per op×target)
#   bits 16..31  round   (gossip round mod 2**16 — disambiguation only)
#   bits  0..15  origin  (the producing rank)
#
# Flow identity in the merged trace is (origin, op_id): op_id alone is
# only rank-unique.  The word 0 means "no context" on the wire.


def pack_ctx(round_: int, op_id: int, origin: int) -> int:
    """Pack (round, op_id, origin_rank) into the u64 wire word."""
    return (((op_id & 0xFFFFFFFF) << 32)
            | ((round_ & 0xFFFF) << 16)
            | (origin & 0xFFFF))


def unpack_ctx(word: int) -> Tuple[int, int, int]:
    """Inverse of :func:`pack_ctx`: returns ``(round, op_id, origin)``."""
    return ((word >> 16) & 0xFFFF, (word >> 32) & 0xFFFFFFFF, word & 0xFFFF)


# ---------------------------------------------------------------------------
# flight ring: fixed-size mmap ring of recent begin/end records
# ---------------------------------------------------------------------------

_RING_MAGIC = 0x42465452  # "BFTR"
_RING_VERSION = 1
_RING_HDR = struct.Struct("<IIIIQ")  # magic, version, cap, recsize, seq-hint
_RING_HDR_SIZE = 64  # header padded to one record boundary
# record: seq, t_ns, kind, round, op_id, origin, aux, name — exactly 64 B
_RING_REC = struct.Struct("<QQIIIiI28s")

KIND_B, KIND_E, KIND_I = 1, 2, 3
_KIND_NAMES = {KIND_B: "B", KIND_E: "E", KIND_I: "I"}


def _ring_capacity() -> int:
    """Ring capacity in records (``BFTPU_TRACE_RING``, default 256)."""
    try:
        cap = int(os.environ.get("BFTPU_TRACE_RING", "") or 256)
    except ValueError:
        cap = 256
    return max(16, cap)


class FlightRing:
    """mmap-backed ring of fixed 64-byte records; SIGKILL-durable."""

    def __init__(self, path: str, cap: int):
        self.path = path
        self.cap = int(cap)
        size = _RING_HDR_SIZE + self.cap * _RING_REC.size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._seq = 0
        _RING_HDR.pack_into(self._mm, 0, _RING_MAGIC, _RING_VERSION,
                            self.cap, _RING_REC.size, 0)

    def append(self, kind: int, name: str, round_: int = 0, op_id: int = 0,
               origin: int = -1, aux: int = 0) -> int:
        """Write one record; returns its sequence number (1-based)."""
        self._seq += 1
        s = self._seq
        off = _RING_HDR_SIZE + ((s - 1) % self.cap) * _RING_REC.size
        _RING_REC.pack_into(
            self._mm, off, s, time.monotonic_ns(), kind,
            round_ & 0xFFFFFFFF, op_id & 0xFFFFFFFF, origin,
            aux & 0xFFFFFFFF, name.encode("utf-8", "replace")[:28])
        struct.pack_into("<Q", self._mm, 16, s)  # header hint for readers
        return s

    def close(self) -> None:
        try:
            self._mm.flush()
            self._mm.close()
        except (ValueError, OSError):
            pass


def read_flight_ring(data_or_path) -> Tuple[List[Dict], List[Dict]]:
    """Decode a flight ring (bytes or path) into ``(records, in_flight)``.

    ``records`` are sorted by sequence; ``in_flight`` is the subset of
    'B' records whose matching 'E' (linked by ``aux`` = B's seq) never
    landed — the ops that were open when the rank died."""
    if isinstance(data_or_path, (bytes, bytearray, memoryview)):
        buf = bytes(data_or_path)
    else:
        with open(data_or_path, "rb") as f:
            buf = f.read()
    if len(buf) < _RING_HDR_SIZE:
        raise ValueError("flight ring truncated")
    magic, ver, cap, recsize, _hint = _RING_HDR.unpack_from(buf, 0)
    if magic != _RING_MAGIC:
        raise ValueError(f"bad flight-ring magic 0x{magic:08x}")
    if recsize != _RING_REC.size:
        raise ValueError(f"flight-ring record size {recsize} != "
                         f"{_RING_REC.size} (version {ver})")
    records: List[Dict] = []
    for k in range(cap):
        off = _RING_HDR_SIZE + k * recsize
        if off + recsize > len(buf):
            break
        seq, t_ns, kind, rnd, op_id, origin, aux, name = (
            _RING_REC.unpack_from(buf, off))
        if seq == 0 or kind not in _KIND_NAMES:
            continue  # never written (or torn mid-write)
        records.append({
            "seq": seq, "t_ns": t_ns, "kind": _KIND_NAMES[kind],
            "round": rnd, "op_id": op_id, "origin": origin, "aux": aux,
            "name": name.rstrip(b"\x00").decode("utf-8", "replace"),
        })
    records.sort(key=lambda r: r["seq"])
    ended = {r["aux"] for r in records if r["kind"] == "E"}
    in_flight = [r for r in records
                 if r["kind"] == "B" and (r["seq"] & 0xFFFFFFFF) not in ended]
    return records, in_flight


def _atomic_write_json(path: str, doc: Dict) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# the tracer
# ---------------------------------------------------------------------------


class Tracer:
    """Active tracer: span buffer + flight ring + clock estimator."""

    enabled = True

    def __init__(self, dirpath: str, rank: Optional[int] = None,
                 job: Optional[str] = None):
        self.dir = dirpath
        self.rank = _resolve_rank() if rank is None else int(rank)
        self.job = _resolve_job() if job is None else str(job)
        self.nranks = 0
        self.round = 0
        self.spans: List[Dict] = []
        self.dropped = 0
        self.clock = ClockEstimator()
        self._op_id = 0
        self._ring: Optional[FlightRing] = None
        self._lock = threading.Lock()
        self._sigterm_installed = False
        os.makedirs(dirpath, exist_ok=True)

    # -- identity -------------------------------------------------------

    def set_identity(self, rank: int, nranks: int, job: str) -> None:
        """Bind rank/job after :func:`islands.init` knows them.  Reopens
        the flight ring at the per-rank path and installs the SIGTERM
        dump handler (main thread only)."""
        self.rank, self.nranks, self.job = int(rank), int(nranks), str(job)
        if self._ring is not None:
            self._ring.close()
            self._ring = None
        self._ensure_ring()
        self.install_sigterm()

    def _base(self) -> str:
        return f"{_safe_name(self.job)}-r{self.rank}"

    def ring_path(self) -> str:
        return os.path.join(self.dir, f"trace-{self._base()}.flight.bin")

    def buffer_path(self) -> str:
        return os.path.join(self.dir, f"trace-{self._base()}.json")

    def flight_json_path(self) -> str:
        return os.path.join(self.dir, f"flight-{self._base()}.json")

    def _ensure_ring(self) -> Optional[FlightRing]:
        if self._ring is None:
            try:
                self._ring = FlightRing(self.ring_path(), _ring_capacity())
            except OSError:
                return None
        return self._ring

    # -- hot path -------------------------------------------------------

    def next_op_id(self) -> int:
        self._op_id += 1
        return self._op_id

    def begin(self, name: str, window: Optional[str] = None) -> Tuple:
        ring = self._ensure_ring()
        seq = ring.append(KIND_B, name, self.round, 0, self.rank) if ring else 0
        return (name, time.monotonic_ns(), seq, window)

    def end(self, tok: Tuple, emit: Optional[List[Dict]] = None,
            consume: Optional[List[Dict]] = None, op_id: int = 0) -> None:
        name, t0, seq, window = tok
        t1 = time.monotonic_ns()
        if self._ring is not None:
            self._ring.append(KIND_E, name, self.round, op_id, self.rank,
                              aux=seq)
        if len(self.spans) >= _MAX_SPANS:
            self.dropped += 1
            return
        span: Dict[str, Any] = {"name": name, "t0": t0, "t1": t1,
                                "round": self.round}
        if window:
            span["win"] = window
        if emit:
            span["emit"] = emit
        if consume:
            span["consume"] = consume
        cur = threading.current_thread()
        if cur is not threading.main_thread():
            # off-main-thread spans (the progress-engine worker) carry
            # their lane so the merged view separates background
            # communication from the training step it overlaps
            span["lane"] = cur.name
        self.spans.append(span)

    def instant(self, name: str, aux: int = 0) -> None:
        ring = self._ensure_ring()
        if ring:
            ring.append(KIND_I, name, self.round, 0, self.rank, aux=aux)
        t = time.monotonic_ns()
        if len(self.spans) < _MAX_SPANS:
            self.spans.append({"name": name, "t0": t, "t1": t,
                               "round": self.round, "ph": "i"})
        else:
            self.dropped += 1

    def advance_round(self) -> int:
        self.round += 1
        return self.round

    # -- clock ----------------------------------------------------------

    def resample_clock(self, job) -> None:
        """Feed one coordinator clock probe into the offset estimator.
        Jobs without a coordinator path (same-host shm: the Linux
        monotonic clock is already shared) simply keep offset 0."""
        probe = getattr(job, "clock_probe", None)
        if probe is None:
            return
        try:
            t0, remote, t1 = probe()
        except Exception:  # noqa: BLE001 - peer death mid-probe is fine
            return
        self.clock.add_sample(t0, remote, t1)

    # -- dumps ----------------------------------------------------------

    def write_buffer(self) -> Optional[str]:
        """Atomically write the span buffer (telemetry-snapshot idiom)."""
        path = self.buffer_path()
        doc = {
            "schema": TRACE_SCHEMA,
            "job": self.job,
            "rank": self.rank,
            "nranks": self.nranks,
            "rounds": self.round,
            "clock": self.clock.as_dict(),
            # wall↔monotonic anchor: lets the merger place wall-clock
            # telemetry journal events on the monotonic span timeline
            "anchor": {"wall_s": time.time(),
                       "mono_ns": time.monotonic_ns()},
            "dropped": self.dropped,
            "spans": self.spans,
        }
        try:
            _atomic_write_json(path, doc)
        except OSError:
            return None
        return path

    def dump_flight(self, reason: str) -> Optional[str]:
        """Write the flight-ring JSON in-process (SIGTERM / fatal error /
        PeerTimeoutError).  SIGKILLed ranks skip this; the spawner
        recovers their ring file instead."""
        ring = self._ensure_ring()
        if ring is None:
            return None
        with self._lock:
            try:
                records, in_flight = read_flight_ring(bytes(ring._mm))
            except (ValueError, OSError):
                return None
            doc = {
                "schema": FLIGHT_SCHEMA,
                "job": self.job,
                "rank": self.rank,
                "reason": reason,
                "records": records,
                "in_flight": in_flight,
            }
            path = self.flight_json_path()
            try:
                _atomic_write_json(path, doc)
            except OSError:
                return None
            return path

    # -- SIGTERM --------------------------------------------------------

    def install_sigterm(self) -> None:
        """Chain a SIGTERM handler that dumps flight + buffer, then
        defers to whatever handler was installed before us."""
        if self._sigterm_installed:
            return
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def _on_sigterm(signum, frame):
                try:
                    self.dump_flight("SIGTERM")
                    self.write_buffer()
                finally:
                    if callable(prev):
                        prev(signum, frame)
                    else:
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        os.kill(os.getpid(), signal.SIGTERM)

            signal.signal(signal.SIGTERM, _on_sigterm)
            self._sigterm_installed = True
        except ValueError:
            pass  # not the main thread — atexit still covers clean exits

    def close(self) -> None:
        if self._ring is not None:
            self._ring.close()
            self._ring = None


class NullTracer:
    """Shared no-op tracer returned when ``BFTPU_TRACING`` is unset."""

    enabled = False
    rank = -1
    job = ""
    round = 0

    def set_identity(self, rank, nranks, job):  # noqa: D102
        pass

    def next_op_id(self):  # noqa: D102
        return 0

    def begin(self, name, window=None):  # noqa: D102
        return None

    def end(self, tok, emit=None, consume=None, op_id=0):  # noqa: D102
        pass

    def instant(self, name, aux=0):  # noqa: D102
        pass

    def advance_round(self):  # noqa: D102
        return 0

    def resample_clock(self, job):  # noqa: D102
        pass

    def write_buffer(self):  # noqa: D102
        return None

    def dump_flight(self, reason):  # noqa: D102
        return None

    def install_sigterm(self):  # noqa: D102
        pass

    def close(self):  # noqa: D102
        pass


NULL_TRACER = NullTracer()

_tracer: Optional[object] = None
_tracer_lock = threading.Lock()


def _atexit_write() -> None:
    t = _tracer
    if t is not None and t.enabled:
        t.write_buffer()
        t.close()


atexit.register(_atexit_write)


def get_tracer():
    """The process tracer: a :class:`Tracer` when ``BFTPU_TRACING`` is
    set, else the shared :class:`NullTracer` (cached either way)."""
    global _tracer
    t = _tracer
    if t is not None:
        return t
    with _tracer_lock:
        if _tracer is None:
            d = tracing_dir()
            _tracer = Tracer(d) if d else NULL_TRACER
        return _tracer


def reset() -> None:
    """Drop the cached tracer so the next :func:`get_tracer` re-reads the
    environment (tests toggle ``BFTPU_TRACING`` around this)."""
    global _tracer
    with _tracer_lock:
        t = _tracer
        _tracer = None
    if t is not None and t is not NULL_TRACER:
        t.close()


# ---------------------------------------------------------------------------
# post-mortem: recover rings of ranks that died without dumping
# ---------------------------------------------------------------------------


def convert_flight_rings(job: str, dirpath: Optional[str] = None,
                         reason: str = "post-mortem") -> List[str]:
    """Convert every flight ring of ``job`` that has no in-process JSON
    dump into ``flight-<job>-r<rank>.json``.  The spawner calls this
    after reaping children so SIGKILLed ranks still get a causal
    postmortem; ranks that dumped on SIGTERM/fatal are left alone."""
    d = dirpath or tracing_dir()
    if not d:
        return []
    out: List[str] = []
    pat = os.path.join(d, f"trace-{_safe_name(job)}-r*.flight.bin")
    for ring_path in sorted(glob.glob(pat)):
        m = re.search(r"-r(\d+)\.flight\.bin$", ring_path)
        if not m:
            continue
        rank = int(m.group(1))
        json_path = os.path.join(
            d, f"flight-{_safe_name(job)}-r{rank}.json")
        if os.path.exists(json_path):
            continue  # the rank dumped itself before dying
        try:
            records, in_flight = read_flight_ring(ring_path)
        except (OSError, ValueError):
            continue
        doc = {
            "schema": FLIGHT_SCHEMA,
            "job": job,
            "rank": rank,
            "reason": reason,
            "records": records,
            "in_flight": in_flight,
        }
        try:
            _atomic_write_json(json_path, doc)
        except OSError:
            continue
        out.append(json_path)
    return out
