"""Min-RTT clock-offset estimation over the TCP coordinator path.

Every rank's spans are stamped with its own ``CLOCK_MONOTONIC``; to merge
them, each rank estimates the offset of its clock from the coordinator's
(rank 0's) with the classic NTP two-point exchange: send local ``t0``,
receive the coordinator's ``remote`` reading, note local ``t1``.  Under
the symmetric-delay assumption the coordinator read the clock at local
time ``(t0 + t1) / 2``, so::

    offset = remote - (t0 + t1) / 2        # coordinator ≈ local + offset
    error  ≤ (t1 - t0) / 2                 # half the round-trip

Asymmetry only widens the error bound, never escapes it, so keeping the
**minimum-RTT** sample (the exchange least disturbed by queueing) gives
the tightest bound — the estimator below retains exactly that sample and
is re-fed once per heartbeat by the resilience detector.

Same-host shm ranks never probe: Linux ``CLOCK_MONOTONIC`` is
system-wide, so their offset is identically 0 with error 0 — the
estimator's initial state.
"""

from __future__ import annotations

from typing import Dict


class ClockEstimator:
    """Keeps the min-RTT offset sample from a stream of clock probes.

    All times are seconds on ``time.monotonic()``'s scale.  ``offset``
    is *coordinator minus local*: add it to a local timestamp to express
    it on the coordinator's clock.  ``err`` bounds ``|true - offset|``.
    """

    def __init__(self):
        self.offset = 0.0
        self.err = 0.0
        self.best_rtt = float("inf")
        self.samples = 0

    def add_sample(self, t0: float, remote: float, t1: float) -> bool:
        """Feed one probe; returns True when it tightened the estimate.
        Probes with non-positive RTT (clock weirdness, retried sockets)
        are discarded."""
        rtt = t1 - t0
        if rtt <= 0.0:
            return False
        self.samples += 1
        if rtt >= self.best_rtt:
            return False
        self.best_rtt = rtt
        self.offset = remote - (t0 + t1) / 2.0
        self.err = rtt / 2.0
        return True

    def as_dict(self) -> Dict:
        return {
            "offset_s": self.offset,
            "err_s": self.err,
            "best_rtt_s": (None if self.best_rtt == float("inf")
                           else self.best_rtt),
            "samples": self.samples,
        }
