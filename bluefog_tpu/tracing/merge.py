"""Clock-aligned cross-rank trace merge, flow events, and critical paths.

Input: per-rank ``trace-<job>-r<rank>.json`` buffers written by
:mod:`bluefog_tpu.tracing.tracer` (plus, optionally, PR 4 telemetry
journals).  Output: one Chrome-trace JSON where

* each rank is a distinct ``pid`` (with a ``process_name`` metadata
  event),
* every span is a ``ph:"X"`` complete event on the **coordinator's
  clock** — each rank's monotonic timestamps are shifted by its min-RTT
  clock offset (:mod:`bluefog_tpu.tracing.clock`),
* every (producer ``emit``, consumer ``consume``) pair that shares a
  trace-context identity ``(origin, op_id)`` becomes a flow arrow
  (``ph:"s"`` at the producing span, ``ph:"f"`` at the consuming span),
* telemetry journal events ride along as ``ph:"i"`` instants, mapped
  from wall clock to the span timeline via each buffer's recorded
  wall↔monotonic anchor.

:func:`critical_path` walks the merged causal graph backwards from each
round's last-finishing ``win_update`` — predecessor = the latest of
(the producer of the latest-arriving consumed flow, the previous span on
the same rank) — yielding the longest causal chain per gossip round and
a straggler-attribution report (per-edge p50/p99 flow latency, which
rank lengthened each round).
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from bluefog_tpu.tracing.tracer import FLIGHT_SCHEMA, TRACE_SCHEMA

MERGED_TRACE_SCHEMA = "bftpu-merged-trace-v1"

# spans that close a gossip round (critical-path roots), in preference
# order: the combine is the canonical round boundary
_ROUND_CLOSERS = ("win_update", "win_update_then_collect")


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------


def find_traces(paths: Sequence[str]) -> List[str]:
    """Expand files/directories into per-rank trace-buffer paths."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "trace-*.json"))))
        else:
            out.append(p)
    # dedupe, preserve order
    seen = set()
    uniq = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


def find_flights(paths: Sequence[str]) -> List[str]:
    """Flight-recorder JSON dumps next to the trace buffers."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "flight-*.json"))))
        elif os.path.basename(p).startswith("flight-"):
            out.append(p)
    return out


def load_trace(path: str) -> Optional[Dict]:
    """Load one per-rank buffer; None when the schema doesn't match
    (merged outputs and flight dumps are silently skipped)."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != TRACE_SCHEMA:
        return None
    return doc


def load_flight(path: str) -> Optional[Dict]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != FLIGHT_SCHEMA:
        return None
    return doc


# ---------------------------------------------------------------------------
# alignment + flow index
# ---------------------------------------------------------------------------


def _aligned_spans(traces: Sequence[Dict]) -> Tuple[List[Dict], float]:
    """Flatten all buffers into span dicts with coordinator-clock
    microsecond timestamps (``t0_us``/``t1_us``), plus the global origin
    subtracted from every timestamp."""
    spans: List[Dict] = []
    for tr in traces:
        rank = int(tr.get("rank", -1))
        off_us = float(tr.get("clock", {}).get("offset_s", 0.0)) * 1e6
        err_us = float(tr.get("clock", {}).get("err_s", 0.0)) * 1e6
        for i, s in enumerate(tr.get("spans", ())):
            spans.append({
                "rank": rank,
                "idx": i,
                "name": s.get("name", "?"),
                "round": int(s.get("round", 0)),
                "t0_us": s.get("t0", 0) / 1e3 + off_us,
                "t1_us": s.get("t1", 0) / 1e3 + off_us,
                "err_us": err_us,
                "ph": s.get("ph", "X"),
                "win": s.get("win"),
                "emit": s.get("emit") or (),
                "consume": s.get("consume") or (),
            })
    t_min = min((s["t0_us"] for s in spans), default=0.0)
    for s in spans:
        s["t0_us"] -= t_min
        s["t1_us"] -= t_min
    return spans, t_min


def flow_index(spans: Sequence[Dict]) -> Tuple[Dict, List[Dict]]:
    """``(producers, flows)``: producers maps flow identity
    ``(origin, op_id)`` to the emitting span; flows lists every consume
    with its resolved producer (or ``None`` when the emitting span was
    lost — e.g. the producer died before writing its buffer)."""
    producers: Dict[Tuple[int, int], Dict] = {}
    for s in spans:
        for e in s["emit"]:
            producers[(s["rank"], int(e["op_id"]))] = s
    flows: List[Dict] = []
    for s in spans:
        for c in s["consume"]:
            key = (int(c.get("origin", -1)), int(c.get("op_id", 0)))
            flows.append({
                "origin": key[0],
                "op_id": key[1],
                "round": int(c.get("round", s["round"])),
                "src": int(c.get("src", key[0])),
                "dst": s["rank"],
                "producer": producers.get(key),
                "consumer": s,
            })
    return producers, flows


# ---------------------------------------------------------------------------
# merge → Chrome trace
# ---------------------------------------------------------------------------


def merge_traces(traces: Sequence[Dict],
                 journals: Optional[Dict[int, List[Dict]]] = None) -> Dict:
    """Merge per-rank buffers into one Chrome-trace dict.

    ``journals`` optionally maps rank → telemetry journal events (as
    returned by :func:`bluefog_tpu.telemetry.read_journal`); they are
    attached as instant events via each rank's wall↔monotonic anchor.
    """
    traces = [t for t in traces if t]
    spans, t_min = _aligned_spans(traces)
    _, flows = flow_index(spans)

    events: List[Dict] = []
    ranks = sorted({int(t.get("rank", -1)) for t in traces})
    clock_by_rank: Dict[str, Dict] = {}
    for t in traces:
        r = int(t.get("rank", -1))
        clock_by_rank[str(r)] = t.get("clock", {})
        events.append({"ph": "M", "pid": r, "tid": 0, "name": "process_name",
                       "args": {"name": f"rank {r} ({t.get('job', '')})"}})

    for s in spans:
        if s["ph"] == "i":
            events.append({"ph": "i", "pid": s["rank"], "tid": 0, "s": "t",
                           "name": s["name"], "ts": s["t0_us"],
                           "args": {"round": s["round"]}})
            continue
        args: Dict = {"round": s["round"]}
        if s["win"]:
            args["win"] = s["win"]
        events.append({"ph": "X", "pid": s["rank"], "tid": 0,
                       "name": s["name"], "ts": s["t0_us"],
                       "dur": max(0.0, s["t1_us"] - s["t0_us"]),
                       "cat": "gossip", "args": args})

    # flow arrows along gossip edges: "s" binds inside the producing
    # span, "f" (bp:"e") inside the consuming span
    for fl in flows:
        p, c = fl["producer"], fl["consumer"]
        if p is None:
            continue  # dangling consume (producer buffer lost)
        fid = f"{fl['origin']}:{fl['op_id']}"
        events.append({"ph": "s", "pid": p["rank"], "tid": 0, "id": fid,
                       "cat": "gossip-flow", "name": "deposit",
                       "ts": max(p["t0_us"], p["t1_us"] - 0.001)})
        events.append({"ph": "f", "bp": "e", "pid": c["rank"], "tid": 0,
                       "id": fid, "cat": "gossip-flow", "name": "deposit",
                       "ts": min(c["t1_us"], c["t0_us"] + 0.001)})

    # telemetry journal instants, wall clock → span timeline per rank
    for t in traces:
        r = int(t.get("rank", -1))
        anchor = t.get("anchor") or {}
        evs = (journals or {}).get(r) or ()
        if not evs or "wall_s" not in anchor:
            continue
        off_us = float(t.get("clock", {}).get("offset_s", 0.0)) * 1e6
        base_us = anchor["mono_ns"] / 1e3 + off_us - t_min
        for ev in evs:
            ts = ev.get("ts")
            if ts is None:
                continue
            events.append({
                "ph": "i", "pid": r, "tid": 1, "s": "t",
                "name": str(ev.get("event", "journal")),
                "cat": "journal",
                "ts": base_us + (float(ts) - anchor["wall_s"]) * 1e6,
                "args": {k: v for k, v in ev.items()
                         if k not in ("event", "ts", "mono")},
            })

    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "schema": MERGED_TRACE_SCHEMA,
            "ranks": ranks,
            "clock": clock_by_rank,
            "flows": len(flows),
        },
    }


# ---------------------------------------------------------------------------
# critical path + straggler attribution
# ---------------------------------------------------------------------------


def _percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return vs[idx]


def critical_path(traces: Sequence[Dict], max_depth: int = 64) -> Dict:
    """Per-round longest causal chain + straggler attribution.

    For each gossip round, start from the last-finishing round-closing
    span (``win_update``) and walk predecessors: the producer of the
    latest-arriving consumed flow, or the previous span on the same
    rank — whichever completed later.  Completion times are
    non-decreasing along every returned path (up to clock error, which
    the walk clamps)."""
    traces = [t for t in traces if t]
    spans, _ = _aligned_spans(traces)
    producers, flows = flow_index(spans)

    by_rank: Dict[int, List[Dict]] = {}
    for s in spans:
        if s["ph"] != "i":
            by_rank.setdefault(s["rank"], []).append(s)
    for lst in by_rank.values():
        lst.sort(key=lambda s: s["t0_us"])
        for i, s in enumerate(lst):
            s["_pos"] = i

    def _prev_on_rank(s: Dict) -> Optional[Dict]:
        lst = by_rank.get(s["rank"], ())
        i = s.get("_pos", 0) - 1
        # skip overlapping spans (nested timeline contexts): predecessor
        # must have completed before this span began
        while i >= 0:
            if lst[i]["t1_us"] <= s["t0_us"] + s["err_us"]:
                return lst[i]
            i -= 1
        return None

    def _pred(s: Dict) -> Optional[Dict]:
        best = _prev_on_rank(s)
        slack = s["err_us"] + 1.0
        for c in s["consume"]:
            p = producers.get((int(c.get("origin", -1)), int(c.get("op_id", 0))))
            if p is None or p is s:
                continue
            if p["t1_us"] > s["t1_us"] + slack:
                continue  # clock skew beyond bound: refuse the edge
            if best is None or p["t1_us"] > best["t1_us"]:
                best = p
        return best

    nrounds = max((s["round"] for s in spans), default=-1) + 1
    rounds_out: List[Dict] = []
    lengthened: Dict[int, int] = {}
    for r in range(nrounds):
        closers = [s for s in spans
                   if s["round"] == r and s["name"] in _ROUND_CLOSERS]
        if not closers:
            continue
        last = max(closers, key=lambda s: s["t1_us"])
        path: List[Dict] = []
        cur: Optional[Dict] = last
        seen = set()
        while cur is not None and len(path) < max_depth:
            key = (cur["rank"], cur.get("_pos", -1), cur["name"])
            if key in seen:
                break
            seen.add(key)
            path.append(cur)
            cur = _pred(cur)
        path.reverse()
        rounds_out.append({
            "round": r,
            "end_rank": last["rank"],
            "t_end_us": last["t1_us"],
            "path": [{"rank": s["rank"], "name": s["name"],
                      "round": s["round"], "t0_us": s["t0_us"],
                      "t_end_us": s["t1_us"]} for s in path],
        })
        lengthened[last["rank"]] = lengthened.get(last["rank"], 0) + 1

    # per-edge flow latency: deposit START → collect completion.  Not
    # end-to-end: on an acked transport the producer span ends at ack
    # receipt, routinely AFTER the remote consumer already collected —
    # measured from t0 the latency is nonnegative up to clock error, so
    # a negative here really does mean the offsets are wrong.
    edge_lat: Dict[str, List[float]] = {}
    negative_flows = 0
    for fl in flows:
        p, c = fl["producer"], fl["consumer"]
        if p is None:
            continue
        lat = c["t1_us"] - p["t0_us"]
        if lat < 0:
            negative_flows += 1
            lat = 0.0
        edge_lat.setdefault(f"{p['rank']}->{c['rank']}", []).append(lat)

    edges = {
        edge: {"n": len(v), "p50_us": _percentile(v, 0.50),
               "p99_us": _percentile(v, 0.99)}
        for edge, v in sorted(edge_lat.items())
    }
    return {
        "rounds": rounds_out,
        "stragglers": {
            "rounds_lengthened_by_rank": {
                str(r): n for r, n in sorted(lengthened.items())},
            "edge_latency": edges,
            "negative_latency_flows": negative_flows,
        },
    }
