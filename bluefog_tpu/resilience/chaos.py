"""Fault-injection harness for the resilience e2e tests.

Faults are injected two ways:

- **from outside**: :func:`kill`, :func:`suspend`, :func:`resume` act
  on a worker pid (SIGKILL / SIGSTOP / SIGCONT) — the test process
  steers its spawned islands;
- **from inside**: workers call :func:`checkpoint(rank, step)` at
  instrumented points; a schedule published through env vars
  (``BFTPU_CHAOS_KILL_RANK`` / ``BFTPU_CHAOS_KILL_STEP`` /
  ``BFTPU_CHAOS_DELAY_S``) makes the matching rank kill itself with
  SIGKILL mid-op — deterministic death at a protocol-relevant point
  (e.g. between the expose and the deposit of a win_put), which no
  external signal can time reliably.  The same machinery schedules
  **gray failures** (``BFTPU_CHAOS_SUSPEND_RANK`` /
  ``BFTPU_CHAOS_SUSPEND_STEP`` / ``BFTPU_CHAOS_SUSPEND_S``: SIGSTOP
  past the heartbeat timeout, then SIGCONT — see :func:`suspend_self`)
  **stragglers** (``BFTPU_CHAOS_SLOW_RANK`` / ``BFTPU_CHAOS_SLOW_STEP``
  / ``BFTPU_CHAOS_SLOW_S`` / ``BFTPU_CHAOS_SLOW_STOP``: a main-thread
  sleep at every checkpoint from the scheduled step on, heartbeats
  unimpaired — see :func:`schedule_slow`), and **join admissions**
  (``BFTPU_CHAOS_JOIN_RANK`` / ``BFTPU_CHAOS_JOIN_STEP``: the rank
  calls ``islands.admit_pending()`` at the scheduled step).

Mailbox corruption for protocol tests goes through
:func:`corrupt_chunk` on a :class:`~bluefog_tpu.native.shm_native.
ChunkRingMirror` — it freezes a deposit mid-chunk exactly the way a
dead writer does, so the dead-writer drain path is exercised without
an actual process death.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional

from bluefog_tpu.sim.clock import resolve_clock as _resolve_clock

__all__ = [
    "kill",
    "suspend",
    "resume",
    "kill_self",
    "suspend_self",
    "checkpoint",
    "schedule_kill",
    "schedule_join",
    "schedule_suspend",
    "schedule_slow",
    "schedule_partition",
    "schedule_serve_kill",
    "schedule_serve_pub_kill",
    "schedule_distrib_kill",
    "schedule_to_json",
    "apply_schedule_json",
    "clear_schedule",
    "set_clock",
    "corrupt_chunk",
]

_KILL_RANK = "BFTPU_CHAOS_KILL_RANK"
_KILL_STEP = "BFTPU_CHAOS_KILL_STEP"
_DELAY_S = "BFTPU_CHAOS_DELAY_S"
_JOIN_RANK = "BFTPU_CHAOS_JOIN_RANK"
_JOIN_STEP = "BFTPU_CHAOS_JOIN_STEP"
_SUSPEND_RANK = "BFTPU_CHAOS_SUSPEND_RANK"
_SUSPEND_STEP = "BFTPU_CHAOS_SUSPEND_STEP"
_SUSPEND_S = "BFTPU_CHAOS_SUSPEND_S"
_SLOW_RANK = "BFTPU_CHAOS_SLOW_RANK"
_SLOW_STEP = "BFTPU_CHAOS_SLOW_STEP"
_SLOW_S = "BFTPU_CHAOS_SLOW_S"
_SLOW_STOP = "BFTPU_CHAOS_SLOW_STOP"
_PARTITION_GROUP = "BFTPU_CHAOS_PARTITION_GROUP"
_PARTITION_STEP = "BFTPU_CHAOS_PARTITION_STEP"
_PARTITION_STOP = "BFTPU_CHAOS_PARTITION_STOP"
_SERVE_KILL_REPLICA = "BFTPU_CHAOS_SERVE_KILL_REPLICA"
_SERVE_KILL_SWAP = "BFTPU_CHAOS_SERVE_KILL_SWAP"
_SERVE_KILL_STOP = "BFTPU_CHAOS_SERVE_KILL_STOP"
_SERVE_PUB_KILL_PUBLISH = "BFTPU_CHAOS_SERVE_PUB_KILL_PUBLISH"
_SERVE_PUB_KILL_PHASE = "BFTPU_CHAOS_SERVE_PUB_KILL_PHASE"
_DISTRIB_KILL_RELAY = "BFTPU_CHAOS_DISTRIB_KILL_RELAY"
_DISTRIB_KILL_SYNC = "BFTPU_CHAOS_DISTRIB_KILL_SYNC"

_ALL_KEYS = (_KILL_RANK, _KILL_STEP, _DELAY_S,
             _JOIN_RANK, _JOIN_STEP,
             _SUSPEND_RANK, _SUSPEND_STEP, _SUSPEND_S,
             _SLOW_RANK, _SLOW_STEP, _SLOW_S, _SLOW_STOP,
             _PARTITION_GROUP, _PARTITION_STEP, _PARTITION_STOP,
             _SERVE_KILL_REPLICA, _SERVE_KILL_SWAP, _SERVE_KILL_STOP,
             _SERVE_PUB_KILL_PUBLISH, _SERVE_PUB_KILL_PHASE,
             _DISTRIB_KILL_RELAY, _DISTRIB_KILL_SYNC)

# sim-campaign knobs (bluefog_tpu/sim/__main__.py reads these as CLI
# defaults) — scrubbed by clear_schedule() alongside the chaos keys,
# because a stale campaign seed or schedule would replay faults into
# the next test's campaign exactly like a stale kill schedule would
_SIM_KEYS = ("BFTPU_SIM_SEED", "BFTPU_SIM_RANKS", "BFTPU_SIM_ROUNDS",
             "BFTPU_SIM_FAULTS", "BFTPU_SIM_TOPOLOGY",
             "BFTPU_SIM_SCHEDULE", "BFTPU_SIM_QUIESCE_ROUNDS",
             "BFTPU_SIM_LATENCY_MS", "BFTPU_SIM_REPRO_DIR",
             "BFTPU_SIM_QUORUM")

# convergence-observatory knobs (bluefog_tpu.lab): a stale probe or
# auto-topology flag leaking across tests changes the next fleet's hot
# path (probe ticks) or its launch topology — schedule-grade state
_LAB_KEYS = ("BFTPU_LAB_PROBE", "BFTPU_LAB_AUTO_TOPOLOGY",
             "BFTPU_LAB_PAYLOAD_BYTES", "BFTPU_LAB_ARTIFACT",
             "BFTPU_LAB_SAMPLE", "BFTPU_LAB_FLUSH")

# serving-plane knobs (bluefog_tpu.serve): a stale lag bound or stale
# policy leaking across tests flips the next replica fleet from warn to
# refuse (or vice versa) — schedule-grade state, same as the lab keys
_SERVE_KEYS = ("BFTPU_SERVE_MAX_LAG", "BFTPU_SERVE_STALE_POLICY",
               "BFTPU_SERVE_RETRIES", "BFTPU_SERVE_BACKOFF_S",
               "BFTPU_SERVE_REPLICAS")

# distribution-plane knobs (bluefog_tpu.serve.distrib): a stale fanout
# reshapes the next fleet's tree, a stale horizon flips delta vs
# full-resync paths, and the BFTPU_CHAOS_DISTRIB_* kill schedules are
# literal fault schedules — all scrubbed with the rest
_DISTRIB_KEYS = ("BFTPU_DISTRIB_FANOUT", "BFTPU_DISTRIB_HORIZON",
                 "BFTPU_DISTRIB_CHUNK_KB", "BFTPU_DISTRIB_TIMEOUT_S",
                 "BFTPU_DISTRIB_RETRIES")

# load-generator + serve-SLO knobs (bluefog_tpu.serve.loadgen): a
# stale rate or schedule changes the next test's offered load, and a
# stale SLO objective arms violation windows the next fleet never
# asked for — schedule-grade state like everything above
_LOADGEN_KEYS = ("BFTPU_LOADGEN_RATE_HZ", "BFTPU_LOADGEN_SCHEDULE",
                 "BFTPU_LOADGEN_SEED", "BFTPU_LOADGEN_DURATION_S",
                 "BFTPU_SERVE_SLO_MS", "BFTPU_SERVE_SLO_STALENESS")

# fleet-monitor knobs (bluefog_tpu/monitor): stale alert thresholds or
# a stale rules override re-arm the previous test's alert policy in the
# next monitor's engine, and a stale scrape cadence or gap changes its
# window coalescing — schedule-grade state like the loadgen SLO keys
_MON_KEYS = ("BFTPU_MONITOR", "BFTPU_MON_SCRAPE_S", "BFTPU_MON_GAP_S",
             "BFTPU_MON_RULES", "BFTPU_MON_SLOTS", "BFTPU_MON_RING",
             "BFTPU_MON_LINGER", "BFTPU_MON_MASS_TOL",
             "BFTPU_MON_EPOCH_STALL_S", "BFTPU_MON_SUSPECT_RATE",
             "BFTPU_MON_SERVE_MAX_LAG", "BFTPU_MON_DISTRIB_STALENESS",
             "BFTPU_MON_CONV_DIVERGE", "BFTPU_MON_CONV_PLATEAU_S",
             "BFTPU_CHAOS_MON_DROP_SCRAPE")

# injectable clock (sim/clock.py seam) for the delay/straggler sleeps;
# process-level signals (suspend_self) always use wall time — you
# cannot virtualize a SIGSTOP
_clock = _resolve_clock(None)


def set_clock(clock=None) -> None:
    """Install the clock used by :func:`checkpoint`'s scheduled sleeps
    (``None`` restores wall time).  The simulator installs its virtual
    clock so a chaos schedule replayed inside a campaign burns virtual
    seconds, not wall seconds."""
    global _clock
    _clock = _resolve_clock(clock)


def kill(pid: int) -> None:
    """SIGKILL a worker process (no cleanup runs — the hard failure)."""
    os.kill(pid, signal.SIGKILL)


def suspend(pid: int) -> None:
    """SIGSTOP a worker — it looks dead to the detector while stopped
    but resumes mid-instruction on :func:`resume` (the gray failure)."""
    os.kill(pid, signal.SIGSTOP)


def resume(pid: int) -> None:
    os.kill(pid, signal.SIGCONT)


def kill_self() -> None:
    """Immediate SIGKILL of the calling process: no atexit, no teardown
    barrier, no segment unlink — exactly what rank death looks like to
    the survivors."""
    os.kill(os.getpid(), signal.SIGKILL)


def suspend_self(duration_s: float) -> None:
    """Gray-failure injection from inside: SIGSTOP the calling process
    for ``duration_s`` seconds, then resume.  A stopped process cannot
    un-stop itself, so a forked helper (immune to the parent's stop)
    sleeps out the outage and delivers the SIGCONT.  Pick a duration
    past the failure timeout and the detector declares the rank dead
    while it is merely slow — the flapping-rank scenario the monotone
    dead set exists for."""
    pid = os.getpid()
    child = os.fork()
    if child == 0:
        time.sleep(duration_s)
        try:
            os.kill(pid, signal.SIGCONT)
        finally:
            os._exit(0)
    os.kill(pid, signal.SIGSTOP)  # execution stops HERE until SIGCONT
    os.waitpid(child, 0)  # reap the resumer


def schedule_kill(env: dict, rank: int, step: int,
                  delay_s: float = 0.0) -> dict:
    """Publish a kill schedule into an env mapping (pass to the worker
    spawn): rank ``rank`` dies at its ``step``-th matching checkpoint."""
    env[_KILL_RANK] = str(int(rank))
    env[_KILL_STEP] = str(int(step))
    if delay_s:
        env[_DELAY_S] = str(float(delay_s))
    return env


def schedule_join(env: dict, rank: int, step: int) -> dict:
    """Publish a join-admission schedule: rank ``rank`` (or every rank,
    with ``rank=-1`` — admission is a membership-wide switch, so -1 is
    the common spelling) calls ``islands.admit_pending()`` at its
    ``step``-th matching checkpoint."""
    env[_JOIN_RANK] = str(int(rank))
    env[_JOIN_STEP] = str(int(step))
    return env


def schedule_suspend(env: dict, rank: int, step: int,
                     duration_s: float = 2.5) -> dict:
    """Publish a gray-failure schedule: rank ``rank`` SIGSTOPs itself
    for ``duration_s`` seconds at its ``step``-th matching checkpoint
    (default 2.5s — past the 2s default failure timeout, so the outage
    is long enough to be declared a death)."""
    env[_SUSPEND_RANK] = str(int(rank))
    env[_SUSPEND_STEP] = str(int(step))
    env[_SUSPEND_S] = str(float(duration_s))
    return env


def schedule_slow(env: dict, rank: int, step: int, delay_s: float,
                  stop: Optional[int] = None) -> dict:
    """Publish a STRAGGLER schedule: rank ``rank`` sleeps ``delay_s``
    seconds in its MAIN thread at every matching checkpoint from step
    ``step`` on (until step ``stop``, exclusive, when given — the
    recovery scenario).  Unlike :func:`schedule_suspend` the heartbeat
    thread keeps beating throughout, so the failure detector never
    declares the rank dead: this is the gray failure — slow but
    responsive — that only the adaptive edge-health machine catches."""
    env[_SLOW_RANK] = str(int(rank))
    env[_SLOW_STEP] = str(int(step))
    env[_SLOW_S] = str(float(delay_s))
    if stop is not None:
        env[_SLOW_STOP] = str(int(stop))
    return env


def schedule_partition(env: dict, group: str, step: int,
                       stop: Optional[int] = None) -> dict:
    """Publish a NETWORK PARTITION schedule: from step ``step`` until
    step ``stop`` (exclusive), cross-group traffic drops and liveness
    goes stale across the cut.  ``group`` is the side spec — a
    pipe-separated list of comma-separated global ranks (``"3"`` =
    rank 3 vs everyone else; see
    :meth:`bluefog_tpu.sim.schedule.Fault.partition`).

    Unlike the other chaos kinds, :func:`checkpoint` does NOT act on
    these keys — a worker cannot self-inject a network property.  The
    keys exist so a partition campaign round-trips through the shared
    fault-schedule format (``schedule_to_json`` /
    ``apply_schedule_json``) and so harnesses that DO own the network
    (the fleet simulator; an iptables-driven e2e rig) can read one
    schedule spelling."""
    env[_PARTITION_GROUP] = str(group)
    env[_PARTITION_STEP] = str(int(step))
    if stop is not None:
        env[_PARTITION_STOP] = str(int(stop))
    return env


def schedule_serve_kill(env: dict, replica: int, swap: int,
                        stop: Optional[int] = None) -> dict:
    """Publish a REPLICA MID-SWAP kill schedule: replica ``replica``
    SIGKILLs itself at its ``swap``-th hot-swap, precisely between
    reading the new committed snapshot and the atomic version flip
    (``Replica.poll_swap``).  ``stop`` is the respawn round — like the
    partition stop it is acted on by harnesses that own the fleet (the
    simulator; an e2e respawning the replica), not by the replica
    itself, and exists so the fault round-trips the shared schedule
    format."""
    env[_SERVE_KILL_REPLICA] = str(int(replica))
    env[_SERVE_KILL_SWAP] = str(int(swap))
    if stop is not None:
        env[_SERVE_KILL_STOP] = str(int(stop))
    return env


def schedule_serve_pub_kill(env: dict, publish: int,
                            phase: str = "payload") -> dict:
    """Publish a PUBLISHER MID-PUBLISH kill schedule: the publisher
    SIGKILLs itself during its ``publish``-th snapshot publication —
    ``phase="payload"`` dies with the standby buffer half-written (seq
    odd), ``phase="flip"`` dies with the payload whole but the header
    not yet flipped.  Both must leave every replica on the previous
    committed version (``SnapshotRegion``'s death matrix)."""
    if phase not in ("payload", "flip"):
        raise ValueError(f"serve_pub_kill phase {phase!r} "
                         "(want 'payload' or 'flip')")
    env[_SERVE_PUB_KILL_PUBLISH] = str(int(publish))
    env[_SERVE_PUB_KILL_PHASE] = phase
    return env


def schedule_distrib_kill(env: dict, relay: Optional[int] = None,
                          sync: Optional[int] = None,
                          n: int = 1) -> dict:
    """Publish a DISTRIBUTION-TREE kill schedule (value format
    ``"replica_id:n"``).  ``relay`` SIGKILLs that subscriber right
    after it installs its ``n``-th generation — its committed store
    flipped (children may already be pulling the new version) but its
    own replica never swapped: mid-fanout relay death, the subtree
    must re-parent.  ``sync`` SIGKILLs the subscriber mid-delta — the
    stream received but the staged generation NOT yet flipped: the
    previous version must keep serving."""
    if relay is not None:
        env[_DISTRIB_KILL_RELAY] = f"{int(relay)}:{int(n)}"
    if sync is not None:
        env[_DISTRIB_KILL_SYNC] = f"{int(sync)}:{int(n)}"
    return env


def schedule_to_json() -> str:
    """Serialize the calling process's env-published chaos schedule to
    the shared fault-schedule JSON (see
    :class:`bluefog_tpu.sim.schedule.FaultSchedule`) — the round-trip
    that lets a flaky chaos e2e be replayed as a deterministic sim
    campaign."""
    from bluefog_tpu.sim.schedule import FaultSchedule

    return FaultSchedule.from_env(os.environ).to_json()


def apply_schedule_json(payload: str, env: Optional[dict] = None) -> dict:
    """Publish a shared-format JSON fault schedule into ``env``
    (default: this process's environment) as chaos keys — the inverse
    of :func:`schedule_to_json`."""
    from bluefog_tpu.sim.schedule import FaultSchedule

    return FaultSchedule.from_json(payload).to_env(
        os.environ if env is None else env)


def clear_schedule() -> None:
    """Scrub EVERY chaos key from the calling process's environment —
    kill, join, and suspend schedules alike (a stale key would replay
    the fault in the next test's workers) — plus the sim-campaign,
    lab, serving-plane, and monitor keys, which are schedules by
    another name."""
    for k in _ALL_KEYS + _SIM_KEYS + _LAB_KEYS + _SERVE_KEYS \
            + _DISTRIB_KEYS + _LOADGEN_KEYS + _MON_KEYS:
        os.environ.pop(k, None)


_counters = {}


def _matches(scheduled: Optional[str], rank: int) -> bool:
    return scheduled is not None and int(scheduled) in (int(rank), -1)


def checkpoint(rank: int, tag: str = "step") -> None:
    """Chaos instrumentation point: count invocations per (rank, tag)
    and execute the scheduled fault(s) when the counter hits the
    scheduled step.  A no-op (a few dict lookups) when no schedule is
    set.  Suspend and join fire exactly once (``==`` their step); kill
    fires at or after its step (``>=`` — the process is gone either
    way)."""
    env = os.environ
    if (_KILL_RANK not in env and _JOIN_RANK not in env
            and _SUSPEND_RANK not in env and _SLOW_RANK not in env):
        return
    delay = env.get(_DELAY_S)
    if delay:
        _clock.sleep(float(delay))
    key = (int(rank), tag)
    n = _counters.get(key, 0) + 1
    _counters[key] = n
    if _matches(env.get(_SLOW_RANK), rank) \
            and n >= int(env.get(_SLOW_STEP, "1")) \
            and (_SLOW_STOP not in env or n < int(env[_SLOW_STOP])):
        _clock.sleep(float(env.get(_SLOW_S, "0.5")))
    if _matches(env.get(_SUSPEND_RANK), rank) \
            and n == int(env.get(_SUSPEND_STEP, "1")):
        suspend_self(float(env.get(_SUSPEND_S, "2.5")))
    if _matches(env.get(_JOIN_RANK), rank) \
            and n == int(env.get(_JOIN_STEP, "1")):
        from bluefog_tpu import islands

        islands.admit_pending()
    if _matches(env.get(_KILL_RANK), rank) \
            and n >= int(env.get(_KILL_STEP, "1")):
        kill_self()


def corrupt_chunk(mirror, data: Optional[bytes] = None,
                  tear_at: int = 0) -> None:
    """Freeze a deposit mid-chunk on a ChunkRingMirror: chunk
    ``tear_at`` is left odd with half its bytes stored and ``wseq``
    stays odd — the exact state a dead writer leaves behind.  Recover
    with ``mirror.force_drain()`` (or resume with
    ``mirror.complete_write()``)."""
    if data is None:
        data = os.urandom(mirror.nbytes)
    mirror.begin_torn_write(data, p=1.0, tear_at=tear_at)
