"""Fault-injection harness for the resilience e2e tests.

Faults are injected two ways:

- **from outside**: :func:`kill`, :func:`suspend`, :func:`resume` act
  on a worker pid (SIGKILL / SIGSTOP / SIGCONT) — the test process
  steers its spawned islands;
- **from inside**: workers call :func:`checkpoint(rank, step)` at
  instrumented points; a schedule published through env vars
  (``BFTPU_CHAOS_KILL_RANK`` / ``BFTPU_CHAOS_KILL_STEP`` /
  ``BFTPU_CHAOS_DELAY_S``) makes the matching rank kill itself with
  SIGKILL mid-op — deterministic death at a protocol-relevant point
  (e.g. between the expose and the deposit of a win_put), which no
  external signal can time reliably.

Mailbox corruption for protocol tests goes through
:func:`corrupt_chunk` on a :class:`~bluefog_tpu.native.shm_native.
ChunkRingMirror` — it freezes a deposit mid-chunk exactly the way a
dead writer does, so the dead-writer drain path is exercised without
an actual process death.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Optional

__all__ = [
    "kill",
    "suspend",
    "resume",
    "kill_self",
    "checkpoint",
    "schedule_kill",
    "clear_schedule",
    "corrupt_chunk",
]

_KILL_RANK = "BFTPU_CHAOS_KILL_RANK"
_KILL_STEP = "BFTPU_CHAOS_KILL_STEP"
_DELAY_S = "BFTPU_CHAOS_DELAY_S"


def kill(pid: int) -> None:
    """SIGKILL a worker process (no cleanup runs — the hard failure)."""
    os.kill(pid, signal.SIGKILL)


def suspend(pid: int) -> None:
    """SIGSTOP a worker — it looks dead to the detector while stopped
    but resumes mid-instruction on :func:`resume` (the gray failure)."""
    os.kill(pid, signal.SIGSTOP)


def resume(pid: int) -> None:
    os.kill(pid, signal.SIGCONT)


def kill_self() -> None:
    """Immediate SIGKILL of the calling process: no atexit, no teardown
    barrier, no segment unlink — exactly what rank death looks like to
    the survivors."""
    os.kill(os.getpid(), signal.SIGKILL)


def schedule_kill(env: dict, rank: int, step: int,
                  delay_s: float = 0.0) -> dict:
    """Publish a kill schedule into an env mapping (pass to the worker
    spawn): rank ``rank`` dies at its ``step``-th matching checkpoint."""
    env[_KILL_RANK] = str(int(rank))
    env[_KILL_STEP] = str(int(step))
    if delay_s:
        env[_DELAY_S] = str(float(delay_s))
    return env


def clear_schedule() -> None:
    for k in (_KILL_RANK, _KILL_STEP, _DELAY_S):
        os.environ.pop(k, None)


_counters = {}


def checkpoint(rank: int, tag: str = "step") -> None:
    """Chaos instrumentation point: count invocations per (rank, tag)
    and execute the scheduled fault when the counter hits the scheduled
    step.  A no-op (two dict lookups) when no schedule is set."""
    kill_rank = os.environ.get(_KILL_RANK)
    if kill_rank is None:
        return
    delay = os.environ.get(_DELAY_S)
    if delay:
        time.sleep(float(delay))
    if int(kill_rank) != int(rank):
        return
    key = (int(rank), tag)
    n = _counters.get(key, 0) + 1
    _counters[key] = n
    if n >= int(os.environ.get(_KILL_STEP, "1")):
        kill_self()


def corrupt_chunk(mirror, data: Optional[bytes] = None,
                  tear_at: int = 0) -> None:
    """Freeze a deposit mid-chunk on a ChunkRingMirror: chunk
    ``tear_at`` is left odd with half its bytes stored and ``wseq``
    stays odd — the exact state a dead writer leaves behind.  Recover
    with ``mirror.force_drain()`` (or resume with
    ``mirror.complete_write()``)."""
    if data is None:
        data = os.urandom(mirror.nbytes)
    mirror.begin_torn_write(data, p=1.0, tear_at=tear_at)
