"""Quorum fencing for membership commits + the ORPHAN quiesce verdict.

Every earlier resilience layer treats a silent peer as a *crash*: the
detector declares it dead, :func:`~bluefog_tpu.resilience.healing.
heal_topology` excises it, life goes on.  A network **partition**
breaks that model — both sides see the other silent, both heal, and
two live islands keep gossiping under one job name with divergent
membership epochs and a double-counted mass ledger (split-brain).

The fence is the classic quorum rule: a heal or demote may only
*commit* when the committer can still account for a **strict majority
of the current membership epoch** as live.  The minority side gets the
other verdict — it is the ORPHAN: it must stop healing, freeze its
windows, park its progress engine, and wait for connectivity to
return, at which point it re-enters through the join machinery
(:func:`bluefog_tpu.islands.merge_orphan`) carrying its debiased
estimate.  At most one epoch lineage can therefore commit progress
during any partition — the invariant the simulator checks after every
event (:mod:`bluefog_tpu.sim.invariants`).

``BFTPU_QUORUM=off`` restores the pre-quorum behavior (every side
heals; fine for fleets whose only failure mode really is crashes).
The default is ``majority``: when a strict majority is visible the
fence changes nothing — heals proceed exactly as before — so only
sub-majority splits behave differently, and those were split-brain
territory anyway.  See docs/RESILIENCE.md "Orphan quiesce".
"""

from __future__ import annotations

import os

__all__ = [
    "OrphanedError",
    "quorum_mode",
    "quorum_enabled",
    "quorum_met",
    "majority_floor",
]


class OrphanedError(RuntimeError):
    """This rank lost membership quorum and quiesced (ORPHAN state).

    Retriable by design: the rank's state is intact and frozen — the
    caller should back off, wait for connectivity, and either retry
    after :func:`bluefog_tpu.islands.merge_orphan` re-admits the rank,
    or surface the stall to its own supervisor.  ``live``/``total``
    record the membership arithmetic behind the verdict.
    """

    def __init__(self, message: str, live: int = -1, total: int = -1,
                 epoch: int = -1):
        super().__init__(message)
        self.live = live
        self.total = total
        self.epoch = epoch


def quorum_mode() -> str:
    """``BFTPU_QUORUM``: ``majority`` (default) fences heal/demote
    commits on a strict-majority live set; ``off`` restores the
    unfenced behavior."""
    mode = os.environ.get("BFTPU_QUORUM", "majority").strip().lower()
    return mode if mode in ("majority", "off") else "majority"


def quorum_enabled() -> bool:
    return quorum_mode() != "off"


def majority_floor(total: int) -> int:
    """Minimum live count that constitutes a strict majority of a
    ``total``-member epoch: ``floor(total/2) + 1``.  A 1-member epoch
    trivially has quorum (itself)."""
    return max(1, int(total) // 2 + 1)


def quorum_met(live: int, total: int) -> bool:
    """Strict-majority test: can ``live`` members of a ``total``-member
    epoch commit a membership change?"""
    return int(live) >= majority_floor(total)
