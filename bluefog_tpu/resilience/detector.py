"""Heartbeat failure detector, piggybacked on the job transport.

Every island rank stamps a per-rank liveness word with the system-wide
monotonic clock; the detector declares a peer dead once its stamp is
older than the configured timeout.  The liveness word lives wherever
the job segment lives, so the detector rides the existing transports:

- **shm**: one cache line per rank in the native job segment
  (``bf_shm_job_heartbeat`` / ``bf_shm_job_liveness``), or the
  heartbeat u64 array in the lockf fallback segment;
- **tcp**: coordinator-mediated leases — each rank heartbeats the
  rank-0 coordinator, which serves the lease table back to
  ``liveness()`` queries (see native/tcp_transport.py).

The job object is duck-typed: any transport exposing ``heartbeat()``
and ``liveness(rank) -> float`` (seconds on ``time.monotonic``'s
clock; 0.0 = never beat) participates.  A transport without the
surface degrades to "everyone is alive" — resilience is opt-in per
transport, never a crash.

Env knobs:

- ``BFTPU_HEARTBEAT_INTERVAL_S`` (default 0.05) — background beat
  period;
- ``BFTPU_FAILURE_TIMEOUT_S`` (default 2.0) — stamp age past which a
  peer is declared dead.  Ranks that have NEVER beaten get a startup
  grace of the same length measured from detector construction.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Set

from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.tracing import tracer as _tracing

__all__ = [
    "PeerTimeoutError",
    "FailureDetector",
    "heartbeat_interval_s",
    "failure_timeout_s",
]


class PeerTimeoutError(RuntimeError):
    """A peer rank failed to respond within its deadline.

    ``rank`` names the unresponsive peer (-1 = the coordinator),
    ``addr`` its transport address ("host:port", when known) and ``op``
    the in-flight operation that hit the deadline.  Raised by the tcp
    transport's bounded waits and by degraded-step retries once the
    retry budget is exhausted.
    """

    def __init__(self, message: str, rank: int = -1,
                 addr: Optional[str] = None, op: Optional[str] = None):
        super().__init__(message)
        self.rank = rank
        self.addr = addr
        self.op = op


def heartbeat_interval_s() -> float:
    try:
        return float(os.environ.get("BFTPU_HEARTBEAT_INTERVAL_S", "0.05"))
    except ValueError:
        return 0.05


def failure_timeout_s() -> float:
    try:
        return float(os.environ.get("BFTPU_FAILURE_TIMEOUT_S", "2.0"))
    except ValueError:
        return 2.0


class FailureDetector:
    """Background heartbeater + liveness judge over a job transport."""

    def __init__(self, job, rank: int, nranks: int,
                 timeout: Optional[float] = None,
                 interval: Optional[float] = None):
        self._job = job
        self.rank = int(rank)
        self.nranks = int(nranks)
        self.timeout = failure_timeout_s() if timeout is None else timeout
        self.interval = (heartbeat_interval_s() if interval is None
                         else interval)
        self._supported = (hasattr(job, "heartbeat")
                           and hasattr(job, "liveness"))
        self._born = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._declared: Set[int] = set()
        self._lock = threading.Lock()
        self.beat()

    @property
    def supported(self) -> bool:
        return self._supported

    def beat(self) -> None:
        """One heartbeat now (the background thread calls this; ops on
        the hot path may too — it is one relaxed store)."""
        if self._supported:
            try:
                self._job.heartbeat()
            except Exception:
                return
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("resilience.heartbeats_sent").inc()
        tr = _tracing.get_tracer()
        if tr.enabled:
            # ride the heartbeat cadence: one clock probe per beat keeps
            # the min-RTT offset estimator fresh without a second timer
            tr.resample_clock(self._job)

    def start(self) -> "FailureDetector":
        if self._thread is None and self._supported:
            self._thread = threading.Thread(
                target=self._run, name="bf-heartbeat", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    def is_alive(self, rank: int) -> bool:
        if not self._supported or rank == self.rank:
            return True
        with self._lock:
            if rank in self._declared:
                return False
        try:
            stamp = float(self._job.liveness(rank))
        except Exception:
            return True
        now = time.monotonic()
        if stamp <= 0.0:
            # never beat: startup grace measured from detector birth
            alive = now - self._born <= self.timeout
        else:
            alive = now - stamp <= self.timeout
        reg = _telemetry.get_registry()
        if reg.enabled:
            which = ("resilience.heartbeats_observed" if alive
                     else "resilience.heartbeats_missed")
            reg.counter(which).inc()
        return alive

    def dead_ranks(self) -> Set[int]:
        """All ranks currently considered dead.  A rank once declared
        dead STAYS dead (the healing rules assume monotone membership
        loss; a restarted rank must rejoin as a new job)."""
        dead = {r for r in range(self.nranks)
                if r != self.rank and not self.is_alive(r)}
        with self._lock:
            new = dead - self._declared
            self._declared |= dead
            declared = set(self._declared)
        for r in sorted(new):
            self._note_declared(r, how="heartbeat")
        return declared

    def declare_dead(self, rank: int) -> None:
        """Externally assert a rank is dead (e.g. the tcp transport saw
        its connection reset, or a test injected the failure)."""
        with self._lock:
            new = int(rank) not in self._declared
            self._declared.add(int(rank))
        if new:
            self._note_declared(int(rank), how="external")

    def _note_declared(self, rank: int, how: str) -> None:
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("resilience.death_declarations").inc()
            reg.journal("death_declared", peer_rank=rank, how=how,
                        timeout_s=self.timeout)

    def __enter__(self) -> "FailureDetector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
