"""Heartbeat failure detector, piggybacked on the job transport.

Every island rank stamps a per-rank liveness word with the system-wide
monotonic clock; the detector declares a peer dead once its stamp is
older than the configured timeout.  The liveness word lives wherever
the job segment lives, so the detector rides the existing transports:

- **shm**: one cache line per rank in the native job segment
  (``bf_shm_job_heartbeat`` / ``bf_shm_job_liveness``), or the
  heartbeat u64 array in the lockf fallback segment;
- **tcp**: coordinator-mediated leases — each rank heartbeats the
  rank-0 coordinator, which serves the lease table back to
  ``liveness()`` queries (see native/tcp_transport.py).

The job object is duck-typed: any transport exposing ``heartbeat()``
and ``liveness(rank) -> float`` (seconds on ``time.monotonic``'s
clock; 0.0 = never beat) participates.  A transport without the
surface degrades to "everyone is alive" — resilience is opt-in per
transport, never a crash.

Env knobs:

- ``BFTPU_HEARTBEAT_INTERVAL_S`` (default 0.05) — background beat
  period;
- ``BFTPU_FAILURE_TIMEOUT_S`` (default 2.0) — stamp age past which a
  peer is declared dead.  Ranks that have NEVER beaten get a startup
  grace of the same length measured from detector construction.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Set

from bluefog_tpu.sim.clock import now_fn as _now_fn
from bluefog_tpu.telemetry import registry as _telemetry
from bluefog_tpu.tracing import tracer as _tracing

__all__ = [
    "PeerTimeoutError",
    "FailureDetector",
    "EdgeHealth",
    "EDGE_ALIVE",
    "EDGE_SUSPECT",
    "EDGE_DEAD",
    "heartbeat_interval_s",
    "failure_timeout_s",
    "suspect_misses",
    "promote_clean",
    "demote_floor_s",
]


class PeerTimeoutError(RuntimeError):
    """A peer rank failed to respond within its deadline.

    ``rank`` names the unresponsive peer (-1 = the coordinator),
    ``addr`` its transport address ("host:port", when known) and ``op``
    the in-flight operation that hit the deadline.  Raised by the tcp
    transport's bounded waits and by degraded-step retries once the
    retry budget is exhausted.
    """

    def __init__(self, message: str, rank: int = -1,
                 addr: Optional[str] = None, op: Optional[str] = None):
        super().__init__(message)
        self.rank = rank
        self.addr = addr
        self.op = op


def heartbeat_interval_s() -> float:
    try:
        return float(os.environ.get("BFTPU_HEARTBEAT_INTERVAL_S", "0.05"))
    except ValueError:
        return 0.05


def failure_timeout_s() -> float:
    try:
        return float(os.environ.get("BFTPU_FAILURE_TIMEOUT_S", "2.0"))
    except ValueError:
        return 2.0


def suspect_misses() -> int:
    """Consecutive deadline-missed deposit gaps (one miss per stale
    gap, however long — see ``islands._adaptive_probe``) before
    ALIVE -> SUSPECT (``BFTPU_SUSPECT_MISSES``)."""
    try:
        return max(1, int(os.environ.get("BFTPU_SUSPECT_MISSES", "3")))
    except ValueError:
        return 3


def promote_clean() -> int:
    """Consecutive clean (on-deadline) observations before a SUSPECT
    rank is promoted back to ALIVE (``BFTPU_PROMOTE_CLEAN``)."""
    try:
        return max(1, int(os.environ.get("BFTPU_PROMOTE_CLEAN", "5")))
    except ValueError:
        return 5


def demote_floor_s() -> float:
    """Hysteresis floor: minimum seconds between consecutive edge-state
    transitions for one peer (``BFTPU_DEMOTE_FLOOR_S``) — no
    demote/promote cycle can be shorter, so a flapping rank cannot
    thrash membership epochs."""
    try:
        return float(os.environ.get("BFTPU_DEMOTE_FLOOR_S", "1.0"))
    except ValueError:
        return 1.0


# -- the three-state gray-failure machine ----------------------------------
#
# The heartbeat detector above answers one binary question: has the rank
# stamped its liveness word recently?  A GRAY failure — throttled,
# SIGSTOP'd-and-resumed, swapping — keeps stamping (the heartbeat thread
# is cheap) while its win ops crawl, so it convoys its neighbors without
# ever tripping the timeout.  EdgeHealth tracks the per-peer *edge*
# signal instead (deadline misses observed on the win-op path) through
# three states:
#
#     ALIVE --(>= suspect_misses consecutive misses)--> SUSPECT
#     SUSPECT --(>= promote_clean consecutive cleans)--> ALIVE
#     any --(death declaration)--> DEAD (absorbing)
#
# with one hysteresis rule: transitions for a peer are at least
# ``floor_s`` apart (DEAD excepted — death is never delayed), so the
# demote/promote cycle a flapping rank can induce is bounded below by
# the floor.  The clock is injectable for deterministic simulation (the
# analysis ``adaptive.hysteresis`` rule drives adversarial schedules
# through a fake clock).

EDGE_ALIVE = "alive"
EDGE_SUSPECT = "suspect"
EDGE_DEAD = "dead"

_EDGE_STATE_CODE = {EDGE_ALIVE: 0, EDGE_SUSPECT: 1, EDGE_DEAD: 2}


class EdgeHealth:
    """Per-peer three-state gray-failure machine (see module comment).

    Peers are identified by whatever ids the caller feeds (the island
    runtime uses GLOBAL ranks so the machine survives membership-epoch
    switches).  Thread-safe; all mutation happens under one lock.
    """

    def __init__(self, misses: Optional[int] = None,
                 clean: Optional[int] = None,
                 floor_s: Optional[float] = None,
                 clock=time.monotonic):
        self.misses = suspect_misses() if misses is None else int(misses)
        self.clean = promote_clean() if clean is None else int(clean)
        self.floor_s = demote_floor_s() if floor_s is None else float(floor_s)
        self._clock = _now_fn(clock)
        self._lock = threading.Lock()
        self._state: dict = {}        # peer -> state string
        self._miss_streak: dict = {}  # peer -> consecutive misses
        self._clean_streak: dict = {} # peer -> consecutive cleans
        self._since: dict = {}        # peer -> last transition time
        self._log: list = []          # [{t, peer, frm, to}]

    def state(self, peer: int) -> str:
        with self._lock:
            return self._state.get(int(peer), EDGE_ALIVE)

    def suspects(self):
        with self._lock:
            return {p for p, s in self._state.items() if s == EDGE_SUSPECT}

    def time_in_state(self, peer: int) -> float:
        with self._lock:
            since = self._since.get(int(peer))
        return float("inf") if since is None else self._clock() - since

    def transitions(self):
        """The transition log ``[{t, peer, frm, to}, ...]`` (copies) —
        the artifact the hysteresis verifier rule audits."""
        with self._lock:
            return [dict(e) for e in self._log]

    def _floor_open(self, peer: int, now: float) -> bool:
        since = self._since.get(peer)
        return since is None or now - since >= self.floor_s

    def _transition(self, peer: int, to: str, now: float,
                    adopted: bool = False) -> None:
        frm = self._state.get(peer, EDGE_ALIVE)
        self._state[peer] = to
        self._since[peer] = now
        self._miss_streak[peer] = 0
        self._clean_streak[peer] = 0
        ev = {"t": now, "peer": peer, "frm": frm, "to": to}
        if adopted:
            ev["adopted"] = True
        self._log.append(ev)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.gauge("adaptive.edge_state", peer=peer).set(
                _EDGE_STATE_CODE[to])
            reg.journal("edge_state", peer=peer, frm=frm, to=to,
                        adopted=adopted)

    def note_miss(self, peer: int) -> str:
        """One edge-deadline miss observed on ``peer``.  Returns the
        (possibly new) state."""
        peer = int(peer)
        now = self._clock()
        with self._lock:
            st = self._state.get(peer, EDGE_ALIVE)
            if st == EDGE_DEAD:
                return st
            self._clean_streak[peer] = 0
            self._miss_streak[peer] = self._miss_streak.get(peer, 0) + 1
            if (st == EDGE_ALIVE
                    and self._miss_streak[peer] >= self.misses
                    and self._floor_open(peer, now)):
                self._transition(peer, EDGE_SUSPECT, now)
            return self._state.get(peer, EDGE_ALIVE)

    def note_clean(self, peer: int) -> str:
        """One on-deadline observation of ``peer`` (a fresh deposit, a
        fast acquire).  Returns the (possibly new) state."""
        peer = int(peer)
        now = self._clock()
        with self._lock:
            st = self._state.get(peer, EDGE_ALIVE)
            if st == EDGE_DEAD:
                return st
            self._miss_streak[peer] = 0
            self._clean_streak[peer] = self._clean_streak.get(peer, 0) + 1
            if (st == EDGE_SUSPECT
                    and self._clean_streak[peer] >= self.clean
                    and self._floor_open(peer, now)):
                self._transition(peer, EDGE_ALIVE, now)
            return self._state.get(peer, EDGE_ALIVE)

    def absolve(self, peer: int) -> str:
        """Adopt a fleet-level PROMOTE verdict for ``peer``.

        After a demotion only the anchor keeps an edge to the straggler,
        so every other member's machine is starved of observations and
        holds the peer SUSPECT forever; when the anchor's (floored,
        evidence-based) promote commits, those stale verdicts would
        instantly re-demote — an epoch thrash no local floor can stop,
        because no local state ever transitions.  Absolving mirrors the
        anchor's verdict: the peer resets to ALIVE with fresh streaks
        and a fresh floor clock (so a relapse is again floored locally).
        Logged with ``adopted=True`` — the hysteresis audit exempts
        mirrored verdicts, whose floor was paid at the anchor.  DEAD
        stays absorbing."""
        peer = int(peer)
        now = self._clock()
        with self._lock:
            st = self._state.get(peer, EDGE_ALIVE)
            if st in (EDGE_DEAD, EDGE_ALIVE):
                return st
            self._transition(peer, EDGE_ALIVE, now, adopted=True)
            return EDGE_ALIVE

    def note_dead(self, peer: int) -> str:
        """Absorbing death (the heartbeat detector's verdict outranks
        the gray-failure machine; never floor-delayed)."""
        peer = int(peer)
        now = self._clock()
        with self._lock:
            if self._state.get(peer) != EDGE_DEAD:
                self._transition(peer, EDGE_DEAD, now)
            return EDGE_DEAD


class FailureDetector:
    """Background heartbeater + liveness judge over a job transport."""

    def __init__(self, job, rank: int, nranks: int,
                 timeout: Optional[float] = None,
                 interval: Optional[float] = None,
                 clock=None):
        self._job = job
        self.rank = int(rank)
        self.nranks = int(nranks)
        self.timeout = failure_timeout_s() if timeout is None else timeout
        self.interval = (heartbeat_interval_s() if interval is None
                         else interval)
        self._supported = (hasattr(job, "heartbeat")
                           and hasattr(job, "liveness"))
        # injectable monotonic clock (sim/clock.py seam): ``None`` is
        # wall time — production behavior unchanged
        self._clock = _now_fn(clock)
        self._born = self._clock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._declared: Set[int] = set()
        self._lock = threading.Lock()
        # optional gray-failure machine (resilience/adaptive.py attaches
        # one keyed by GLOBAL rank): death declarations flow into it so
        # DEAD outranks SUSPECT; ``to_peer`` maps this detector's local
        # ranks to the machine's peer ids (identity when unset)
        self.edge_health: Optional[EdgeHealth] = None
        self.to_peer = None
        self.beat()

    @property
    def supported(self) -> bool:
        return self._supported

    def beat(self) -> None:
        """One heartbeat now (the background thread calls this; ops on
        the hot path may too — it is one relaxed store)."""
        if self._supported:
            try:
                self._job.heartbeat()
            except Exception:
                return
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("resilience.heartbeats_sent").inc()
        tr = _tracing.get_tracer()
        if tr.enabled:
            # ride the heartbeat cadence: one clock probe per beat keeps
            # the min-RTT offset estimator fresh without a second timer
            tr.resample_clock(self._job)

    def start(self) -> "FailureDetector":
        if self._thread is None and self._supported:
            self._thread = threading.Thread(
                target=self._run, name="bf-heartbeat", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval):
            self.beat()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=1.0)
            self._thread = None

    def is_alive(self, rank: int) -> bool:
        if not self._supported or rank == self.rank:
            return True
        with self._lock:
            if rank in self._declared:
                return False
        try:
            stamp = float(self._job.liveness(rank))
        except Exception:
            return True
        now = self._clock()
        if stamp <= 0.0:
            # never beat: startup grace measured from detector birth
            alive = now - self._born <= self.timeout
        else:
            alive = now - stamp <= self.timeout
        reg = _telemetry.get_registry()
        if reg.enabled:
            which = ("resilience.heartbeats_observed" if alive
                     else "resilience.heartbeats_missed")
            reg.counter(which).inc()
        return alive

    def dead_ranks(self) -> Set[int]:
        """All ranks currently considered dead.  A rank once declared
        dead STAYS dead (the healing rules assume monotone membership
        loss; a restarted rank must rejoin as a new job)."""
        dead = {r for r in range(self.nranks)
                if r != self.rank and not self.is_alive(r)}
        with self._lock:
            new = dead - self._declared
            self._declared |= dead
            declared = set(self._declared)
        for r in sorted(new):
            self._note_declared(r, how="heartbeat")
        return declared

    def declare_dead(self, rank: int) -> None:
        """Externally assert a rank is dead (e.g. the tcp transport saw
        its connection reset, or a test injected the failure)."""
        with self._lock:
            new = int(rank) not in self._declared
            self._declared.add(int(rank))
        if new:
            self._note_declared(int(rank), how="external")

    def _note_declared(self, rank: int, how: str) -> None:
        if self.edge_health is not None:
            peer = rank if self.to_peer is None else self.to_peer(rank)
            self.edge_health.note_dead(peer)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("resilience.death_declarations").inc()
            reg.journal("death_declared", peer_rank=rank, how=how,
                        timeout_s=self.timeout)

    def __enter__(self) -> "FailureDetector":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
