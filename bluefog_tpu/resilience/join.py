"""Elastic membership: the rank-join protocol for island jobs.

PR 3 taught the fleet to SHRINK (heal_topology excises the dead); this
module is the GROW side: a brand-new process rendezvouses with a live
job, is granted a **fresh global rank** (the monotone-dead-set contract
— a restarted rank never reuses its old identity), and the whole
membership moves together to a new **epoch**.

The coordination medium is a **membership board**: one JSON document in
the shm dir (``bf_<job>_membership``), updated read-modify-write under
an ``lockf`` sidecar lock and published by atomic rename, plus the
8-byte **membership-epoch word** (``shm_native.membership_epoch``) as
the cheap has-anything-changed probe.  On the pure-TCP transport the
coordinator serves the same rendezvous primitives as wire ops
(``_OP_JOIN_RANK`` / ``_OP_EPOCH`` in native/tcp_transport.py) for the
multi-host deployment where joiner and members share no filesystem.

Protocol (see docs/RESILIENCE.md, "Elastic membership"):

1. the joiner **posts a request** on the board and polls for a grant;
2. every member calls :func:`bluefog_tpu.islands.admit_pending` at a
   round barrier; the **sponsor** (lowest live global rank) grants all
   pending requests: it assigns fresh global ranks off the board's
   monotone ``next_rank`` counter, computes the grown topology
   (:func:`~bluefog_tpu.resilience.healing.grow_topology` over the live
   member graph), and commits an **epoch record** — members, dense
   edge list, window metadata, sponsor — in one atomic board write;
3. every member (and the joiner) observes the record and performs the
   **epoch switch**: drain + retire outstanding mailbox deposits into
   the mass ledger, close the old epoch's segments, bind the
   epoch-suffixed job namespace (``<job>_e<N>``, segments sized for the
   new member count), recreate the windows, and barrier;
4. the joiner onboards by reading the sponsor's exposed window state
   (the ``broadcast`` window path) and enters with **unit mass at the
   sponsor's debiased estimate**, so Σx/Σp is preserved at consensus —
   the admitted mass is journaled (``MASS_JOIN_ADMITTED``) and the
   ledger balance at the switch barrier is journaled per rank
   (``epoch_switch``), which is what the analysis
   ``resilience.membership-epoch`` rule audits.

Env knobs:

- ``BFTPU_JOIN_TIMEOUT_S`` (default 60) — joiner-side wait for a grant
  (members admit at their own round cadence);
- ``BFTPU_JOIN_POLL_S`` (default 0.05) — board poll period.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import networkx as nx

from bluefog_tpu.native import shm_native
from bluefog_tpu.resilience import healing as _healing
from bluefog_tpu.sim.clock import resolve_clock as _resolve_clock

__all__ = [
    "BOARD_SCHEMA",
    "MembershipBoard",
    "JoinGrant",
    "epoch_job",
    "join_timeout_s",
    "join_poll_s",
]

BOARD_SCHEMA = "bftpu-membership/1"


def join_timeout_s() -> float:
    try:
        return float(os.environ.get("BFTPU_JOIN_TIMEOUT_S", "60"))
    except ValueError:
        return 60.0


def join_poll_s() -> float:
    try:
        return float(os.environ.get("BFTPU_JOIN_POLL_S", "0.05"))
    except ValueError:
        return 0.05


def epoch_job(job: str, epoch: int) -> str:
    """The shm namespace for a membership epoch.  Epoch 0 is the launch
    namespace unchanged (pre-elastic jobs never see a suffix); later
    epochs get ``_e<N>``, which still matches the ``bf_<job>_*`` cleanup
    glob so crashed-run hygiene reclaims every epoch's segments."""
    return job if int(epoch) == 0 else f"{job}_e{int(epoch)}"


@dataclasses.dataclass(frozen=True)
class JoinGrant:
    """One admitted joiner's view of an epoch record."""

    rank: int                     # fresh global rank
    epoch: int
    members: Tuple[int, ...]      # sorted global ranks of the new epoch
    sponsor: int                  # sponsor's global rank
    record: dict                  # the full epoch record

    @property
    def local_rank(self) -> int:
        return self.members.index(self.rank)

    @property
    def sponsor_local(self) -> int:
        return self.members.index(self.sponsor)

    @property
    def size(self) -> int:
        return len(self.members)


def record_graph(record: dict) -> nx.DiGraph:
    """Rebuild the epoch's dense MH-weighted topology from the record's
    edge list — every member and the joiner derive the SAME graph from
    the SAME committed record (consensus by construction, not by
    re-derivation)."""
    from bluefog_tpu import topology_util

    G = nx.DiGraph()
    G.add_nodes_from(range(len(record["members"])))
    G.add_edges_from((int(u), int(v)) for u, v in record["edges"])
    topology_util.MetropolisHastingsWeights(G)
    G.graph["grown_from"] = tuple(int(j) for j in record.get("joined", ()))
    if record.get("reweight"):
        # adaptive reweight records tag the graph like demote_topology
        # does, so the analysis rules see the same artifact either way
        G.graph["demoted_from"] = tuple(
            int(g) for g in record.get("demoted", ()))
    return G


class MembershipBoard:
    """The job's membership document: requests in, epoch records out.

    All mutation is read-modify-write under an exclusive ``lockf`` on a
    sidecar lock file (the lock file is never replaced, so the lock is
    on a stable inode), and the document itself is published by atomic
    rename — readers never see a torn JSON.
    """

    def __init__(self, job: str, clock=None):
        self.job = job
        # injectable clock (sim/clock.py seam) for the grant-poll loop;
        # ``None`` is wall time — production behavior unchanged
        self._clock = _resolve_clock(clock)
        base = shm_native.seg_name(job, "membership")[1:]
        self.path = os.path.join(shm_native._FALLBACK_DIR, base)
        self.lock_path = self.path + ".lock"

    # -- document I/O -----------------------------------------------------

    def read(self) -> Optional[dict]:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _publish(self, doc: dict) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def _locked(self):
        import contextlib
        import fcntl

        @contextlib.contextmanager
        def cm():
            fd = os.open(self.lock_path, os.O_RDWR | os.O_CREAT, 0o600)
            try:
                fcntl.lockf(fd, fcntl.LOCK_EX)
                yield
            finally:
                fcntl.lockf(fd, fcntl.LOCK_UN)
                os.close(fd)

        return cm()

    def _publish_epoch_word(self, epoch: int) -> None:
        """Publish the 8-byte membership-epoch word — the cheap
        has-anything-changed probe members poll at round barriers.
        Separated out so a transport that keeps its epoch word
        somewhere other than the shm segment (the simulator's
        in-memory board) can override just this."""
        shm_native.publish_membership_epoch(self.job, int(epoch))

    # -- lifecycle --------------------------------------------------------

    def ensure(self, nranks: int) -> dict:
        """Idempotently create the epoch-0 document (any member may call
        this; first writer wins)."""
        with self._locked():
            doc = self.read()
            if doc is not None:
                return doc
            doc = {
                "schema": BOARD_SCHEMA,
                "job": self.job,
                "epoch": 0,
                "next_rank": int(nranks),
                "members": list(range(int(nranks))),
                "requests": [],
                "epochs": [],
            }
            self._publish(doc)
            return doc

    # -- joiner side ------------------------------------------------------

    def post_request(self, retiring: int = -1) -> str:
        """Publish a join request; returns the request id to poll on.

        ``retiring`` names a global rank this joiner is abandoning — a
        merging orphan re-enters under a fresh rank while its quiesced
        old identity still looks alive (heartbeats only stopped at the
        merge).  Members MUST excise it before granting: the grown
        view's new-epoch barrier would otherwise wait forever on an
        identity that never switches (``islands.admit_pending`` treats
        it exactly like a detector-confirmed corpse).
        """
        req_id = f"{socket.gethostname()}-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        with self._locked():
            doc = self.read()
            if doc is None:
                raise RuntimeError(
                    f"no membership board for job {self.job!r} — is the "
                    "job running (islands.init publishes the board)?")
            req = {"req": req_id, "pid": os.getpid(),
                   "host": socket.gethostname(), "t": time.time()}
            if int(retiring) >= 0:
                req["retiring"] = int(retiring)
            doc["requests"].append(req)
            self._publish(doc)
        return req_id

    def wait_for_grant(self, req_id: str,
                       timeout: Optional[float] = None) -> JoinGrant:
        """Poll until some epoch record grants ``req_id`` a rank."""
        deadline = self._clock.deadline(join_timeout_s()
                                        if timeout is None else timeout)
        poll = join_poll_s()
        while True:
            doc = self.read()
            if doc is not None:
                for rec in reversed(doc["epochs"]):
                    granted = rec.get("granted", {})
                    if req_id in granted:
                        return JoinGrant(
                            rank=int(granted[req_id]),
                            epoch=int(rec["epoch"]),
                            members=tuple(int(m) for m in rec["members"]),
                            sponsor=int(rec["sponsor"]),
                            record=rec,
                        )
            if self._clock.expired(deadline):
                raise TimeoutError(
                    f"join request {req_id} not granted within timeout "
                    f"(job {self.job!r}; is any member calling "
                    "islands.admit_pending()?)")
            self._clock.sleep(poll)

    # -- sponsor side -----------------------------------------------------

    def pending_requests(self) -> List[dict]:
        doc = self.read()
        return list(doc["requests"]) if doc else []

    def epoch_record(self, epoch: int) -> Optional[dict]:
        doc = self.read()
        if doc is None:
            return None
        for rec in doc["epochs"]:
            if int(rec["epoch"]) == int(epoch):
                return rec
        return None

    def grant(self, sponsor: int, live_members: Sequence[int],
              live_graph: nx.DiGraph, windows: List[dict],
              associated_p: bool, prev_epoch: int) -> Optional[dict]:
        """Commit the next epoch record admitting every pending request.

        Deterministic from the board state + the sponsor's live view:
        fresh ranks come off the monotone ``next_rank`` counter, the
        grown topology comes from :func:`grow_topology` over the live
        member graph (global labels), and the dense edge list of the
        result is what gets committed — so a raced second sponsor (a
        momentary disagreement about who is lowest-alive) finds the
        record already present and returns it unchanged.

        Returns the committed record, or None if there was nothing to
        grant.
        """
        with self._locked():
            doc = self.read()
            if doc is None:
                raise RuntimeError(f"membership board vanished for "
                                   f"{self.job!r}")
            new_epoch = int(prev_epoch) + 1
            for rec in doc["epochs"]:
                if int(rec["epoch"]) == new_epoch:
                    return rec  # already committed by a raced sponsor
            reqs = list(doc["requests"])
            if not reqs:
                return None
            fresh = list(range(int(doc["next_rank"]),
                               int(doc["next_rank"]) + len(reqs)))
            grown = _healing.grow_topology(live_graph, fresh)
            rec = {
                "epoch": new_epoch,
                "members": [int(m) for m in grown.to_global],
                "joined": fresh,
                "removed": sorted(set(doc["members"])
                                  - set(int(m) for m in live_members)),
                "granted": {r["req"]: rank
                            for r, rank in zip(reqs, fresh)},
                "sponsor": int(sponsor),
                "edges": [[int(u), int(v)]
                          for u, v in grown.topology.edges],
                "windows": windows,
                "associated_p": bool(associated_p),
            }
            doc["epochs"].append(rec)
            doc["epoch"] = new_epoch
            doc["members"] = rec["members"]
            doc["next_rank"] = int(doc["next_rank"]) + len(reqs)
            doc["requests"] = []
            self._publish(doc)
        # the cheap probe members poll at round barriers
        self._publish_epoch_word(new_epoch)
        return rec

    # -- adaptive-topology side (resilience/adaptive.py) ------------------

    def commit_reweight(self, committer: int, prev_epoch: int,
                        members: Sequence[int], edges: Sequence,
                        windows: List[dict], associated_p: bool,
                        demoted: Sequence[int], promoted: Sequence[int],
                        base_edges: Sequence) -> Optional[dict]:
        """Commit a **reweight** epoch record: same member set, new
        topology — the adaptive demote/promote switch (straggler degree
        capped, or restored).  The record carries ``reweight: True`` so
        the switch points can tell it from a join grant, plus the
        demoted set and the base (pre-demotion) edge list any member
        needs to compute the NEXT demote or the promote restore.

        First-wins and idempotent like :meth:`grant`: raced observers
        of the same straggler find epoch ``prev_epoch + 1`` already
        committed and get that record back (the caller checks its
        ``reweight`` flag — a raced JOIN grant wins the epoch and the
        demote retries next tick).  Returns the committed-or-existing
        record.
        """
        with self._locked():
            doc = self.read()
            if doc is None:
                raise RuntimeError(f"membership board vanished for "
                                   f"{self.job!r}")
            new_epoch = int(prev_epoch) + 1
            for rec in doc["epochs"]:
                if int(rec["epoch"]) == new_epoch:
                    return rec  # first observer (or a join) won the epoch
            rec = {
                "epoch": new_epoch,
                "members": [int(m) for m in members],
                "joined": [],
                "removed": [],
                "granted": {},
                "sponsor": int(committer),
                "edges": [[int(u), int(v)] for u, v in edges],
                "windows": windows,
                "associated_p": bool(associated_p),
                "reweight": True,
                "demoted": [int(g) for g in demoted],
                "promoted": [int(g) for g in promoted],
                "base_edges": [[int(u), int(v)] for u, v in base_edges],
            }
            doc["epochs"].append(rec)
            doc["epoch"] = new_epoch
            self._publish(doc)
        self._publish_epoch_word(new_epoch)
        return rec
