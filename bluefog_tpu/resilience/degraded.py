"""Degraded-step semantics: deadlines, retry/backoff, and mass-conserving
weight renormalization on neighbor loss.

Two primitives:

- :func:`with_deadline` — run a blocking transport op under a deadline
  with bounded retries and exponential backoff, raising
  :class:`DeadlineExceeded` (a ``TimeoutError``) instead of hanging.
  The island win ops wrap their barrier/mutex/peer waits in this so "no
  win-op blocks past its deadline" holds end to end.

- :func:`renormalize_weights` — given a combine's ``(self_weight,
  neighbor_weights)`` row and a dead-rank set, drop the dead neighbors
  and rescale the survivors so the row still sums to EXACTLY 1.  For
  plain gossip this keeps the step a convex average; for push-sum it is
  the mass-conserving fallback: the associated scalar ``p`` is combined
  with the SAME renormalized row, so the x/p ratio stays a consistent
  estimate and Σp over the survivors is conserved — the dead rank's
  in-flight mass was already excised by the force-drain (see
  DEPOSIT_COMMITS_AFTER_PAYLOAD in native/shm_native.py).
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Iterable, Tuple, TypeVar

from bluefog_tpu.sim.clock import resolve_clock as _resolve_clock
from bluefog_tpu.telemetry import registry as _telemetry

__all__ = [
    "DeadlineExceeded",
    "op_deadline_s",
    "with_deadline",
    "renormalize_weights",
]

T = TypeVar("T")


class DeadlineExceeded(TimeoutError):
    """A win op exhausted its deadline + retry budget."""


def op_deadline_s() -> float:
    """Per-attempt deadline for blocking win-op waits
    (``BFTPU_OP_DEADLINE_S``, default generous — legitimate barrier
    waits can be long)."""
    try:
        return float(os.environ.get("BFTPU_OP_DEADLINE_S", "30.0"))
    except ValueError:
        return 30.0


def with_deadline(fn: Callable[[float], T], describe: str,
                  deadline: float = None, retries: int = 2,
                  backoff: float = 0.05,
                  on_timeout: Callable[[], None] = None,
                  clock=None) -> T:
    """Call ``fn(remaining_seconds)`` under a total deadline.

    ``fn`` receives the per-attempt budget and must raise TimeoutError
    when it expires (the transports' timed waits do).  Between attempts
    ``on_timeout`` runs (the hook where the caller consults the failure
    detector and heals) and the backoff doubles.  After ``retries``
    failed attempts, DeadlineExceeded is raised naming the op.
    ``clock`` is the sim/clock.py seam for the backoff pause; ``None``
    is wall time.
    """
    clk = _resolve_clock(clock)
    total = op_deadline_s() if deadline is None else float(deadline)
    per_attempt = total / max(1, retries)
    pause = backoff
    last: Exception = None
    for attempt in range(max(1, retries)):
        try:
            return fn(per_attempt)
        except TimeoutError as e:
            last = e
            reg = _telemetry.get_registry()
            if reg.enabled:
                reg.counter("resilience.deadline_retries").inc()
            if on_timeout is not None:
                on_timeout()
            if attempt + 1 < max(1, retries):
                clk.sleep(pause)
                pause *= 2
    reg = _telemetry.get_registry()
    if reg.enabled:
        reg.counter("resilience.deadline_exhausted").inc()
        reg.journal("deadline_exhausted", op=describe, deadline_s=total,
                    attempts=max(1, retries))
    raise DeadlineExceeded(
        f"{describe} exceeded its {total:.3f}s deadline "
        f"after {max(1, retries)} attempts: {last}")


def renormalize_weights(self_weight: float,
                        neighbor_weights: Dict[int, float],
                        dead: Iterable[int],
                        ) -> Tuple[float, Dict[int, float]]:
    """Drop dead neighbors from a combine row and rescale so it sums
    to exactly 1 (mass-conserving degraded combine).

    If every neighbor is dead the row degenerates to ``(1.0, {})`` —
    the rank keeps gossiping with itself until the healed topology
    reconnects it.
    """
    dead_set = set(int(r) for r in dead)
    alive = {int(r): float(w) for r, w in neighbor_weights.items()
             if int(r) not in dead_set}
    total = float(self_weight) + sum(alive.values())
    if not alive or total <= 0.0:
        return 1.0, {}
    scale = 1.0 / total
    return float(self_weight) * scale, {r: w * scale
                                        for r, w in alive.items()}
