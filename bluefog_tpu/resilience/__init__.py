"""Failure detection, topology healing, and degraded-step gossip.

The whole point of decentralized gossip (BlueFog, arXiv:2111.04287) is
that there is no single coordinator to lose — this subsystem makes the
island runtime live up to that: a heartbeat **failure detector**
piggybacked on the job segment (shm: per-rank epoch-stamped liveness
words; tcp: coordinator-mediated leases), **topology healing** that
re-derives a doubly-stochastic survivor topology and recompiles the
shift-class plan when ranks die, **degraded-step semantics** (deadlines
with retry/backoff; mass-conserving weight renormalization on neighbor
loss, so push-sum stays correct), **adaptive topology** (a three-state
gray-failure machine over per-edge deadline misses that demotes a
straggler to one anchor edge — and promotes it back — without ever
declaring it dead; adaptive.py), and a **fault-injection harness**
for the chaos e2e tests.

Push-sum-style algorithms are provably robust on time-varying directed
graphs (Nedić & Olshevsky) — the math already tolerates lost neighbors;
these modules make the runtime tolerate them too.  See
docs/RESILIENCE.md for the full contract.
"""

from bluefog_tpu.resilience.adaptive import (
    AdaptivePolicy,
    adaptive_enabled,
    edge_deadline_factor,
    edge_deadline_floor_s,
)
from bluefog_tpu.resilience.detector import (
    EDGE_ALIVE,
    EDGE_DEAD,
    EDGE_SUSPECT,
    EdgeHealth,
    FailureDetector,
    PeerTimeoutError,
    demote_floor_s,
    failure_timeout_s,
    heartbeat_interval_s,
    promote_clean,
    suspect_misses,
)
from bluefog_tpu.resilience.degraded import (
    DeadlineExceeded,
    op_deadline_s,
    renormalize_weights,
    with_deadline,
)
from bluefog_tpu.resilience.healing import (
    HealedTopology,
    demote_topology,
    grow_topology,
    heal_topology,
    healed_weight_matrix,
)
from bluefog_tpu.resilience.join import (
    JoinGrant,
    MembershipBoard,
    epoch_job,
    join_poll_s,
    join_timeout_s,
)
from bluefog_tpu.resilience.quorum import (
    OrphanedError,
    majority_floor,
    quorum_enabled,
    quorum_met,
    quorum_mode,
)

__all__ = [
    "FailureDetector",
    "PeerTimeoutError",
    "failure_timeout_s",
    "heartbeat_interval_s",
    "EdgeHealth",
    "EDGE_ALIVE",
    "EDGE_SUSPECT",
    "EDGE_DEAD",
    "suspect_misses",
    "promote_clean",
    "demote_floor_s",
    "AdaptivePolicy",
    "adaptive_enabled",
    "edge_deadline_floor_s",
    "edge_deadline_factor",
    "DeadlineExceeded",
    "op_deadline_s",
    "renormalize_weights",
    "with_deadline",
    "HealedTopology",
    "demote_topology",
    "grow_topology",
    "heal_topology",
    "healed_weight_matrix",
    "JoinGrant",
    "MembershipBoard",
    "epoch_job",
    "join_poll_s",
    "join_timeout_s",
    "OrphanedError",
    "quorum_mode",
    "quorum_enabled",
    "quorum_met",
    "majority_floor",
]
