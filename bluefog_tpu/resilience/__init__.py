"""Failure detection, topology healing, and degraded-step gossip.

The whole point of decentralized gossip (BlueFog, arXiv:2111.04287) is
that there is no single coordinator to lose — this subsystem makes the
island runtime live up to that: a heartbeat **failure detector**
piggybacked on the job segment (shm: per-rank epoch-stamped liveness
words; tcp: coordinator-mediated leases), **topology healing** that
re-derives a doubly-stochastic survivor topology and recompiles the
shift-class plan when ranks die, **degraded-step semantics** (deadlines
with retry/backoff; mass-conserving weight renormalization on neighbor
loss, so push-sum stays correct), and a **fault-injection harness**
for the chaos e2e tests.

Push-sum-style algorithms are provably robust on time-varying directed
graphs (Nedić & Olshevsky) — the math already tolerates lost neighbors;
these modules make the runtime tolerate them too.  See
docs/RESILIENCE.md for the full contract.
"""

from bluefog_tpu.resilience.detector import (
    FailureDetector,
    PeerTimeoutError,
    failure_timeout_s,
    heartbeat_interval_s,
)
from bluefog_tpu.resilience.degraded import (
    DeadlineExceeded,
    op_deadline_s,
    renormalize_weights,
    with_deadline,
)
from bluefog_tpu.resilience.healing import (
    HealedTopology,
    grow_topology,
    heal_topology,
    healed_weight_matrix,
)
from bluefog_tpu.resilience.join import (
    JoinGrant,
    MembershipBoard,
    epoch_job,
    join_poll_s,
    join_timeout_s,
)

__all__ = [
    "FailureDetector",
    "PeerTimeoutError",
    "failure_timeout_s",
    "heartbeat_interval_s",
    "DeadlineExceeded",
    "op_deadline_s",
    "renormalize_weights",
    "with_deadline",
    "HealedTopology",
    "grow_topology",
    "heal_topology",
    "healed_weight_matrix",
    "JoinGrant",
    "MembershipBoard",
    "epoch_job",
    "join_poll_s",
    "join_timeout_s",
]
