"""Topology healing: rebuild a valid gossip topology over the survivors.

Given a dead-rank set, the healing rule is:

1. take the subgraph INDUCED by the survivors (every edge whose two
   endpoints both survived);
2. SYMMETRIZE it (add the reverse of every surviving edge) — directed
   topologies like the one-directional exponential graph lose in/out
   balance when ranks are excised, and only a symmetric neighbor
   relation admits a doubly-stochastic Metropolis–Hastings weighting;
3. if the result is not strongly connected (or has isolated survivors),
   add a ring over the sorted survivors — gossip averaging needs a
   positive spectral gap, which needs connectivity;
4. relabel the sorted survivors to 0..m-1 (``compile_plan`` requires
   contiguous node ids) and keep the local↔global maps;
5. re-weight with Metropolis–Hastings
   (``w_uv = 1/(1 + max(deg(u), deg(v)))``), which on a symmetric graph
   yields a DOUBLY stochastic mixing matrix — the property that makes
   plain gossip averaging converge to the true average on the survivor
   set — and recompile the shift-class plan.

The healed plan drives both the SPMD emulation (windows.py) and the
analysis rules; the island runtime applies the same membership change
in place via degraded weights (see resilience/degraded.py) without
reallocating its shm segments.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu import topology_util
from bluefog_tpu.core.plan import CommPlan, compile_plan

__all__ = ["HealedTopology", "heal_topology", "healed_weight_matrix"]


@dataclasses.dataclass(frozen=True)
class HealedTopology:
    """A survivor topology with its plan and local↔global rank maps."""

    survivors: Tuple[int, ...]   # sorted global ranks still alive
    dead: Tuple[int, ...]        # sorted global ranks excised
    topology: nx.DiGraph         # relabeled 0..m-1, MH-weighted
    plan: CommPlan               # compiled over the relabeled topology
    to_local: Dict[int, int]     # global rank -> local node id
    to_global: Tuple[int, ...]   # local node id -> global rank
    reconnected: bool            # ring edges were added for connectivity

    @property
    def size(self) -> int:
        return len(self.survivors)

    def local_in_neighbors(self, global_rank: int) -> Tuple[int, ...]:
        """Global ranks of ``global_rank``'s in-neighbors in the healed
        topology."""
        v = self.to_local[global_rank]
        return tuple(sorted(self.to_global[u]
                            for u in self.topology.predecessors(v)))


def _symmetrized_induced(topo: nx.DiGraph,
                         survivors: Iterable[int]) -> nx.DiGraph:
    keep = set(survivors)
    G = nx.DiGraph()
    G.add_nodes_from(sorted(keep))
    for u, v in topo.edges:
        if u == v or u not in keep or v not in keep:
            continue
        G.add_edge(u, v)
        G.add_edge(v, u)
    return G


def heal_topology(topo: nx.DiGraph, dead: Iterable[int]) -> HealedTopology:
    """Excise ``dead`` from ``topo`` and return a connected, MH-weighted,
    doubly-stochastic survivor topology with a freshly compiled plan.

    Raises ValueError if every rank is dead or ``dead`` contains ranks
    not in the topology.
    """
    nodes = set(int(n) for n in topo.nodes)
    dead_set = set(int(r) for r in dead)
    if not dead_set <= nodes:
        raise ValueError(
            f"dead ranks {sorted(dead_set - nodes)} not in topology")
    survivors = tuple(sorted(nodes - dead_set))
    if not survivors:
        raise ValueError("no survivors: every rank is dead")

    G = _symmetrized_induced(topo, survivors)
    reconnected = False
    m = len(survivors)
    if m > 1 and not nx.is_strongly_connected(G):
        # restore connectivity (and a positive spectral gap) with a
        # bidirectional ring over the sorted survivors
        reconnected = True
        for i in range(m):
            u, v = survivors[i], survivors[(i + 1) % m]
            if u != v:
                G.add_edge(u, v)
                G.add_edge(v, u)

    to_global = survivors
    to_local = {g: i for i, g in enumerate(survivors)}
    H = nx.relabel_nodes(G, to_local, copy=True)
    topology_util.MetropolisHastingsWeights(H)
    H.graph["healed_from"] = tuple(sorted(dead_set))

    plan = compile_plan(H)
    return HealedTopology(
        survivors=survivors,
        dead=tuple(sorted(dead_set)),
        topology=H,
        plan=plan,
        to_local=to_local,
        to_global=to_global,
        reconnected=reconnected,
    )


def healed_weight_matrix(healed: HealedTopology) -> np.ndarray:
    """The healed mixing matrix W (m × m, local ids): row- AND
    column-stochastic by construction (symmetric graph + MH weights)."""
    return healed.plan.mixing_matrix()
