"""Topology healing: rebuild a valid gossip topology over the survivors.

Given a dead-rank set, the healing rule is:

1. take the subgraph INDUCED by the survivors (every edge whose two
   endpoints both survived);
2. SYMMETRIZE it (add the reverse of every surviving edge) — directed
   topologies like the one-directional exponential graph lose in/out
   balance when ranks are excised, and only a symmetric neighbor
   relation admits a doubly-stochastic Metropolis–Hastings weighting;
3. if the result is not strongly connected (or has isolated survivors),
   add a ring over the sorted survivors — gossip averaging needs a
   positive spectral gap, which needs connectivity;
4. relabel the sorted survivors to 0..m-1 (``compile_plan`` requires
   contiguous node ids) and keep the local↔global maps;
5. re-weight with Metropolis–Hastings
   (``w_uv = 1/(1 + max(deg(u), deg(v)))``), which on a symmetric graph
   yields a DOUBLY stochastic mixing matrix — the property that makes
   plain gossip averaging converge to the true average on the survivor
   set — and recompile the shift-class plan.

The healed plan drives both the SPMD emulation (windows.py) and the
analysis rules; the island runtime applies the same membership change
in place via degraded weights (see resilience/degraded.py) without
reallocating its shm segments.

:func:`grow_topology` is the inverse direction — elastic scale-OUT.
Joining ranks are spliced into the sorted-member ring (their two ring
neighbors are the attachment edges), the grown graph is symmetrized
and MH re-weighted exactly like a healed one, and the recompiled
plan's ``stochasticity_error`` pins the grown W doubly stochastic
before any rank gossips under it.  Both directions return the same
:class:`HealedTopology` record, so shrink/grow/shrink sequences
compose: ``grown.topology`` (global-rank node labels restored via
``to_global``) feeds straight back into the next membership change.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu import topology_util
from bluefog_tpu.core.plan import CommPlan, compile_plan

__all__ = [
    "HealedTopology",
    "heal_topology",
    "grow_topology",
    "demote_topology",
    "healed_weight_matrix",
]

# doubly-stochastic residual above which a grown plan is rejected
# outright (float-epsilon scale; a symmetric MH-weighted graph lands
# orders of magnitude below this)
_STOCHASTICITY_TOL = 1e-9


@dataclasses.dataclass(frozen=True)
class HealedTopology:
    """A survivor topology with its plan and local↔global rank maps."""

    survivors: Tuple[int, ...]   # sorted global ranks still alive
    dead: Tuple[int, ...]        # sorted global ranks excised
    topology: nx.DiGraph         # relabeled 0..m-1, MH-weighted
    plan: CommPlan               # compiled over the relabeled topology
    to_local: Dict[int, int]     # global rank -> local node id
    to_global: Tuple[int, ...]   # local node id -> global rank
    reconnected: bool            # ring edges were added for connectivity
    joined: Tuple[int, ...] = () # sorted global ranks spliced in (grow)
    demoted: Tuple[int, ...] = () # sorted global ranks degree-capped

    @property
    def size(self) -> int:
        return len(self.survivors)

    def local_in_neighbors(self, global_rank: int) -> Tuple[int, ...]:
        """Global ranks of ``global_rank``'s in-neighbors in the healed
        topology."""
        v = self.to_local[global_rank]
        return tuple(sorted(self.to_global[u]
                            for u in self.topology.predecessors(v)))


def _symmetrized_induced(topo: nx.DiGraph,
                         survivors: Iterable[int]) -> nx.DiGraph:
    keep = set(survivors)
    G = nx.DiGraph()
    G.add_nodes_from(sorted(keep))
    for u, v in topo.edges:
        if u == v or u not in keep or v not in keep:
            continue
        G.add_edge(u, v)
        G.add_edge(v, u)
    return G


def heal_topology(topo: nx.DiGraph, dead: Iterable[int]) -> HealedTopology:
    """Excise ``dead`` from ``topo`` and return a connected, MH-weighted,
    doubly-stochastic survivor topology with a freshly compiled plan.

    Raises ValueError if every rank is dead or ``dead`` contains ranks
    not in the topology.
    """
    nodes = set(int(n) for n in topo.nodes)
    dead_set = set(int(r) for r in dead)
    if not dead_set <= nodes:
        raise ValueError(
            f"dead ranks {sorted(dead_set - nodes)} not in topology")
    survivors = tuple(sorted(nodes - dead_set))
    if not survivors:
        raise ValueError("no survivors: every rank is dead")

    G = _symmetrized_induced(topo, survivors)
    reconnected = False
    m = len(survivors)
    if m > 1 and not nx.is_strongly_connected(G):
        # restore connectivity (and a positive spectral gap) with a
        # bidirectional ring over the sorted survivors
        reconnected = True
        for i in range(m):
            u, v = survivors[i], survivors[(i + 1) % m]
            if u != v:
                G.add_edge(u, v)
                G.add_edge(v, u)

    to_global = survivors
    to_local = {g: i for i, g in enumerate(survivors)}
    H = nx.relabel_nodes(G, to_local, copy=True)
    topology_util.MetropolisHastingsWeights(H)
    H.graph["healed_from"] = tuple(sorted(dead_set))

    plan = compile_plan(H)
    return HealedTopology(
        survivors=survivors,
        dead=tuple(sorted(dead_set)),
        topology=H,
        plan=plan,
        to_local=to_local,
        to_global=to_global,
        reconnected=reconnected,
    )


def grow_topology(topo: nx.DiGraph,
                  joiners: Iterable[int]) -> HealedTopology:
    """Splice ``joiners`` (fresh global ranks) into ``topo`` and return
    a connected, MH-weighted, doubly-stochastic grown topology with a
    freshly compiled plan — :func:`heal_topology`'s twin for elastic
    scale-out.

    The attachment rule is deterministic (every member computes the
    same grown graph from the same membership view, no consensus round
    needed — the grow-side mirror of the monotone-dead-set argument):
    each joiner is connected bidirectionally to its two neighbors in
    the sorted circular order of the grown member set, i.e. spliced
    into the member ring.  Existing edges are kept (symmetrized), so
    the incumbents' gossip locality is preserved and only the splice
    points gain degree.

    Raises ValueError for an empty joiner set or a joiner already in
    the topology, and RuntimeError if the grown plan's
    ``stochasticity_error`` is not float-epsilon doubly stochastic
    (cannot happen for a symmetric MH-weighted graph; the check pins
    the contract before any rank gossips under the grown W).
    """
    nodes = set(int(n) for n in topo.nodes)
    join_set = set(int(r) for r in joiners)
    if not join_set:
        raise ValueError("no joiners: grow_topology needs >= 1 new rank")
    if join_set & nodes:
        raise ValueError(
            f"joiners {sorted(join_set & nodes)} already in topology "
            "(a restarted rank must rejoin under a FRESH global rank)")

    members = tuple(sorted(nodes | join_set))
    G = _symmetrized_induced(topo, nodes)
    G.add_nodes_from(sorted(join_set))
    m = len(members)
    for j in sorted(join_set):
        i = members.index(j)
        for nb in (members[i - 1], members[(i + 1) % m]):
            if nb != j:
                G.add_edge(j, nb)
                G.add_edge(nb, j)

    reconnected = False
    if m > 1 and not nx.is_strongly_connected(G):
        # splicing joiners cannot disconnect incumbents, but the OLD
        # graph may already have been disconnected — same ring repair
        # as heal_topology
        reconnected = True
        for i in range(m):
            u, v = members[i], members[(i + 1) % m]
            if u != v:
                G.add_edge(u, v)
                G.add_edge(v, u)

    to_global = members
    to_local = {g: i for i, g in enumerate(members)}
    H = nx.relabel_nodes(G, to_local, copy=True)
    topology_util.MetropolisHastingsWeights(H)
    H.graph["grown_from"] = tuple(sorted(join_set))

    plan = compile_plan(H)
    row_err, col_err = plan.stochasticity_error()
    if max(row_err, col_err) > _STOCHASTICITY_TOL:
        raise RuntimeError(
            f"grown plan not doubly stochastic: row={row_err:.3e} "
            f"col={col_err:.3e} (tol {_STOCHASTICITY_TOL:.0e})")
    return HealedTopology(
        survivors=members,
        dead=(),
        topology=H,
        plan=plan,
        to_local=to_local,
        to_global=to_global,
        reconnected=reconnected,
        joined=tuple(sorted(join_set)),
    )


def demote_topology(topo: nx.DiGraph,
                    stragglers: Iterable[int]) -> HealedTopology:
    """Cap each straggler's gossip degree to ONE edge without excising
    it — the gray-failure middle ground between full membership and
    death.  Every member (stragglers included) stays in the view; a
    straggler keeps exactly one bidirectional **anchor** edge to its
    lowest-id healthy neighbor (or, if every neighbor is itself a
    straggler, to the lowest healthy member), so it still receives and
    contributes mass — just without sitting on anyone else's critical
    path.  The healthy core is re-symmetrized, ring-repaired if the
    straggler was a cut vertex, Metropolis–Hastings re-weighted, and
    recompiled — the exact pipeline heal/grow run, so the demoted W is
    doubly stochastic with a positive spectral gap by the same
    construction.

    Deterministic from (topo, stragglers): every member computes the
    same demoted graph from the same inputs, so the epoch record any
    observer commits is the one every other observer would have
    committed.

    Raises ValueError for an empty straggler set, stragglers outside
    the topology, or fewer than one healthy member.
    """
    nodes = set(int(n) for n in topo.nodes)
    strag = set(int(r) for r in stragglers)
    if not strag:
        raise ValueError("no stragglers: demote_topology needs >= 1 rank")
    if not strag <= nodes:
        raise ValueError(
            f"straggler(s) {sorted(strag - nodes)} not in topology")
    healthy = sorted(nodes - strag)
    if not healthy:
        raise ValueError("every member is a straggler: nothing to "
                         "anchor to (heal or wait instead)")
    members = tuple(sorted(nodes))

    G = _symmetrized_induced(topo, members)
    G.add_nodes_from(members)  # isolated members survive symmetrization
    for s in sorted(strag):
        nbrs = sorted(set(G.successors(s)))
        anchors = [u for u in nbrs if u not in strag]
        anchor = anchors[0] if anchors else healthy[0]
        for u in nbrs:
            if u != anchor:
                G.remove_edge(s, u)
                G.remove_edge(u, s)
        if anchor != s and not G.has_edge(s, anchor):
            G.add_edge(s, anchor)
            G.add_edge(anchor, s)

    # the straggler may have been a cut vertex of the healthy core:
    # ring-repair over the HEALTHY members only (a ring through a
    # straggler would re-raise its degree past the cap)
    reconnected = False
    m = len(healthy)
    if m > 1 and not nx.is_strongly_connected(G.subgraph(healthy)):
        reconnected = True
        for i in range(m):
            u, v = healthy[i], healthy[(i + 1) % m]
            if u != v:
                G.add_edge(u, v)
                G.add_edge(v, u)

    to_global = members
    to_local = {g: i for i, g in enumerate(members)}
    H = nx.relabel_nodes(G, to_local, copy=True)
    topology_util.MetropolisHastingsWeights(H)
    H.graph["demoted_from"] = tuple(sorted(strag))

    plan = compile_plan(H)
    row_err, col_err = plan.stochasticity_error()
    if max(row_err, col_err) > _STOCHASTICITY_TOL:
        raise RuntimeError(
            f"demoted plan not doubly stochastic: row={row_err:.3e} "
            f"col={col_err:.3e} (tol {_STOCHASTICITY_TOL:.0e})")
    return HealedTopology(
        survivors=members,
        dead=(),
        topology=H,
        plan=plan,
        to_local=to_local,
        to_global=to_global,
        reconnected=reconnected,
        demoted=tuple(sorted(strag)),
    )


def healed_weight_matrix(healed: HealedTopology) -> np.ndarray:
    """The healed mixing matrix W (m × m, local ids): row- AND
    column-stochastic by construction (symmetric graph + MH weights)."""
    return healed.plan.mixing_matrix()
