"""Adaptive topology: the straggler-aware edge-health control loop.

The heartbeat detector (detector.py) answers "is the rank's process
alive?"; this module answers the harder gray-failure question — "is the
EDGE healthy enough to sit on my critical path?" — and drives the
three-state machine (:class:`~bluefog_tpu.resilience.detector.
EdgeHealth`) that routes gossip around ranks that are slow but
responsive.

Two signals feed the machine, both observed on the win-op path with no
extra communication:

- **deposit freshness** — each ``win_update`` probes every in-edge's
  slot version (a monotone deposit count).  A changed version is a
  fresh deposit: the elapsed *gap* since the previous change is a clean
  observation and a sample for the pooled gap histogram.  An unchanged
  version older than the **edge deadline** is a miss — counted ONCE per
  stale gap, however long, so a synchronous caller polling at ms
  cadence cannot turn one marginal gap into a SUSPECT streak (only a
  rank that misses gap after gap accumulates one).
- **mutex acquire time** — a straggler sleeping inside its critical
  section convoys every neighbor's ``win_mutex``.  Acquire durations
  past the acquire deadline are misses.  Acquires never count as
  *clean* observations: a fast lock proves the lock word is free, not
  that the rank is gossiping (a rank sleeping outside its critical
  section acquires fast while depositing nothing).

The deadlines are adaptive: ``max(floor, factor × pooled p50)`` over
the respective histogram (:meth:`~bluefog_tpu.telemetry.registry.
Histogram.quantile` on the same fixed buckets telemetry exports).  The
p50 — not the p99 — is the baseline on purpose: under a convoy every
edge slows down together, so a tail quantile would chase the straggler
and never fire, while the median tracks the healthy cadence.  Until
``min_obs`` samples arrive nothing can miss (cold-start warmup: the
first rounds of a job are legitimately slow).

The policy object is **registry-independent** (it owns bare
:class:`~bluefog_tpu.telemetry.registry.Histogram` instances), so
adaptivity works with telemetry off; when a registry IS enabled the
state transitions publish ``adaptive.edge_state`` gauges and
``edge_state`` journal events (see EdgeHealth), and the policy mirrors
its deadline and miss counts as gauges/counters.

It is also keyed by **global** rank and owned by the island context —
NOT by the per-epoch FailureDetector — so hysteresis clocks and streaks
survive the membership-epoch switches its own demotions trigger.

Env knobs (see docs/RESILIENCE.md, "Adaptive topology"):

- ``BFTPU_ADAPTIVE`` (default 0) — enable the control loop;
- ``BFTPU_EDGE_DEADLINE_S`` (default 0.25) — deadline floor, seconds;
- ``BFTPU_EDGE_DEADLINE_FACTOR`` (default 8) — deadline as a multiple
  of the pooled p50;
- plus the machine's own ``BFTPU_SUSPECT_MISSES`` /
  ``BFTPU_PROMOTE_CLEAN`` / ``BFTPU_DEMOTE_FLOOR_S`` (detector.py).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from bluefog_tpu.resilience.detector import EdgeHealth
from bluefog_tpu.sim.clock import now_fn as _now_fn
from bluefog_tpu.telemetry import registry as _telemetry

__all__ = [
    "AdaptivePolicy",
    "adaptive_enabled",
    "edge_deadline_floor_s",
    "edge_deadline_factor",
    "MIN_OBSERVATIONS",
]

# pooled samples below which no deadline exists (cold-start warmup)
MIN_OBSERVATIONS = 8


def adaptive_enabled() -> bool:
    """Whether the adaptive edge-health control loop runs
    (``BFTPU_ADAPTIVE``; default off — demotion changes the topology,
    which a training script must opt into)."""
    return os.environ.get("BFTPU_ADAPTIVE", "0") not in ("0", "", "false")


def edge_deadline_floor_s() -> float:
    """Edge-deadline floor in seconds (``BFTPU_EDGE_DEADLINE_S``)."""
    try:
        return float(os.environ.get("BFTPU_EDGE_DEADLINE_S", "0.25"))
    except ValueError:
        return 0.25


def edge_deadline_factor() -> float:
    """Edge deadline as a multiple of the pooled p50
    (``BFTPU_EDGE_DEADLINE_FACTOR``)."""
    try:
        return float(os.environ.get("BFTPU_EDGE_DEADLINE_FACTOR", "8"))
    except ValueError:
        return 8.0


class AdaptivePolicy:
    """Edge observations in, EdgeHealth transitions out.

    Thread-compatible with the island runtime: observations arrive from
    the win-op path (one thread), reads (``suspects`` via ``health``)
    from the same thread; the internal lock only guards the pooled
    histograms against a concurrent metrics scrape.
    """

    def __init__(self, floor_s: Optional[float] = None,
                 factor: Optional[float] = None,
                 min_obs: Optional[int] = None,
                 health: Optional[EdgeHealth] = None,
                 clock=time.monotonic):
        self.floor_s = (edge_deadline_floor_s() if floor_s is None
                        else float(floor_s))
        self.factor = (edge_deadline_factor() if factor is None
                       else float(factor))
        self.min_obs = MIN_OBSERVATIONS if min_obs is None else int(min_obs)
        self.health = EdgeHealth(clock=clock) if health is None else health
        self._clock = _now_fn(clock)
        self._lock = threading.Lock()
        # bare histograms (no registry): pooled over ALL edges — the
        # healthy-cadence baseline the per-edge deadline compares against
        self._gap = _telemetry.Histogram("adaptive.edge_gap_s", {})
        self._acq = _telemetry.Histogram("adaptive.acquire_s", {})
        self.gap_misses = 0
        self.acquire_misses = 0
        # peer -> clock time of the last demote/promote epoch switch
        # that changed the peer's standing (the commit-level floor gate:
        # even if per-member machine states diverge, no peer's epoch
        # standing may flap faster than the hysteresis floor)
        self._epoch_changed: dict = {}
        # critical-path corroboration (tracing feed): peer -> count of
        # rounds the peer's edge lengthened.  Only consulted while the
        # feed is live — see corroborated().
        self._cp_live = False
        self._cp_blame: dict = {}

    # -- deadlines ---------------------------------------------------------

    def _deadline(self, hist) -> Optional[float]:
        with self._lock:
            if hist.count < self.min_obs:
                return None
            p50 = hist.quantile(0.5)
        if p50 != p50:  # NaN: empty histogram
            return None
        return max(self.floor_s, self.factor * p50)

    def gap_deadline_s(self) -> Optional[float]:
        """Current deposit-gap deadline, or None during warmup."""
        return self._deadline(self._gap)

    def acquire_deadline_s(self) -> Optional[float]:
        """Current mutex-acquire deadline, or None during warmup."""
        return self._deadline(self._acq)

    # -- the commit-level hysteresis gate ----------------------------------

    def note_epoch_change(self, peers) -> None:
        """Record that a reweight epoch switch just changed the standing
        (demoted <-> member) of ``peers`` — starts their commit floor."""
        now = self._clock()
        for p in peers:
            self._epoch_changed[int(p)] = now

    def epoch_floor_open(self, peer: int) -> bool:
        """Whether enough time has passed since ``peer``'s standing last
        changed to commit another change (the machine's own floor gates
        local transitions; this gates the fleet-level epoch cycle, which
        must hold even when member machines disagree)."""
        t = self._epoch_changed.get(int(peer))
        return t is None or self._clock() - t >= self.health.floor_s

    # -- observations ------------------------------------------------------

    def note_fresh(self, peer: int, gap_s: float,
                   clean: bool = True) -> None:
        """A deposit arrived on ``peer``'s edge after ``gap_s`` seconds
        — a pooled-baseline sample and, when the gap made the deadline,
        a clean observation.  ``clean=False`` is the gap-end of a
        MISSED gap: its miss was already counted mid-gap, and crediting
        the straggler a clean for finally depositing would reset the
        streak — a rank missing gap after gap would alternate
        miss/clean forever and ``suspect_misses`` consecutive misses
        would be unreachable."""
        with self._lock:
            self._gap.observe(float(gap_s))
        if clean:
            self.health.note_clean(peer)

    def note_stale(self, peer: int, age_s: float) -> bool:
        """``peer``'s edge has produced nothing for ``age_s`` seconds.
        Returns True when that is past the deadline (a miss — the
        caller applies the round-local ABSORB combine).  Callers
        deduplicate to ONE call per stale gap (``_adaptive_probe``
        tracks per-edge whether the current gap already missed) — each
        call here IS one machine miss."""
        d = self.gap_deadline_s()
        if d is None or float(age_s) <= d:
            return False
        self.gap_misses += 1
        self.health.note_miss(peer)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("adaptive.gap_misses").inc()
            reg.gauge("adaptive.edge_deadline_s").set(d)
        return True

    def note_acquire(self, peer: int, dur_s: float) -> bool:
        """One ``win_mutex`` acquire of ``peer``'s lock took ``dur_s``
        seconds.  Returns True when that is past the acquire deadline
        (a miss).  Never counts as clean — see module docstring.

        Attribution: the shm transports keep an acquire-time holder
        word (``HolderBoard`` in shm_native), so the islands caller
        passes the rank that actually HELD the lock during the wait —
        a straggler asleep inside its critical section is blamed
        directly, not the innocent owner of the contended window.  On
        transports without the board (TCP/routed) the caller falls back
        to the window owner, and the streak machinery absorbs the
        error: an innocent rank keeps depositing, and every fresh
        deposit resets its miss streak — only a rank that both misses
        and produces nothing accumulates the ``suspect_misses``
        consecutive misses a demotion needs."""
        d = self.acquire_deadline_s()
        with self._lock:
            self._acq.observe(float(dur_s))
        if d is None or float(dur_s) <= d:
            return False
        self.acquire_misses += 1
        self.health.note_miss(peer)
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("adaptive.acquire_misses").inc()
        return True

    # -- critical-path corroboration (tracing feed) ------------------------

    def set_live_feed(self, active: bool) -> None:
        """Whether the tracer is currently live (the caller checks each
        round — tracing can be flipped at runtime via ``bftpu-top``).
        While live, :meth:`corroborated` requires critical-path blame;
        while off, it passes everything through (gap staleness alone
        decides, exactly the PR-8 behavior)."""
        self._cp_live = bool(active)

    def note_round_blame(self, peer: int, n: int = 1) -> None:
        """``peer``'s edge lengthened ``n`` of my rounds — the live,
        rank-local form of the tracer's per-round critical-path
        attribution (a deadline-missed in-edge is by construction the
        op my round waited on).  Monotone: counts only accumulate."""
        p = int(peer)
        self._cp_blame[p] = self._cp_blame.get(p, 0) + max(0, int(n))
        reg = _telemetry.get_registry()
        if reg.enabled:
            reg.counter("adaptive.cp_blame", peer=p).inc(max(0, int(n)))

    def feed_critical_path(self, rounds_lengthened_by_rank) -> None:
        """Merge a merged-trace attribution map (``tracing
        --critical-path``'s ``rounds_lengthened_by_rank``) into the
        blame counts.  Max-merge per rank keeps the feed monotone when
        the same trace window is fed twice."""
        for peer, count in dict(rounds_lengthened_by_rank).items():
            p = int(peer)
            self._cp_blame[p] = max(self._cp_blame.get(p, 0), int(count))

    def critical_path_blame(self, peer: int) -> int:
        """Rounds ``peer`` is currently blamed for lengthening."""
        return int(self._cp_blame.get(int(peer), 0))

    def corroborated(self, peer: int) -> bool:
        """The demote AND-gate: with the tracing feed live, a suspect
        may only be demoted when the critical path also blames it — a
        rank can go gap-stale from MY vantage (a convoy, a dropped
        deposit) without ever lengthening a round, and demoting it
        would re-route gossip around a healthy member.  With the feed
        off this is a pass-through, not a veto: staleness alone
        decides, as before the feed existed."""
        return (not self._cp_live) or self.critical_path_blame(peer) >= 1
