"""Deterministic fleet simulator: virtual-clock fault campaigns over
the REAL protocol state machines.

The package has two faces:

- the **clock seam** (:mod:`bluefog_tpu.sim.clock`): a tiny ``Clock``
  abstraction (monotonic ``now`` / ``sleep`` / ``deadline``) that the
  resilience modules accept by injection and default to wall time —
  production behavior is bit-for-bit unchanged, but a
  :class:`~bluefog_tpu.sim.events.VirtualClock` lets the same code run
  against an event-queue scheduler that advances time instantly;

- the **fleet lab** (:mod:`bluefog_tpu.sim.fleet` /
  :mod:`bluefog_tpu.sim.campaign`): a single-process ``SimTransport``
  implementing the mailbox/window contract (deposit, collect,
  versions, mutex, liveness words, membership board) against
  in-memory state, a fleet driver that runs the real
  ``FailureDetector`` / ``EdgeHealth`` / ``AdaptivePolicy`` /
  ``heal_topology`` / ``grow_topology`` / ``demote_topology`` /
  ``MembershipBoard`` code paths at 256+ ranks in seconds, and a
  campaign runner (``python -m bluefog_tpu.sim``) that injects seeded
  fault schedules, checks the standing invariants after every
  protocol event, and shrinks violations delta-debugging-style to a
  minimal replayable repro.

Import is deliberately light: only the clock surface loads eagerly
(the resilience package imports it on every startup); the fleet lab
(numpy + networkx) loads on first attribute access.
"""

from __future__ import annotations

from bluefog_tpu.sim.clock import (  # noqa: F401
    Clock, FakeClock, RealClock, REAL_CLOCK, now_fn, resolve_clock)

__all__ = [
    "Clock",
    "RealClock",
    "FakeClock",
    "REAL_CLOCK",
    "now_fn",
    "resolve_clock",
    "EventLoop",
    "VirtualClock",
    "Fault",
    "FaultSchedule",
    "SimTransport",
    "SimBoard",
    "SimConfig",
    "CampaignResult",
    "run_campaign",
    "shrink_schedule",
]

_LAZY = {
    "EventLoop": "bluefog_tpu.sim.events",
    "VirtualClock": "bluefog_tpu.sim.events",
    "Fault": "bluefog_tpu.sim.schedule",
    "FaultSchedule": "bluefog_tpu.sim.schedule",
    "SimTransport": "bluefog_tpu.sim.transport",
    "SimBoard": "bluefog_tpu.sim.transport",
    "SimConfig": "bluefog_tpu.sim.campaign",
    "CampaignResult": "bluefog_tpu.sim.campaign",
    "run_campaign": "bluefog_tpu.sim.campaign",
    "shrink_schedule": "bluefog_tpu.sim.campaign",
}


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
