"""Trace-fitted per-edge latency: close the loop from real traces to
the sim's latency model (ROADMAP item 4).

The tracing subsystem already measures per-edge deposit→collect
latency on real fleets (``bluefog_tpu.tracing.merge`` critical-path
reports carry ``stragglers.edge_latency`` as ``{"u->v": {"n",
"p50_us", "p99_us"}}``).  This module turns those two quantiles into
an **empirical quantile sampler** per edge — piecewise-linear inverse
CDF through the anchors

    (0.00, p50/2)  (0.50, p50)  (0.99, p99)  (1.00, p99)

so half the draws land below the measured median and the tail tops out
at the measured p99 (the head anchor at p50/2 keeps the support off
zero without inventing a tail below anything observed).  Crude, but it
is fitted to *measured* marginals instead of the uniform
``cfg.latency_s`` guess, and it keeps the campaign deterministic: the
sampler consumes exactly one ``rng.random()`` per draw, same as the
uniform path it replaces.

``load_trace_latency`` accepts either a critical-path report (the
``--critical-path`` output of ``python -m bluefog_tpu.tracing``), the
``stragglers`` sub-object, or a bare ``edge_latency`` mapping, and
returns the ``SimConfig.latency_table`` rows (seconds, not µs).  A
``"*"`` row is synthesized from the pooled median of all edges so
edges the trace never saw still draw from measured scale.
"""

from __future__ import annotations

import json
from typing import Dict, Sequence, Tuple

__all__ = ["EmpiricalLatency", "load_trace_latency"]


class EmpiricalLatency:
    """Per-edge inverse-CDF samplers built from latency_table rows.

    Rows are ``(edge_key, p50_s, p99_s)`` with edge_key ``"u->v"`` or
    ``"*"`` (the fallback for unlisted edges).  ``sample(u, v, rng)``
    draws one latency using one ``rng.random()`` call.
    """

    def __init__(self, table: Sequence[Tuple[str, float, float]]):
        self._anchors: Dict[str, Tuple[float, float, float]] = {}
        for key, p50, p99 in table:
            p50 = max(0.0, float(p50))
            p99 = max(p50, float(p99))
            self._anchors[str(key)] = (p50 / 2.0, p50, p99)
        if not self._anchors:
            raise ValueError("empty latency table")
        if "*" not in self._anchors:
            # pooled fallback: median of the per-edge anchors
            p50s = sorted(a[1] for a in self._anchors.values())
            p99s = sorted(a[2] for a in self._anchors.values())
            mid = len(p50s) // 2
            self._anchors["*"] = (p50s[mid] / 2.0, p50s[mid], p99s[mid])

    def __len__(self) -> int:
        return len([k for k in self._anchors if k != "*"])

    def quantile(self, u: int, v: int, q: float) -> float:
        """The fitted latency at quantile ``q`` for edge ``u->v``."""
        lo, p50, p99 = self._anchors.get(
            f"{int(u)}->{int(v)}", self._anchors["*"])
        q = min(1.0, max(0.0, float(q)))
        if q <= 0.5:
            return lo + (p50 - lo) * (q / 0.5)
        if q <= 0.99:
            return p50 + (p99 - p50) * ((q - 0.5) / 0.49)
        return p99

    def sample(self, u: int, v: int, rng) -> float:
        # exactly ONE rng.random() per draw — stream-compatible with
        # the rng.uniform() call this replaces, so arming the table
        # never shifts any other seeded stream in the campaign
        return self.quantile(u, v, rng.random())


def load_trace_latency(path: str) -> Tuple[Tuple[str, float, float], ...]:
    """Read a merged-trace critical-path report into latency_table
    rows ``((edge_key, p50_s, p99_s), ...)`` — µs in, seconds out."""
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    edges = doc
    for key in ("stragglers", "edge_latency"):
        if isinstance(edges, dict) and key in edges:
            edges = edges[key]
    if not isinstance(edges, dict) or not edges:
        raise ValueError(
            f"{path}: no edge_latency mapping found (want a "
            f"critical-path report or a bare edge->quantiles dict)")
    rows = []
    for edge, q in sorted(edges.items()):
        try:
            p50 = float(q["p50_us"]) / 1e6
            p99 = float(q["p99_us"]) / 1e6
        except (TypeError, KeyError, ValueError):
            raise ValueError(
                f"{path}: edge {edge!r} lacks p50_us/p99_us") from None
        rows.append((str(edge), p50, max(p50, p99)))
    return tuple(rows)
