"""In-memory mailbox/window transport + membership board for the sim.

``SimTransport`` implements the contract the island runtime's windows
speak — **deposit** (writer-side, commit-on-delivery), **collect**
(reader-side drain), monotone **versions**, per-rank **liveness
words**, a **mutex** with holder attribution, and the **membership
board** — against plain dicts, with an event-queue scheduler standing
in for the wire.  The protocol state machines layered on top
(``FailureDetector``, ``EdgeHealth``, ``AdaptivePolicy``,
``MembershipBoard.grant``/``commit_reweight``, the healing planners)
are the REAL ones, imported from their production modules.

Two ledgers are kept, mirroring the telemetry mass-ledger semantics
(docs/OBSERVABILITY.md):

- **counts** per global rank: ``deposits`` (writer-side, one per
  committed version), ``collected``/``drained``/``pending``
  (reader-side retirement).  Settlement mirrors ``islands.heal``:
  survivors ADOPT a corpse's writer-side version counts on their own
  in-slots and WRITE OFF their own committed deposits to the corpse
  as pending; a dead/fenced rank's own counters are excluded from the
  merged balance exactly like a corpse that never wrote a snapshot.

- **mass** (the push-sum ``x`` and ``p`` floats): every unit lives in
  exactly one of {a live rank, a slot, an in-flight message, the
  ``lost`` bucket}, and every transfer between buckets happens inside
  one event — so ``live + slots + inflight + lost == initial +
  joined`` holds after EVERY event, which is the invariant the
  campaign checker audits continuously.

Fault surface: ``kill`` (mass seized, in-slots severed, messages drop
on dead in both directions), suspension and slow-down are driven by
the fleet (they are scheduling phenomena, not transport state).
"""

from __future__ import annotations

import json
import math
from contextlib import contextmanager
from typing import Dict, List, Optional, Set, Tuple

from bluefog_tpu.native import capabilities as _caps
from bluefog_tpu.resilience.join import MembershipBoard
from bluefog_tpu.sim.clock import Clock, resolve_clock
from bluefog_tpu.sim.events import EventLoop

__all__ = ["SimTransport", "SimBoard", "SimJobView", "Slot"]


class Slot:
    """One (epoch, dst, src) mail slot: a monotone version counter and
    the accumulated (x, p) payload awaiting collect."""

    __slots__ = ("version", "seen", "x", "p", "adopted", "severed")

    def __init__(self):
        self.version = 0   # monotone committed-deposit count
        self.seen = 0      # versions the collector has retired
        self.x = 0.0
        self.p = 0.0
        self.adopted = False
        self.severed = False


class SimJobView:
    """The duck-typed job transport one rank's ``FailureDetector``
    sees: ``heartbeat()`` stamps MY liveness word, ``liveness(local)``
    reads a peer's, with local ranks resolved through this epoch's
    member list — the same global/local split the real epoch-suffixed
    job segments give the detector."""

    def __init__(self, transport: "SimTransport",
                 members: Tuple[int, ...], my_global: int):
        self._t = transport
        self._members = tuple(int(m) for m in members)
        self._g = int(my_global)

    def heartbeat(self) -> None:
        self._t.beat(self._g)

    def liveness(self, rank: int) -> float:
        # partition-aware: across an active cut the reader keeps
        # seeing the stamp frozen at the cut, so its detector times
        # the far side out exactly like a crash
        return self._t.liveness_seen(self._g, self._members[int(rank)])


class SimTransport:
    """See module docstring."""

    CAPS = _caps.TransportCaps(
        name="sim",
        fused_accumulate=True,   # deposit folds (x, p) into the slot
        fused_scale=False,       # campaigns pre-weight their deposits
        fused_combine=False,     # collect returns scalars; nothing to fuse
        zero_copy_collect=True,  # collect IS the atomic drain, no copy
        chunked_streaming=False,  # virtual wire delivers whole payloads
        wire_quantization=False,
        resume=False,            # a severed sim edge stays severed
    )

    def __init__(self, loop: EventLoop, clock: Clock):
        self.loop = loop
        self.clock = clock
        # liveness words: global rank -> last heartbeat stamp
        self._liveness: Dict[int, float] = {}
        # mail slots: (epoch, dst_g, src_g) -> Slot
        self._slots: Dict[Tuple[int, int, int], Slot] = {}
        # writer-side committed-deposit counts: (epoch, src_g, dst_g) -> n
        self._deposited_to: Dict[Tuple[int, int, int], int] = {}
        # in-flight messages: msg id -> (x, p) for exact mass accounting
        self._inflight: Dict[int, Tuple[float, float]] = {}
        self._next_msg = 0
        # epochs a collector has retired: late deliveries bounce
        self._retired: Set[Tuple[int, int]] = set()
        self.killed: Set[int] = set()
        # ranks whose ledgers were adopted by survivors (corpses and
        # fenced zombies) — excluded from the merged count balance
        self.adopted_ranks: Set[int] = set()
        # the 8-byte membership-epoch word (SimBoard publishes here)
        self.epoch_word = 0
        # cheap join-request flag: SimBoard._publish keeps it current
        # so sponsors don't JSON-parse the whole board every round
        self.join_pending = False
        # count ledgers, per global rank
        self.deposits: Dict[int, int] = {}
        self.collected: Dict[int, int] = {}
        self.drained: Dict[int, int] = {}
        self.pending: Dict[int, int] = {}
        # mass buckets
        self.lost_x = 0.0
        self.lost_p = 0.0
        # mutexes: key -> (holder, acquired_at)
        self._mutex: Dict[object, Tuple[object, float]] = {}
        # network partition: global rank -> group id while a cut is
        # active (None = fully connected).  Liveness words and the
        # epoch word freeze ACROSS the cut (the snapshots below are
        # what the far side keeps reading), and cross-group deliveries
        # drop to the lost bucket — a partition severs traffic, it
        # does not destroy state.
        self._partition_groups: Optional[Dict[int, int]] = None
        self._board_group = 0
        self._frozen_liveness: Dict[int, float] = {}
        self._frozen_epoch_word = 0

    # -- network partition -------------------------------------------------

    def set_partition(self, groups: Dict[int, int],
                      board_group: int) -> None:
        """Cut the network along ``groups`` (a COMPLETE global-rank ->
        group-id map; unknown ranks — e.g. a joiner spawned mid-cut —
        land with the board).  ``board_group`` names the side the
        membership board lives on: everyone else sees the epoch word
        frozen and their board ops stall, exactly like an unreachable
        filesystem."""
        self._partition_groups = {int(g): int(i)
                                  for g, i in groups.items()}
        self._board_group = int(board_group)
        self._frozen_liveness = dict(self._liveness)
        self._frozen_epoch_word = self.epoch_word

    def clear_partition(self) -> None:
        self._partition_groups = None
        self._frozen_liveness = {}

    @property
    def partitioned(self) -> bool:
        return self._partition_groups is not None

    def _group_of(self, g: int) -> int:
        assert self._partition_groups is not None
        return self._partition_groups.get(int(g), self._board_group)

    def _crosses(self, a: int, b: int) -> bool:
        return (self._partition_groups is not None
                and self._group_of(a) != self._group_of(b))

    def liveness_seen(self, reader: int, g: int) -> float:
        """The liveness stamp ``reader`` observes for ``g``: the live
        word, unless a partition separates them — then the stamp frozen
        at the cut (the far side looks like it stopped beating)."""
        if self._crosses(reader, g):
            return self._frozen_liveness.get(int(g), 0.0)
        return self.liveness(g)

    def epoch_word_seen(self, reader: int) -> int:
        """The membership-epoch word ``reader`` observes: frozen at the
        cut for ranks partitioned away from the board."""
        if (self._partition_groups is not None
                and self._group_of(reader) != self._board_group):
            return self._frozen_epoch_word
        return self.epoch_word

    def board_reachable(self, g: int) -> bool:
        return (self._partition_groups is None
                or self._group_of(g) == self._board_group)

    # -- liveness words ----------------------------------------------------

    def beat(self, g: int) -> None:
        if g not in self.killed:
            self._liveness[int(g)] = self.clock.now()

    def liveness(self, g: int) -> float:
        return self._liveness.get(int(g), 0.0)

    def job_view(self, members, my_global: int) -> SimJobView:
        return SimJobView(self, members, my_global)

    # -- mailbox -----------------------------------------------------------

    def _slot(self, epoch: int, dst: int, src: int) -> Slot:
        key = (int(epoch), int(dst), int(src))
        s = self._slots.get(key)
        if s is None:
            s = self._slots[key] = Slot()
        return s

    def deposit(self, epoch: int, src: int, dst: int, x: float, p: float,
                latency_s: float) -> None:
        """Writer-side deposit: the payload rides the (virtual) wire
        for ``latency_s`` and COMMITS at delivery — a writer that dies
        in flight committed zero mass (drop-on-dead), mirroring
        DEPOSIT_COMMITS_AFTER_PAYLOAD."""
        self._next_msg += 1
        mid = self._next_msg
        self._inflight[mid] = (float(x), float(p))
        ep, s_, d_ = int(epoch), int(src), int(dst)

        def _deliver():
            mx, mp = self._inflight.pop(mid)
            if (s_ in self.killed or d_ in self.killed
                    or (ep, d_) in self._retired
                    # a delivery caught crossing an active cut drops —
                    # the mass leaves live circulation (lost bucket),
                    # never silently evaporates
                    or self._crosses(s_, d_)):
                self.lost_x += mx
                self.lost_p += mp
                return
            slot = self._slot(ep, d_, s_)
            if slot.severed:
                self.lost_x += mx
                self.lost_p += mp
                return
            slot.version += 1
            slot.x += mx
            slot.p += mp
            self.deposits[s_] = self.deposits.get(s_, 0) + 1
            k = (ep, s_, d_)
            self._deposited_to[k] = self._deposited_to.get(k, 0) + 1

        self.loop.after(latency_s, _deliver)

    def collect(self, epoch: int, dst: int, src: int
                ) -> Tuple[float, float, int]:
        """Reader-side drain: returns the accumulated (x, p) and the
        number of fresh versions retired (0 when the slot is empty)."""
        slot = self._slots.get((int(epoch), int(dst), int(src)))
        if slot is None or slot.severed:
            return 0.0, 0.0, 0
        fresh = slot.version - slot.seen
        if fresh <= 0:
            return 0.0, 0.0, 0
        x, p = slot.x, slot.p
        slot.x = 0.0
        slot.p = 0.0
        slot.seen = slot.version
        self.collected[int(dst)] = self.collected.get(int(dst), 0) + fresh
        return x, p, fresh

    def read_version(self, epoch: int, dst: int, src: int) -> int:
        slot = self._slots.get((int(epoch), int(dst), int(src)))
        return 0 if slot is None else slot.version

    # -- fault + settlement surface ---------------------------------------

    def kill(self, g: int) -> Tuple[float, float]:
        """Mark ``g`` dead: its liveness word freezes, every message
        to/from it drops from now on, and its in-slots are severed
        (their uncollected mass leaves live circulation).  Returns the
        slot mass seized so the fleet can move the rank's own exposed
        mass to ``lost`` in the same event."""
        g = int(g)
        self.killed.add(g)
        self.adopted_ranks.add(g)
        seized_x = seized_p = 0.0
        for key, slot in self._slots.items():
            ep, dst, src = key
            if dst == g:
                if not slot.severed:
                    seized_x += slot.x
                    seized_p += slot.p
                    slot.x = 0.0
                    slot.p = 0.0
                    slot.severed = True
        self.lost_x += seized_x
        self.lost_p += seized_p
        return seized_x, seized_p

    def heal_settle(self, survivor: int, dead: int, epoch: int) -> dict:
        """One survivor's ledger settlement for one corpse, mirroring
        ``islands.heal``: ADOPT the corpse's writer-side version counts
        on my in-slots (the monotone version IS that count), force-DRAIN
        whatever the slots still hold, and WRITE OFF my own committed
        deposits to the corpse (every epoch — the corpse retires
        nothing ever again) as pending.

        Adoption spans EVERY epoch of the (survivor, corpse) pair, not
        just the current one: a corpse declared dead after an epoch
        switch (a suspend-zombie that slept through a join) committed
        its last deposits under the OLD epoch, and those versions were
        already collected/retired by the survivor — skipping them would
        leave the merged ledger short exactly that count once the
        corpse's own counters are excluded from the merge."""
        sg, dg = int(survivor), int(dead)
        self.adopted_ranks.add(dg)
        out = {"adopted": 0, "drained": 0, "written_off": 0}
        for key, slot in self._slots.items():
            if key[1] != sg or key[2] != dg:
                continue
            if slot.adopted:
                continue
            slot.adopted = True
            out["adopted"] += slot.version
            self.deposits[sg] = self.deposits.get(sg, 0) + slot.version
            stale = slot.version - slot.seen
            if stale > 0:
                out["drained"] += stale
                self.drained[sg] = self.drained.get(sg, 0) + stale
                slot.seen = slot.version
            self.lost_x += slot.x
            self.lost_p += slot.p
            slot.x = 0.0
            slot.p = 0.0
            slot.severed = True
        written = 0
        for key in [k for k in self._deposited_to
                    if k[1] == sg and k[2] == dg]:
            written += self._deposited_to.pop(key)
        if written:
            out["written_off"] = written
            self.pending[sg] = self.pending.get(sg, 0) + written
        return out

    def retire_epoch(self, g: int, epoch: int, in_srcs) -> Tuple[int, float]:
        """Collector-side epoch retirement at a switch: probe every
        in-slot's uncollected versions as pending (they cross the
        switch as ledger pending, never combined — their mass leaves
        live circulation), then refuse late deliveries."""
        g, epoch = int(g), int(epoch)
        pend = 0
        mass_x = 0.0
        for src in sorted(int(s) for s in in_srcs):
            slot = self._slots.get((epoch, g, src))
            if slot is None or slot.severed:
                continue
            stale = slot.version - slot.seen
            if stale > 0:
                pend += stale
                slot.seen = slot.version
            mass_x += slot.x
            self.lost_x += slot.x
            self.lost_p += slot.p
            slot.x = 0.0
            slot.p = 0.0
            slot.severed = True
        if pend:
            self.pending[g] = self.pending.get(g, 0) + pend
        self._retired.add((epoch, g))
        return pend, mass_x

    def probe_pending(self, g: int, epoch: int, in_srcs) -> int:
        """Shutdown-style pending probe (no sever): retire whatever
        each in-slot still holds as pending — the quiesce-time
        settlement that closes the count ledger."""
        g, epoch = int(g), int(epoch)
        pend = 0
        for src in sorted(int(s) for s in in_srcs):
            slot = self._slots.get((epoch, g, src))
            if slot is None or slot.severed:
                continue
            stale = slot.version - slot.seen
            if stale > 0:
                pend += stale
                slot.seen = slot.version
        if pend:
            self.pending[g] = self.pending.get(g, 0) + pend
        return pend

    # -- aggregate views for the invariant checkers ------------------------

    def slot_mass(self) -> Tuple[float, float]:
        # fsum is exact, so the sum is order-independent — no need to
        # sort for determinism (this runs after every event)
        x = math.fsum(s.x for s in self._slots.values())
        p = math.fsum(s.p for s in self._slots.values())
        return x, p

    def inflight_mass(self) -> Tuple[float, float]:
        x = math.fsum(v[0] for v in self._inflight.values())
        p = math.fsum(v[1] for v in self._inflight.values())
        return x, p

    def outstanding_slot_mass(self) -> float:
        """Uncollected slot x — diagnostic only."""
        return self.slot_mass()[0]

    def ledger(self, include=None) -> dict:
        """The merged count ledger over ``include`` ranks (default:
        every rank except the adopted/killed, mirroring which ranks
        write snapshots), in ``telemetry.merge.ledger_balance`` shape."""
        if include is None:
            ranks = (set(self.deposits) | set(self.collected)
                     | set(self.drained) | set(self.pending))
            include = ranks - self.adopted_ranks
        inc = {int(r) for r in include}
        dep = sum(self.deposits.get(r, 0) for r in inc)
        col = sum(self.collected.get(r, 0) for r in inc)
        dra = sum(self.drained.get(r, 0) for r in inc)
        pen = sum(self.pending.get(r, 0) for r in inc)
        return {"deposits": dep, "collected": col, "drained": dra,
                "pending": pen,
                "balanced": dep == col + dra + pen}

    # -- mutex (holder-attributed, virtual-clock timed) --------------------

    def mutex_acquire(self, key, holder, timeout_s: float = 5.0,
                      poll_s: float = 0.001) -> bool:
        """Acquire the named mutex, spinning on the virtual clock (the
        re-entrant sleep lets the current holder's release event fire
        mid-acquire, exactly like a blocked thread would observe)."""
        deadline = self.clock.deadline(timeout_s)
        while True:
            cur = self._mutex.get(key)
            if cur is None:
                self._mutex[key] = (holder, self.clock.now())
                return True
            if self.clock.expired(deadline):
                return False
            self.clock.sleep(poll_s)

    def mutex_release(self, key, holder) -> None:
        cur = self._mutex.get(key)
        if cur is not None and cur[0] == holder:
            del self._mutex[key]

    def mutex_holder(self, key):
        cur = self._mutex.get(key)
        return None if cur is None else cur[0]


class SimBoard(MembershipBoard):
    """The membership board against an in-memory document.

    Only the I/O seam is overridden — ``read``/``_publish`` go through
    a JSON round-trip (same torn-write-free semantics as the atomic
    rename, plus a free serializability check), the lock is a no-op
    (single-threaded event loop), request ids are deterministic, and
    the epoch word publishes into the :class:`SimTransport`.  The
    protocol methods — ``ensure``, ``grant`` (grow_topology + monotone
    next_rank + first-wins idempotence), ``commit_reweight``,
    ``wait_for_grant`` (on the virtual clock) — run UNCHANGED from
    :class:`~bluefog_tpu.resilience.join.MembershipBoard`.
    """

    def __init__(self, job: str, transport: SimTransport,
                 clock: Optional[Clock] = None):
        self.job = job
        self._clock = resolve_clock(
            transport.clock if clock is None else clock)
        self._transport = transport
        self._doc: Optional[str] = None  # serialized, like the file
        self._req_seq = 0

    def read(self) -> Optional[dict]:
        return None if self._doc is None else json.loads(self._doc)

    def _publish(self, doc: dict) -> None:
        self._doc = json.dumps(doc)
        self._transport.join_pending = bool(doc.get("requests"))

    def _locked(self):
        @contextmanager
        def cm():
            yield

        return cm()

    def _publish_epoch_word(self, epoch: int) -> None:
        self._transport.epoch_word = int(epoch)

    def post_request(self, retiring: int = -1) -> str:
        """Deterministic request ids (the real board's
        hostname-pid-uuid ids would break bit-identical replay)."""
        self._req_seq += 1
        req_id = f"sim-join-{self._req_seq}"
        with self._locked():
            doc = self.read()
            if doc is None:
                raise RuntimeError(
                    f"no membership board for job {self.job!r} — is the "
                    "fleet initialized (SimFleet publishes the board)?")
            req = {"req": req_id, "pid": self._req_seq,
                   "host": "sim", "t": self._clock.now()}
            if int(retiring) >= 0:
                req["retiring"] = int(retiring)
            doc["requests"].append(req)
            self._publish(doc)
        return req_id
