"""The standing invariants the fleet simulator audits continuously.

These are the properties the resilience design ARGUES hold at every
point of every fault interleaving (docs/RESILIENCE.md); the simulator
turns the argument into a check that runs after every protocol event
of a campaign.  Each checker returns ``None`` when the invariant
holds, or a human-readable violation string — the campaign layer
records, never raises, so one violation cannot mask later ones and
the shrinker can count them.

The numeric core of the doubly-stochastic check
(:func:`stochastic_violations`) is shared with the static analysis
plane — ``analysis.plan_rules.check_mixing_stochastic`` wraps the same
function over compiled plans, so the property audited offline on a
plan and online on a campaign's healed/demoted/grown graphs is
literally the same code.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "stochastic_violations",
    "check_doubly_stochastic",
    "check_mass_conservation",
    "check_epoch_monotone",
    "check_minority_demotion",
    "check_consensus",
    "check_single_lineage",
    "check_partition_merge_mass",
    "check_serve_version_monotone",
    "check_serve_snapshot_committed",
    "check_distrib_tree",
    "check_distrib_staleness",
    "check_request_slo",
    "check_request_staleness",
    "check_open_loop",
    "demotion_cap",
]

#: float-epsilon tolerance for stochasticity sums (matches the
#: analysis plan rules)
STOCHASTIC_TOL = 1e-6


def stochastic_violations(W: np.ndarray, expect_column: bool = True,
                          tol: float = STOCHASTIC_TOL) -> List[str]:
    """Row/column/negativity violations of a mixing matrix, as message
    strings (empty list = doubly stochastic within ``tol``)."""
    out: List[str] = []
    rows = W.sum(axis=1)
    bad_rows = np.flatnonzero(np.abs(rows - 1.0) > tol)
    if bad_rows.size:
        out.append(
            f"row(s) {bad_rows[:6].tolist()} sum to "
            f"{rows[bad_rows[:6]].tolist()} (expected 1±{tol}) — gossip "
            "would not converge to a consensus")
    if expect_column:
        cols = W.sum(axis=0)
        bad_cols = np.flatnonzero(np.abs(cols - 1.0) > tol)
        if bad_cols.size:
            out.append(
                f"column(s) {bad_cols[:6].tolist()} sum to "
                f"{cols[bad_cols[:6]].tolist()} (expected 1±{tol}) — the "
                "fixed point drifts away from the true average")
    if (W < -tol).any():
        neg = np.argwhere(W < -tol)[:6].tolist()
        out.append(f"negative mixing weight(s) at {neg}")
    return out


def check_doubly_stochastic(G, tol: float = STOCHASTIC_TOL
                            ) -> Optional[str]:
    """Every healed/demoted/grown topology a campaign installs must
    carry a doubly stochastic W (the property that makes push-sum
    converge to the true average on the member set)."""
    from bluefog_tpu import topology_util

    W = topology_util.GetWeightMatrix(G)
    bad = stochastic_violations(np.asarray(W), expect_column=True,
                                tol=tol)
    return None if not bad else "; ".join(bad)


def check_mass_conservation(live_x: float, live_p: float, transport,
                            initial: Tuple[float, float],
                            joined: Tuple[float, float],
                            tol: float = 1e-8) -> Optional[str]:
    """Every unit of push-sum mass lives in exactly one bucket —
    ``live + slots + inflight + lost == initial + joined`` — after
    EVERY event (transfers are intra-event).  ``tol`` is absolute on
    the relative-to-scale residual."""
    sx, sp = transport.slot_mass()
    ix, ip = transport.inflight_mass()
    want_x = initial[0] + joined[0]
    want_p = initial[1] + joined[1]
    have_x = live_x + sx + ix + transport.lost_x
    have_p = live_p + sp + ip + transport.lost_p
    scale_x = max(1.0, abs(want_x))
    scale_p = max(1.0, abs(want_p))
    dx = abs(have_x - want_x) / scale_x
    dp = abs(have_p - want_p) / scale_p
    if dx > tol or dp > tol:
        return (f"mass off balance: x residual {have_x - want_x:.3e} "
                f"(live {live_x:.6g} + slots {sx:.6g} + inflight "
                f"{ix:.6g} + lost {transport.lost_x:.6g} != initial "
                f"{initial[0]:.6g} + joined {joined[0]:.6g}), p residual "
                f"{have_p - want_p:.3e}")
    return None


def check_epoch_monotone(prev: int, cur: int) -> Optional[str]:
    """The membership-epoch word only ever moves forward (a backward
    word would re-admit a retired epoch's mailboxes)."""
    if cur < prev:
        return (f"membership epoch word went backward: {prev} -> {cur}")
    return None


def demotion_cap(n_members: int) -> int:
    """The adaptive-topology minority cap: strictly fewer than half of
    the members may be demoted (``(n-1)//2``) — the healthy majority
    must keep carrying the gossip."""
    return max(0, (int(n_members) - 1) // 2)


def check_minority_demotion(n_members: int,
                            n_demoted: int) -> Optional[str]:
    if n_demoted > demotion_cap(n_members):
        return (f"{n_demoted} of {n_members} members demoted — over the "
                f"minority cap {demotion_cap(n_members)} (the healthy "
                "majority must keep carrying the gossip)")
    return None


def check_single_lineage(committed_groups) -> Optional[str]:
    """At most ONE side of an active partition may commit membership
    progress (heal, demote/promote, grant) — the split-brain fence.
    ``committed_groups`` is the set of partition-group ids that
    committed during the current window; two or more means both sides
    advanced their own epoch lineage, and their ledgers have already
    diverged."""
    gs = sorted({int(g) for g in committed_groups})
    if len(gs) > 1:
        return (f"split-brain: partition sides {gs} each committed "
                "membership progress during one partition window — at "
                "most one epoch lineage may advance (the minority must "
                "ORPHAN and quiesce)")
    return None


def check_partition_merge_mass(anchor: Tuple[float, float],
                               current: Tuple[float, float],
                               tol: float = 1e-8) -> Optional[str]:
    """Mass is conserved ACROSS a partition + merge: the conserved
    quantity ``live + slots + inflight + lost - joined`` snapshotted
    when the cut landed (``anchor``) must still hold after every event
    of the window and the merge-back — an orphan whose old mass is not
    written off when it re-enters with unit mass shows up here as a
    double count."""
    dx = abs(current[0] - anchor[0]) / max(1.0, abs(anchor[0]))
    dp = abs(current[1] - anchor[1]) / max(1.0, abs(anchor[1]))
    if dx > tol or dp > tol:
        return (f"mass not conserved across partition+merge: x residual "
                f"{current[0] - anchor[0]:.3e} vs the cut-time anchor "
                f"{anchor[0]:.6g}, p residual {current[1] - anchor[1]:.3e}"
                f" vs {anchor[1]:.6g}")
    return None


def check_serve_version_monotone(prev: int, cur: int) -> Optional[str]:
    """The serving plane's snapshot version is strictly monotone — at
    the publisher (the region header survives publisher death, so a
    successor must continue past the highest committed version, never
    restart at 1) and at every replica (a hot-swap only ever installs
    a NEWER version; flipping backward would serve stale weights to
    traffic that already saw the new ones)."""
    if cur <= prev:
        return (f"serve version went backward: {prev} -> {cur} — a "
                "publisher re-committed (or a replica flipped to) a "
                "stale snapshot version")
    return None


def check_serve_snapshot_committed(served: float,
                                   committed) -> Optional[str]:
    """Whatever a replica serves must be byte-identical to SOME
    committed snapshot — never a torn mix of two versions.  The
    double-buffer seqlock guarantees this in the real region (a reader
    that catches a mid-write buffer retries); ``committed`` is the
    campaign's list of ``(version, payload)`` commits."""
    if any(served == p for _, p in committed):
        return None
    vs = [v for v, _ in committed]
    return (f"served payload {served!r} matches NO committed snapshot "
            f"(committed versions {vs[:8]}{'...' if len(vs) > 8 else ''})"
            " — a torn read mixed two buffer generations")


def check_distrib_tree(parents: Dict[int, int],
                       fanout: int) -> Optional[str]:
    """The distribution fan-out tree must stay a tree: every replica
    reaches the publisher (connected, acyclic) and no relay feeds more
    than ``fanout`` children.  Delegates to the REAL repair code's
    validator (:func:`bluefog_tpu.serve.distrib.tree.tree_valid`) so
    the property audited in the sim and enforced by the coordinator is
    literally the same function.  The publisher itself is uncapped —
    it is the root of last resort when every relay is saturated."""
    from bluefog_tpu.serve.distrib import tree as _tree

    return _tree.tree_valid(dict(parents), int(fanout))


def check_distrib_staleness(replica: int, lag: int,
                            slo: int) -> Optional[str]:
    """A tree-fed replica may trail the publisher's committed version
    by at most ``slo`` versions (0 = unbounded).  A relay death whose
    subtree never re-parents shows up here: the orphaned children stop
    adopting new versions while the publisher keeps committing."""
    if slo > 0 and lag > slo:
        return (f"distrib replica {replica} is {lag} versions behind "
                f"the publisher (staleness SLO {slo}) — its feed path "
                "stalled (dead relay never re-parented?)")
    return None


def check_request_slo(replica: int, latency_s: float, slo_s: float,
                      attributed: bool) -> Optional[str]:
    """Every admitted serve request completes within the latency SLO
    *or* its violation overlaps an injected fault window (a replica
    kill, publisher death, tree re-parent).  ``attributed=True`` means
    the campaign found such a window — a violation with a cause is the
    system degrading as designed; one without is a silent SLO hole
    (e.g. a drain path that skips polls)."""
    if slo_s <= 0 or latency_s <= slo_s or attributed:
        return None
    return (f"replica {replica}: request latency {latency_s:.3f}s "
            f"exceeds the {slo_s:.3f}s SLO with NO fault window to "
            "attribute it to — a silent serve-path stall")


def check_request_staleness(replica: int, lag: int, slo: int,
                            attributed: bool) -> Optional[str]:
    """A request must be served within ``slo`` versions of the
    committed head (0 = unbounded) unless publish churn / a kill / a
    re-parent window explains the trail — the staleness-SLO twin of
    :func:`check_request_slo`, audited per served request under
    churn."""
    if slo <= 0 or lag <= slo or attributed:
        return None
    return (f"replica {replica} served a request {lag} versions stale "
            f"(staleness SLO {slo}) outside every fault window — the "
            "swap path fell behind with nothing to blame")


def check_open_loop(sched_t: float, charged_t: float,
                    tol: float = 1e-9) -> Optional[str]:
    """The open-loop contract: latency is charged from the SCHEDULED
    send instant, never re-anchored to when the server got around to
    it.  A drain that rewrites send times hides queueing delay —
    coordinated omission, the measurement bug the real load generator
    exists to avoid."""
    if charged_t <= sched_t + tol:
        return None
    return (f"request scheduled at t={sched_t:.3f} had latency charged "
            f"from t={charged_t:.3f} — the drain re-anchored the send "
            "time (coordinated omission: queueing delay vanished)")


def check_consensus(estimates: Dict[int, float], tol: float = 1e-6,
                    scale: float = 1.0) -> Optional[str]:
    """At quiesce every live rank's debiased estimate ``x/p`` must
    agree (push-sum consensus).  ``scale`` normalizes the spread (the
    caller passes the magnitude of the true average)."""
    if len(estimates) < 2:
        return None
    vals = [estimates[g] for g in sorted(estimates)]
    lo, hi = min(vals), max(vals)
    spread = (hi - lo) / max(1.0, abs(scale))
    if spread > tol:
        glo = min(estimates, key=lambda g: estimates[g])
        ghi = max(estimates, key=lambda g: estimates[g])
        return (f"no consensus at quiesce: spread {spread:.3e} > {tol:g} "
                f"(rank {glo} at {estimates[glo]:.9g}, rank {ghi} at "
                f"{estimates[ghi]:.9g})")
    return None
