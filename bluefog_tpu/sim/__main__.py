"""Campaign CLI: ``python -m bluefog_tpu.sim``.

Runs one seeded fault campaign over the real protocol state machines
and exits 0 on a clean run, 1 on any invariant violation — the shape
a CI job wants.  On violation with ``--shrink`` (the default), the
schedule is delta-debugged down to the minimal sub-schedule that
still reproduces the violation and written as a repro file that
``--replay`` re-runs from nothing but the file.

Flags default from the sim env family — ``BFTPU_SIM_RANKS``,
``BFTPU_SIM_ROUNDS``, ``BFTPU_SIM_SEED``, ``BFTPU_SIM_TOPOLOGY``,
``BFTPU_SIM_FAULTS``, ``BFTPU_SIM_QUIESCE_ROUNDS``,
``BFTPU_SIM_LATENCY_MS``, ``BFTPU_SIM_SCHEDULE``,
``BFTPU_SIM_REPRO_DIR``, ``BFTPU_SIM_QUORUM`` (all documented in
docs/OBSERVABILITY.md) —
so a chaos-style harness can parameterize a campaign the same way it
parameterizes a fault schedule; explicit flags always win.

Examples::

    python -m bluefog_tpu.sim --ranks 256 --rounds 50 --seed 7 \\
        --faults kill,slow,join
    python -m bluefog_tpu.sim --replay repro-mass-conservation.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from bluefog_tpu.sim.campaign import (
    SimConfig, run_campaign, shrink_schedule, write_repro, replay,
    load_repro)
from bluefog_tpu.sim.schedule import FAULT_KINDS, FaultSchedule

_TOPOLOGIES = ("exp2", "exp", "sym_exp4", "ring", "ring_uni", "star",
               "mesh2d", "full")


def _env(key: str, default=None):
    v = os.environ.get(key)
    return default if v is None or v == "" else v


def _parse_faults(spec: str) -> tuple:
    kinds = tuple(k.strip() for k in spec.split(",") if k.strip())
    bad = [k for k in kinds if k not in FAULT_KINDS]
    if bad:
        raise SystemExit(f"bftpu-sim: unknown fault kind(s) {bad} "
                         f"(one of {list(FAULT_KINDS)})")
    return kinds


def _parse_latency_ms(spec: str) -> tuple:
    try:
        lo, hi = (float(p) for p in spec.split(","))
    except ValueError:
        raise SystemExit("bftpu-sim: --latency-ms wants 'LO,HI' "
                         f"(got {spec!r})")
    return (lo / 1000.0, hi / 1000.0)


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.sim",
        description=__doc__.split("\n\n")[1],
    )
    ap.add_argument("--ranks", type=int,
                    default=int(_env("BFTPU_SIM_RANKS", 64)))
    ap.add_argument("--rounds", type=int,
                    default=int(_env("BFTPU_SIM_ROUNDS", 50)))
    ap.add_argument("--seed", type=int,
                    default=int(_env("BFTPU_SIM_SEED", 0)))
    ap.add_argument("--topology", choices=_TOPOLOGIES,
                    default=str(_env("BFTPU_SIM_TOPOLOGY", "exp2")))
    ap.add_argument("--faults", type=_parse_faults,
                    default=_parse_faults(
                        str(_env("BFTPU_SIM_FAULTS", "kill,slow,join"))),
                    help="comma list of fault kinds to draw from "
                         f"(subset of {','.join(FAULT_KINDS)})")
    ap.add_argument("--quiesce-rounds", type=int,
                    default=int(_env("BFTPU_SIM_QUIESCE_ROUNDS", 40)),
                    help="fault-free rounds appended so push-sum can "
                         "re-converge before the consensus audit")
    ap.add_argument("--latency-ms", type=_parse_latency_ms,
                    default=_parse_latency_ms(
                        str(_env("BFTPU_SIM_LATENCY_MS", "2,20"))),
                    metavar="LO,HI",
                    help="per-edge virtual wire latency range")
    ap.add_argument("--schedule", metavar="PATH",
                    default=_env("BFTPU_SIM_SCHEDULE"),
                    help="run an explicit fault-schedule JSON file "
                         "instead of generating one from the seed")
    ap.add_argument("--replay", metavar="REPRO",
                    help="re-run a repro file (config + schedule come "
                         "from the file; other flags are ignored)")
    ap.add_argument("--shrink", dest="shrink", action="store_true",
                    default=True,
                    help="on violation, ddmin the schedule to a "
                         "minimal repro (default)")
    ap.add_argument("--no-shrink", dest="shrink", action="store_false")
    ap.add_argument("--repro-dir", metavar="DIR",
                    default=_env("BFTPU_SIM_REPRO_DIR", "."),
                    help="where repro files are written")
    ap.add_argument("--journal-dir", metavar="DIR",
                    help="emit per-rank telemetry journals + snapshots "
                         "(validate with python -m bluefog_tpu.telemetry)")
    ap.add_argument("--debug-bug", action="append", default=[],
                    metavar="NAME",
                    help="seed an intentional bug (mass_leak, "
                         "cap_bypass, split_brain) — the campaign "
                         "should CATCH it")
    ap.add_argument("--serve-every", type=int, default=0,
                    metavar="N",
                    help="arm the serving plane: the publisher commits "
                         "a snapshot every N rounds (0 = off)")
    ap.add_argument("--serve-replicas", type=int, default=0,
                    metavar="K",
                    help="hot-swap replica models polling the "
                         "committed head (0 = off)")
    ap.add_argument("--arrivals", choices=("poisson", "fixed"),
                    default="",
                    help="replay an open-loop request process against "
                         "the serving replicas (needs --serve-every "
                         "and --serve-replicas); arms the request-SLO "
                         "and staleness-SLO standing invariants")
    ap.add_argument("--arrival-rate", type=float, default=2.0,
                    metavar="HZ",
                    help="requests per virtual second per replica")
    ap.add_argument("--request-slo-ms", type=float, default=0.0,
                    metavar="MS",
                    help="per-request latency SLO on the virtual "
                         "clock (0 = 2x the round period)")
    ap.add_argument("--request-staleness-slo", type=int, default=0,
                    metavar="V",
                    help="max versions behind the committed head a "
                         "served request may be (0 = unbounded)")
    ap.add_argument("--latency-from-trace", metavar="FILE",
                    help="fit the per-edge gossip latency to a merged "
                         "trace's critical-path report (replaces the "
                         "uniform --latency-ms draw with empirical "
                         "per-edge quantile samplers)")
    ap.add_argument("--quorum", choices=("majority", "off"),
                    default=str(_env("BFTPU_SIM_QUORUM", "majority")),
                    help="membership-commit quorum fence (mirrors "
                         "BFTPU_QUORUM; explicit in the config so "
                         "repro files replay identically)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout")
    return ap


def _print(summary: dict, as_json: bool, violations: List[dict]) -> None:
    if as_json:
        print(json.dumps(summary, indent=2, sort_keys=True))
        return
    print(f"bftpu-sim: {'OK' if summary['ok'] else 'VIOLATED'} "
          f"digest={summary['digest']} members={summary['members']} "
          f"events={summary['events']} faults={summary['faults']} "
          f"spread={summary['estimate_spread']:.3e}")
    arr = summary.get("arrivals")
    if arr:
        print(f"bftpu-sim: arrivals {arr['process']}@{arr['rate']:g}/s "
              f"admitted={arr['admitted']} served={arr['served']} "
              f"attributed={arr['attributed']} "
              f"violations={arr['violations']}")
    led = summary.get("ledger") or {}
    print(f"bftpu-sim: ledger deposits={led.get('deposits')} "
          f"collected={led.get('collected')} "
          f"drained={led.get('drained')} pending={led.get('pending')} "
          f"balanced={led.get('balanced')}")
    for v in violations[:5]:
        print(f"bftpu-sim: violation {v['name']} @rank {v['rank']}: "
              f"{v['detail']}")
    if len(violations) > 5:
        print(f"bftpu-sim: ... and {len(violations) - 5} more")


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.replay:
        cfg, schedule, doc = load_repro(args.replay)
        res = run_campaign(cfg, schedule)
        summary = res.summary()
        want = doc.get("violation")
        if want is not None:
            names = {v["name"] for v in res.violations}
            summary["reproduced"] = want["name"] in names
            if not args.json:
                print(f"bftpu-sim: replay {'REPRODUCED' if summary['reproduced'] else 'DID NOT reproduce'} "
                      f"{want['name']} (schedule of {len(schedule)})")
        _print(summary, args.json, res.violations)
        # a replay FAILS when it can't reproduce the recorded bug —
        # that means the repro went stale
        if want is not None:
            return 0 if summary["reproduced"] else 1
        return 0 if res.ok else 1

    latency_table = ()
    if args.latency_from_trace:
        from bluefog_tpu.sim.latency import load_trace_latency
        try:
            latency_table = load_trace_latency(args.latency_from_trace)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            raise SystemExit(f"bftpu-sim: --latency-from-trace: {e}")
        if not args.json:
            print(f"bftpu-sim: latency fitted to "
                  f"{len(latency_table)} traced edge(s) from "
                  f"{args.latency_from_trace}")
    cfg = SimConfig(
        ranks=args.ranks, rounds=args.rounds, seed=args.seed,
        topology=args.topology, faults=tuple(args.faults),
        quiesce_rounds=args.quiesce_rounds,
        latency_s=tuple(args.latency_ms),
        journal_dir=args.journal_dir,
        debug_bugs=tuple(args.debug_bug),
        quorum=args.quorum,
        serve_every=args.serve_every,
        serve_replicas=args.serve_replicas,
        arrivals=args.arrivals,
        arrival_rate=args.arrival_rate,
        request_slo_s=args.request_slo_ms / 1000.0,
        request_staleness_slo=args.request_staleness_slo,
        latency_table=latency_table,
    )
    schedule = None
    if args.schedule:
        with open(args.schedule, "r", encoding="utf-8") as f:
            schedule = FaultSchedule.from_json(f.read())
    res = run_campaign(cfg, schedule)
    summary = res.summary()

    if not res.ok and args.shrink:
        full = res.schedule
        minimal, viol, runs = shrink_schedule(cfg, full)
        os.makedirs(args.repro_dir, exist_ok=True)
        name = (viol or {"name": "unknown"})["name"].replace("/", "-")
        path = os.path.join(
            args.repro_dir,
            f"repro-{name}-seed{cfg.seed}-n{cfg.ranks}.json")
        write_repro(path, cfg, minimal, viol, digest=res.digest)
        summary["shrunk"] = {
            "from": len(full), "to": len(minimal),
            "campaigns": runs, "repro": path,
            "violation": (viol or {}).get("name"),
        }
        if not args.json:
            print(f"bftpu-sim: shrunk {len(full)} -> {len(minimal)} "
                  f"fault(s) in {runs} campaign(s); repro: {path}")
    _print(summary, args.json, res.violations)
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
