"""One fault-schedule format for chaos e2e tests and sim campaigns.

A schedule is an ordered tuple of :class:`Fault` records — ``(kind,
step, rank, duration_s, stop)`` — where ``step`` counts protocol
rounds on the victim's own cadence, exactly like
:func:`bluefog_tpu.resilience.chaos.checkpoint` counts its
instrumented steps.  The same four kinds exist on both sides:

====== ==========================================================
kind   semantics (chaos env keys / sim campaign)
====== ==========================================================
kill   SIGKILL at step (``BFTPU_CHAOS_KILL_RANK`` and
       ``BFTPU_CHAOS_KILL_STEP``) / rank dies, mass seized,
       in-flight drops on dead
suspend SIGSTOP for ``duration_s`` then SIGCONT
       (``BFTPU_CHAOS_SUSPEND_RANK``, ``BFTPU_CHAOS_SUSPEND_STEP``,
       ``BFTPU_CHAOS_SUSPEND_S``) / heartbeats stop, rounds stall
slow   main-thread sleep per step from ``step`` until ``stop``
       (``BFTPU_CHAOS_SLOW_RANK``, ``BFTPU_CHAOS_SLOW_STEP``,
       ``BFTPU_CHAOS_SLOW_S``, ``BFTPU_CHAOS_SLOW_STOP``) / round
       cadence stretched by ``duration_s`` — the gray failure
       adaptive demotion catches
join   a joiner posts on the membership board at step
       (``BFTPU_CHAOS_JOIN_RANK``, ``BFTPU_CHAOS_JOIN_STEP``) / a
       fresh SimRank rendezvouses
partition network partition from ``step`` until ``stop``: cross-group
       traffic drops, liveness words and the membership-epoch word go
       stale across the cut (``BFTPU_CHAOS_PARTITION_GROUP``,
       ``BFTPU_CHAOS_PARTITION_STEP``, ``BFTPU_CHAOS_PARTITION_STOP``)
       / the quorum-fenced minority ORPHANs and merges back on heal
serve_kill replica ``rank`` SIGKILLs mid-swap at its ``step``-th
       hot-swap, respawning at round ``stop``
       (``BFTPU_CHAOS_SERVE_KILL_REPLICA``,
       ``BFTPU_CHAOS_SERVE_KILL_SWAP``,
       ``BFTPU_CHAOS_SERVE_KILL_STOP``) / the sim replica dies between
       read and flip, its served version stays monotone across rejoin
serve_pub_kill the publisher SIGKILLs during its ``step``-th publish;
       ``group`` carries the phase — ``payload`` (standby buffer torn)
       or ``flip`` (payload whole, header not flipped)
       (``BFTPU_CHAOS_SERVE_PUB_KILL_PUBLISH``,
       ``BFTPU_CHAOS_SERVE_PUB_KILL_PHASE``) / survivors keep serving
       the previous committed snapshot, the successor continues the
       version sequence
====== ==========================================================

A partition's sides ride in ``group``: a pipe-separated list of
comma-separated global ranks (``"3"`` = rank 3 vs everyone else;
``"0,1|6,7"`` = two explicit islands plus the implicit rest).  Ranks
not named in any group form one extra implicit group, so the compact
one-sided spelling shrinks well under ddmin.

``to_json``/``from_json`` round-trip losslessly.  ``to_env`` projects
onto the chaos env keys — which hold at most ONE schedule per kind
(that is the chaos format's capacity, not this one's); projecting a
multi-fault campaign keeps the earliest fault of each kind and
reports what it dropped.  ``from_env`` lifts a chaos env schedule
into a one-fault-per-kind ``FaultSchedule``, so a flaky wall-clock
e2e can be replayed as a deterministic campaign.

Determinism: :meth:`FaultSchedule.generate` derives everything from a
seeded ``random.Random`` — same ``(seed, ranks, rounds, kinds)``,
same schedule, bit for bit.
"""

from __future__ import annotations

import dataclasses
import json
import random
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from bluefog_tpu.resilience import chaos as _chaos

__all__ = ["Fault", "FaultSchedule", "SCHEDULE_SCHEMA", "FAULT_KINDS",
           "GENERATE_KINDS"]

SCHEDULE_SCHEMA = "bftpu-fault-schedule/1"
FAULT_KINDS = ("kill", "suspend", "slow", "join", "partition",
               "serve_kill", "serve_pub_kill")
#: the kinds :meth:`FaultSchedule.generate` draws from by default — the
#: classic fleet faults.  The serve kinds are opt-in (pass them in
#: ``kinds`` explicitly): keeping the default draw set frozen keeps
#: every previously pinned ``generate(seed, ...)`` schedule, and hence
#: every pinned campaign event digest, bit-identical.
GENERATE_KINDS = ("kill", "suspend", "slow", "join", "partition")


@dataclasses.dataclass(frozen=True, order=True)
class Fault:
    """One scheduled fault.  Ordering is ``(step, kind, rank)`` so a
    sorted schedule is canonical — two schedules with the same fault
    set serialize identically.  ``group`` is the partition-side spec
    (empty for every other kind) and rides LAST so pre-partition
    schedules order, construct, and serialize exactly as before."""

    step: int
    kind: str
    rank: int
    duration_s: float = 0.0
    stop: Optional[int] = None
    group: str = ""

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} "
                             f"(one of {FAULT_KINDS})")
        if self.kind == "partition" and not self.group:
            raise ValueError("partition fault needs a group spec "
                             "(e.g. '3' or '0,1|6,7')")
        if self.kind == "serve_pub_kill" and self.group not in (
                "", "payload", "flip"):
            raise ValueError(
                f"serve_pub_kill phase {self.group!r} (the group field "
                "carries the phase: 'payload' or 'flip')")

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "step": int(self.step),
             "rank": int(self.rank)}
        if self.duration_s:
            d["duration_s"] = float(self.duration_s)
        if self.stop is not None:
            d["stop"] = int(self.stop)
        if self.group:
            d["group"] = str(self.group)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Fault":
        return cls(kind=str(d["kind"]), step=int(d["step"]),
                   rank=int(d["rank"]),
                   duration_s=float(d.get("duration_s", 0.0)),
                   stop=(None if d.get("stop") is None
                         else int(d["stop"])),
                   group=str(d.get("group", "")))

    @classmethod
    def partition(cls, groups, start: int, stop: int) -> "Fault":
        """The ``partition(groups, t0, t1)`` constructor: cross-group
        traffic drops from round ``start`` until round ``stop``.
        ``groups`` is an iterable of rank iterables; ranks named in no
        group form one implicit extra side."""
        spec = "|".join(",".join(str(int(r)) for r in sorted(grp))
                        for grp in groups)
        return cls(kind="partition", step=int(start), rank=-1,
                   stop=int(stop), group=spec)

    def groups(self) -> Tuple[Tuple[int, ...], ...]:
        """Parse the ``group`` spec into explicit rank tuples (the
        implicit "rest" side is the fleet's to derive — it knows who is
        alive when the cut lands)."""
        return tuple(
            tuple(sorted(int(x) for x in part.split(",") if x.strip()))
            for part in self.group.split("|") if part.strip())


class FaultSchedule:
    """An immutable, canonically-ordered tuple of faults + the seed
    that generated it (None for hand-written schedules)."""

    def __init__(self, faults: Iterable[Fault] = (),
                 seed: Optional[int] = None):
        self.faults: Tuple[Fault, ...] = tuple(sorted(faults))
        self.seed = seed

    def __len__(self) -> int:
        return len(self.faults)

    def __iter__(self):
        return iter(self.faults)

    def __eq__(self, other) -> bool:
        return (isinstance(other, FaultSchedule)
                and self.faults == other.faults)

    def __repr__(self) -> str:
        return (f"FaultSchedule(seed={self.seed}, "
                f"faults={[f.to_dict() for f in self.faults]})")

    def subset(self, faults: Sequence[Fault]) -> "FaultSchedule":
        """A schedule holding exactly ``faults`` (the shrinker's
        building block); the seed tags along for provenance."""
        return FaultSchedule(faults, seed=self.seed)

    # -- JSON round-trip ---------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "schema": SCHEDULE_SCHEMA,
            "seed": self.seed,
            "faults": [f.to_dict() for f in self.faults],
        }, sort_keys=True)

    @classmethod
    def from_json(cls, payload: str) -> "FaultSchedule":
        doc = json.loads(payload)
        schema = doc.get("schema")
        if schema != SCHEDULE_SCHEMA:
            raise ValueError(f"not a fault schedule (schema={schema!r}, "
                             f"want {SCHEDULE_SCHEMA!r})")
        return cls((Fault.from_dict(d) for d in doc.get("faults", ())),
                   seed=doc.get("seed"))

    # -- chaos env interop -------------------------------------------------

    def to_env(self, env: Optional[dict] = None) -> dict:
        """Project onto the chaos env keys.  The chaos format holds at
        most ONE schedule per kind, so the earliest fault of each kind
        wins — a lossy projection for multi-fault campaigns (the JSON
        form is the lossless one)."""
        env = {} if env is None else env
        first: Dict[str, Fault] = {}
        for f in self.faults:
            if f.kind not in first:
                first[f.kind] = f
        for kind, f in first.items():
            if kind == "kill":
                _chaos.schedule_kill(env, f.rank, f.step,
                                     delay_s=f.duration_s)
            elif kind == "suspend":
                _chaos.schedule_suspend(
                    env, f.rank, f.step,
                    duration_s=f.duration_s or 2.5)
            elif kind == "slow":
                _chaos.schedule_slow(env, f.rank, f.step,
                                     delay_s=f.duration_s or 0.5,
                                     stop=f.stop)
            elif kind == "join":
                _chaos.schedule_join(env, f.rank, f.step)
            elif kind == "partition":
                _chaos.schedule_partition(env, f.group, f.step,
                                          stop=f.stop)
            elif kind == "serve_kill":
                _chaos.schedule_serve_kill(env, f.rank, f.step,
                                           stop=f.stop)
            elif kind == "serve_pub_kill":
                _chaos.schedule_serve_pub_kill(
                    env, f.step, phase=f.group or "payload")
        return env

    @classmethod
    def from_env(cls, env) -> "FaultSchedule":
        """Lift a chaos env schedule (at most one fault per kind) into
        the shared format."""
        faults: List[Fault] = []
        if _chaos._KILL_RANK in env:
            faults.append(Fault(
                kind="kill", rank=int(env[_chaos._KILL_RANK]),
                step=int(env.get(_chaos._KILL_STEP, "1")),
                duration_s=float(env.get(_chaos._DELAY_S, "0"))))
        if _chaos._SUSPEND_RANK in env:
            faults.append(Fault(
                kind="suspend", rank=int(env[_chaos._SUSPEND_RANK]),
                step=int(env.get(_chaos._SUSPEND_STEP, "1")),
                duration_s=float(env.get(_chaos._SUSPEND_S, "2.5"))))
        if _chaos._SLOW_RANK in env:
            stop = env.get(_chaos._SLOW_STOP)
            faults.append(Fault(
                kind="slow", rank=int(env[_chaos._SLOW_RANK]),
                step=int(env.get(_chaos._SLOW_STEP, "1")),
                duration_s=float(env.get(_chaos._SLOW_S, "0.5")),
                stop=None if stop is None else int(stop)))
        if _chaos._JOIN_RANK in env:
            faults.append(Fault(
                kind="join", rank=int(env[_chaos._JOIN_RANK]),
                step=int(env.get(_chaos._JOIN_STEP, "1"))))
        if _chaos._PARTITION_GROUP in env:
            stop = env.get(_chaos._PARTITION_STOP)
            faults.append(Fault(
                kind="partition", rank=-1,
                step=int(env.get(_chaos._PARTITION_STEP, "1")),
                stop=None if stop is None else int(stop),
                group=str(env[_chaos._PARTITION_GROUP])))
        if _chaos._SERVE_KILL_REPLICA in env:
            stop = env.get(_chaos._SERVE_KILL_STOP)
            faults.append(Fault(
                kind="serve_kill",
                rank=int(env[_chaos._SERVE_KILL_REPLICA]),
                step=int(env.get(_chaos._SERVE_KILL_SWAP, "1")),
                stop=None if stop is None else int(stop)))
        if _chaos._SERVE_PUB_KILL_PUBLISH in env:
            faults.append(Fault(
                kind="serve_pub_kill", rank=-1,
                step=int(env[_chaos._SERVE_PUB_KILL_PUBLISH]),
                group=str(env.get(_chaos._SERVE_PUB_KILL_PHASE,
                                  "payload"))))
        return cls(faults)

    # -- seeded generation -------------------------------------------------

    @classmethod
    def generate(cls, seed: int, ranks: int, rounds: int,
                 kinds: Sequence[str] = GENERATE_KINDS,
                 n_faults: Optional[int] = None,
                 max_kills_frac: float = 0.25) -> "FaultSchedule":
        """Deterministically derive a campaign schedule from a seed.

        Kills are capped at ``max_kills_frac`` of the fleet (the
        healing rules assume a surviving majority), fault steps land
        in the first ~2/3 of the campaign so the quiesce window can
        actually quiesce, and every choice comes off one seeded
        ``random.Random`` — the same seed replays the same schedule.
        """
        rng = random.Random(int(seed))
        kinds = (tuple(k for k in kinds if k in FAULT_KINDS)
                 or GENERATE_KINDS)
        if n_faults is None:
            n_faults = max(1, min(8, ranks // 8, rounds // 4))
        max_kills = max(1, int(ranks * max_kills_frac))
        horizon = max(2, (2 * rounds) // 3)
        faults: List[Fault] = []
        kills = 0
        victims = set()
        partitions = 0
        for _ in range(int(n_faults)):
            kind = rng.choice(kinds)
            if kind == "kill" and kills >= max_kills:
                kind = "slow" if "slow" in kinds else "join"
            step = rng.randrange(1, horizon + 1)
            if kind == "serve_kill":
                # rank names the replica ordinal, not a fleet victim
                faults.append(Fault(
                    kind="serve_kill", step=max(1, step // 4),
                    rank=rng.randrange(0, 2),
                    stop=min(rounds, step + rng.randrange(3, 8))))
                continue
            if kind == "serve_pub_kill":
                faults.append(Fault(
                    kind="serve_pub_kill", step=max(1, step // 4),
                    rank=-1, group=rng.choice(("payload", "flip"))))
                continue
            if kind == "partition":
                # one window at a time (the fleet runs one cut), the
                # named side strictly sub-majority so the implicit rest
                # keeps quorum and can sponsor the merge-back
                if partitions >= 1:
                    continue
                partitions += 1
                size = rng.randrange(1, max(2, min(ranks // 4,
                                                   (ranks - 1) // 2) + 1))
                side = sorted(rng.sample(range(ranks), size))
                stop = min(rounds, step + rng.randrange(4, 10))
                faults.append(Fault.partition([side], step, stop))
                continue
            # victims are distinct (two faults on one rank is a valid
            # scenario but shrinks poorly: keep campaigns orthogonal)
            pool = [r for r in range(ranks) if r not in victims]
            if not pool:
                break
            rank = rng.choice(pool)
            if kind == "kill":
                kills += 1
                victims.add(rank)
                faults.append(Fault(kind="kill", step=step, rank=rank))
            elif kind == "suspend":
                victims.add(rank)
                faults.append(Fault(kind="suspend", step=step, rank=rank,
                                    duration_s=rng.uniform(2.5, 4.0)))
            elif kind == "slow":
                victims.add(rank)
                # long enough that the stale window (gap minus the
                # adaptive deadline) spans several observer polls —
                # a shorter slow is a legitimate fault the machine
                # correctly rides out without demoting
                dur = rng.uniform(0.5, 1.5)
                stop = min(rounds, step + rng.randrange(5, 15))
                faults.append(Fault(kind="slow", step=step, rank=rank,
                                    duration_s=dur, stop=stop))
            else:  # join — rank names the joiner ordinal, not a victim
                faults.append(Fault(kind="join", step=step,
                                    rank=ranks + len(faults)))
        return cls(faults, seed=int(seed))
