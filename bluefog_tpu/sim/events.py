"""Discrete-event scheduler + the virtual clock bound to it.

The loop is a plain ``heapq`` of ``(time, seq, callback)`` with a
monotone sequence tie-break, so two events at the same instant fire in
schedule order and a same-seed run replays the EXACT event sequence —
the determinism contract the campaign runner's bit-identical-replay
test rides on.

The one non-obvious design point is **re-entrancy**:
``VirtualClock.sleep(dt)`` does not suspend anything — it calls
``loop.run_until(now + dt)``, draining every event due in the window
and then landing time on the target.  A real blocking poll loop
(``MembershipBoard.wait_for_grant``: read board → sleep → read board)
therefore runs UNMODIFIED inside an event callback: each of its
"sleeps" gives every other rank scheduled in the window a turn, which
is exactly what the OS scheduler would have done with threads — minus
the nondeterminism.  ``run_until`` nests safely because the heap and
the ``now`` watermark are shared and time only moves forward; an
outer frame resuming after a nested drain simply finds fewer events
due.
"""

from __future__ import annotations

import heapq
from typing import Callable, List, Optional, Tuple

from bluefog_tpu.sim.clock import Clock

__all__ = ["EventLoop", "VirtualClock"]


class EventLoop:
    """Virtual-time event queue (see module docstring)."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._heap: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self.events_fired = 0

    @property
    def now(self) -> float:
        return self._now

    def empty(self) -> bool:
        return not self._heap

    def __len__(self) -> int:
        return len(self._heap)

    def at(self, t: float, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` at absolute virtual time ``t`` (clamped to
        now — a late schedule fires immediately, never in the past)."""
        self._seq += 1
        heapq.heappush(self._heap, (max(float(t), self._now),
                                    self._seq, fn))

    def after(self, dt: float, fn: Callable[[], None]) -> None:
        self.at(self._now + max(0.0, float(dt)), fn)

    def run_until(self, target: float) -> None:
        """Fire every event due at or before ``target``, then advance
        time to ``target``.  Re-entrant (see module docstring)."""
        while self._heap and self._heap[0][0] <= target:
            t, _, fn = heapq.heappop(self._heap)
            if t > self._now:
                self._now = t
            self.events_fired += 1
            fn()
        if target > self._now:
            self._now = target

    def run(self, until: Optional[float] = None,
            max_events: int = 50_000_000) -> None:
        """Drain the queue (optionally stopping once the next event
        lies past ``until``).  ``max_events`` is a runaway backstop —
        a self-rescheduling event that never stops would otherwise
        spin forever."""
        fired = 0
        while self._heap:
            if until is not None and self._heap[0][0] > until:
                break
            t, _, fn = heapq.heappop(self._heap)
            if t > self._now:
                self._now = t
            self.events_fired += 1
            fired += 1
            if fired > max_events:
                raise RuntimeError(
                    f"event loop exceeded {max_events} events — runaway "
                    "reschedule?")
            fn()
        if until is not None and until > self._now:
            self._now = until


class VirtualClock(Clock):
    """The :class:`~bluefog_tpu.sim.clock.Clock` face of an
    :class:`EventLoop`: ``now`` reads the loop watermark, ``sleep``
    drains the loop through the window (re-entrant poll-loop trick)."""

    def __init__(self, loop: EventLoop):
        self.loop = loop

    def now(self) -> float:
        return self.loop.now

    def sleep(self, seconds: float) -> None:
        self.loop.run_until(self.loop.now + max(0.0, float(seconds)))
