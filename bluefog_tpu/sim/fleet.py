"""The fleet driver: N simulated ranks running the REAL protocol.

Each :class:`SimRank` owns the same state-machine objects a live
island rank owns — a :class:`~bluefog_tpu.resilience.detector.
FailureDetector` over the transport's liveness words, an
:class:`~bluefog_tpu.resilience.detector.EdgeHealth` gray-failure
machine keyed by global rank, an :class:`~bluefog_tpu.resilience.
adaptive.AdaptivePolicy` fed deposit-gap observations off the mailbox
versions, and the shared :class:`~bluefog_tpu.sim.transport.SimBoard`
(the real ``MembershipBoard`` protocol methods).  Topology changes go
through the real planners (``heal_topology`` / ``grow_topology`` /
``demote_topology`` / ``record_graph``), memoized fleet-wide — the
planners are pure, so every rank that heals the same view shares one
compile.

The gossip itself is scalar push-sum: each rank's round collects its
in-slots (all-ones collect rows: a late deposit is simply picked up
next round — the mass-conserving plain drop of ``islands.win_update``
ABSORB), then deposits ``W[v,u]·x`` to each out-neighbor and keeps
the column residual — so Σx and Σp are conserved by construction and
the transport can audit conservation after every event.

Faults (from a :class:`~bluefog_tpu.sim.schedule.FaultSchedule`) fire
on the victim's own round counter, exactly like
``chaos.checkpoint`` counts steps:

- ``kill`` — the rank's mass is seized to the lost bucket, its
  in-slots sever, survivors detect via heartbeat timeout and run the
  heal/settlement path;
- ``suspend`` — heartbeats and rounds stall for ``duration_s``; past
  the failure timeout the fleet declares it dead and a resumed zombie
  finds itself fenced (adopted) and exits;
- ``slow`` — the round cadence stretches by ``duration_s`` while
  heartbeats keep beating: the gray failure only the adaptive
  edge-health machine catches (demote to anchor, promote on
  recovery);
- ``join`` — a joiner posts on the board and blocks in
  ``wait_for_grant`` on the virtual clock; the sponsor (lowest live
  global rank) grants via the real ``grant`` path and the joiner
  enters with unit mass at the sponsor's debiased estimate;
- ``partition`` — cross-group traffic drops and liveness/epoch words
  freeze across the cut for a window of rounds; each side's detector
  times the other out, the quorum fence (same rule as
  ``bluefog_tpu.resilience.quorum``) lets only a strict-majority side
  heal while the minority ORPHANs (parks its rounds, touches neither
  the board nor the shared ledgers), and on heal the orphans merge
  back through the real join machinery carrying their debiased
  estimates with their stale mass written off;
- ``serve_kill`` — a serving replica (see ``cfg.serve_replicas``)
  dies mid-hot-swap on its ``step``-th swap attempt and, when
  ``stop`` is set, respawns at that round as a fresh incarnation;
- ``serve_pub_kill`` — the publisher dies on its ``step``-th publish,
  mid-payload (``group="payload"``: the standby buffer is torn,
  nothing commits, survivors keep the previous version) or mid-flip
  (``group="flip"``: the buffer is whole, the successor's attach
  repairs forward to the new version).

Invariants are checked after every protocol event (see
:mod:`bluefog_tpu.sim.invariants`); violations are recorded, never
raised — the campaign layer decides whether to shrink.
"""

from __future__ import annotations

import bisect
import math
import random
from typing import Dict, List, Optional, Set, Tuple

import networkx as nx

from bluefog_tpu.resilience import healing as _healing
from bluefog_tpu.resilience import quorum as _quorum
from bluefog_tpu.resilience.adaptive import AdaptivePolicy
from bluefog_tpu.resilience.detector import (
    EDGE_ALIVE, EdgeHealth, FailureDetector)
from bluefog_tpu.resilience.join import record_graph
from bluefog_tpu.sim import invariants as _inv
from bluefog_tpu.sim.events import EventLoop, VirtualClock
from bluefog_tpu.sim.schedule import Fault, FaultSchedule
from bluefog_tpu.sim.transport import SimBoard, SimTransport

__all__ = ["SimRank", "SimFleet"]

_T0 = 1.0  # virtual launch instant (nonzero: a 0.0 heartbeat stamp
           # would read as "never beat" to the detector)


class SimRank:
    """One simulated rank: real state machines + scalar push-sum."""

    def __init__(self, g: int, x: float, p: float):
        self.g = int(g)
        self.x = float(x)
        self.p = float(p)
        self.epoch = 0
        self.epoch_members: Tuple[int, ...] = ()
        self.members: Tuple[int, ...] = ()
        self.graph: Optional[nx.DiGraph] = None
        self.base_key = None          # memo key of the pre-demotion base
        self.cfg_key = None           # memo key of the current topology
        self.known_dead: Set[int] = set()
        self.demoted: Set[int] = set()
        self.round_idx = 0
        self.done = False
        self.suspended_until = 0.0
        self.slow_until_step: Optional[int] = None
        self.slow_delay = 0.0
        self.exited = False
        self.killed = False
        self.orphaned = False
        self.detector: Optional[FailureDetector] = None
        self.health: Optional[EdgeHealth] = None
        self.policy: Optional[AdaptivePolicy] = None
        # per in-edge adaptive probe state: src_g -> [version, t, missed]
        self.edge_seen: Dict[int, list] = {}

    @property
    def estimate(self) -> float:
        return self.x / self.p if self.p > 0 else float("nan")

    def live_members(self) -> List[int]:
        return [m for m in self.members if m not in self.known_dead]


class SimFleet:
    """Drives ``cfg.ranks`` SimRanks through ``cfg.rounds`` fault-laden
    rounds plus ``cfg.quiesce_rounds`` clean ones on one event loop."""

    def __init__(self, cfg, schedule: Optional[FaultSchedule] = None):
        self.cfg = cfg
        self.schedule = schedule or FaultSchedule()
        self.loop = EventLoop(start=_T0)
        self.clock = VirtualClock(self.loop)
        self.transport = SimTransport(self.loop, self.clock)
        self.board = SimBoard(cfg.job, self.transport)
        self.rng = random.Random(int(cfg.seed) ^ 0x5EED0F)
        self.event_log: List[tuple] = []
        self.violations: List[dict] = []
        # lab oracle feed: (round, rank, |Δestimate|) per round when
        # cfg.trace_consensus — same observable as the islands probe
        # (bluefog_tpu.lab.probe), kept OUT of event_log so digests
        # and repro files are byte-identical with tracing on or off
        self.consensus_trace: List[tuple] = []
        self._conv_prev: Dict[int, float] = {}
        # fleet-monitor twin (cfg.monitor): the live scraper's
        # AlertEngine run against the VIRTUAL clock, sampled once per
        # round_period.  Rules are built explicitly from cfg — never
        # the BFTPU_MON_* env — so a monitored campaign replays
        # bit-identically anywhere; alert windows ride the final dict
        # ("monitor"), NOT the event_log, so digests and repro files
        # are unchanged whether the twin is on or off.
        self._monitor = None
        self._mon_next = 0.0
        self._mon_samples = 0
        self._mon_demote_ex = 0.0
        if getattr(cfg, "monitor", False):
            from bluefog_tpu.monitor.rules import AlertEngine, AlertRule

            self._monitor = AlertEngine(rules=(
                AlertRule("mass_imbalance", "mass_err", "gt",
                          float(cfg.mass_tol),
                          "sim: conservation residual past cfg.mass_tol"),
                AlertRule("epoch_fork", "epoch_fork", "nonzero", 0.0,
                          "sim: two live member views of one epoch"),
                AlertRule("demote_storm", "demote_excess", "gt", 0.0,
                          "sim: committed demotions exceed the cap"),
                AlertRule("request_slo", "request_slo", "nonzero", 0.0,
                          "sim: admitted request overdue unserved"),
            ), gap_s=(0.01 if "mon_flap" in cfg.debug_bugs else 2.5)
                * float(cfg.round_period))
            self._mon_next = float(_T0)
        self._epoch_word_seen = 0
        self._topo_cache: Dict[object, tuple] = {}
        # graphs already audited doubly stochastic (id -> graph ref)
        self._graphs_ok: Dict[int, object] = {}
        # committed epoch records, cached fleet-wide (read-only)
        self._epoch_recs: Dict[int, dict] = {}
        self._registries: Dict[int, object] = {}
        self.ranks: Dict[int, SimRank] = {}
        self.joiners_spawned = 0
        self.orphans_merged = 0
        # quorum fencing mirrors the production rule (cfg.quorum is
        # explicit so repro files replay identically regardless of
        # BFTPU_QUORUM); the split_brain seeded bug disables the fence
        # so the single-lineage invariant can catch the violation
        self._quorum_on = (
            getattr(cfg, "quorum", "majority") != "off"
            and "split_brain" not in getattr(cfg, "debug_bugs", ()))
        # active partition window state: global rank -> group id while
        # a cut is live, the set of group ids that committed membership
        # progress during the window, and the cut-time mass anchor for
        # the partition+merge conservation invariant
        self._partition: Optional[Dict[int, int]] = None
        self._board_group = 0
        self._lineage: Set[int] = set()
        self._partition_anchor: Optional[Tuple[float, float]] = None
        # serving plane (armed only when cfg.serve_every > 0): the
        # committed snapshot history (version, payload), the region
        # header's persisted version word, the fleet-wide publish
        # ordinal (serve_pub_kill faults index it), and the replica
        # models keyed by replica id (rank 1000+i in logs, mirroring
        # REPLICA_RANK_BASE)
        self._serve_every = int(getattr(cfg, "serve_every", 0) or 0)
        self._serve_replica_n = int(
            getattr(cfg, "serve_replicas", 0) or 0)
        self._serve_committed: List[Tuple[int, float]] = []
        self._serve_version = 0
        self._serve_pub_count = 0
        self._serve_replicas: Dict[int, dict] = {}
        # distribution tree (cfg.distrib_fanout > 0 with the serve
        # plane armed): replica id -> parent replica id
        # (serve.distrib.tree.PUBLISHER = fed by the region directly)
        # plus a per-feed-edge propagation latency.  The latency stream
        # is DEDICATED (seed ^ 0xD157), so arming the tree never
        # perturbs the seeded streams existing digests derive from.
        self._distrib_fanout = (
            int(getattr(cfg, "distrib_fanout", 0) or 0)
            if self._serve_every > 0 and self._serve_replica_n > 0
            else 0)
        self._distrib_parents: Dict[int, int] = {}
        self._distrib_lat: Dict[int, float] = {}
        self._distrib_rng = random.Random(int(cfg.seed) ^ 0xD157)
        self._distrib_reparents = 0
        self._distrib_joins = 0
        #: committed version -> virtual commit instant (feeds the
        #: per-edge propagation gate; never reaches the event log)
        self._serve_commit_t: Dict[int, float] = {}
        # serve traffic model (cfg.arrivals, armed only with the serve
        # plane): per-replica open-loop request schedules precomputed
        # from the SAME pure arrival_times() the real load generator
        # uses (its dedicated ^0x10AD seed stream — arming traffic
        # draws nothing from self.rng, so existing digests hold).
        # Fault windows carry the attribution story: a request-SLO or
        # staleness-SLO miss is a violation only when NO injected
        # fault window overlaps it.
        self._arrivals = (str(getattr(cfg, "arrivals", "") or "")
                          if self._serve_every > 0
                          and self._serve_replica_n > 0 else "")
        self._req_slo = float(getattr(cfg, "request_slo_s", 0.0) or 0.0)
        if self._arrivals and self._req_slo <= 0:
            self._req_slo = 2.0 * cfg.round_period
        self._req_stale_slo = int(
            getattr(cfg, "request_staleness_slo", 0) or 0)
        self._req_served = 0
        self._req_violations = 0
        self._req_attributed = 0
        self._arr_windows: List[dict] = []
        self._arr_kill_open: Dict[int, dict] = {}
        self._arr_stale_open: List[dict] = []
        # trace-fitted per-edge gossip latency (cfg.latency_table):
        # replaces the uniform draw in _send with an empirical quantile
        # sampler consuming exactly one rng.random() per edge
        self._lat_model = None
        _table = getattr(cfg, "latency_table", ()) or ()
        if _table:
            from bluefog_tpu.sim.latency import EmpiricalLatency
            self._lat_model = EmpiricalLatency(_table)
        # faults indexed by (victim global rank, step); joins and
        # partitions fire on their own timers (no single victim);
        # serve faults key on replica id / publish ordinal instead of
        # global rank, so they must not land in the rank-fault map
        self._faults: Dict[Tuple[int, int], Fault] = {}
        self._join_faults: List[Fault] = []
        self._partition_faults: List[Fault] = []
        self._serve_kill_faults: Dict[int, Fault] = {}
        self._serve_pub_faults: Dict[int, Fault] = {}
        for f in self.schedule:
            if f.kind == "join":
                self._join_faults.append(f)
            elif f.kind == "partition":
                self._partition_faults.append(f)
            elif f.kind == "serve_kill":
                self._serve_kill_faults[f.rank] = f
            elif f.kind == "serve_pub_kill":
                self._serve_pub_faults[f.step] = f
            else:
                self._faults[(f.rank, f.step)] = f
        self._build()

    # -- construction ------------------------------------------------------

    def _mk_registry(self, g: int):
        if not self.cfg.journal_dir:
            return None
        reg = self._registries.get(g)
        if reg is None:
            from bluefog_tpu.telemetry.registry import Registry

            reg = Registry(out_dir=self.cfg.journal_dir, rank=g,
                           job=self.cfg.job)
            self._registries[g] = reg
        return reg

    def _journal(self, g: int, event: str, **fields) -> None:
        reg = self._mk_registry(g)
        if reg is not None and reg.enabled:
            reg.journal(event, **fields)

    def _base_topology(self) -> nx.DiGraph:
        from bluefog_tpu import topology_util as tu

        n = int(self.cfg.ranks)
        builders = {
            "exp2": tu.ExponentialTwoGraph,
            "exp": tu.ExponentialGraph,
            "sym_exp4": tu.SymmetricExponentialGraph,
            "ring": tu.RingGraph,
            "ring_uni": lambda n: tu.RingGraph(n, connect_style=1),
            "star": tu.StarGraph,
            "mesh2d": tu.MeshGrid2DGraph,
            "full": tu.FullyConnectedGraph,
        }
        try:
            build = builders[self.cfg.topology]
        except KeyError:
            raise ValueError(
                f"unknown sim topology {self.cfg.topology!r} "
                f"(one of {sorted(builders)})") from None
        return build(n)

    def _rows(self, G: nx.DiGraph):
        """Per-local-rank send rows: (keep_fraction, [(dst_local, w)]).
        Edge (u, v) carries W[v, u] — the weight v applies to u's
        value — so u's column residual is 1 - Σ out-weights; with the
        MH weights doubly stochastic, depositing ``w·x`` per edge and
        keeping the residual conserves Σx exactly (up to fp)."""
        rows = {}
        for u in sorted(G.nodes):
            out = [(int(v), float(G[u][v]["weight"]))
                   for v in sorted(G.successors(u))]
            keep = 1.0 - sum(w for _, w in out)
            rows[int(u)] = (keep, out)
        return rows

    def _topo_entry(self, key, build):
        """Fleet-wide memo for pure topology computations: every rank
        healing/adopting the same view shares one planner run (the
        planners cost ~70 ms at N=256 — per-rank recompute would
        dominate the whole campaign)."""
        ent = self._topo_cache.get(key)
        if ent is None:
            ent = self._topo_cache[key] = build()
        return ent

    def _build(self) -> None:
        cfg = self.cfg
        G = self._topo_entry(("epoch", 0), lambda: self._base_topology())
        members = tuple(range(cfg.ranks))
        rows = self._rows(G)
        self.board.ensure(cfg.ranks)
        for g in range(cfg.ranks):
            r = SimRank(g, x=float(g), p=1.0)
            r.members = r.epoch_members = members
            r.graph = G
            r.cfg_key = r.base_key = ("epoch", 0)
            self.ranks[g] = r
            self._wire_rank(r)
        self.initial_x = sum(r.x for r in self.ranks.values())
        self.initial_p = sum(r.p for r in self.ranks.values())
        self.joined_x = 0.0
        self.joined_p = 0.0
        self._rows_cache = {("epoch", 0): rows}
        # stagger starts so rounds interleave like free-running
        # processes (deterministically); cfg.lockstep zeroes the
        # stagger so the fleet iterates synchronously (lab oracle mode)
        for g in range(cfg.ranks):
            off = 0.0 if getattr(cfg, "lockstep", False) \
                else (g * 37 % 101) / 101.0
            self.loop.at(_T0 + off * cfg.hb_interval, self._hb_event(g))
            self.loop.at(_T0 + off * cfg.round_period,
                         self._round_event(g))
        if self._serve_every > 0:
            for i in range(self._serve_replica_n):
                self._serve_replicas[i] = {
                    "version": 0, "payload": None, "swaps": 0,
                    "steps": 0, "killed": False, "fired": False,
                    "install_t": 0.0}
                # traffic starts after the first publish + one adopt
                # poll can have landed — a request against a replica
                # that CANNOT have a snapshot yet is a model artifact,
                # not an SLO story
                self._arm_arrivals(
                    self._serve_replicas[i], i,
                    _T0 + (self._serve_every + 2) * cfg.round_period)
                if self._distrib_fanout > 0:
                    from bluefog_tpu.serve.distrib import tree as _dtree
                    self._distrib_parents[i] = _dtree.parent_of(
                        i, self._distrib_fanout)
                    self._distrib_lat[i] = self._distrib_edge_latency()
                off = 0.0 if getattr(cfg, "lockstep", False) \
                    else ((1000 + i) * 37 % 101) / 101.0
                self.loop.at(_T0 + off * cfg.round_period,
                             self._serve_replica_event(i))
            jr = int(getattr(cfg, "distrib_join_round", 0) or 0)
            jn = int(getattr(cfg, "distrib_join_n", 0) or 0)
            if self._distrib_fanout > 0 and jr > 0 and jn > 0:
                self.loop.at(_T0 + jr * cfg.round_period,
                             self._distrib_join_storm_event(jn))
        for f in self._join_faults:
            self.loop.at(_T0 + f.step * cfg.round_period,
                         self._joiner_event(f))
        for f in self._partition_faults:
            t0 = _T0 + f.step * cfg.round_period
            if f.stop is not None:
                t1 = _T0 + f.stop * cfg.round_period
            else:
                t1 = t0 + (f.duration_s or 5 * cfg.round_period)
            self.loop.at(t0, self._partition_start_event(f))
            self.loop.at(max(t1, t0), self._partition_end_event(f))
        self.end_time = _T0 + (cfg.rounds + cfg.quiesce_rounds + 2) \
            * cfg.round_period

    def _wire_rank(self, r: SimRank) -> None:
        cfg = self.cfg
        view = self.transport.job_view(r.epoch_members, r.g)
        local = r.epoch_members.index(r.g)
        r.detector = FailureDetector(
            view, local, len(r.epoch_members),
            timeout=cfg.hb_timeout, interval=cfg.hb_interval,
            clock=self.clock.now)
        if r.health is None:
            r.health = EdgeHealth(misses=cfg.suspect_misses,
                                  clean=cfg.promote_clean,
                                  floor_s=cfg.demote_floor_s,
                                  clock=self.clock.now)
            r.policy = AdaptivePolicy(floor_s=cfg.edge_deadline_floor_s,
                                      factor=cfg.edge_deadline_factor,
                                      min_obs=cfg.adaptive_min_obs,
                                      health=r.health,
                                      clock=self.clock.now)
        r.detector.edge_health = r.health
        members = r.epoch_members
        r.detector.to_peer = lambda lr, _m=members: _m[lr]

    # -- event bodies ------------------------------------------------------

    def _all_done(self) -> bool:
        """Every live rank has finished its rounds — the campaign's
        quiesce point.  Heartbeats must keep beating until HERE, not
        until a fixed wall time: rounds stretch under slow faults and
        suspensions, and a straggler still running rounds after its
        peers stopped stamping would declare the whole fleet dead."""
        return all(r.done or r.killed or r.exited
                   for r in self.ranks.values())

    def _hb_event(self, g: int):
        def fire():
            r = self.ranks.get(g)
            if r is None or r.killed or r.exited:
                return
            if self._all_done():
                return
            if self.loop.now >= r.suspended_until:
                r.detector.beat()
            self.loop.after(self.cfg.hb_interval, self._hb_event(g))
        return fire

    def _round_event(self, g: int):
        def fire():
            r = self.ranks.get(g)
            if r is None or r.killed or r.exited:
                return
            if r.orphaned:
                # parked: an orphan runs no rounds (windows frozen,
                # progress engine quiesced).  The partition-heal event
                # owns its future — merge-back or fencing.
                return
            now = self.loop.now
            if now < r.suspended_until:
                self.loop.at(r.suspended_until, self._round_event(g))
                return
            r.round_idx += 1
            step = r.round_idx
            if step > self.cfg.rounds + self.cfg.quiesce_rounds:
                r.done = True
                return
            fault = self._faults.get((g, step))
            if fault is not None and self._apply_fault(r, fault):
                return  # killed (or suspended: round deferred)
            self._round_body(r)
            delay = 0.0
            if (r.slow_until_step is not None
                    and step >= 0 and r.round_idx < r.slow_until_step):
                delay = r.slow_delay
            elif (r.slow_until_step is not None
                  and r.round_idx >= r.slow_until_step):
                r.slow_until_step = None
                self._log("slow_end", r.g)
            self.loop.after(self.cfg.round_period + delay,
                            self._round_event(g))
        return fire

    def _apply_fault(self, r: SimRank, f: Fault) -> bool:
        """Returns True when the round body must not run (kill or
        suspend — a stopped process executes nothing)."""
        if f.kind == "kill":
            self._log("kill", r.g, step=r.round_idx)
            r.killed = True
            self.transport.kill(r.g)
            self.transport.lost_x += r.x
            self.transport.lost_p += r.p
            r.x = 0.0
            r.p = 0.0
            if self._arrivals:
                # a gossip-rank death can stall the publish cadence
                # (heal + quorum re-fence) — staleness is excused
                # fleet-wide until the next successful commit
                self._arr_stale_open.append(self._arr_window(
                    "rank_fault", -1, ("staleness",), None))
            self._check("kill", r.g)
            return True
        if f.kind == "suspend":
            dur = f.duration_s or 2.5
            self._log("suspend", r.g, step=r.round_idx, duration=dur)
            r.suspended_until = self.loop.now + dur
            self.loop.at(r.suspended_until, self._round_event(r.g))
            if self._arrivals:
                self._arr_stale_open.append(self._arr_window(
                    "rank_fault", -1, ("staleness",), None))
            self._check("suspend", r.g)
            return True
        if f.kind == "slow":
            self._log("slow_start", r.g, step=r.round_idx,
                      delay=f.duration_s)
            r.slow_delay = f.duration_s or 0.5
            r.slow_until_step = f.stop if f.stop is not None else 10 ** 9
            return False
        return False

    def _round_body(self, r: SimRank) -> None:
        # 1. failure detection -> heal
        dead_local = r.detector.dead_ranks()
        dead_global = {r.epoch_members[d] for d in dead_local}
        new_dead = dead_global - r.known_dead
        if new_dead:
            self._heal(r, new_dead)
        # 2. membership-epoch probe (the cheap word, then the board)
        self._probe_epochs(r)
        if r.exited:
            return
        # 3. sponsor-side admission (every round, like a round barrier
        # with a chaos join schedule of rank=-1).  The transport-level
        # flag (kept current by SimBoard._publish) makes the common
        # no-joiner round skip the board's JSON parse entirely.
        if self.transport.join_pending \
                and self.transport.board_reachable(r.g) \
                and self.board.pending_requests():
            live = r.live_members()
            if live and r.g == min(live):
                self._grant(r)
        # 4. adaptive demote/promote
        if self.cfg.adaptive:
            self._adaptive_step(r)
        # 5. combine whatever the in-slots hold
        self._combine(r)
        # 6. deposit this round's shares
        self._send(r)
        # 6b. lab oracle: per-rank successive-estimate difference, the
        # sim twin of the islands convergence probe
        if getattr(self.cfg, "trace_consensus", False):
            est = r.estimate
            prev = self._conv_prev.get(r.g)
            if prev is not None and est == est and prev == prev:
                self.consensus_trace.append(
                    (r.round_idx, r.g, abs(est - prev)))
            self._conv_prev[r.g] = est
        # 7. continuous audit: the lowest live rank checks the global
        # mass balance once per round (every protocol event above
        # checked it already; this catches combine/send-path leaks)
        live = r.live_members()
        if live and r.g == min(live):
            self._check("round", r.g)
        # 8. serving plane: the lowest live rank is the publisher —
        # every cfg.serve_every rounds it commits its debiased
        # estimate as the next snapshot version (quorum-fenced like
        # the real islands.serve_publish; an orphan never reaches this
        # line because its rounds are parked)
        if (self._serve_every > 0 and live and r.g == min(live)
                and r.round_idx % self._serve_every == 0):
            self._serve_publish(r)

    # -- membership machinery ---------------------------------------------

    def _heal(self, r: SimRank, new_dead: Set[int]) -> None:
        # quorum fence BEFORE any settlement: a minority-side heal
        # would adopt a live peer's ledger and fork the lineage — the
        # orphan must park without touching shared state
        if self._quorum_on:
            total = len(r.epoch_members)
            dead_all = (r.known_dead | new_dead) & set(r.epoch_members)
            live = total - len(dead_all)
            if not _quorum.quorum_met(live, total):
                self._enter_orphan(r, live, total)
                return
        for d in sorted(new_dead):
            settlement = self.transport.heal_settle(r.g, d, r.epoch)
            self._journal(r.g, "heal", dead=[d], epoch=r.epoch,
                          **settlement)
        r.known_dead |= new_dead
        dead_local = sorted(r.members.index(d) for d in new_dead
                            if d in r.members)
        if not dead_local:
            self._log("heal", r.g, dead=sorted(new_dead), noop=True)
            return
        old_members = r.members
        key = ("heal", r.cfg_key, tuple(dead_local))
        healed = self._topo_entry(
            key, lambda: _healing.heal_topology(r.graph, dead_local))
        survivors = tuple(old_members[l] for l in healed.to_global)
        if r.base_key == r.cfg_key:
            r.base_key = key
        else:
            # demoted view: heal the pre-demotion base in parallel so a
            # later promote restores from a corpse-free base
            bkey = ("heal", r.base_key, tuple(dead_local))
            base_graph = self._graph_of(r.base_key)
            self._topo_entry(
                bkey,
                lambda: _healing.heal_topology(base_graph, dead_local))
            r.base_key = bkey
            r.demoted &= set(survivors)
        r.members = survivors
        r.graph = healed.topology
        r.cfg_key = key
        self._note_lineage(r.g)
        self._log("heal", r.g, dead=sorted(new_dead),
                  members=len(survivors))
        self._check("heal", r.g, graph=r.graph)

    def _graph_of(self, key) -> nx.DiGraph:
        ent = self._topo_cache[key]
        if isinstance(ent, nx.DiGraph):
            return ent
        # planner results carry .topology
        return ent.topology

    def _rows_of(self, key, G: nx.DiGraph):
        rows = self._rows_cache.get(key)
        if rows is None:
            rows = self._rows_cache[key] = self._rows(G)
        return rows

    def _probe_epochs(self, r: SimRank) -> None:
        """Adopt every committed epoch past mine.  Committed records
        are immutable, so the first prober's board read is shared
        fleet-wide (adopters only READ the record)."""
        # partition-aware read: a rank cut away from the board keeps
        # seeing the epoch word frozen at the cut, so it can neither
        # adopt nor be fenced by the far side's commits
        while self.transport.epoch_word_seen(r.g) > r.epoch \
                and not r.exited:
            rec = self._epoch_recs.get(r.epoch + 1)
            if rec is None:
                rec = self.board.epoch_record(r.epoch + 1)
                if rec is None:
                    break
                self._epoch_recs[r.epoch + 1] = rec
            self._adopt(r, rec)

    def _adopt(self, r: SimRank, rec: dict) -> None:
        new_members = tuple(int(m) for m in rec["members"])
        old_epoch = r.epoch
        # collector-side retirement of the old epoch's in-slots
        in_srcs = [r.members[u] for u in r.graph.predecessors(
            r.members.index(r.g))] if r.g in r.members else []
        pend, _ = self.transport.retire_epoch(r.g, old_epoch, in_srcs)
        led = self.transport.ledger(include={r.g})
        self._journal(r.g, "epoch_switch", old_epoch=old_epoch,
                      new_epoch=int(rec["epoch"]), global_rank=r.g,
                      joined=list(rec.get("joined", ())),
                      demoted=list(rec.get("demoted", ())),
                      **{f"ledger_{k}": v for k, v in led.items()
                         if k != "balanced"})
        if r.g not in new_members:
            # fenced: the fleet moved on without me (a zombie resumed
            # past its own death declaration).  Exit without a
            # snapshot — survivors adopted my ledger.
            self.transport.adopted_ranks.add(r.g)
            self.transport.lost_x += r.x
            self.transport.lost_p += r.p
            r.x = 0.0
            r.p = 0.0
            r.exited = True
            self._log("fenced", r.g, epoch=int(rec["epoch"]))
            self._check("fenced", r.g)
            return
        ekey = ("rec", int(rec["epoch"]))
        G = self._topo_entry(ekey, lambda: record_graph(rec))
        r.epoch = int(rec["epoch"])
        r.epoch_members = r.members = new_members
        r.graph = G
        r.cfg_key = ekey
        if rec.get("reweight"):
            r.demoted = {int(d) for d in rec.get("demoted", ())}
            bkey = ("recbase", int(rec["epoch"]))
            r.base_key = bkey
            if bkey not in self._topo_cache:
                B = nx.DiGraph()
                B.add_nodes_from(range(len(new_members)))
                B.add_edges_from((int(u), int(v))
                                 for u, v in rec["base_edges"])
                from bluefog_tpu import topology_util as tu

                tu.MetropolisHastingsWeights(B)
                self._topo_cache[bkey] = B
        else:
            r.demoted = set()
            r.base_key = ekey
        for d in rec.get("promoted", ()):
            r.health.absolve(int(d))
        changed = set(rec.get("demoted", ())) | set(rec.get("promoted", ()))
        if changed and r.policy is not None:
            r.policy.note_epoch_change(changed)
        # fresh detector over the new epoch's member view (the real
        # switch restarts it over the new job namespace)
        self._wire_rank(r)
        # known dead stay dead only if still relevant; new epochs never
        # include a declared corpse granted by a healed sponsor
        r.known_dead &= set(new_members)
        r.edge_seen = {}
        self._log("epoch_switch", r.g, epoch=r.epoch,
                  members=len(new_members),
                  reweight=bool(rec.get("reweight")))
        self._check("epoch_switch", r.g, graph=G,
                    demoted=r.demoted, members=new_members)

    def _grant(self, r: SimRank) -> None:
        # the grown view must not include a corpse (mirror
        # islands.admit_pending's pre-grant heal)
        dead_local = r.detector.dead_ranks()
        new_dead = {r.epoch_members[d] for d in dead_local} - r.known_dead
        if new_dead:
            self._heal(r, new_dead)
        live = r.live_members()
        if r.g != min(live):
            return
        Gg = nx.relabel_nodes(r.graph,
                              {l: g for l, g in enumerate(r.members)},
                              copy=True)
        rec = self.board.grant(r.g, live, Gg, [], True, r.epoch)
        if rec is not None:
            self._note_lineage(r.g)
            self._log("grant", r.g, epoch=int(rec["epoch"]),
                      joined=list(rec["joined"]))
            self._journal(r.g, "join_admitted",
                          joined=list(rec["joined"]),
                          epoch=int(rec["epoch"]), sponsor=r.g)
            self._check("grant", r.g)

    def _joiner_event(self, f: Fault):
        def fire():
            if self.loop.now >= self.end_time:
                return
            self.joiners_spawned += 1
            req = self.board.post_request()
            self._log("join_post", -1, req=req)
            try:
                grant = self.board.wait_for_grant(
                    req, timeout=self.cfg.join_timeout_s)
            except TimeoutError:
                self._log("join_timeout", -1, req=req)
                return
            rec = grant.record
            sponsor = self.ranks.get(int(rec["sponsor"]))
            if sponsor is None or sponsor.killed:
                alive = [m for m in rec["members"]
                         if m in self.ranks
                         and not self.ranks[m].killed]
                sponsor = self.ranks[alive[0]] if alive else None
            est = sponsor.estimate if sponsor is not None else 0.0
            j = SimRank(grant.rank, x=est, p=1.0)
            self.joined_x += j.x
            self.joined_p += j.p
            j.epoch = int(rec["epoch"])
            j.epoch_members = j.members = tuple(
                int(m) for m in rec["members"])
            ekey = ("rec", j.epoch)
            j.graph = self._topo_entry(ekey, lambda: record_graph(rec))
            j.cfg_key = j.base_key = ekey
            self.ranks[j.g] = j
            self._wire_rank(j)
            self._journal(j.g, "epoch_switch", old_epoch=None,
                          new_epoch=j.epoch, global_rank=j.g,
                          joined=list(rec.get("joined", ())),
                          mass_admitted=j.x)
            self._log("join_enter", j.g, epoch=j.epoch,
                      sponsor=int(rec["sponsor"]))
            off = (j.g * 37 % 101) / 101.0
            self.loop.after(off * self.cfg.hb_interval,
                            self._hb_event(j.g))
            self.loop.after(off * self.cfg.round_period,
                            self._round_event(j.g))
            self._check("join", j.g)
        return fire

    # -- partition + orphan machinery -------------------------------------

    def _enter_orphan(self, r: SimRank, live: int, total: int) -> None:
        """The minority verdict: park the rank (rounds stop, shared
        state untouched) until the partition heals and the merge event
        re-admits it through the join machinery."""
        if r.orphaned:
            return
        r.orphaned = True
        self._journal(r.g, "orphan_entered", epoch=r.epoch,
                      global_rank=r.g, live=live, total=total,
                      floor=_quorum.majority_floor(total))
        self._log("orphan", r.g, live=live, total=total)
        self._check("orphan", r.g)

    def _note_lineage(self, g: int) -> None:
        """Record which partition side just committed membership
        progress (heal / grant / reweight) — the single-lineage
        invariant's feed.  A no-op outside a partition window."""
        if self._partition is not None:
            self._lineage.add(self._partition.get(int(g),
                                                  self._board_group))

    def _mass_anchor(self) -> Tuple[float, float]:
        """The conserved quantity ``live + slots + inflight + lost -
        joined`` — constant across every event, snapshotted at a cut
        as the partition+merge conservation anchor."""
        lx = math.fsum(r.x for r in self.ranks.values()
                       if not r.killed and not r.exited)
        lp = math.fsum(r.p for r in self.ranks.values()
                       if not r.killed and not r.exited)
        sx, sp = self.transport.slot_mass()
        ix, ip = self.transport.inflight_mass()
        return (lx + sx + ix + self.transport.lost_x - self.joined_x,
                lp + sp + ip + self.transport.lost_p - self.joined_p)

    def _partition_start_event(self, f: Fault):
        def fire():
            if self._partition is not None:
                return  # one cut at a time
            current = {g for g, r in self.ranks.items()
                       if not r.killed and not r.exited}
            groups: Dict[int, int] = {}
            for i, side in enumerate(f.groups()):
                for g in side:
                    groups[int(g)] = i + 1
            for g in current:
                groups.setdefault(g, 0)  # the implicit "rest" side
            # the board lives with the lowest live rank's side (the
            # real board sits on the sponsor host's filesystem)
            live_now = sorted(current)
            board_group = groups.get(live_now[0], 0) if live_now else 0
            self.transport.set_partition(groups, board_group)
            self._partition = groups
            self._board_group = board_group
            self._lineage = set()
            self._partition_anchor = self._mass_anchor()
            self._log("partition_start", -1, groups=f.group,
                      board_side=board_group)
            self._check("partition_start", -1)
        return fire

    def _partition_end_event(self, f: Fault):
        def fire():
            if self._partition is None:
                return
            self.transport.clear_partition()
            orphans = sorted(g for g, r in self.ranks.items()
                             if r.orphaned and not r.killed
                             and not r.exited)
            self._log("partition_heal", -1, orphans=orphans)
            self._partition = None
            self._lineage = set()
            # the anchor stays armed: the conserved quantity must
            # still hold through every merge-back below
            for g in orphans:
                self.loop.after(0.0, self._merge_orphan_event(g))
            self._check("partition_heal", -1)
        return fire

    def _merge_orphan_event(self, g: int):
        def fire():
            r = self.ranks.get(g)
            if r is None or r.killed or r.exited or not r.orphaned:
                return
            est = r.estimate
            carried = est if est == est else 0.0
            # the old identity retires: survivors healed it out and
            # adopted its ledger, so its stale mass is written off and
            # it re-enters below with unit mass at its carried
            # (debiased) estimate — mirroring islands.merge_orphan
            self.transport.adopted_ranks.add(g)
            self.transport.lost_x += r.x
            self.transport.lost_p += r.p
            r.x = 0.0
            r.p = 0.0
            r.exited = True
            self._log("merge_post", g, carried=round(carried, 9))
            self._check("merge_post", g)
            req = self.board.post_request()
            try:
                grant = self.board.wait_for_grant(
                    req, timeout=self.cfg.join_timeout_s)
            except TimeoutError:
                # nobody left to sponsor (e.g. an even split orphaned
                # everyone): the rank stays fenced, mass written off
                self._log("merge_timeout", g, req=req)
                self._check("merge_timeout", g)
                return
            rec = grant.record
            j = SimRank(grant.rank, x=carried, p=1.0)
            self.joined_x += j.x
            self.joined_p += j.p
            j.epoch = int(rec["epoch"])
            j.epoch_members = j.members = tuple(
                int(m) for m in rec["members"])
            ekey = ("rec", j.epoch)
            j.graph = self._topo_entry(ekey, lambda: record_graph(rec))
            j.cfg_key = j.base_key = ekey
            self.ranks[j.g] = j
            self._wire_rank(j)
            self.orphans_merged += 1
            self._journal(j.g, "orphan_merged", old_rank=g,
                          new_rank=j.g, epoch=j.epoch,
                          carried_estimate=carried)
            self._log("merge_enter", j.g, epoch=j.epoch, old=g,
                      sponsor=int(rec["sponsor"]))
            off = (j.g * 37 % 101) / 101.0
            self.loop.after(off * self.cfg.hb_interval,
                            self._hb_event(j.g))
            self.loop.after(off * self.cfg.round_period,
                            self._round_event(j.g))
            self._check("merge", j.g)
        return fire

    # -- serving plane ------------------------------------------------------

    def _kill_rank(self, r: SimRank) -> None:
        """SIGKILL semantics shared with the ``kill`` fault: mass is
        seized to the lost bucket and the in-slots sever; survivors
        detect via heartbeat timeout and heal."""
        r.killed = True
        self.transport.kill(r.g)
        self.transport.lost_x += r.x
        self.transport.lost_p += r.p
        r.x = 0.0
        r.p = 0.0

    def _serve_publish(self, r: SimRank) -> None:
        """The publisher analog of ``islands.serve_publish``: fence on
        quorum, then commit (version, debiased estimate) — the version
        word persists fleet-wide (the region header survives publisher
        death), so a successor continues strictly monotone."""
        if r.orphaned:
            # the quorum denial can land mid-round (the detector
            # verdict at step 1 orphans the rank, but this round body
            # keeps running) — the real serve_publish raises
            # OrphanedError here via its _orphan_guard
            self._log("serve_fenced", r.g, orphaned=True)
            return
        if self._quorum_on:
            total = len(r.epoch_members)
            dead = r.known_dead & set(r.epoch_members)
            live_n = total - len(dead)
            if not _quorum.quorum_met(live_n, total):
                self._log("serve_fenced", r.g, live=live_n, total=total)
                return
        self._serve_pub_count += 1
        version = self._serve_version + 1
        if ("serve_version_reset" in self.cfg.debug_bugs
                and self._serve_pub_count > 1):
            version = 1  # seeded bug: handoff forgets the header word
        f = self._serve_pub_faults.get(self._serve_pub_count)
        payload = r.estimate
        if f is not None:
            phase = f.group or "payload"
            self._log("serve_pub_kill", r.g,
                      publish=self._serve_pub_count, phase=phase)
            if phase == "flip":
                # payload buffer whole, death mid-header-flip: the
                # successor's attach repairs forward to this version
                self._serve_commit(r.g, version, payload, repaired=True)
            # payload phase: standby buffer torn (odd seq), header
            # intact — nothing commits, survivors keep the old version
            if self._arrivals:
                self._arr_stale_open.append(self._arr_window(
                    "pub_kill", -1, ("staleness",), None))
            self._kill_rank(r)
            self._check("serve_pub_kill", r.g)
            return
        self._serve_commit(r.g, version, payload)

    def _serve_commit(self, g: int, version: int, payload: float,
                      repaired: bool = False) -> None:
        err = _inv.check_serve_version_monotone(self._serve_version,
                                                version)
        if err:
            self._violate("serve-monotone", f"at publish: {err}", g)
        self._serve_version = max(self._serve_version, version)
        self._serve_committed.append((version, payload))
        self._serve_commit_t[version] = self.loop.now
        if self._arrivals:
            # a successful commit bounds every open staleness excuse:
            # replicas have one propagation pad to catch up, then the
            # staleness SLO re-arms
            pad = self._arr_pad()
            for w in self._arr_stale_open:
                w["t1"] = self.loop.now + pad
            self._arr_stale_open = []
            self._arr_window("publish", -1, ("staleness",),
                             self.loop.now + pad)
        aux = {"repaired": True} if repaired else {}
        self._log("serve_publish", g, version=version, **aux)

    def _serve_replica_event(self, i: int):
        def fire():
            rep = self._serve_replicas[i]
            if rep["killed"] or self._all_done() \
                    or self.loop.now >= self.end_time:
                return
            self._serve_replica_step(i, rep)
            self.loop.after(self.cfg.round_period,
                            self._serve_replica_event(i))
        return fire

    def _serve_replica_join_event(self, i: int):
        def fire():
            rep = self._serve_replicas[i]
            if self._all_done() or self.loop.now >= self.end_time:
                return
            # a respawned replica is a fresh incarnation: nothing
            # installed, version floor back at 0 (per-replica
            # monotonicity is per incarnation, as in the real fleet);
            # in a distribution tree it re-joins as a leaf (its old
            # slot was reassigned away when it died)
            rep.update(version=0, payload=None, killed=False)
            if (self._distrib_fanout > 0
                    and i not in self._distrib_parents):
                self._distrib_place(i)
            self._log("serve_replica_join", 1000 + i)
            w = self._arr_kill_open.pop(i, None)
            if w is not None:
                # the respawn needs to re-adopt (possibly down a fresh
                # tree edge) and drain its backlog before the SLO
                # re-arms for this replica
                w["t1"] = self.loop.now + self._arr_pad() \
                    + self.cfg.round_period
            self.loop.after(0.0, self._serve_replica_event(i))
        return fire

    def _serve_replica_step(self, i: int, rep: dict) -> None:
        if self._distrib_fanout > 0:
            # tree-fed: adopt only what has propagated down the feed
            # edge, and only FORWARD (a re-parent under a lagging
            # relay must not regress the served version — mirrors
            # Replica.poll_swap's monotone skip)
            avail = self._distrib_visible(i, rep)
            if avail is not None and avail[0] > rep["version"]:
                if not self._serve_replica_adopt(i, rep, *avail):
                    return
            slo = int(getattr(self.cfg, "distrib_slo", 0) or 0)
            err = _inv.check_distrib_staleness(
                i, self._serve_version - rep["version"], slo)
            if err:
                self._violate("distrib-staleness", err, 1000 + i)
        elif self._serve_committed:
            version, payload = self._serve_committed[-1]
            if version != rep["version"]:
                if not self._serve_replica_adopt(i, rep, version,
                                                 payload):
                    return
        # serve from whatever is installed; every served byte must be
        # some committed snapshot (the torn-read invariant)
        if rep["payload"] is not None:
            err = _inv.check_serve_snapshot_committed(
                rep["payload"], self._serve_committed)
            if err:
                self._violate("serve-committed",
                              f"replica {i}: {err}", 1000 + i)
            rep["steps"] += 1
            if self._arrivals:
                self._drain_requests(i, rep)

    def _serve_replica_adopt(self, i: int, rep: dict, version: int,
                             payload: float) -> bool:
        """One hot-swap attempt at replica ``i``.  Returns False when
        the chaos kill fault fires instead (the replica died mid-swap
        and must not serve this step)."""
        f = self._serve_kill_faults.get(i)
        if (f is not None and not rep["fired"]
                and rep["swaps"] + 1 == f.step):
            # die mid-swap (between the read and the flip): nothing
            # torn lands — the installed snapshot is still whole when
            # the process dies
            rep["fired"] = True
            rep["killed"] = True
            self._log("serve_replica_kill", 1000 + i,
                      swap=rep["swaps"] + 1, version=version)
            if self._distrib_fanout > 0:
                self._distrib_on_kill(i)
            if f.stop is not None:
                self.loop.at(
                    _T0 + f.stop * self.cfg.round_period,
                    self._serve_replica_join_event(i))
            if self._arrivals:
                # every request this replica queues from here until its
                # respawn (plus one adopt+drain pad) has a cause
                self._arr_kill_open[i] = self._arr_window(
                    "replica_kill", i, ("latency", "staleness"), None)
            return False
        err = _inv.check_serve_version_monotone(rep["version"],
                                                version)
        if err:
            self._violate("serve-monotone",
                          f"replica {i}: {err}", 1000 + i)
        new_payload = payload
        if ("serve_torn" in self.cfg.debug_bugs
                and rep["payload"] is not None):
            # seeded bug: the swap mixes old and new buffer
            # bytes instead of flipping one whole generation
            new_payload = 0.5 * (rep["payload"] + payload)
        rep["version"] = version
        rep["payload"] = new_payload
        rep["swaps"] += 1
        rep["install_t"] = self.loop.now
        self._log("serve_swap", 1000 + i, version=version)
        return True

    # -- serve traffic model (loadgen analog) ------------------------------

    def _arm_arrivals(self, rep: dict, i: int, t_start: float) -> None:
        """Precompute replica ``i``'s open-loop arrival schedule on the
        virtual clock (absolute instants).  The schedule is fixed here,
        before any request fires, and NEVER re-anchored — a killed
        replica's requests keep arriving and queue against its respawn,
        exactly like the real driver's overdue backlog."""
        if not self._arrivals:
            return
        cfg = self.cfg
        horizon = _T0 + (cfg.rounds + cfg.quiesce_rounds) \
            * cfg.round_period
        dur = horizon - t_start
        rep["drains"] = 0
        rep["arr_i"] = 0
        if dur <= 0:
            rep["arr"] = []
            return
        from bluefog_tpu.serve.loadgen.arrivals import arrival_times
        offs = arrival_times(self._arrivals, cfg.arrival_rate, dur,
                             int(cfg.seed), stream=i)
        rep["arr"] = [t_start + o for o in offs]

    def _arr_pad(self) -> float:
        """How long after a cause event its staleness effect may
        legitimately linger: one adopt poll plus propagation down the
        deepest feed chain (tree-fed fleets adopt one hop per poll)."""
        depth = 0
        if self._distrib_fanout > 0:
            from bluefog_tpu.serve.distrib import tree as _dtree
            depth = _dtree.tree_depth(self._distrib_parents)
        lo, hi = self.cfg.latency_s
        return (depth + 1) * (self.cfg.round_period + float(hi)) \
            + self.cfg.round_period

    def _arr_window(self, kind: str, replica: int, covers: tuple,
                    t1: Optional[float]) -> dict:
        w = {"kind": kind, "replica": int(replica),
             "t0": self.loop.now, "t1": t1, "covers": covers}
        self._arr_windows.append(w)
        return w

    def _arr_attributed(self, i: int, kind: str, t0: float,
                        t1: float) -> bool:
        """Does any injected-fault window that covers failure mode
        ``kind`` (for replica ``i`` or fleet-wide) overlap [t0, t1]?"""
        for w in self._arr_windows:
            if kind not in w["covers"]:
                continue
            if w["replica"] not in (-1, i):
                continue
            wt1 = w["t1"] if w["t1"] is not None else float("inf")
            if t0 <= wt1 and w["t0"] <= t1:
                return True
        return False

    def _drain_requests(self, i: int, rep: dict) -> None:
        """Serve every admitted request (scheduled instant <= now) at
        replica ``i``, charging open-loop latency and auditing both
        request invariants per request."""
        arr = rep.get("arr")
        if not arr:
            return
        rep["drains"] += 1
        if ("slo_silent_violation" in self.cfg.debug_bugs
                and rep["drains"] % 3 != 1):
            return  # seeded bug: the queue sits through two polls
        now = self.loop.now
        k = rep["arr_i"]
        n = 0
        worst = 0.0
        lag = self._serve_version - rep["version"]
        while k < len(arr) and arr[k] <= now:
            sched = arr[k]
            charged = sched
            if "loadgen_omission" in self.cfg.debug_bugs:
                charged = now  # seeded bug: re-anchor the send time
            err = _inv.check_open_loop(sched, charged)
            if err:
                self._req_violations += 1
                self._violate("open-loop", err, 1000 + i)
            latency = now - charged
            self._req_served += 1
            if self._req_slo > 0 and latency > self._req_slo:
                att = self._arr_attributed(i, "latency", sched, now)
                if att:
                    self._req_attributed += 1
                err = _inv.check_request_slo(i, latency, self._req_slo,
                                             att)
                if err:
                    self._req_violations += 1
                    self._violate("request-slo", err, 1000 + i)
            if self._req_stale_slo > 0 and lag > self._req_stale_slo:
                att = self._arr_attributed(i, "staleness", sched, now)
                if att:
                    self._req_attributed += 1
                err = _inv.check_request_staleness(
                    i, lag, self._req_stale_slo, att)
                if err:
                    self._req_violations += 1
                    self._violate("request-staleness", err, 1000 + i)
            worst = max(worst, latency)
            k += 1
            n += 1
        if n:
            rep["arr_i"] = k
            self._log("serve_requests", 1000 + i, n=n,
                      worst=round(worst, 9), lag=lag)

    def _check_arrivals(self, point: str, g: int) -> None:
        """The standing form of the two request invariants, audited
        after every protocol event: no live replica may be sitting on
        a queued request already past the SLO, or serving further
        behind the head than the staleness SLO, without a fault window
        to blame — catches a silent stall BEFORE the drain would."""
        if not self._arrivals:
            return
        now = self.loop.now
        for i, rep in self._serve_replicas.items():
            arr = rep.get("arr")
            if not arr or rep["killed"] or rep["payload"] is None:
                continue  # kill/warmup paths are audited at drain time
            k = rep["arr_i"]
            if k < len(arr) and self._req_slo > 0:
                age = now - arr[k]
                if age > self._req_slo and not self._arr_attributed(
                        i, "latency", arr[k], now):
                    err = _inv.check_request_slo(i, age, self._req_slo,
                                                 False)
                    if err:
                        self._req_violations += 1
                        self._violate("request-slo",
                                      f"at {point} (queued): {err}",
                                      1000 + i)
            if self._req_stale_slo > 0:
                lag = self._serve_version - rep["version"]
                if lag > self._req_stale_slo \
                        and not self._arr_attributed(
                            i, "staleness", now, now):
                    err = _inv.check_request_staleness(
                        i, lag, self._req_stale_slo, False)
                    if err:
                        self._req_violations += 1
                        self._violate("request-staleness",
                                      f"at {point}: {err}", 1000 + i)

    # -- distribution tree (serve.distrib model) ---------------------------

    def _distrib_edge_latency(self) -> float:
        lo, hi = self.cfg.latency_s
        return self._distrib_rng.uniform(float(lo), float(hi))

    def _distrib_check_tree(self, g: int) -> None:
        err = _inv.check_distrib_tree(self._distrib_parents,
                                      self._distrib_fanout)
        if err:
            self._violate("distrib-tree", err, g)

    def _distrib_dead(self) -> set:
        return {j for j, rj in self._serve_replicas.items()
                if rj["killed"]}

    def _distrib_visible(self, i: int, rep: dict):
        """What replica ``i`` sees through its feed edge right now:
        the newest snapshot its parent installed (or the newest region
        commit when publisher-fed) whose per-edge propagation latency
        has elapsed, or None while the edge has nothing newer."""
        now = self.loop.now
        lat = self._distrib_lat.get(i, 0.0)
        parent = self._distrib_parents.get(i, -1)
        if parent >= 0:
            prep = self._serve_replicas.get(parent)
            if prep is None or prep["killed"]:
                # dead feed edge with no reassignment (the
                # distrib_stall seeded bug): the subtree freezes and
                # the staleness SLO catches it
                return None
            if prep["payload"] is None or now < prep["install_t"] + lat:
                return None
            return prep["version"], prep["payload"]
        for version, payload in reversed(self._serve_committed):
            if now >= self._serve_commit_t.get(version, 0.0) + lat:
                return version, payload
        return None

    def _distrib_on_kill(self, i: int) -> None:
        """A tree node died: its direct children re-parent via the
        SAME greedy repair the real coordinator runs
        (serve.distrib.tree.reassign — subtrees ride along), and tree
        validity is re-audited.  The distrib_stall seeded bug skips
        the repair, so the orphaned subtree freezes and the staleness
        SLO fires instead."""
        from bluefog_tpu.serve.distrib import tree as _dtree

        if "distrib_stall" in self.cfg.debug_bugs:
            return
        old = dict(self._distrib_parents)
        cap = "distrib_degree_overflow" not in self.cfg.debug_bugs
        self._distrib_parents = _dtree.reassign(
            old, i, self._distrib_fanout, degree_cap=cap)
        self._distrib_lat.pop(i, None)
        for c in sorted(self._distrib_parents):
            if old.get(c) != self._distrib_parents[c]:
                self._distrib_reparents += 1
                self._distrib_lat[c] = self._distrib_edge_latency()
                self._log("distrib_reparent", 1000 + c, dead=i,
                          parent=self._distrib_parents[c])
        self._distrib_check_tree(1000 + i)

    def _distrib_place(self, i: int) -> None:
        """Graft replica ``i`` into the tree as a leaf (a join-storm
        arrival, or a respawned incarnation re-joining)."""
        from bluefog_tpu.serve.distrib import tree as _dtree

        cap = "distrib_degree_overflow" not in self.cfg.debug_bugs
        p = _dtree.choose_parent(i, self._distrib_parents,
                                 self._distrib_fanout,
                                 dead=self._distrib_dead(),
                                 degree_cap=cap)
        self._distrib_parents[i] = p
        self._distrib_lat[i] = self._distrib_edge_latency()
        self._distrib_joins += 1
        self._log("distrib_join", 1000 + i, parent=p)
        self._distrib_check_tree(1000 + i)

    def _distrib_join_storm_event(self, n: int):
        def fire():
            if self._all_done() or self.loop.now >= self.end_time:
                return
            base = max(self._serve_replicas, default=-1) + 1
            for j in range(n):
                i = base + j
                self._serve_replicas[i] = {
                    "version": 0, "payload": None, "swaps": 0,
                    "steps": 0, "killed": False, "fired": False,
                    "install_t": 0.0}
                self._arm_arrivals(
                    self._serve_replicas[i], i,
                    self.loop.now + 2 * self.cfg.round_period)
                self._distrib_place(i)
                off = ((1000 + i) * 37 % 101) / 101.0
                self.loop.after(off * self.cfg.round_period,
                                self._serve_replica_event(i))
        return fire

    # -- adaptive demote/promote ------------------------------------------

    def _adaptive_step(self, r: SimRank) -> None:
        if r.health is None or len(r.members) < 3:
            return
        live = set(r.live_members())
        suspects = {s for s in r.health.suspects()
                    if s in live and s not in r.demoted and s != r.g}
        gated = sorted(
            (s for s in suspects
             if r.policy.epoch_floor_open(s) and r.policy.corroborated(s)),
            key=lambda s: (-r.health.time_in_state(s), s))
        cap = (len(live) - 1) // 2
        if "cap_bypass" in self.cfg.debug_bugs:
            cap = len(live)  # seeded bug: no minority cap
        room = cap - len(r.demoted)
        if gated and room > 0:
            picks = set(gated[:room])
            self._commit_reweight(r, r.demoted | picks, promoted=())
            return
        promo = [d for d in sorted(r.demoted)
                 if d in live and r.health.state(d) == EDGE_ALIVE
                 and self._is_anchor(r, d)
                 and r.policy.epoch_floor_open(d)]
        if promo:
            self._commit_reweight(r, r.demoted - set(promo),
                                  promoted=tuple(promo))

    def _is_anchor(self, r: SimRank, straggler_g: int) -> bool:
        if straggler_g not in r.members:
            return False
        sl = r.members.index(straggler_g)
        nbrs = set(r.graph.predecessors(sl)) | set(r.graph.successors(sl))
        nbrs.discard(sl)
        return len(nbrs) == 1 and r.members.index(r.g) in nbrs

    def _commit_reweight(self, r: SimRank, demote_set: Set[int],
                         promoted: Tuple[int, ...]) -> None:
        if not self.transport.board_reachable(r.g):
            return  # cut away from the board: the commit would stall
        base_graph = self._graph_of(r.base_key)
        demote_local = sorted(r.members.index(d) for d in demote_set
                              if d in r.members)
        key = ("demote", r.base_key, tuple(demote_local))
        if demote_local:
            plan = self._topo_entry(
                key,
                lambda: _healing.demote_topology(base_graph,
                                                 demote_local))
            edges = list(plan.topology.edges)
        else:
            plan = self._topo_entry(
                ("restore", r.base_key),
                lambda: _healing.heal_topology(base_graph, []))
            edges = list(plan.topology.edges)
        rec = self.board.commit_reweight(
            r.g, r.epoch, list(r.members), edges, [], True,
            sorted(demote_set), sorted(promoted),
            list(base_graph.edges))
        if rec is not None and rec.get("reweight") \
                and int(rec["sponsor"]) == r.g \
                and int(rec["epoch"]) == r.epoch + 1:
            self._note_lineage(r.g)
            kind = "promote_commit" if promoted else "demote_commit"
            self._log(kind, r.g, epoch=int(rec["epoch"]),
                      demoted=sorted(demote_set),
                      promoted=sorted(promoted))
            self._check("reweight", r.g,
                        commit_members=len(r.live_members()),
                        commit_demoted=len(demote_set))

    # -- gossip ------------------------------------------------------------

    def _combine(self, r: SimRank) -> None:
        if r.g not in r.members:
            return
        me = r.members.index(r.g)
        now = self.loop.now
        dl = r.policy.gap_deadline_s() if (
            self.cfg.adaptive and r.policy is not None) else None
        for u in sorted(r.graph.predecessors(me)):
            src = r.members[u]
            if src in r.known_dead:
                continue
            ver = self.transport.read_version(r.epoch, r.g, src)
            seen = r.edge_seen.get(src)
            if seen is None:
                r.edge_seen[src] = [ver, now, False]
            elif ver > seen[0]:
                gap = now - seen[1]
                if self.cfg.adaptive:
                    clean = dl is None or gap <= dl
                    r.policy.note_fresh(src, gap, clean=clean)
                r.edge_seen[src] = [ver, now, False]
            else:
                age = now - seen[1]
                if (self.cfg.adaptive and dl is not None and age > dl
                        and not seen[2]):
                    r.policy.note_stale(src, age)
                    seen[2] = True
            cx, cp, fresh = self.transport.collect(r.epoch, r.g, src)
            if fresh:
                if "mass_leak" in self.cfg.debug_bugs:
                    cx *= 1.0 - 1e-3  # seeded bug: combine leaks mass
                r.x += cx
                r.p += cp

    def _send(self, r: SimRank) -> None:
        if r.g not in r.members:
            return
        me = r.members.index(r.g)
        rows = self._rows_of(r.cfg_key, r.graph)
        keep, out = rows[me]
        if not out:
            return
        sent_x = 0.0
        sent_p = 0.0
        lo, hi = self.cfg.latency_s
        for v, w in out:
            dst = r.members[v]
            if dst in r.known_dead:
                # degraded send: the weight a dead neighbor would have
                # received stays with the sender (mass-conserving)
                continue
            lat = (self.rng.uniform(lo, hi) if self._lat_model is None
                   else self._lat_model.sample(r.g, dst, self.rng))
            mx = w * r.x
            mp = w * r.p
            sent_x += mx
            sent_p += mp
            self.transport.deposit(r.epoch, r.g, dst, mx, mp, lat)
        r.x -= sent_x
        r.p -= sent_p

    # -- invariants, logging, results -------------------------------------

    def _log(self, kind: str, g: int, **aux) -> None:
        t = round(self.loop.now, 9)
        items = tuple(sorted(aux.items()))
        self.event_log.append((t, kind, int(g), items))

    def _violate(self, name: str, detail: str, g: int = -1) -> None:
        v = {"t": round(self.loop.now, 9), "name": name,
             "detail": detail, "rank": int(g)}
        self.violations.append(v)
        self._log("violation", g, name=name)
        if len(self.violations) >= 50:
            # runaway guard: a broken invariant fires on every
            # subsequent event; 50 samples are plenty for the shrinker
            self._faults.clear()

    def _check(self, point: str, g: int, graph: Optional[nx.DiGraph] = None,
               demoted: Optional[Set[int]] = None,
               members: Optional[Tuple[int, ...]] = None,
               commit_members: Optional[int] = None,
               commit_demoted: Optional[int] = None) -> None:
        """The standing invariants, audited after every protocol
        event (see module docstring)."""
        err = _inv.check_mass_conservation(
            live_x=math.fsum(r.x for r in self.ranks.values()
                             if not r.killed and not r.exited),
            live_p=math.fsum(r.p for r in self.ranks.values()
                             if not r.killed and not r.exited),
            transport=self.transport,
            initial=(self.initial_x, self.initial_p),
            joined=(self.joined_x, self.joined_p),
            tol=self.cfg.mass_tol)
        if err:
            self._violate("mass-conservation", f"at {point}: {err}", g)
        word = self.transport.epoch_word
        err = _inv.check_epoch_monotone(self._epoch_word_seen, word)
        if err:
            self._violate("epoch-monotone", f"at {point}: {err}", g)
        self._epoch_word_seen = max(self._epoch_word_seen, word)
        # partition-window invariants (standing: audited after every
        # event, like the rest — they just only arm once a cut lands)
        if self._lineage:
            err = _inv.check_single_lineage(self._lineage)
            if err:
                self._violate("single-lineage", f"at {point}: {err}", g)
        if self._partition_anchor is not None:
            err = _inv.check_partition_merge_mass(
                self._partition_anchor, self._mass_anchor(),
                tol=self.cfg.mass_tol)
            if err:
                self._violate("partition-mass", f"at {point}: {err}", g)
        if graph is not None and id(graph) not in self._graphs_ok:
            err = _inv.check_doubly_stochastic(graph)
            if err:
                self._violate("doubly-stochastic",
                              f"at {point}: {err}", g)
            else:
                # memoized plan graphs are shared fleet-wide; verify
                # each object once (the dict keeps it alive so the id
                # can't be recycled)
                self._graphs_ok[id(graph)] = graph
        if demoted is not None and members is not None:
            err = _inv.check_minority_demotion(len(members), len(demoted))
            if err:
                self._violate("minority-demotion",
                              f"adopted at {point}: {err}", g)
        if commit_members is not None and commit_demoted is not None:
            err = _inv.check_minority_demotion(commit_members,
                                               commit_demoted)
            if err:
                self._violate("minority-demotion",
                              f"committed at {point}: {err}", g)
        self._check_arrivals(point, g)
        if self._monitor is not None:
            if commit_members is not None and commit_demoted is not None:
                # demotion pressure is event-borne, not state-borne:
                # remember the worst excess seen since the last sample
                self._mon_demote_ex = max(
                    self._mon_demote_ex,
                    float(commit_demoted - _inv.demotion_cap(
                        commit_members)))
            while self.loop.now >= self._mon_next:
                self._monitor_sample(self._mon_next)
                self._mon_next += float(self.cfg.round_period)

    def _monitor_sample(self, t: float) -> None:
        """One virtual-clock scrape: derive the monitor series from the
        fleet state and feed the SAME engine the live scraper runs.
        Virtual time serves as both monotonic and wall twin."""
        points: List[Tuple[str, str, float]] = []
        # conservation residual over the same buckets the standing
        # invariant sums (live + slots + inflight + lost vs initial +
        # joined, relative to scale)
        sx, sp = self.transport.slot_mass()
        ix, ip = self.transport.inflight_mass()
        live_x = math.fsum(r.x for r in self.ranks.values()
                           if not r.killed and not r.exited)
        live_p = math.fsum(r.p for r in self.ranks.values()
                           if not r.killed and not r.exited)
        want_x = self.initial_x + self.joined_x
        want_p = self.initial_p + self.joined_p
        dx = abs(live_x + sx + ix + self.transport.lost_x - want_x) \
            / max(1.0, abs(want_x))
        dp = abs(live_p + sp + ip + self.transport.lost_p - want_p) \
            / max(1.0, abs(want_p))
        points.append(("mass_err", "fleet", max(dx, dp)))
        # split brain: two live, non-orphan groups at one epoch whose
        # views MUTUALLY exclude each other's live holders.  Merely
        # different views are a normal heal-adoption transient (the
        # laggard's view is a superset of the adopter's); a fork means
        # each side has healed the other side out while both still run.
        by_epoch: Dict[int, Dict[tuple, List[int]]] = {}
        for g, r in sorted(self.ranks.items()):
            if r.killed or r.exited or r.orphaned:
                continue
            by_epoch.setdefault(r.epoch, {}).setdefault(
                tuple(r.members), []).append(g)
        fork = 0.0
        for vs in by_epoch.values():
            items = sorted(vs.items())
            for a in range(len(items)):
                for b in range(a + 1, len(items)):
                    va, ha = items[a]
                    vb, hb = items[b]
                    if "mon_naive_fork" in self.cfg.debug_bugs:
                        # seeded defect: a detector that alarms on ANY
                        # view divergence — heal transients included
                        fork = 1.0
                    elif (any(g not in va for g in hb)
                            and any(g not in vb for g in ha)):
                        fork = 1.0
        points.append(("epoch_fork", "fleet", fork))
        points.append(("demote_excess", "fleet", self._mon_demote_ex))
        self._mon_demote_ex = 0.0
        # overdue admitted-but-unserved requests per replica model
        if self._arrivals:
            for i, rep in sorted(self._serve_replicas.items()):
                arr = rep.get("arr")
                if not arr:
                    continue
                k = bisect.bisect_right(arr, t)
                overdue = any(t - arr[j] > self._req_slo
                              for j in range(rep["arr_i"], k))
                points.append(("request_slo", f"replica{i}",
                               1.0 if overdue else 0.0))
        self._mon_samples += 1
        if "mon_silent" in self.cfg.debug_bugs:
            # seeded defect: a monitor that scrapes but never feeds its
            # engine — every alert goes silent
            points = []
        self._monitor.feed(t, points, wall=t)

    def run(self) -> None:
        self.loop.run(max_events=self.cfg.max_events)

    def finalize(self) -> dict:
        """Quiesce-time settlement + the final invariant audit."""
        # fence zombies that never noticed (suspended past the end)
        for g, r in sorted(self.ranks.items()):
            if not r.killed and not r.exited and r.g not in self._members_now():
                self.transport.adopted_ranks.add(g)
                self.transport.lost_x += r.x
                self.transport.lost_p += r.p
                r.x = 0.0
                r.p = 0.0
                r.exited = True
                self._log("fenced", g, at="finalize")
        # shutdown-style board sync: a rank that finished its rounds
        # early stops probing, but stragglers may have committed
        # later epochs behind its back (demote/promote churn) — adopt
        # them now so the pending probe runs against the slots peers
        # actually deposited into (the real shutdown barrier does the
        # same final sync before settling)
        for g, r in sorted(self.ranks.items()):
            if not r.killed and not r.exited:
                self._probe_epochs(r)
        members = self._members_now()
        for g in members:
            r = self.ranks[g]
            me = r.members.index(r.g)
            in_srcs = [r.members[u] for u in r.graph.predecessors(me)]
            self.transport.probe_pending(g, r.epoch, in_srcs)
        self._check("finalize", -1)
        ledger = self.transport.ledger()
        if not ledger["balanced"]:
            self._violate(
                "ledger-balance",
                f"deposits {ledger['deposits']} != collected "
                f"{ledger['collected']} + drained {ledger['drained']} "
                f"+ pending {ledger['pending']}")
        ests = {g: self.ranks[g].estimate for g in members}
        err = _inv.check_consensus(ests, tol=self.cfg.consensus_tol,
                                   scale=max(1.0, abs(self.initial_x)
                                             / max(1, self.initial_p)))
        if err:
            self._violate("consensus", err)
        if self.cfg.journal_dir:
            self._write_snapshots(members)
        epoch = max((self.ranks[g].epoch for g in members), default=0)
        out = {"members": sorted(members), "epoch": epoch,
               "ledger": ledger, "estimates": ests}
        if self._serve_every > 0:
            # replicas outlive the training rounds: one final poll so
            # a replica whose cadence straddled the last publish still
            # converges to the committed head before the audit (a
            # tree-fed fleet needs one sweep per relay level — the
            # head propagates one hop per poll)
            from bluefog_tpu.serve.distrib import tree as _dtree
            sweeps = 1 if self._distrib_fanout <= 0 else \
                max(1, _dtree.tree_depth(self._distrib_parents) + 1)
            # virtual time is frozen here, so in-flight edge latency
            # would never elapse: the quiesce drain zeroes it (every
            # real edge has long since delivered by end_time)
            self._distrib_lat = {k: 0.0 for k in self._distrib_lat}
            for _ in range(sweeps):
                for i, rep in sorted(self._serve_replicas.items()):
                    if not rep["killed"]:
                        self._serve_replica_step(i, rep)
            out["serve"] = {
                "published": self._serve_version,
                "commits": len(self._serve_committed),
                "replicas": {
                    i: {"version": rep["version"],
                        "swaps": rep["swaps"], "steps": rep["steps"],
                        "killed": rep["killed"]}
                    for i, rep in sorted(self._serve_replicas.items())}}
            if self._distrib_fanout > 0:
                out["serve"]["distrib"] = {
                    "fanout": self._distrib_fanout,
                    "parents": dict(sorted(
                        self._distrib_parents.items())),
                    "depth": _dtree.tree_depth(self._distrib_parents),
                    "reparents": self._distrib_reparents,
                    "joins": self._distrib_joins,
                }
            if self._arrivals:
                # requests admitted before end-of-campaign that never
                # drained (their replica died and stayed dead) must
                # still be accounted: attributed to the open kill
                # window, or a silent drop
                now = self.loop.now
                for i, rep in sorted(self._serve_replicas.items()):
                    arr = rep.get("arr")
                    if not arr:
                        continue
                    k = rep["arr_i"]
                    while k < len(arr) and arr[k] <= now:
                        if self._arr_attributed(i, "latency",
                                                arr[k], now):
                            self._req_attributed += 1
                        else:
                            self._req_violations += 1
                            self._violate(
                                "request-slo",
                                f"at finalize (unserved): replica {i} "
                                f"request scheduled at t={arr[k]:.3f} "
                                "was never served and no fault window "
                                "explains the drop", 1000 + i)
                        k += 1
                    rep["arr_i"] = k
                out["arrivals"] = {
                    "process": self._arrivals,
                    "rate": self.cfg.arrival_rate,
                    "slo_s": self._req_slo,
                    "staleness_slo": self._req_stale_slo,
                    "admitted": sum(
                        rep["arr_i"]
                        for rep in self._serve_replicas.values()
                        if rep.get("arr") is not None),
                    "served": self._req_served,
                    "violations": self._req_violations,
                    "attributed": self._req_attributed,
                    "windows": len(self._arr_windows),
                }
        if self._monitor is not None:
            # catch up the sample grid to the quiesce instant, then
            # flush every still-open window — an alert that never got
            # its quiet gap is an alert, not a lost record
            while self._mon_next <= self.loop.now:
                self._monitor_sample(self._mon_next)
                self._mon_next += float(self.cfg.round_period)
            self._monitor.close()
            out["monitor"] = {
                "samples": self._mon_samples,
                "firings": self._monitor.firings,
                "alerts": [dict(w) for w in self._monitor.windows],
            }
        return out

    def _members_now(self) -> Set[int]:
        alive = [r for _, r in sorted(self.ranks.items())
                 if not r.killed and not r.exited]
        if not alive:
            return set()
        # an orphan's member view is frozen pre-cut — never let it
        # define the fleet (a still-parked orphan at quiesce is fenced,
        # not consulted)
        pool = [r for r in alive if not r.orphaned] or alive
        top = max(pool, key=lambda r: (r.epoch, -r.g))
        view = set(top.members) - self.transport.adopted_ranks \
            - self.transport.killed
        return {g for g in view
                if g in self.ranks and not self.ranks[g].killed
                and not self.ranks[g].exited}

    def _write_snapshots(self, members: Set[int]) -> None:
        from bluefog_tpu.telemetry import registry as _treg

        t = self.transport
        for g in sorted(members):
            reg = self._mk_registry(g)
            if reg is None or not reg.enabled:
                continue
            reg.counter(_treg.LEDGER_DEPOSITS).add(t.deposits.get(g, 0))
            reg.counter(_treg.LEDGER_COLLECTED).add(t.collected.get(g, 0))
            reg.counter(_treg.LEDGER_DRAINED).add(t.drained.get(g, 0))
            reg.counter(_treg.LEDGER_PENDING).add(t.pending.get(g, 0))
            reg.write_snapshot()
