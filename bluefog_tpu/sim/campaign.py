"""Campaign runner: seeded fault campaigns + shrink-to-seed replay.

A **campaign** is one deterministic run of :class:`~bluefog_tpu.sim.
fleet.SimFleet`: ``N`` ranks, a seeded :class:`~bluefog_tpu.sim.
schedule.FaultSchedule`, a named topology, and the standing invariants
audited after every protocol event.  Everything derives from
``(SimConfig, FaultSchedule)`` — same pair, same event log, bit for
bit (the ``digest`` is a sha256 over the canonical event-log JSON, so
"bit-identical" is one string comparison).

When a campaign violates an invariant, :func:`shrink_schedule` runs
delta debugging (ddmin) over the fault set: it re-runs the campaign on
ever-smaller subsets, keeping any subset that still reproduces the
SAME violation, until the schedule is 1-minimal — removing any single
fault makes the violation vanish.  The result is written as a **repro
file** (config + minimal schedule + the violation it reproduces) that
:func:`replay` re-runs from nothing but the file — the artifact a bug
report attaches.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, List, Optional, Sequence, Tuple

from bluefog_tpu.sim.fleet import SimFleet
from bluefog_tpu.sim.schedule import Fault, FaultSchedule

__all__ = [
    "SimConfig",
    "CampaignResult",
    "run_campaign",
    "shrink_schedule",
    "write_repro",
    "load_repro",
    "replay",
    "REPRO_SCHEMA",
]

REPRO_SCHEMA = "bftpu-sim-repro/1"


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Everything a campaign derives from (beyond the schedule).

    The timing constants are explicit — NOT read from the ``BFTPU_*``
    env — so a repro file replays identically regardless of the
    environment it runs in.  The defaults are scaled-down versions of
    the production ones (rounds are 0.2 virtual seconds, failure
    timeout 1 s ≈ 5 rounds, edge deadline floor 0.3 s) so a
    ``duration_s ≈ 0.5–1.5 s`` slow fault actually trips the adaptive
    deadline and a kill is detected within a handful of rounds.
    """

    ranks: int = 64
    rounds: int = 50
    seed: int = 0
    topology: str = "exp2"
    faults: Tuple[str, ...] = ("kill", "slow", "join")
    quiesce_rounds: int = 40
    job: str = "sim"
    # timing (virtual seconds)
    round_period: float = 0.2
    hb_interval: float = 0.05
    hb_timeout: float = 1.0
    join_timeout_s: float = 30.0
    latency_s: Tuple[float, float] = (0.002, 0.02)
    # adaptive topology (sim-scaled: factor 2 over the pooled p50 —
    # the production default of 8 would put the deadline past every
    # slow fault the generator emits)
    adaptive: bool = True
    suspect_misses: int = 3
    promote_clean: int = 5
    demote_floor_s: float = 1.0
    edge_deadline_floor_s: float = 0.3
    edge_deadline_factor: float = 1.5
    adaptive_min_obs: int = 8
    # invariant tolerances
    mass_tol: float = 1e-8
    # a demoted straggler mixes through one anchor edge, so its
    # estimate trails the fleet by ~1e-3 relative after a 40-round
    # quiesce; the seeded-bug magnitudes the check exists to catch
    # (leaked mass, non-stochastic plans) sit orders above this
    consensus_tol: float = 2e-3
    # quorum fencing for membership commits (mirrors BFTPU_QUORUM,
    # but explicit so repro files replay identically regardless of the
    # environment): "majority" fences heal/demote commits on a
    # strict-majority live set — the partition minority ORPHANs and
    # merges back on heal; "off" lets every side heal (pre-quorum
    # behavior, split-brain territory under partitions)
    quorum: str = "majority"
    # serving plane: serve_every > 0 arms a publisher analog — the
    # lowest live rank commits its debiased estimate as snapshot
    # version v+1 every serve_every rounds (quorum-fenced exactly like
    # islands.serve_publish) — and serve_replicas > 0 spawns hot-swap
    # replica models that flip to the newest committed version and
    # serve from it each round.  Both default OFF, and every serve
    # event is gated on them, so a serve-disabled config logs zero new
    # events: existing digests and repro files are unchanged.
    serve_every: int = 0
    serve_replicas: int = 0
    # distribution tree (serve.distrib model): distrib_fanout > 0 (with
    # the serve plane armed) organizes the replica models into a
    # bounded-degree fan-out tree — each replica adopts a committed
    # version only after its parent installed it plus a seeded per-edge
    # latency, a dead parent re-parents the child via the same greedy
    # repair the real coordinator runs (serve.distrib.tree.reassign),
    # and tree validity (connected / acyclic / degree-capped) is
    # checked after every distrib event.  distrib_slo > 0 additionally
    # bounds per-replica staleness (versions behind the publisher) as a
    # standing invariant.  distrib_join_round/N arm a join storm: N
    # fresh replicas grafted into the tree at that round.  All default
    # OFF — a distrib-disabled config logs zero new events, so existing
    # digests and repro files are unchanged.
    distrib_fanout: int = 0
    distrib_slo: int = 0
    distrib_join_round: int = 0
    distrib_join_n: int = 0
    # serve traffic model (bluefog_tpu.serve.loadgen analog): arrivals
    # = "poisson" | "fixed" replays the load generator's open-loop
    # arrival process against the replica models on the VIRTUAL clock —
    # arrival_rate requests/virtual-second per replica, schedules drawn
    # from the same pure arrival_times() the real driver uses (a
    # dedicated seed stream: arming traffic never perturbs existing
    # digests).  Two standing invariants arm with it: every admitted
    # request is served within request_slo_s (0 = 2×round_period) or
    # its violation overlaps an injected fault window (replica kill /
    # publish churn / tree re-parent), and request_staleness_slo > 0
    # bounds the served version lag the same way.  Requests are charged
    # open-loop (latency from the SCHEDULED send), and the open-loop
    # invariant fires if a drain ever re-anchors a send time
    # (coordinated omission).  All default OFF.
    arrivals: str = ""
    arrival_rate: float = 2.0
    request_slo_s: float = 0.0
    request_staleness_slo: int = 0
    # trace-fitted gossip latency (ROADMAP item 4): per-edge empirical
    # quantile anchors ((edge_key, p50_s, p99_s), ...) with edge_key
    # "u->v" or "*" — loaded from a merged trace's critical-path report
    # by ``python -m bluefog_tpu.sim --latency-from-trace``.  Empty ()
    # keeps the uniform latency_s draw (existing digests unchanged).
    latency_table: Tuple = ()
    # plumbing
    max_events: int = 20_000_000
    journal_dir: Optional[str] = None
    # seeded bugs the campaign should CATCH: mass_leak (combine leaks
    # mass), cap_bypass (no minority demotion cap), split_brain (the
    # quorum fence is skipped, so both partition sides heal and the
    # single-lineage invariant fires), serve_version_reset (a publisher
    # handoff restarts snapshot versions at 1 — the serve-monotone
    # invariant fires), serve_torn (replica swaps mix old and new
    # buffer bytes — the serve-committed invariant fires),
    # distrib_degree_overflow (tree repair ignores the fan-out cap, so
    # a re-parent overloads a relay — the tree-validity invariant
    # fires), distrib_stall (children of a dead relay never re-parent —
    # the staleness-SLO invariant fires), slo_silent_violation (a
    # replica drains its request queue only every third poll, so
    # queueing delay silently exceeds the request SLO with no fault to
    # blame — the request-slo invariant fires), loadgen_omission (the
    # drain re-anchors each request's send time to "now", hiding the
    # queueing delay — the open-loop invariant fires), mon_silent (the
    # monitor twin scrapes but never feeds its alert engine — the
    # alert-completeness audit fires), mon_flap (the twin's gap-close
    # is set below the sample cadence, so one sustained breach flaps a
    # window per sample — the window-coalescing audit fires),
    # mon_naive_fork (the fork detector alarms on ANY view divergence,
    # so a clean heal transient raises a spurious epoch_fork — the
    # false-alarm-free audit fires)
    debug_bugs: Tuple[str, ...] = ()
    # convergence observatory (bluefog_tpu.lab): record per-rank
    # successive-estimate differences each round.  The trace rides in
    # CampaignResult, NOT the event log — digests (and every existing
    # repro file) are unchanged whether it is on or off.
    trace_consensus: bool = False
    # fleet-monitor twin (bluefog_tpu.monitor): run the SAME declarative
    # alert engine the live scraper runs, against the virtual clock —
    # sampling the fleet once per round_period.  Alert windows ride the
    # final dict ("monitor"), NOT the event log, so digests (and every
    # existing repro file) are unchanged whether it is on or off.  The
    # monitor rule family holds two standing invariants over it: every
    # seeded runtime-fault bug raises its matching alert, and the
    # pinned clean campaigns raise zero, bit-identically.
    monitor: bool = False
    # lockstep=True drops the per-rank start stagger so every round
    # fires at the same virtual instant; with deposit latency > 0 each
    # round then collects exactly the previous round's deposits — the
    # synchronous ``x ← Wx`` iterate a barriered real fleet runs, which
    # is what makes the sim usable as the lab sweep's rate oracle.
    lockstep: bool = False

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["faults"] = list(self.faults)
        d["latency_s"] = list(self.latency_s)
        d["debug_bugs"] = list(self.debug_bugs)
        d["latency_table"] = [list(row) for row in self.latency_table]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "SimConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in d.items() if k in known}
        for tup in ("faults", "latency_s", "debug_bugs"):
            if tup in kw and kw[tup] is not None:
                kw[tup] = tuple(kw[tup])
        if kw.get("latency_table") is not None:
            # nested: JSON round-trips the anchor rows as lists
            kw["latency_table"] = tuple(
                tuple(row) for row in kw["latency_table"])
        return cls(**kw)


@dataclasses.dataclass
class CampaignResult:
    """One campaign's verdict + the determinism artifact."""

    ok: bool
    violations: List[dict]
    digest: str                    # sha256 of the canonical event log
    events: int                    # protocol events logged
    loop_events: int               # scheduler events fired
    final: dict                    # members / ledger / estimates
    schedule: FaultSchedule
    config: SimConfig
    event_log: List[tuple] = dataclasses.field(default_factory=list)
    # (round, rank, err) samples when cfg.trace_consensus (lab oracle)
    consensus_trace: List[tuple] = dataclasses.field(default_factory=list)

    def summary(self) -> dict:
        est = self.final.get("estimates", {})
        vals = sorted(est.values())
        return {
            "ok": self.ok,
            "violations": len(self.violations),
            "digest": self.digest[:16],
            "members": len(self.final.get("members", ())),
            "ledger": self.final.get("ledger"),
            "estimate_spread": (vals[-1] - vals[0]) if len(vals) > 1
            else 0.0,
            "events": self.events,
            "loop_events": self.loop_events,
            "faults": len(self.schedule),
            **({"arrivals": self.final["arrivals"]}
               if "arrivals" in self.final else {}),
        }


def _event_log_digest(event_log: Sequence[tuple]) -> str:
    payload = json.dumps(event_log, sort_keys=True,
                         separators=(",", ":")).encode()
    return hashlib.sha256(payload).hexdigest()


def run_campaign(cfg: SimConfig,
                 schedule: Optional[FaultSchedule] = None
                 ) -> CampaignResult:
    """One deterministic campaign.  ``schedule=None`` generates the
    canonical schedule for ``cfg.seed``."""
    if schedule is None:
        schedule = FaultSchedule.generate(cfg.seed, cfg.ranks,
                                          cfg.rounds, cfg.faults)
    fleet = SimFleet(cfg, schedule)
    fleet.run()
    final = fleet.finalize()
    return CampaignResult(
        ok=not fleet.violations,
        violations=list(fleet.violations),
        digest=_event_log_digest(fleet.event_log),
        events=len(fleet.event_log),
        loop_events=fleet.loop.events_fired,
        final=final,
        schedule=schedule,
        config=cfg,
        event_log=list(fleet.event_log),
        consensus_trace=list(fleet.consensus_trace),
    )


# -- delta-debugging shrink ------------------------------------------------


def _reproduces(cfg: SimConfig, schedule: FaultSchedule,
                faults: Sequence[Fault], target: str) -> bool:
    res = run_campaign(cfg, schedule.subset(faults))
    return any(v["name"] == target for v in res.violations)


def shrink_schedule(cfg: SimConfig, schedule: FaultSchedule,
                    target: Optional[str] = None
                    ) -> Tuple[FaultSchedule, Optional[dict], int]:
    """ddmin over the fault set: the smallest sub-schedule that still
    reproduces the first violation (or ``target`` by name).

    Returns ``(minimal_schedule, violation, campaigns_run)`` —
    ``violation`` is None when the full schedule doesn't violate
    anything (nothing to shrink).  The result is 1-minimal: removing
    any single remaining fault makes the violation vanish.
    """
    base = run_campaign(cfg, schedule)
    runs = 1
    if not base.violations:
        return schedule, None, runs
    if target is None:
        target = base.violations[0]["name"]

    faults = list(schedule.faults)
    n = 2
    while len(faults) >= 2:
        chunk = max(1, len(faults) // n)
        subsets = [faults[i:i + chunk]
                   for i in range(0, len(faults), chunk)]
        reduced = False
        # try each subset alone, then each complement
        for cand in subsets + [
                [f for f in faults if f not in set(s)]
                for s in subsets if len(subsets) > 2]:
            if not cand or len(cand) == len(faults):
                continue
            runs += 1
            if _reproduces(cfg, schedule, cand, target):
                faults = list(cand)
                n = max(2, min(n - 1, len(faults)))
                reduced = True
                break
        if not reduced:
            if n >= len(faults):
                break
            n = min(len(faults), n * 2)

    # a violation that reproduces with NO faults at all (a seeded code
    # bug rather than a fault interaction) shrinks to the empty
    # schedule — the repro then blames the config alone
    if faults:
        runs += 1
        if _reproduces(cfg, schedule, [], target):
            faults = []

    minimal = schedule.subset(faults)
    res = run_campaign(cfg, minimal)
    runs += 1
    viol = next((v for v in res.violations if v["name"] == target),
                res.violations[0] if res.violations else None)
    return minimal, viol, runs


# -- repro files -----------------------------------------------------------


def write_repro(path: str, cfg: SimConfig, schedule: FaultSchedule,
                violation: Optional[dict],
                digest: Optional[str] = None) -> str:
    doc = {
        "schema": REPRO_SCHEMA,
        "config": cfg.to_dict(),
        "schedule": json.loads(schedule.to_json()),
        "violation": violation,
        "digest": digest,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def load_repro(path: str) -> Tuple[SimConfig, FaultSchedule, dict]:
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    if doc.get("schema") != REPRO_SCHEMA:
        raise ValueError(f"not a sim repro file (schema="
                         f"{doc.get('schema')!r}, want {REPRO_SCHEMA!r})")
    cfg = SimConfig.from_dict(doc["config"])
    schedule = FaultSchedule.from_json(json.dumps(doc["schedule"]))
    return cfg, schedule, doc


def replay(path: str) -> CampaignResult:
    """Re-run a repro file's campaign from nothing but the file."""
    cfg, schedule, _ = load_repro(path)
    return run_campaign(cfg, schedule)
