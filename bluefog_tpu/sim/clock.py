"""The clock seam: injectable monotonic time for the resilience stack.

Every time-dependent protocol decision in the repo — heartbeat
staleness, edge-deadline misses, hysteresis floors, join-lease
timeouts, retry backoff — reads ONE of two primitives: a monotonic
``now()`` and a ``sleep()``.  This module names that surface so it can
be swapped:

- :class:`RealClock` (the default everywhere) delegates to
  ``time.monotonic`` / ``time.sleep`` — production behavior is
  bit-for-bit what it was before the seam existed;
- :class:`FakeClock` is a manually-advanced clock for unit tests
  (deadlines fire at EXACT virtual instants, no wall sleeps);
- :class:`~bluefog_tpu.sim.events.VirtualClock` binds ``sleep`` to an
  event-queue scheduler, so real blocking poll loops
  (``MembershipBoard.wait_for_grant``, ``with_deadline`` backoff) run
  single-threaded inside the simulator while other ranks' events fire
  during the "sleep".

Two injection conventions coexist in the codebase and both are
honored here:

- modules that only ever READ time (``EdgeHealth``,
  ``AdaptivePolicy``) take a bare callable (``clock=time.monotonic``);
  :func:`now_fn` normalizes a ``Clock`` | callable | ``None`` into
  that callable;
- modules that also SLEEP (``join``, ``degraded``, ``chaos``) take a
  ``Clock``; :func:`resolve_clock` normalizes ``None`` → the shared
  :data:`REAL_CLOCK` and a bare callable → a read-only wrapper whose
  ``sleep`` still really sleeps (a now-only fake must not spin a poll
  loop into a busy-wait).
"""

from __future__ import annotations

import time

__all__ = [
    "Clock",
    "RealClock",
    "FakeClock",
    "REAL_CLOCK",
    "now_fn",
    "resolve_clock",
]


class Clock:
    """Monotonic now / sleep / deadline.  Subclasses override
    :meth:`now` and :meth:`sleep`; everything else derives."""

    def now(self) -> float:
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def deadline(self, timeout_s: float) -> float:
        """The absolute instant ``timeout_s`` from now."""
        return self.now() + float(timeout_s)

    def expired(self, deadline: float) -> bool:
        return self.now() >= deadline


class RealClock(Clock):
    """Wall time: ``time.monotonic`` / ``time.sleep``."""

    def now(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeClock(Clock):
    """A manually-advanced clock for deterministic unit tests.

    ``sleep`` advances time instantly (and remembers how long it was
    asked to sleep, so tests can assert the poll cadence); ``advance``
    moves time without a sleep.  No wall time is ever consumed.
    """

    def __init__(self, start: float = 0.0):
        self._t = float(start)
        self.slept: list = []

    def now(self) -> float:
        return self._t

    def sleep(self, seconds: float) -> None:
        self.slept.append(float(seconds))
        self._t += max(0.0, float(seconds))

    def advance(self, seconds: float) -> float:
        self._t += max(0.0, float(seconds))
        return self._t


class _NowOnlyClock(Clock):
    """Wrap a bare ``now``-callable into a Clock whose ``sleep`` still
    really sleeps (see module docstring)."""

    def __init__(self, now_callable):
        self._now = now_callable

    def now(self) -> float:
        return float(self._now())

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


REAL_CLOCK = RealClock()


def now_fn(clock=None):
    """Normalize ``Clock`` | callable | ``None`` to a now-callable (the
    convention ``EdgeHealth`` / ``AdaptivePolicy`` already use)."""
    if clock is None:
        return time.monotonic
    if isinstance(clock, Clock):
        return clock.now
    return clock


def resolve_clock(clock=None) -> Clock:
    """Normalize ``Clock`` | callable | ``None`` to a ``Clock``."""
    if clock is None:
        return REAL_CLOCK
    if isinstance(clock, Clock):
        return clock
    return _NowOnlyClock(clock)
