"""Process/context basics: init, ranks, mesh, topology installation.

TPU-native sibling of the reference's ``bluefog/common/basics.py`` +
``bluefog/common/operations.cc`` init path [U] (SURVEY.md §3.1).  Where the
reference's ``bf.init()`` boots MPI, spawns the background communication
thread and builds MPI graph communicators, ours builds a
``jax.sharding.Mesh`` over the TPU slice and compiles topologies into cached
``ppermute`` plans — there is no background thread because under SPMD the
program order *is* the coordination protocol (SURVEY.md §7 design stance).

Rank model: one rank per device (the reference's one rank per GPU).  Eager
API arrays are **rank-major**: leading axis = rank, sharded over the mesh.
"""

from __future__ import annotations

import math
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import networkx as nx
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import topology_util
from bluefog_tpu.common.config import Config
from bluefog_tpu.common.logging_util import logger
from bluefog_tpu.core.plan import CommPlan, compile_plan

__all__ = [
    "NODES_AXIS",
    "MACHINES_AXIS",
    "LOCAL_AXIS",
    "BlueFogContext",
    "init",
    "shutdown",
    "is_initialized",
    "context",
    "size",
    "rank",
    "local_size",
    "local_rank",
    "machine_size",
    "machine_rank",
    "mesh",
    "hierarchical_mesh",
    "set_topology",
    "load_topology",
    "set_machine_topology",
    "load_machine_topology",
    "in_neighbor_ranks",
    "out_neighbor_ranks",
    "in_neighbor_machine_ranks",
    "out_neighbor_machine_ranks",
    "is_topo_weighted",
    "is_machine_topo_weighted",
    "unified_mpi_window_model_supported",
    "rank_major_sharding",
    "replicated_sharding",
    "local_ranks",
    "to_rank_major_global",
    "local_slice",
]

# Mesh axis names.  A single flat axis for rank-level gossip; a factored
# (machines, local) view of the same devices for hierarchical ops.
NODES_AXIS = "bf_nodes"
MACHINES_AXIS = "bf_machines"
LOCAL_AXIS = "bf_local"


def _machine_grid(
    devs: Sequence[jax.Device], local_size: Optional[int]
) -> np.ndarray:
    """Devices as a ``[machines, local]`` grid whose machine axis follows the
    REAL interconnect hierarchy (round-1 verdict missing #2).

    Machine grouping, in priority order:

    1. explicit ``local_size`` argument — the caller's factoring wins;
    2. multislice: group by ``device.slice_index`` (the boundary between ICI
       domains — collectives over ``bf_machines`` ride DCN, over ``bf_local``
       ride ICI), the portable spelling of
       ``mesh_utils.create_hybrid_device_mesh``'s contract;
    3. multi-process: group by ``device.process_index`` (one machine per
       host process, the reference's ``-H host:slots`` machine notion [U]);
    4. single process, single slice: one machine spanning all devices.

    Within a machine, devices keep their ``jax.devices()`` order; machines
    are ordered by their (slice or process) index so every process computes
    the identical grid.
    """
    if local_size is not None:
        if len(devs) % local_size != 0:
            raise ValueError(
                f"size {len(devs)} not divisible by local_size {local_size}"
            )
        return np.array(devs).reshape(len(devs) // local_size, local_size)

    def group_by(key_fn) -> Optional[np.ndarray]:
        groups: Dict[int, List[jax.Device]] = {}
        for d in devs:
            groups.setdefault(key_fn(d), []).append(d)
        if len(groups) <= 1:
            return None
        rows = [groups[k] for k in sorted(groups)]
        if len({len(r) for r in rows}) != 1:
            # ragged grouping (heterogeneous hosts) cannot form a mesh axis;
            # silently collapsing to one machine would invert the hierarchy
            # (DCN links treated as intra-machine)
            raise ValueError(
                "devices group unevenly across machines "
                f"({sorted((k, len(v)) for k, v in groups.items())}); pass "
                "local_size= explicitly to choose a factoring"
            )
        return np.array(rows)

    # BLUEFOG_SIMULATE_SLICES=k: treat the device list as k contiguous
    # fake slices — the slice-boundary branch becomes testable end-to-end
    # on hosts without real multislice hardware (round-2 verdict weak #5).
    # Every process sees the same jax.devices() order, so the grid is
    # identical everywhere, exactly like real slice_index grouping.
    sim = os.environ.get("BLUEFOG_SIMULATE_SLICES")
    if sim:
        k = int(sim)
        if k > 1:
            if len(devs) % k != 0:
                raise ValueError(
                    f"BLUEFOG_SIMULATE_SLICES={k} does not divide "
                    f"{len(devs)} devices"
                )
            return np.array(devs).reshape(k, len(devs) // k)

    # normalize missing/None slice_index to a sortable int: a platform
    # exposing slice_index=None on SOME devices and ints on others must
    # not make sorted(groups) raise on mixed key types
    def slice_key(d):
        v = getattr(d, "slice_index", 0)
        return -1 if v is None else int(v)

    slice_grid = group_by(slice_key)
    if slice_grid is not None:
        return slice_grid
    proc_grid = group_by(lambda d: d.process_index)
    if proc_grid is not None:
        return proc_grid
    return np.array(devs).reshape(1, len(devs))


def _topo_key(topo: nx.DiGraph) -> Tuple:
    return (
        topo.number_of_nodes(),
        tuple(sorted((int(u), int(v), round(float(d.get("weight", 1.0)), 12))
                     for u, v, d in topo.edges(data=True))),
    )


class BlueFogContext:
    """Global framework state (the reference's ``BluefogGlobalState``
    singleton, ``bluefog/common/global_state.h`` [U], minus the thread)."""

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        local_size: Optional[int] = None,
        topology: Optional[nx.DiGraph] = None,
    ):
        self.config = Config.from_env()
        devs = list(devices) if devices is not None else jax.devices()
        grid = _machine_grid(devs, local_size)
        self.machine_size_, self.local_size_ = grid.shape
        # rank order is machine-major (rank // local_size == machine index),
        # so a process's / slice's ranks form one contiguous block — the
        # layout multi-host global arrays and hierarchical ops both assume
        self.devices = list(grid.reshape(-1))
        self.size = len(self.devices)
        self.mesh = Mesh(grid.reshape(-1), (NODES_AXIS,))
        self.hier_mesh = Mesh(grid, (MACHINES_AXIS, LOCAL_AXIS))
        self._plan_cache: Dict[Tuple, CommPlan] = {}
        self._jit_cache: Dict[Tuple, object] = {}
        self._lock = threading.Lock()
        self.topology: Optional[nx.DiGraph] = None
        self.machine_topology: Optional[nx.DiGraph] = None
        self.windows: Dict[str, object] = {}  # name -> windows._Window
        # name -> pack/unpack metadata for pytree (fused) windows
        self.win_fusion: Dict[str, object] = {}
        self.win_associated_p_enabled = False
        self.set_topology(
            topology
            if topology is not None
            else topology_util.ExponentialTwoGraph(self.size)
        )
        if self.machine_size_ > 1:
            self.set_machine_topology(
                topology_util.ExponentialTwoGraph(self.machine_size_)
            )

    # -- topology ---------------------------------------------------------

    def set_topology(self, topo: nx.DiGraph) -> bool:
        if topo.number_of_nodes() != self.size:
            raise ValueError(
                f"topology has {topo.number_of_nodes()} nodes, world size is {self.size}"
            )
        if self.topology is not None and topology_util.IsTopologyEquivalent(
            topo, self.topology
        ):
            logger.debug("set_topology: identical topology, skipping")
            return False
        self.topology = topo
        self.plan  # eagerly compile + cache
        return True

    def set_machine_topology(self, topo: nx.DiGraph) -> bool:
        if topo.number_of_nodes() != self.machine_size_:
            raise ValueError(
                f"machine topology has {topo.number_of_nodes()} nodes, "
                f"machine size is {self.machine_size_}"
            )
        self.machine_topology = topo
        self.machine_plan
        return True

    def plan_for(self, topo: nx.DiGraph, **overrides) -> CommPlan:
        key = (_topo_key(topo), tuple(sorted(overrides.items())))
        with self._lock:
            if key not in self._plan_cache:
                self._plan_cache[key] = compile_plan(topo, **overrides)
            return self._plan_cache[key]

    def jit_cache(self, key, builder):
        """Compiled-callable cache shared by the eager op veneers."""
        with self._lock:
            fn = self._jit_cache.get(key)
            if fn is None:
                fn = self._jit_cache[key] = builder()
            return fn

    @property
    def plan(self) -> CommPlan:
        return self.plan_for(self.topology)

    @property
    def machine_plan(self) -> CommPlan:
        return self.plan_for(self.machine_topology)


_context: Optional[BlueFogContext] = None


def _cpu_platform_selected() -> bool:
    """True when the user pinned jax to the CPU backend (env or config) —
    checked without touching jax.default_backend(), which would initialize
    the XLA client before jax.distributed.initialize gets a chance to run."""
    plats = os.environ.get("JAX_PLATFORMS") or getattr(
        jax.config, "jax_platforms", None
    ) or ""
    return "cpu" in str(plats).replace(" ", "").split(",")


def _maybe_enable_cpu_collectives() -> None:
    """Cross-process collectives on the plain CPU backend need the gloo
    implementation (jax >= 0.4.34); without it every psum/all-gather across
    processes raises "Multiprocess computations aren't implemented on the
    CPU backend".  No-op on jax builds that predate the option."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def _distributed_is_initialized() -> bool:
    """jax < 0.5 has no ``jax.distributed.is_initialized``; fall back to the
    client handle the service keeps on the module (None until initialize)."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    state = getattr(jax.distributed, "global_state", None)
    return state is not None and getattr(state, "client", None) is not None


def init(
    topology: Optional[nx.DiGraph] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    local_size: Optional[int] = None,
    distributed: Optional[bool] = None,
) -> None:
    """Initialize bluefog_tpu (reference ``bf.init()`` — SURVEY.md §3.1).

    Multi-host: when ``distributed`` is True — or left None with a
    coordinator address in the environment (``JAX_COORDINATOR_ADDRESS``, as
    exported by ``bftpu-run``) — ``jax.distributed.initialize()`` runs
    first (the TPU-native ``MPI_Init``), then the mesh spans every process's
    devices.  Default topology: ``ExponentialTwoGraph(size)`` (the
    reference's default).

    ``local_size`` overrides devices-per-machine for hierarchical ops; by
    default it is ``jax.local_device_count()``.
    """
    global _context
    if distributed is None:
        distributed = bool(
            os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("COORDINATOR_ADDRESS")
        )
    # NB: probing jax.process_count() here would itself initialize the XLA
    # backend and make jax.distributed.initialize raise — ask the
    # distributed service directly whether it is already up
    if distributed and not _distributed_is_initialized():
        # jax.distributed.initialize only auto-detects num_processes /
        # process_id on TPU/Slurm/OMPI — forward bftpu-run's env explicitly
        # so plain multi-host (CPU sim included) bootstraps too
        kwargs = {}
        addr = (os.environ.get("JAX_COORDINATOR_ADDRESS")
                or os.environ.get("COORDINATOR_ADDRESS"))
        if addr:
            kwargs["coordinator_address"] = addr
        if os.environ.get("JAX_NUM_PROCESSES"):
            kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        if os.environ.get("JAX_PROCESS_ID"):
            kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
        if _cpu_platform_selected():
            _maybe_enable_cpu_collectives()
        jax.distributed.initialize(**kwargs)
    _context = BlueFogContext(devices=devices, local_size=local_size, topology=topology)


def shutdown() -> None:
    """Reference ``bf.shutdown()``; releases the context."""
    global _context
    _context = None


def is_initialized() -> bool:
    return _context is not None


def context() -> BlueFogContext:
    if _context is None:
        raise RuntimeError("bluefog_tpu is not initialized; call bluefog_tpu.init()")
    return _context


def size() -> int:
    """World size = number of devices (ranks) in the mesh."""
    return context().size


def rank() -> int:
    """Global rank of this process's first addressable device.

    Single-controller (one process): always 0 — eager ops act on all ranks
    at once (rank-major arrays), so this exists for launch scripts and
    logging parity with the reference's per-process rank.  Multi-host: the
    first of this process's contiguous rank block (= ``machine_rank() *
    local_size()``); each process feeds its own block via
    :func:`local_ranks` / the eager veneer's process-local inputs.
    """
    ctx = context()
    first = min(
        (i for i, d in enumerate(ctx.devices) if d.process_index == jax.process_index()),
        default=0,
    )
    return first


def local_size() -> int:
    return context().local_size_


def local_rank() -> int:
    return rank() % context().local_size_


def machine_size() -> int:
    return context().machine_size_


def machine_rank() -> int:
    return rank() // context().local_size_


def mesh() -> Mesh:
    """The flat 1-D ``(bf_nodes,)`` mesh over all ranks."""
    return context().mesh


def hierarchical_mesh() -> Mesh:
    """The same devices viewed as ``(bf_machines, bf_local)``."""
    return context().hier_mesh


def set_topology(topology: Optional[nx.DiGraph] = None) -> bool:
    """Install the virtual topology (reference ``bf.set_topology`` [U]).
    Defaults to ``ExponentialTwoGraph(size)``.  Returns True if changed."""
    ctx = context()
    if topology is None:
        topology = topology_util.ExponentialTwoGraph(ctx.size)
    return ctx.set_topology(topology)


def load_topology() -> nx.DiGraph:
    """Return the installed topology (reference ``bf.load_topology`` [U])."""
    return context().topology


def set_machine_topology(topology: nx.DiGraph) -> bool:
    """Install the machine-level topology used by
    ``hierarchical_neighbor_allreduce`` (reference
    ``bf.set_machine_topology`` [U])."""
    return context().set_machine_topology(topology)


def load_machine_topology() -> nx.DiGraph:
    return context().machine_topology


def in_neighbor_ranks(rank_: Optional[int] = None) -> List[int]:
    """In-neighbors of ``rank_`` (default: this process's rank) under the
    installed topology (reference ``bf.in_neighbor_ranks`` [U])."""
    r = rank() if rank_ is None else rank_
    return list(context().plan.in_neighbors[r])


def out_neighbor_ranks(rank_: Optional[int] = None) -> List[int]:
    r = rank() if rank_ is None else rank_
    return list(context().plan.out_neighbors[r])


def in_neighbor_machine_ranks(machine_rank_: Optional[int] = None) -> List[int]:
    ctx = context()
    if ctx.machine_topology is None:
        return []
    r = machine_rank() if machine_rank_ is None else machine_rank_
    return list(ctx.machine_plan.in_neighbors[r])


def out_neighbor_machine_ranks(machine_rank_: Optional[int] = None) -> List[int]:
    ctx = context()
    if ctx.machine_topology is None:
        return []
    r = machine_rank() if machine_rank_ is None else machine_rank_
    return list(ctx.machine_plan.out_neighbors[r])


def is_topo_weighted() -> bool:
    """Whether the installed topology carries explicit (non-uniform) weights
    (reference ``bf.is_topo_weighted`` [U])."""
    return bool(context().topology.graph.get("weighted", False))


def is_machine_topo_weighted() -> bool:
    topo = context().machine_topology
    return bool(topo.graph.get("weighted", False)) if topo is not None else False


def unified_mpi_window_model_supported() -> bool:
    """Reference API parity (``bf.unified_mpi_window_model_supported`` [U]).

    Always True here: the mailbox emulation gives every rank a uniform
    window model by construction (no MPI implementation quirks to detect).
    """
    return True


# -- sharding helpers used across the eager API ---------------------------


def rank_major_sharding(ctx: Optional[BlueFogContext] = None) -> NamedSharding:
    """Sharding for rank-major arrays: leading axis split over ranks."""
    ctx = ctx or context()
    return NamedSharding(ctx.mesh, P(NODES_AXIS))


def replicated_sharding(ctx: Optional[BlueFogContext] = None) -> NamedSharding:
    ctx = ctx or context()
    return NamedSharding(ctx.mesh, P())


def local_ranks() -> List[int]:
    """Global rank indices owned by THIS process, in global order (one
    contiguous block under the machine-major layout)."""
    ctx = context()
    pi = jax.process_index()
    return [i for i, d in enumerate(ctx.devices) if d.process_index == pi]


def to_rank_major_global(x):
    """Pytree of host arrays → rank-major arrays on the mesh.

    Single process: plain device transfer (every rank is addressable).
    Multi-process (the reference's per-node ``bfrun`` world, SURVEY.md
    §3.5): eager host data cannot become a global sharded array by
    ``jnp.asarray`` — each process supplies EITHER the full rank-major
    array ``[size, ...]`` (identical across processes, e.g. replicated
    params) OR just its own ranks' rows ``[len(local_ranks()), ...]``
    (e.g. its data shards), and the global array is assembled with
    ``jax.make_array_from_process_local_data``.  Arrays that are already
    global pass through untouched.
    """
    ctx = context()
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(jnp.asarray, x)
    sh = rank_major_sharding(ctx)
    mine = local_ranks()

    def leaf(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            return a  # already a global array
        a = np.asarray(a)
        if a.ndim == 0 or a.shape[0] not in (ctx.size, len(mine)):
            raise ValueError(
                f"rank-major leaf has leading dim {a.shape[:1]}; expected "
                f"size={ctx.size} (full, replicated across processes) or "
                f"{len(mine)} (this process's rank rows {mine})"
            )
        gshape = (ctx.size,) + a.shape[1:]
        return jax.make_array_from_process_local_data(sh, a, gshape)

    return jax.tree_util.tree_map(leaf, x)


def local_slice(x):
    """This process's rank rows of a rank-major array, as host numpy
    ``[len(local_ranks()), ...]`` — the read-side inverse of
    :func:`to_rank_major_global` (single process: the full array)."""

    def leaf(a):
        if isinstance(a, jax.Array) and not a.is_fully_addressable:
            shards = a.addressable_shards
            if a.ndim == 0 or all(
                s.index == () or s.index[0].start is None for s in shards
            ):
                # replicated (or 0-d) leaf: every shard IS the value —
                # concatenating would silently duplicate it per device
                return np.asarray(shards[0].data)
            by_start = {s.index[0].start: s for s in shards}
            ordered = [by_start[k] for k in sorted(by_start)]
            return np.concatenate(
                [np.asarray(s.data) for s in ordered], axis=0
            )
        return np.asarray(a)

    return jax.tree_util.tree_map(leaf, x)
