"""Comm-plan compiler: virtual topology -> XLA ``ppermute`` schedule.

TPU-native sibling of the reference's MPI graph-communicator construction
(``MPI_Dist_graph_create_adjacent`` in ``bluefog/common/mpi_context.cc`` [U])
and of the NCCL controller's grouped send/recv lists
(``bluefog/common/nccl_controller.cc`` [U]) — see SURVEY.md §2.4.

A weighted digraph over ranks is compiled once into a ``CommPlan``: the edge
set is partitioned into *shift classes* (edges sharing the same
``(dst - src) mod n``).  Within a shift class every rank appears at most once
as source and at most once as destination, so each class is exactly one
``lax.ppermute``.  For circulant topologies (ring, exponential(-2), fully
connected) the class count equals the graph degree — the information-
theoretic minimum number of permutation rounds — and each class is a uniform
rotation that maps onto wraparound ICI torus hops.

Per class the plan carries dense per-rank weight vectors (receive weight, and
optional send scale for dst-weighted dynamic gossip) so the weighted combine
is a fused multiply-add on device, mirroring the local combine the reference
does after ``MPI_Neighbor_allgather`` (``mpi_controller.cc`` [U]).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

from bluefog_tpu import topology_util

__all__ = ["PermClass", "CommPlan", "compile_plan", "plan_from_neighbor_lists"]


@dataclasses.dataclass(frozen=True)
class PermClass:
    """One ``ppermute`` round.

    perm:         tuple of (src, dst) pairs, static at trace time.
    recv_weights: shape [size]; weight rank d applies to the value it
                  receives this round (0.0 when d receives nothing — XLA
                  delivers zeros to non-destinations, so the FMA is safe).
    recv_mask:    shape [size]; 1 where the rank receives this round.
                  (recv_weights alone cannot encode this: a legitimate
                  zero-weight edge still delivers a value.)
    send_mask:    shape [size]; 1.0 where the rank sends this round.  Used by
                  dst-weighted gossip to scale at the sender.
    slot_index:   shape [size]; position of this round's source in the
                  receiving rank's ascending in-neighbor list (-1 if the
                  rank receives nothing) — drives neighbor_allgather's
                  output placement.
    """

    perm: Tuple[Tuple[int, int], ...]
    recv_weights: Tuple[float, ...]
    recv_mask: Tuple[int, ...]
    send_mask: Tuple[float, ...]
    slot_index: Tuple[int, ...]

    @property
    def shift(self) -> Optional[int]:
        """The uniform rotation amount, or None if not a pure rotation."""
        n = len(self.recv_weights)
        shifts = {(d - s) % n for s, d in self.perm}
        return shifts.pop() if len(shifts) == 1 else None


@dataclasses.dataclass(frozen=True)
class CommPlan:
    """Compiled gossip schedule for one topology on one mesh axis."""

    size: int
    self_weights: Tuple[float, ...]  # [size]
    classes: Tuple[PermClass, ...]
    in_degrees: Tuple[int, ...]  # [size]
    out_degrees: Tuple[int, ...]  # [size]
    # in_neighbor_slots[d] = ordered in-neighbors of d (ascending rank) —
    # defines the row order of neighbor_allgather output.
    in_neighbors: Tuple[Tuple[int, ...], ...]
    out_neighbors: Tuple[Tuple[int, ...], ...]

    @property
    def max_in_degree(self) -> int:
        return max(self.in_degrees) if self.in_degrees else 0

    @property
    def is_regular(self) -> bool:
        return len(set(self.in_degrees)) <= 1 and len(set(self.out_degrees)) <= 1

    def mixing_matrix(self) -> np.ndarray:
        """Reconstruct W (for tests): W[d, s] = weight of s's value at d."""
        W = np.zeros((self.size, self.size))
        np.fill_diagonal(W, self.self_weights)
        for cls in self.classes:
            for s, d in cls.perm:
                W[d, s] += cls.recv_weights[d]
        return W

    def stochasticity_error(self) -> Tuple[float, float]:
        """(max |row sum - 1|, max |col sum - 1|) of the mixing matrix.

        Row error ~0 means weighted combines are convex (any valid plan);
        col error ~0 additionally means gossip preserves the global
        average — the contract healed survivor plans must meet
        (resilience/healing.py)."""
        W = self.mixing_matrix()
        row = float(np.abs(W.sum(axis=1) - 1.0).max()) if self.size else 0.0
        col = float(np.abs(W.sum(axis=0) - 1.0).max()) if self.size else 0.0
        return row, col


def _edge_classes_and_slots(size, edges):
    """Per-edge (class index, allgather slot).  Uses the native C++ compiler
    (plan_compiler.cc, sibling of the reference's graph-communicator build
    [U]) when available; pure-Python fallback otherwise."""
    try:
        from bluefog_tpu.native.plan_native import compile_edge_classes

        native = compile_edge_classes(size, edges)
    except Exception:
        native = None
    if native is not None:
        cls_arr, slot_arr, _ = native
        return list(cls_arr), list(slot_arr)
    in_neighbors = [sorted(s for s, d in edges if d == v) for v in range(size)]
    shifts = sorted({(d - s) % size for s, d in edges})
    class_of_shift = {sh: i for i, sh in enumerate(shifts)}
    cls = [class_of_shift[(d - s) % size] for s, d in edges]
    slot = [in_neighbors[d].index(s) for s, d in edges]
    return cls, slot


def _classes_from_edges(
    size: int,
    edges: Sequence[Tuple[int, int]],
    recv_weight: Dict[Tuple[int, int], float],
) -> Tuple[PermClass, ...]:
    edges = sorted(edges)
    if not edges:
        return ()
    cls_of, slot_of = _edge_classes_and_slots(size, edges)
    n_classes = max(cls_of) + 1
    perm = [[] for _ in range(n_classes)]
    rw = [[0.0] * size for _ in range(n_classes)]
    rm = [[0] * size for _ in range(n_classes)]
    sm = [[0.0] * size for _ in range(n_classes)]
    slot = [[-1] * size for _ in range(n_classes)]
    for i, (s, d) in enumerate(edges):
        c = cls_of[i]
        perm[c].append((s, d))
        rw[c][d] = recv_weight[(s, d)]
        rm[c][d] = 1
        sm[c][s] = 1.0
        slot[c][d] = slot_of[i]
    return tuple(
        PermClass(
            perm=tuple(sorted(perm[c])),
            recv_weights=tuple(rw[c]),
            recv_mask=tuple(rm[c]),
            send_mask=tuple(sm[c]),
            slot_index=tuple(slot[c]),
        )
        for c in range(n_classes)
    )


def compile_plan(
    topo: nx.DiGraph,
    self_weight=None,
    neighbor_weight: Optional[float] = None,
) -> CommPlan:
    """Compile a weighted digraph into a CommPlan.

    By default weights come from the graph (``GetRecvWeights`` convention);
    ``self_weight`` (scalar or per-rank sequence) / ``neighbor_weight``
    override them uniformly (the reference's
    ``neighbor_allreduce(self_weight=..., src_weights=...)`` scalar path
    [U]).  Self-loop edges need no transfer: their weight folds into the
    rank's self weight, preserving row-stochasticity.
    """
    size = topo.number_of_nodes()
    if sorted(topo.nodes) != list(range(size)):
        raise ValueError("topology nodes must be exactly 0..size-1")
    edges = [(int(u), int(v)) for u, v in topo.edges if u != v]
    recv_w: Dict[Tuple[int, int], float] = {}
    self_w = [1.0] * size
    for d in range(size):
        sw, rw = topology_util.GetRecvWeights(topo, d)
        sw += rw.pop(d, 0.0)  # fold self-loop weight back into self
        for s, w in rw.items():
            recv_w[(s, d)] = w if neighbor_weight is None else neighbor_weight
        if self_weight is None:
            self_w[d] = sw
        elif np.isscalar(self_weight):
            self_w[d] = float(self_weight)
        else:
            self_w[d] = float(self_weight[d])
    classes = _classes_from_edges(size, edges, recv_w)
    in_nb = tuple(tuple(sorted(int(u) for u in topo.predecessors(d))) for d in range(size))
    out_nb = tuple(tuple(sorted(int(v) for v in topo.successors(d))) for d in range(size))
    return CommPlan(
        size=size,
        self_weights=tuple(self_w),
        classes=classes,
        in_degrees=tuple(len(x) for x in in_nb),
        out_degrees=tuple(len(x) for x in out_nb),
        in_neighbors=in_nb,
        out_neighbors=out_nb,
    )


def plan_from_neighbor_lists(
    size: int,
    src_ranks: Sequence[Sequence[int]],
    src_weights: Optional[Sequence[Dict[int, float]]] = None,
    self_weights: Optional[Sequence[float]] = None,
) -> CommPlan:
    """Build a plan from per-rank dynamic neighbor lists (the reference's
    per-call ``src_weights=``/``dst_weights=`` dynamic-topology path in
    ``bluefog/torch/mpi_ops.py`` [U]).

    src_ranks[d] lists the ranks d receives from this step.  Weights default
    to the uniform average 1/(deg+1).
    """
    edges = []
    recv_w: Dict[Tuple[int, int], float] = {}
    self_w = []
    for d in range(size):
        srcs = list(src_ranks[d])
        if len(set(srcs)) != len(srcs):
            raise ValueError(f"rank {d} has duplicate sources {srcs}")
        for s in srcs:
            if not 0 <= s < size or s == d:
                raise ValueError(f"invalid source {s} for rank {d}")
            edges.append((s, d))
            if src_weights is not None:
                recv_w[(s, d)] = float(src_weights[d][s])
            else:
                recv_w[(s, d)] = 1.0 / (len(srcs) + 1)
        if self_weights is not None:
            self_w.append(float(self_weights[d]))
        elif src_weights is not None:
            self_w.append(1.0 - sum(recv_w[(s, d)] for s in srcs))
        else:
            self_w.append(1.0 / (len(srcs) + 1))
    classes = _classes_from_edges(size, edges, recv_w)
    in_nb = tuple(tuple(sorted(src_ranks[d])) for d in range(size))
    out_lists = topology_util.InferDestinationFromSourceRanks(src_ranks)
    out_nb = tuple(tuple(x) for x in out_lists)
    return CommPlan(
        size=size,
        self_weights=tuple(self_w),
        classes=classes,
        in_degrees=tuple(len(x) for x in in_nb),
        out_degrees=tuple(len(x) for x in out_nb),
        in_neighbors=in_nb,
        out_neighbors=out_nb,
    )
