"""Virtual-topology library: weighted digraph constructors + dynamic generators.

TPU-native sibling of the reference's ``bluefog/common/topology_util.py`` [U]
(SURVEY.md §2.2).  A *topology* is a ``networkx.DiGraph`` over ranks
``0..size-1`` whose edge ``(u, v)`` means "rank v receives rank u's tensor",
with edge attribute ``weight`` = the combine coefficient receiver ``v``
assigns to ``u``'s value.  Every constructor produces a **row-stochastic**
mixing matrix ``W`` (``W[v, u]`` = weight of ``u``'s value at ``v``;
``W[v, v] = 1 - sum of in-weights``), the invariant decentralized averaging
needs for convergence (arXiv:2111.04287 §2).

Graphs whose mixing matrix is also column-stochastic (all constructors here
except ``StarGraph``/``MeshGrid2DGraph`` with default uniform weights on
irregular degree distributions — those use Metropolis–Hastings weights to
restore double stochasticity) preserve the global average exactly.

Dynamic-topology generators yield per-step ``(send_ranks, recv_ranks)``
pairs implementing one-peer rotating gossip; on TPU each step lowers to a
single ``lax.ppermute`` along the ICI torus.
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

__all__ = [
    "ExponentialTwoGraph",
    "ExponentialGraph",
    "SymmetricExponentialGraph",
    "RingGraph",
    "StarGraph",
    "MeshGrid2DGraph",
    "FullyConnectedGraph",
    "IsRegularGraph",
    "IsTopologyEquivalent",
    "MetropolisHastingsWeights",
    "GetRecvWeights",
    "GetSendWeights",
    "GetWeightMatrix",
    "GetDynamicOnePeerSendRecvRanks",
    "GetInnerOuterRingDynamicSendRecvRanks",
    "GetInnerOuterExpo2DynamicSendRecvRanks",
    "GetExp2DynamicSendRecvMachineRanks",
    "InferSourceFromDestinationRanks",
    "InferDestinationFromSourceRanks",
]


def _check_size(size: int) -> None:
    if not isinstance(size, (int, np.integer)) or size < 1:
        raise ValueError(f"topology size must be a positive int, got {size!r}")


def _finalize(G: nx.DiGraph, weighted: bool) -> nx.DiGraph:
    """Stamp bookkeeping attributes used by GetRecvWeights / the core plan
    compiler."""
    G.graph["weighted"] = weighted
    return G


def _uniform_in_weights(G: nx.DiGraph) -> None:
    """Assign each in-edge of v the weight 1/(in_degree(v)+1).

    Self weight (implicit) becomes the same 1/(d+1): the uniform-average
    convention of the reference's exp/ring constructors [U].
    """
    for v in G.nodes:
        d = G.in_degree(v)
        for u in G.predecessors(v):
            G[u][v]["weight"] = 1.0 / (d + 1)


def ExponentialTwoGraph(size: int) -> nx.DiGraph:
    """Static exponential-2 digraph: rank i receives from (i - 2^j) % size and
    sends to (i + 2^j) % size for j = 0..ceil(log2(size))-1.

    The reference's flagship topology (``topology_util.ExponentialTwoGraph``
    [U]): O(log n) degree, spectral gap good enough that gossip matches
    allreduce convergence.  On a TPU ICI torus the 2^j hops map to repeated
    doubling ``ppermute`` shifts.
    """
    _check_size(size)
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    if size > 1:
        nbits = int(math.ceil(math.log2(size)))
        offsets = sorted({(1 << j) % size for j in range(nbits)} - {0})
        for i in range(size):
            for off in offsets:
                G.add_edge((i - off) % size, i)
    _uniform_in_weights(G)
    return _finalize(G, weighted=False)


def ExponentialGraph(size: int, base: int = 2) -> nx.DiGraph:
    """Exponential digraph with offsets base^j for all j with base^j < size.

    Equals ``ExponentialTwoGraph`` when ``size`` is a power of ``base``
    (reference ``topology_util.ExponentialGraph`` [U]).
    """
    _check_size(size)
    if base < 2:
        raise ValueError("base must be >= 2")
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    offsets = []
    off = 1
    while off < size:
        offsets.append(off)
        off *= base
    for i in range(size):
        for off in offsets:
            G.add_edge((i - off) % size, i)
    _uniform_in_weights(G)
    return _finalize(G, weighted=False)


def SymmetricExponentialGraph(size: int, base: int = 4) -> nx.DiGraph:
    """Exponential graph with symmetric offsets ±base^j (reference
    ``topology_util.SymmetricExponentialGraph`` [U]).  The resulting mixing
    matrix is symmetric hence doubly stochastic.
    """
    _check_size(size)
    if base < 2:
        raise ValueError("base must be >= 2")
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    offsets = set()
    off = 1
    while off < size:
        offsets.add(off % size)
        offsets.add((-off) % size)
        off *= base
    offsets -= {0}
    for i in range(size):
        for off in sorted(offsets):
            G.add_edge((i - off) % size, i)
    _uniform_in_weights(G)
    return _finalize(G, weighted=False)


def RingGraph(size: int, connect_style: int = 0) -> nx.DiGraph:
    """Ring topology (reference ``topology_util.RingGraph`` [U]).

    connect_style 0: bidirectional (receive from both ring neighbors);
    1: unidirectional, receive from left  (i-1 -> i);
    2: unidirectional, receive from right (i+1 -> i).

    Maps 1:1 onto a wraparound ICI torus axis — each step is one physical hop.
    """
    _check_size(size)
    if connect_style not in (0, 1, 2):
        raise ValueError(f"connect_style must be 0, 1, or 2, got {connect_style}")
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    if size > 1:
        for i in range(size):
            if connect_style in (0, 1):
                G.add_edge((i - 1) % size, i)
            if connect_style in (0, 2) and size > 2:
                G.add_edge((i + 1) % size, i)
            elif connect_style == 2 and size == 2:
                G.add_edge((i + 1) % size, i)
    _uniform_in_weights(G)
    return _finalize(G, weighted=False)


def StarGraph(size: int, center_rank: int = 0) -> nx.DiGraph:
    """Star topology: every rank exchanges with ``center_rank`` only
    (reference ``topology_util.StarGraph`` [U]).

    Degrees are irregular, so uniform 1/(d+1) weights would not be doubly
    stochastic; Metropolis–Hastings weights
    ``w_uv = 1 / (1 + max(deg(u), deg(v)))`` restore it, preserving the
    global average under gossip.
    """
    _check_size(size)
    if not 0 <= center_rank < size:
        raise ValueError("center_rank out of range")
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    for i in range(size):
        if i != center_rank:
            G.add_edge(center_rank, i)
            G.add_edge(i, center_rank)
    _metropolis_hastings_weights(G)
    return _finalize(G, weighted=True)


def MetropolisHastingsWeights(G: nx.DiGraph) -> nx.DiGraph:
    """Re-weight every edge in place with the Metropolis–Hastings rule
    ``w_uv = 1 / (1 + max(deg(u), deg(v)))`` and return ``G``.

    On a symmetric graph this yields a doubly stochastic mixing matrix
    regardless of how irregular the degree distribution is — the same
    rule the irregular constructors (star, mesh) apply, and the one
    :func:`bluefog_tpu.resilience.healing.heal_topology` uses to restore
    double stochasticity after ranks are excised.
    """
    for u, v in G.edges:
        G[u][v]["weight"] = 1.0 / (1 + max(G.in_degree(u), G.in_degree(v)))
    G.graph["weighted"] = True
    return G


# internal alias kept for the constructors above
_metropolis_hastings_weights = MetropolisHastingsWeights


def MeshGrid2DGraph(size: int, shape: Optional[Tuple[int, int]] = None) -> nx.DiGraph:
    """2-D (non-wraparound) grid with 4-neighborhood and Metropolis–Hastings
    weights (reference ``topology_util.MeshGrid2DGraph`` [U]).

    ``shape`` defaults to the most-square factorization of ``size``.
    """
    _check_size(size)
    if shape is None:
        a = int(math.sqrt(size))
        while size % a != 0:
            a -= 1
        shape = (a, size // a)
    nrow, ncol = shape
    if nrow * ncol != size:
        raise ValueError(f"shape {shape} does not factor size {size}")
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    for r in range(nrow):
        for c in range(ncol):
            i = r * ncol + c
            if c + 1 < ncol:
                j = i + 1
                G.add_edge(i, j)
                G.add_edge(j, i)
            if r + 1 < nrow:
                j = i + ncol
                G.add_edge(i, j)
                G.add_edge(j, i)
    _metropolis_hastings_weights(G)
    G.graph["shape"] = (nrow, ncol)
    return _finalize(G, weighted=True)


def FullyConnectedGraph(size: int) -> nx.DiGraph:
    """Complete digraph, weight 1/size everywhere: one gossip step equals a
    global average (reference ``topology_util.FullyConnectedGraph`` [U])."""
    _check_size(size)
    G = nx.DiGraph()
    G.add_nodes_from(range(size))
    for i, j in itertools.permutations(range(size), 2):
        G.add_edge(i, j, weight=1.0 / size)
    return _finalize(G, weighted=True)


# --------------------------------------------------------------------------
# Introspection helpers
# --------------------------------------------------------------------------


def IsRegularGraph(topo: nx.DiGraph) -> bool:
    """True iff every node has the same in-degree and the same out-degree
    (reference ``topology_util.IsRegularGraph`` [U])."""
    degs_in = {d for _, d in topo.in_degree()}
    degs_out = {d for _, d in topo.out_degree()}
    return len(degs_in) <= 1 and len(degs_out) <= 1


def IsTopologyEquivalent(topo1: Optional[nx.DiGraph], topo2: Optional[nx.DiGraph]) -> bool:
    """Node/edge/weight equality up to float tolerance (reference
    ``topology_util.IsTopologyEquivalent`` [U])."""
    if topo1 is None or topo2 is None:
        return topo1 is topo2
    if set(topo1.nodes) != set(topo2.nodes):
        return False
    if set(topo1.edges) != set(topo2.edges):
        return False
    for u, v in topo1.edges:
        w1 = topo1[u][v].get("weight", 1.0)
        w2 = topo2[u][v].get("weight", 1.0)
        if abs(w1 - w2) > 1e-12:
            return False
    return True


def GetRecvWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """(self_weight, {in_neighbor: weight}) for ``rank``; self weight is
    1 - sum(in-weights) (reference ``topology_util.GetRecvWeights`` [U])."""
    recv = {int(u): float(topo[u][rank]["weight"]) for u in topo.predecessors(rank)}
    return 1.0 - sum(recv.values()), recv


def GetSendWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """(self_weight, {out_neighbor: weight dst assigns to us}) (reference
    ``topology_util.GetSendWeights`` [U])."""
    send = {int(v): float(topo[rank][v]["weight"]) for v in topo.successors(rank)}
    return 1.0 - sum(send.values()), send


def GetWeightMatrix(topo: nx.DiGraph) -> np.ndarray:
    """Dense mixing matrix W with W[v, u] = weight of u's value at v and
    W[v, v] = self weight.  Rows sum to 1 by construction."""
    n = topo.number_of_nodes()
    W = np.zeros((n, n))
    for v in range(n):
        sw, recv = GetRecvWeights(topo, v)
        W[v, v] = sw
        for u, w in recv.items():
            W[v, u] = w
    return W


# --------------------------------------------------------------------------
# Dynamic (per-step) topology generators
# --------------------------------------------------------------------------


def GetDynamicOnePeerSendRecvRanks(
    size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Infinite generator of one-peer exp-2 rotations: at step t each rank
    sends to (rank + 2^(t mod nbits)) and receives from (rank - 2^(t mod
    nbits)) (reference ``topology_util.GetDynamicOnePeerSendRecvRanks`` [U]).

    Every step the edge set is a single permutation — exactly one
    ``lax.ppermute`` on TPU.
    """
    _check_size(size)
    if not 0 <= self_rank < size:
        raise ValueError("self_rank out of range")
    nbits = max(1, int(math.ceil(math.log2(size)))) if size > 1 else 1
    for t in itertools.count():
        if size == 1:
            yield [], []
            continue
        off = (1 << (t % nbits)) % size
        if off == 0:
            off = 1
        yield [(self_rank + off) % size], [(self_rank - off) % size]


def GetInnerOuterRingDynamicSendRecvRanks(
    world_size: int, local_size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Alternate an intra-machine ("inner") ring step with a cross-machine
    ("outer") ring step at fixed local index (reference
    ``topology_util.GetInnerOuterRingDynamicSendRecvRanks`` [U]).
    """
    _check_size(world_size)
    if world_size % local_size != 0:
        raise ValueError("world_size must be a multiple of local_size")
    nmachines = world_size // local_size
    machine, lrank = divmod(self_rank, local_size)
    for t in itertools.count():
        if t % 2 == 0 and local_size > 1:
            send = machine * local_size + (lrank + 1) % local_size
            recv = machine * local_size + (lrank - 1) % local_size
            yield [send], [recv]
        elif nmachines > 1:
            send = ((machine + 1) % nmachines) * local_size + lrank
            recv = ((machine - 1) % nmachines) * local_size + lrank
            yield [send], [recv]
        else:
            yield [], []


def GetInnerOuterExpo2DynamicSendRecvRanks(
    world_size: int, local_size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Alternate intra-machine exp-2 rotation with cross-machine exp-2
    rotation at fixed local index (reference
    ``topology_util.GetInnerOuterExpo2DynamicSendRecvRanks`` [U])."""
    _check_size(world_size)
    if world_size % local_size != 0:
        raise ValueError("world_size must be a multiple of local_size")
    nmachines = world_size // local_size
    machine, lrank = divmod(self_rank, local_size)
    in_bits = max(1, int(math.ceil(math.log2(local_size)))) if local_size > 1 else 1
    out_bits = max(1, int(math.ceil(math.log2(nmachines)))) if nmachines > 1 else 1
    ti = to = 0
    for t in itertools.count():
        if t % 2 == 0 and local_size > 1:
            off = (1 << (ti % in_bits)) % local_size or 1
            ti += 1
            send = machine * local_size + (lrank + off) % local_size
            recv = machine * local_size + (lrank - off) % local_size
            yield [send], [recv]
        elif nmachines > 1:
            off = (1 << (to % out_bits)) % nmachines or 1
            to += 1
            send = ((machine + off) % nmachines) * local_size + lrank
            recv = ((machine - off) % nmachines) * local_size + lrank
            yield [send], [recv]
        else:
            yield [], []


def GetExp2DynamicSendRecvMachineRanks(
    world_size: int, local_size: int, self_rank: int, local_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Machine-level one-peer exp-2 rotation for hierarchical ops: yields
    *machine* indices, only meaningful for ranks with ``local_rank == 0``
    (reference ``topology_util.GetExp2DynamicSendRecvMachineRanks`` [U])."""
    _check_size(world_size)
    if world_size % local_size != 0:
        raise ValueError("world_size must be a multiple of local_size")
    nmachines = world_size // local_size
    machine = self_rank // local_size
    bits = max(1, int(math.ceil(math.log2(nmachines)))) if nmachines > 1 else 1
    for t in itertools.count():
        if nmachines == 1 or local_rank != 0:
            yield [], []
            continue
        off = (1 << (t % bits)) % nmachines or 1
        yield [(machine + off) % nmachines], [(machine - off) % nmachines]


# --------------------------------------------------------------------------
# Rank-inference helpers
# --------------------------------------------------------------------------
#
# In the reference these are *collective* calls (each rank contributes its
# list and an allgather assembles the global picture) [U].  Under JAX's
# single-controller SPMD model the global picture is already in one process,
# so these are pure functions over all ranks' lists.


def InferDestinationFromSourceRanks(
    src_ranks: Sequence[Sequence[int]],
) -> List[List[int]]:
    """Given per-rank *source* lists (src_ranks[r] = ranks r receives from),
    return per-rank *destination* lists (who r must send to)."""
    n = len(src_ranks)
    dst: List[List[int]] = [[] for _ in range(n)]
    for r, srcs in enumerate(src_ranks):
        for s in srcs:
            if not 0 <= s < n:
                raise ValueError(f"rank {r} lists out-of-range source {s}")
            dst[s].append(r)
    return [sorted(d) for d in dst]


def InferSourceFromDestinationRanks(
    dst_ranks: Sequence[Sequence[int]],
) -> List[List[int]]:
    """Given per-rank *destination* lists, return per-rank *source* lists."""
    n = len(dst_ranks)
    src: List[List[int]] = [[] for _ in range(n)]
    for r, dsts in enumerate(dst_ranks):
        for d in dsts:
            if not 0 <= d < n:
                raise ValueError(f"rank {r} lists out-of-range destination {d}")
            src[d].append(r)
    return [sorted(s) for s in src]
