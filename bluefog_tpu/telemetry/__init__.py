"""bluefog_tpu.telemetry — cross-rank metrics, counters, and event journal.

The layer `timeline.py` (chrome-trace spans) and `profiling.py` (offline
slope timing) do not provide: always-on, lock-light counters / gauges /
fixed-bucket histograms plus a per-rank JSONL event journal, threaded
through the gossip hot paths (islands win ops, shm mailbox, tcp
transport) and the failure paths (resilience detector / healing /
degraded steps).

Enable with ``BFTPU_TELEMETRY=1`` (or ``=<dir>`` to choose where
per-rank snapshot + journal files land; default ``/tmp/bftpu_telemetry``).
When the variable is unset, ``get_registry()`` returns a shared
``NullRegistry`` whose metric handles are no-ops — instrumented call
sites cost one attribute load and a falsy branch.

Merge per-rank snapshots with ``python -m bluefog_tpu.telemetry`` (JSON
and Prometheus text exposition), or programmatically via
:func:`merge_snapshots` / :func:`merge_job_snapshots`.  See
docs/OBSERVABILITY.md.

Stdlib-only: importable without jax, numpy, or the native library.
"""

from bluefog_tpu.telemetry.registry import (
    DEFAULT_LATENCY_BUCKETS_S,
    SERVE_LATENCY_BUCKETS_S,
    LEDGER_COLLECTED,
    LEDGER_DEPOSITS,
    LEDGER_DRAINED,
    LEDGER_PENDING,
    SNAPSHOT_SCHEMA,
    Counter,
    Gauge,
    Histogram,
    NullRegistry,
    Registry,
    add_op_listener,
    get_registry,
    journal_max_bytes,
    journal_paths,
    note_op,
    read_journal,
    remove_op_listener,
    reset,
    telemetry_dir,
)
from bluefog_tpu.telemetry.merge import (
    MERGED_SCHEMA,
    find_snapshots,
    ledger_balance,
    load_snapshot,
    merge_job_snapshots,
    merge_snapshots,
    to_prometheus,
)

__all__ = [
    "SNAPSHOT_SCHEMA",
    "MERGED_SCHEMA",
    "DEFAULT_LATENCY_BUCKETS_S",
    "SERVE_LATENCY_BUCKETS_S",
    "LEDGER_DEPOSITS",
    "LEDGER_COLLECTED",
    "LEDGER_DRAINED",
    "LEDGER_PENDING",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "NullRegistry",
    "get_registry",
    "reset",
    "telemetry_dir",
    "read_journal",
    "journal_paths",
    "journal_max_bytes",
    "note_op",
    "add_op_listener",
    "remove_op_listener",
    "find_snapshots",
    "load_snapshot",
    "merge_snapshots",
    "merge_job_snapshots",
    "ledger_balance",
    "to_prometheus",
]
