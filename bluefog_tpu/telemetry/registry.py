"""Metrics registry: counters, gauges, fixed-bucket histograms, and a
per-rank JSONL event journal.

Design constraints (docs/OBSERVABILITY.md):

- **stdlib-only** — island workers import this before (or instead of)
  jax/numpy; a heavy import here would tax every spawned rank;
- **near-zero cost when off** — ``BFTPU_TELEMETRY`` unset returns the
  shared :class:`NullRegistry`, whose metric handles are one shared
  no-op object; hot paths additionally guard clock reads behind
  ``reg.enabled`` so a disabled run pays one attribute load per op;
- **lock-light when on** — each metric owns one small lock held for a
  single ``+=``; the registry lock is only taken on metric *creation*
  (call sites cache handles or hit a dict lookup);
- **crash-tolerant journal** — every event is one flushed JSON line, so
  a rank SIGKILLed mid-write corrupts at most the final line, which the
  reader (:func:`read_journal`) skips and counts.

Snapshots: each enabled rank writes
``<dir>/telemetry-<job>-r<rank>.json`` at exit (atexit) or on an
explicit :meth:`Registry.write_snapshot`.  The launcher and
``python -m bluefog_tpu.telemetry`` merge these per-rank files into one
cross-rank summary (see :mod:`bluefog_tpu.telemetry.merge`).

Chrome-trace integration: when ``BLUEFOG_TIMELINE`` is also set, counter
values are sampled into the timeline as chrome ``"ph": "C"`` counter
events (rate-limited per counter; final values emitted at snapshot), so
metrics and spans land in one profile.
"""

from __future__ import annotations

import atexit
import bisect
import json
import os
import re
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "SNAPSHOT_SCHEMA",
    "LEDGER_DEPOSITS",
    "LEDGER_COLLECTED",
    "LEDGER_DRAINED",
    "LEDGER_PENDING",
    "MASS_JOIN_ADMITTED",
    "DEFAULT_LATENCY_BUCKETS_S",
    "SERVE_LATENCY_BUCKETS_S",
    "quantile_from_buckets",
    "telemetry_dir",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "NullRegistry",
    "get_registry",
    "reset",
    "read_journal",
    "journal_paths",
    "journal_max_bytes",
    "note_op",
    "add_op_listener",
    "remove_op_listener",
]

#: Snapshot file schema tag (analysis `telemetry.snapshot-schema` pins it).
SNAPSHOT_SCHEMA = "bftpu-telemetry-snapshot/1"

#: Mailbox mass-ledger counters.  The islands layer counts every
#: post-creation mailbox deposit on the WRITER rank and every version it
#: retires (atomic collect, force-drain, or left pending at free) on the
#: READER rank; summed across ranks on a quiescent job,
#: deposits == collected + drained + pending EXACTLY — the conservation
#: invariant the analysis `telemetry.conservation` rule checks.
LEDGER_DEPOSITS = "shm.ledger.deposits"
LEDGER_COLLECTED = "shm.ledger.collected"
LEDGER_DRAINED = "shm.ledger.drained"
LEDGER_PENDING = "shm.ledger.pending"

#: Elastic-membership extension of the mass ledger: push-sum mass a
#: joiner brings INTO the network (p = 1.0 per window, carried at the
#: sponsor's debiased estimate, so Σx/Σp is preserved at consensus).
#: Every admission also journals an ``epoch_switch`` event holding the
#: four ledger counters at the switch barrier — the per-epoch balance
#: the analysis ``resilience.membership-epoch`` rule checks (no
#: committed deposit from epoch e is consumed under view e+1 without
#: appearing as collected/drained/pending at the switch).
MASS_JOIN_ADMITTED = "resilience.join_mass_admitted"

#: Default histogram bucket upper bounds for op latencies, in seconds
#: (1 µs .. 10 s, roughly half-decade steps; +Inf bucket is implicit).
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
    1e-2, 5e-2, 1e-1, 5e-1, 1.0, 5.0, 10.0,
)

#: Log-spaced buckets for request-level serve latency (0.1 ms .. ~2.2 s
#: in 30 steps of 10^0.15 ≈ 1.41x).  The half-decade DEFAULT buckets
#: give the tail quantile only 2 edges per decade — a p99 interpolated
#: between 0.5 s and 1.0 s is useless for an SLO at 250 ms; constant
#: RELATIVE resolution (~41% per bucket, ~6.7 edges/decade) keeps the
#: p99 estimate within one bucket ratio anywhere in the 0.1 ms–2 s
#: open-loop tail the load generator charges queueing delay into.
SERVE_LATENCY_BUCKETS_S: Tuple[float, ...] = tuple(
    round(10.0 ** (-4 + 0.15 * i), 10) for i in range(30))

_DEFAULT_DIR = "/tmp/bftpu_telemetry"

#: minimum seconds between chrome-trace counter samples per counter
_TIMELINE_SAMPLE_S = 0.05


def telemetry_dir() -> Optional[str]:
    """The telemetry output directory, or None when telemetry is off.
    ``BFTPU_TELEMETRY`` semantics: unset/empty/"0" = off; "1" = on with
    the default directory; anything else = on, value IS the directory."""
    v = os.environ.get("BFTPU_TELEMETRY", "")
    if not v or v == "0":
        return None
    return _DEFAULT_DIR if v == "1" else v


def _resolve_rank() -> int:
    for var in ("BLUEFOG_ISLAND_RANK", "JAX_PROCESS_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


def _resolve_job() -> str:
    return os.environ.get("BLUEFOG_ISLAND_JOB", "local")


def _safe_name(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]", "_", s)


def _labels_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class _NullMetric:
    """Shared no-op metric handle (the disabled path)."""

    __slots__ = ()

    def inc(self, n=1):
        pass

    add = inc

    def set(self, v):
        pass

    def observe(self, v):
        pass

    def quantile(self, q):
        return float("nan")


_NULL = _NullMetric()


class Counter:
    """Monotone counter (int or float increments)."""

    __slots__ = ("name", "labels", "value", "_lock", "_sampler", "_last_ts")

    def __init__(self, name: str, labels: Dict[str, object],
                 sampler: Optional[Callable] = None):
        self.name = name
        self.labels = dict(labels)
        self.value = 0
        self._lock = threading.Lock()
        self._sampler = sampler
        self._last_ts = 0.0

    def inc(self, n=1):
        if n < 0:
            raise ValueError(f"counter {self.name} decremented by {n}")
        with self._lock:
            self.value += n
        if self._sampler is not None:
            self._sampler(self)

    add = inc

    def to_dict(self) -> dict:
        return {"name": self.name, "labels": self.labels, "value": self.value}


class Gauge:
    """Last-value gauge (also tracks the max ever set)."""

    __slots__ = ("name", "labels", "value", "max", "_lock")

    def __init__(self, name: str, labels: Dict[str, object]):
        self.name = name
        self.labels = dict(labels)
        self.value = 0.0
        self.max = 0.0
        self._lock = threading.Lock()

    def set(self, v):
        v = float(v)
        with self._lock:
            self.value = v
            if v > self.max:
                self.max = v

    def add(self, v):
        """Signed delta on the last value (e.g. queue depth up/down from
        two threads) — a read-modify-write ``set`` would race."""
        v = float(v)
        with self._lock:
            self.value += v
            if self.value > self.max:
                self.max = self.value

    def to_dict(self) -> dict:
        return {"name": self.name, "labels": self.labels,
                "value": self.value, "max": self.max}


def quantile_from_buckets(buckets, counts, q: float) -> float:
    """Prometheus-style interpolated quantile from fixed buckets.

    ``buckets`` are the finite upper edges, ``counts`` the per-bucket
    tallies (len(buckets)+1, with the implicit +Inf bucket last).  The
    q-th observation is located by cumulative count and linearly
    interpolated within its bucket (lower edge 0 for the first bucket);
    observations in the +Inf bucket clamp to the last finite edge — the
    estimate is conservative there, never invented.  NaN on an empty
    histogram."""
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile {q} outside [0, 1]")
    total = sum(counts)
    if total == 0:
        return float("nan")
    target = q * total
    cum = 0.0
    for i, c in enumerate(counts[:-1]):
        if cum + c >= target and c > 0:
            lo = 0.0 if i == 0 else float(buckets[i - 1])
            hi = float(buckets[i])
            frac = (target - cum) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return float(buckets[-1])


class Histogram:
    """Fixed-bucket histogram with prometheus ``le`` semantics: a value
    lands in the FIRST bucket whose upper bound is >= the value (exact
    bucket-edge values count into that edge's bucket); values above the
    last edge land in the implicit +Inf bucket (``counts[-1]``)."""

    __slots__ = ("name", "labels", "buckets", "counts", "sum", "_lock")

    def __init__(self, name: str, labels: Dict[str, object],
                 buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_S):
        b = tuple(float(x) for x in buckets)
        if list(b) != sorted(b) or len(set(b)) != len(b):
            raise ValueError(f"histogram {name}: buckets must be strictly "
                             f"increasing, got {b}")
        self.name = name
        self.labels = dict(labels)
        self.buckets = b
        self.counts = [0] * (len(b) + 1)
        self.sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        idx = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[idx] += 1
            self.sum += v

    @property
    def count(self) -> int:
        return sum(self.counts)

    def quantile(self, q: float) -> float:
        """Interpolated q-quantile (0 <= q <= 1) of the observations —
        p50/p99 for the adaptive edge-health policy and the merge CLI.
        NaN while empty; +Inf-bucket hits clamp to the last finite
        edge (see :func:`quantile_from_buckets`)."""
        with self._lock:
            counts = list(self.counts)
        return quantile_from_buckets(self.buckets, counts, q)

    def to_dict(self) -> dict:
        return {"name": self.name, "labels": self.labels,
                "buckets": list(self.buckets), "counts": list(self.counts),
                "sum": self.sum}


class Registry:
    """One process's metric store + event journal.

    ``out_dir=None`` builds an in-memory registry (tests and the analysis
    rule corpus drive these directly); the process-wide instance from
    :func:`get_registry` always has a directory.
    """

    enabled = True

    def __init__(self, out_dir: Optional[str] = None,
                 rank: Optional[int] = None, job: Optional[str] = None,
                 timeline_sampling: Optional[bool] = None):
        self.out_dir = out_dir
        self.rank = _resolve_rank() if rank is None else int(rank)
        self.job = _resolve_job() if job is None else str(job)
        self._metrics: Dict[Tuple, object] = {}
        # memo for note_op's per-op counter: handle lookup by labels costs
        # ~2µs (kwargs + sorted label key); op notes ride every window op
        self._op_counters: Dict[str, Counter] = {}
        self._lock = threading.Lock()
        self._journal_fh = None
        self._journal_lock = threading.Lock()
        self._journal_bytes = 0
        self._journal_max_bytes = journal_max_bytes()
        self._mono0 = time.monotonic()
        if timeline_sampling is None:
            timeline_sampling = bool(os.environ.get("BLUEFOG_TIMELINE"))
        self._timeline_sampling = timeline_sampling

    # -- metric handles ----------------------------------------------------
    def _get(self, kind, name: str, labels: Dict[str, object], factory):
        key = (kind, name, _labels_key(labels))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = factory()
                    self._metrics[key] = m
        return m

    def counter(self, name: str, **labels) -> Counter:
        sampler = self._sample_counter if self._timeline_sampling else None
        return self._get("c", name, labels,
                         lambda: Counter(name, labels, sampler))

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("g", name, labels, lambda: Gauge(name, labels))

    def histogram(self, name: str,
                  buckets: Tuple[float, ...] = DEFAULT_LATENCY_BUCKETS_S,
                  **labels) -> Histogram:
        return self._get("h", name, labels,
                         lambda: Histogram(name, labels, buckets))

    # -- chrome-trace counter events ---------------------------------------
    def _timeline_writer(self):
        # lazy: bluefog_tpu.timeline imports jax.profiler — only touch it
        # when BLUEFOG_TIMELINE is actually set (then jax is loaded anyway)
        try:
            from bluefog_tpu.timeline import _get_writer

            return _get_writer()
        except Exception:
            return None

    def _sample_counter(self, c: Counter, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - c._last_ts < _TIMELINE_SAMPLE_S:
            return
        w = self._timeline_writer()
        if w is None:
            return
        c._last_ts = now
        label = c.name if not c.labels else (
            c.name + "{" + ",".join(f"{k}={v}" for k, v in
                                    sorted(c.labels.items())) + "}")
        w.record_counter(label, w.now_us(), float(c.value))

    # -- event journal -----------------------------------------------------
    @property
    def journal_path(self) -> Optional[str]:
        if self.out_dir is None:
            return None
        return os.path.join(
            self.out_dir,
            f"telemetry-{_safe_name(self.job)}-r{self.rank}.events.jsonl")

    @property
    def snapshot_path(self) -> Optional[str]:
        if self.out_dir is None:
            return None
        return os.path.join(
            self.out_dir,
            f"telemetry-{_safe_name(self.job)}-r{self.rank}.json")

    def journal(self, event: str, **fields) -> None:
        """Append one event line (flushed immediately: a SIGKILL tears at
        most the line in flight)."""
        path = self.journal_path
        if path is None:
            return
        rec = {"event": event, "ts": time.time(),
               "mono": time.monotonic() - self._mono0,
               "rank": self.rank, "job": self.job, "pid": os.getpid()}
        rec.update(fields)
        try:
            line = json.dumps(rec) + "\n"
        except (TypeError, ValueError):
            rec = {k: repr(v) for k, v in rec.items()}
            line = json.dumps(rec) + "\n"
        with self._journal_lock:
            if self._journal_fh is None:
                os.makedirs(self.out_dir, exist_ok=True)
                self._journal_fh = open(path, "a", encoding="utf-8")
                try:
                    self._journal_bytes = os.path.getsize(path)
                except OSError:
                    self._journal_bytes = 0
            if (self._journal_max_bytes > 0
                    and self._journal_bytes + len(line)
                    > self._journal_max_bytes
                    and self._journal_bytes > 0):
                # size-capped rotation (BFTPU_JOURNAL_MAX_MB): the
                # current file becomes <path>.1 (one generation — high-N
                # fleets bound disk at ~2x the cap per rank) and the
                # write lands in a fresh file.  Readers consult
                # journal_paths() so rotated events still merge.
                self._journal_fh.close()
                try:
                    os.replace(path, path + ".1")
                except OSError:
                    pass
                self._journal_fh = open(path, "a", encoding="utf-8")
                self._journal_bytes = 0
            self._journal_fh.write(line)
            self._journal_fh.flush()
            self._journal_bytes += len(line)

    # -- snapshots ---------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            metrics = list(self._metrics.items())
        counters, gauges, hists = [], [], []
        for (kind, _, _), m in sorted(metrics, key=lambda kv: kv[0][:2]):
            if kind == "c":
                counters.append(m.to_dict())
            elif kind == "g":
                gauges.append(m.to_dict())
            else:
                hists.append(m.to_dict())
        return {
            "schema": SNAPSHOT_SCHEMA,
            "job": self.job,
            "rank": self.rank,
            "pid": os.getpid(),
            "time_unix": time.time(),
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
        }

    def write_snapshot(self, path: Optional[str] = None) -> Optional[str]:
        """Write the snapshot atomically (tmp + rename); final counter
        values also ride into the chrome trace when sampling is on."""
        path = self.snapshot_path if path is None else path
        if path is None:
            return None
        if self._timeline_sampling:
            with self._lock:
                counters = [m for (k, _, _), m in self._metrics.items()
                            if k == "c"]
            for c in counters:
                self._sample_counter(c, force=True)
        snap = self.snapshot()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f)
        os.replace(tmp, path)
        return path

    def close(self) -> None:
        with self._journal_lock:
            if self._journal_fh is not None:
                try:
                    self._journal_fh.close()
                except OSError:
                    pass
                self._journal_fh = None


class NullRegistry:
    """The disabled registry: every handle is the shared no-op metric."""

    enabled = False
    out_dir = None
    rank = 0
    job = "off"

    def counter(self, name, **labels):
        return _NULL

    def gauge(self, name, **labels):
        return _NULL

    def histogram(self, name, buckets=None, **labels):
        return _NULL

    def journal(self, event, **fields):
        pass

    def snapshot(self):
        return {}

    def write_snapshot(self, path=None):
        return None

    def close(self):
        pass

    def close(self):
        pass


_NULL_REGISTRY = NullRegistry()
_global: Optional[Registry] = None
_global_lock = threading.Lock()


def _atexit_snapshot() -> None:
    reg = _global
    if reg is not None:
        try:
            reg.write_snapshot()
        except Exception:
            pass
        reg.close()


def get_registry():
    """The process-wide registry: a live :class:`Registry` when
    ``BFTPU_TELEMETRY`` is set (snapshot registered atexit), else the
    shared :class:`NullRegistry`.  Cached after first resolution — tests
    toggling the env var mid-process must call :func:`reset`."""
    global _global
    reg = _global
    if reg is not None:
        return reg
    d = telemetry_dir()
    if d is None:
        # cache the off verdict too — hot paths (detector sweeps) call
        # this per poll, and the env lookup dominates when disabled
        with _global_lock:
            if _global is None:
                _global = _NULL_REGISTRY
            return _global
    with _global_lock:
        if _global is None:
            _global = Registry(out_dir=d)
            atexit.register(_atexit_snapshot)
        return _global


def reset() -> None:
    """Drop the cached process-wide registry (tests only)."""
    global _global
    with _global_lock:
        if _global is not None:
            _global.close()
        _global = None


def journal_max_bytes() -> int:
    """Per-rank journal size cap in bytes (``BFTPU_JOURNAL_MAX_MB``;
    unset/0 = unlimited).  Past the cap the live file rotates to
    ``<path>.1`` — see :meth:`Registry.journal`."""
    try:
        mb = float(os.environ.get("BFTPU_JOURNAL_MAX_MB", "0"))
    except ValueError:
        return 0
    return int(mb * 1024 * 1024) if mb > 0 else 0


def journal_paths(path: str) -> List[str]:
    """All existing files of one rank's journal, oldest first — the
    rotated generation (``<path>.1``) before the live file, so a
    chronological reader just concatenates."""
    return [p for p in (path + ".1", path) if os.path.exists(p)]


def read_journal(path: str) -> Tuple[List[dict], int]:
    """Parse a JSONL journal, skipping torn/invalid lines.  Returns
    ``(events, n_bad)`` — a rank killed mid-write leaves at most its
    final line torn, so ``n_bad`` should be 0 or 1."""
    events: List[dict] = []
    bad = 0
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict):
                events.append(rec)
            else:
                bad += 1
    return events, bad


# ---------------------------------------------------------------------------
# win-op event stream (the single bookkeeping path for window traffic)
# ---------------------------------------------------------------------------

_op_listeners: List[Callable[[str, str], None]] = []
_op_listeners_lock = threading.Lock()


def add_op_listener(fn: Callable[[str, str], None]) -> None:
    """Subscribe to ``(op, window_name)`` win-op events.
    ``windows.record_win_ops()`` is the canonical consumer."""
    with _op_listeners_lock:
        _op_listeners.append(fn)


def remove_op_listener(fn: Callable[[str, str], None]) -> None:
    with _op_listeners_lock:
        try:
            _op_listeners.remove(fn)
        except ValueError:
            pass


def note_op(op: str, name: Optional[str]) -> None:
    """Record one window op: bumps the ``win_ops.total`` counter (when
    telemetry is on) and fans out to the registered listeners.  Both the
    SPMD emulation (:mod:`bluefog_tpu.windows`) and the island runtime
    (:mod:`bluefog_tpu.islands`) publish through this single path."""
    reg = get_registry()
    if reg.enabled:
        c = reg._op_counters.get(op)
        if c is None:
            c = reg._op_counters[op] = reg.counter("win_ops.total", op=op)
        c.inc()
    if _op_listeners:
        n = "*" if name is None else name
        for fn in list(_op_listeners):
            fn(op, n)
