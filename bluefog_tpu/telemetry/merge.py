"""Cross-rank snapshot aggregation + Prometheus text exposition.

Per-rank snapshot files (``telemetry-<job>-r<rank>.json``, written by
:class:`bluefog_tpu.telemetry.Registry` at exit) merge into ONE summary:
counters sum, gauges aggregate (sum/min/max), histograms add bucket-wise.
The merged dict also carries a ``ledger`` section evaluating the mailbox
mass-conservation identity (deposits == collected + drained + pending on
a quiescent job) — the same identity the analysis
``telemetry.conservation`` rule verifies.

Stdlib-only, like the rest of the package.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from bluefog_tpu.telemetry.registry import (
    LEDGER_COLLECTED,
    LEDGER_DEPOSITS,
    LEDGER_DRAINED,
    LEDGER_PENDING,
    SNAPSHOT_SCHEMA,
    _safe_name,
    quantile_from_buckets,
)

__all__ = [
    "MERGED_SCHEMA",
    "find_snapshots",
    "load_snapshot",
    "merge_snapshots",
    "ledger_balance",
    "to_prometheus",
    "merge_job_snapshots",
]

MERGED_SCHEMA = "bftpu-telemetry-merged/1"


def find_snapshots(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into snapshot paths.  A directory yields
    every ``telemetry-*.json`` in it (merged outputs are filtered out at
    load time by their schema tag)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "telemetry-*.json"))))
        else:
            out.append(p)
    return out


def load_snapshot(path: str) -> Optional[dict]:
    """One snapshot dict, or None when the file is not a per-rank
    snapshot (wrong schema — e.g. a previous merged summary)."""
    with open(path, "r", encoding="utf-8") as f:
        snap = json.load(f)
    if not isinstance(snap, dict) or snap.get("schema") != SNAPSHOT_SCHEMA:
        return None
    return snap


def _key(entry: dict) -> Tuple:
    labels = entry.get("labels") or {}
    return (entry["name"], tuple(sorted((k, str(v))
                                        for k, v in labels.items())))


def merge_snapshots(snaps: List[dict]) -> dict:
    """Aggregate per-rank snapshots into one cross-rank summary."""
    counters: Dict[Tuple, dict] = {}
    gauges: Dict[Tuple, dict] = {}
    hists: Dict[Tuple, dict] = {}
    ranks, jobs = [], []
    for snap in snaps:
        ranks.append(snap.get("rank", -1))
        job = snap.get("job")
        if job and job not in jobs:
            jobs.append(job)
        for c in snap.get("counters", []):
            k = _key(c)
            cur = counters.get(k)
            if cur is None:
                counters[k] = {"name": c["name"],
                               "labels": dict(c.get("labels") or {}),
                               "value": c["value"]}
            else:
                cur["value"] += c["value"]
        for g in snap.get("gauges", []):
            k = _key(g)
            v = float(g["value"])
            cur = gauges.get(k)
            if cur is None:
                gauges[k] = {"name": g["name"],
                             "labels": dict(g.get("labels") or {}),
                             "sum": v, "min": v,
                             "max": float(g.get("max", v)), "n": 1}
            else:
                cur["sum"] += v
                cur["min"] = min(cur["min"], v)
                cur["max"] = max(cur["max"], float(g.get("max", v)))
                cur["n"] += 1
        for h in snap.get("histograms", []):
            k = _key(h)
            cur = hists.get(k)
            if cur is None:
                hists[k] = {"name": h["name"],
                            "labels": dict(h.get("labels") or {}),
                            "buckets": list(h["buckets"]),
                            "counts": list(h["counts"]),
                            "sum": float(h["sum"])}
            elif list(h["buckets"]) == cur["buckets"]:
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], h["counts"])]
                cur["sum"] += float(h["sum"])
            # mismatched bucket layouts are skipped (schema rule flags them)
    for h in hists.values():
        # cross-rank latency quantiles ride the merged buckets — the
        # same estimator the adaptive edge-health policy runs per rank
        for q, key in ((0.5, "p50"), (0.99, "p99")):
            v = quantile_from_buckets(h["buckets"], h["counts"], q)
            h[key] = None if v != v else v  # NaN -> null for JSON
    merged = {
        "schema": MERGED_SCHEMA,
        "ranks": sorted(ranks),
        "jobs": jobs,
        "counters": [counters[k] for k in sorted(counters)],
        "gauges": [gauges[k] for k in sorted(gauges)],
        "histograms": [hists[k] for k in sorted(hists)],
    }
    merged["ledger"] = ledger_balance(merged)
    return merged


def _counter_total(merged: dict, name: str) -> float:
    return sum(c["value"] for c in merged.get("counters", [])
               if c["name"] == name)


def ledger_balance(merged: dict) -> dict:
    """Evaluate the mailbox conservation identity over a merged summary."""
    deposits = _counter_total(merged, LEDGER_DEPOSITS)
    collected = _counter_total(merged, LEDGER_COLLECTED)
    drained = _counter_total(merged, LEDGER_DRAINED)
    pending = _counter_total(merged, LEDGER_PENDING)
    return {
        "deposits": deposits,
        "collected": collected,
        "drained": drained,
        "pending": pending,
        "balanced": deposits == collected + drained + pending,
    }


def _prom_name(name: str) -> str:
    out = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in name)
    return f"bftpu_{out}"


def _prom_labels(labels: Dict[str, object], extra: str = "") -> str:
    items = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        items.append(extra)
    return "{" + ",".join(items) + "}" if items else ""


def to_prometheus(merged: dict) -> str:
    """Prometheus text exposition (0.0.4) of a merged summary."""
    lines: List[str] = []
    typed = set()

    def _type(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for c in merged.get("counters", []):
        n = _prom_name(c["name"])
        _type(n, "counter")
        lines.append(f"{n}{_prom_labels(c['labels'])} {c['value']}")
    for g in merged.get("gauges", []):
        n = _prom_name(g["name"])
        _type(n, "gauge")
        base = dict(g["labels"])
        for agg in ("sum", "min", "max"):
            extra = 'agg="%s"' % agg
            lines.append(f"{n}{_prom_labels(base, extra)} {g[agg]}")
    for h in merged.get("histograms", []):
        n = _prom_name(h["name"])
        _type(n, "histogram")
        cum = 0
        for le, cnt in zip(h["buckets"], h["counts"]):
            cum += cnt
            extra = 'le="%s"' % le
            lines.append(f"{n}_bucket{_prom_labels(h['labels'], extra)} {cum}")
        cum += h["counts"][-1]
        inf = 'le="+Inf"'
        lines.append(f"{n}_bucket{_prom_labels(h['labels'], inf)} {cum}")
        lines.append(f"{n}_sum{_prom_labels(h['labels'])} {h['sum']}")
        lines.append(f"{n}_count{_prom_labels(h['labels'])} {cum}")
    return "\n".join(lines) + "\n"


def merge_job_snapshots(dir_value: Optional[str], job: str) -> Optional[str]:
    """Launcher-side collection: merge ``telemetry-<job>-r*.json`` under
    the telemetry dir into ``telemetry-<job>-merged.json`` (plus a
    ``.prom`` text exposition next to it).  Returns the merged path, or
    None when telemetry was off or no rank wrote a snapshot."""
    if not dir_value or dir_value == "0":
        return None
    from bluefog_tpu.telemetry.registry import _DEFAULT_DIR

    d = _DEFAULT_DIR if dir_value == "1" else dir_value
    pattern = os.path.join(d, f"telemetry-{_safe_name(job)}-r*.json")
    snaps = []
    for p in sorted(glob.glob(pattern)):
        try:
            snap = load_snapshot(p)
        except (OSError, ValueError):
            continue
        if snap is not None:
            snaps.append(snap)
    if not snaps:
        return None
    merged = merge_snapshots(snaps)
    out = os.path.join(d, f"telemetry-{_safe_name(job)}-merged.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(merged, f, indent=2)
    with open(out[:-len(".json")] + ".prom", "w", encoding="utf-8") as f:
        f.write(to_prometheus(merged))
    return out
