"""Cross-rank snapshot aggregation + Prometheus text exposition.

Per-rank snapshot files (``telemetry-<job>-r<rank>.json``, written by
:class:`bluefog_tpu.telemetry.Registry` at exit) merge into ONE summary:
counters sum, gauges aggregate (sum/min/max), histograms add bucket-wise.
The merged dict also carries a ``ledger`` section evaluating the mailbox
mass-conservation identity (deposits == collected + drained + pending on
a quiescent job) — the same identity the analysis
``telemetry.conservation`` rule verifies.

Stdlib-only, like the rest of the package.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

from bluefog_tpu.telemetry.registry import (
    LEDGER_COLLECTED,
    LEDGER_DEPOSITS,
    LEDGER_DRAINED,
    LEDGER_PENDING,
    SNAPSHOT_SCHEMA,
    _safe_name,
    quantile_from_buckets,
)

__all__ = [
    "MERGED_SCHEMA",
    "SLO_REPORT_SCHEMA",
    "SLO_CAUSE_KINDS",
    "find_snapshots",
    "find_journals",
    "load_snapshot",
    "read_journal",
    "merge_snapshots",
    "ledger_balance",
    "to_prometheus",
    "merge_job_snapshots",
    "slo_report",
    "check_request_records",
]

MERGED_SCHEMA = "bftpu-telemetry-merged/1"
SLO_REPORT_SCHEMA = "bftpu-slo-report/1"

#: Journal event kinds that can *explain* an SLO violation window: weight
#: publication and swap activity, staleness rejections and their retries,
#: distribution-tree churn, and the start of a load phase (warm-up).  A
#: chaos harness that SIGKILLs replicas journals ``serve_respawn`` from
#: the parent; it joins here too.
SLO_CAUSE_KINDS = (
    "serve_publish",
    "serve_swap",
    "serve_retry",
    "serve_stale",
    "serve_respawn",
    "distrib_publish",
    "distrib_reparent",
    "distrib_resync",
    "loadgen_start",
)


def find_snapshots(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into snapshot paths.  A directory yields
    every ``telemetry-*.json`` in it (merged outputs are filtered out at
    load time by their schema tag)."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "telemetry-*.json"))))
        else:
            out.append(p)
    return out


def load_snapshot(path: str) -> Optional[dict]:
    """One snapshot dict, or None when the file is not a per-rank
    snapshot (wrong schema — e.g. a previous merged summary)."""
    with open(path, "r", encoding="utf-8") as f:
        snap = json.load(f)
    if not isinstance(snap, dict) or snap.get("schema") != SNAPSHOT_SCHEMA:
        return None
    return snap


def _key(entry: dict) -> Tuple:
    labels = entry.get("labels") or {}
    return (entry["name"], tuple(sorted((k, str(v))
                                        for k, v in labels.items())))


def merge_snapshots(snaps: List[dict]) -> dict:
    """Aggregate per-rank snapshots into one cross-rank summary."""
    counters: Dict[Tuple, dict] = {}
    gauges: Dict[Tuple, dict] = {}
    hists: Dict[Tuple, dict] = {}
    ranks, jobs = [], []
    for snap in snaps:
        ranks.append(snap.get("rank", -1))
        job = snap.get("job")
        if job and job not in jobs:
            jobs.append(job)
        for c in snap.get("counters", []):
            k = _key(c)
            cur = counters.get(k)
            if cur is None:
                counters[k] = {"name": c["name"],
                               "labels": dict(c.get("labels") or {}),
                               "value": c["value"]}
            else:
                cur["value"] += c["value"]
        for g in snap.get("gauges", []):
            k = _key(g)
            v = float(g["value"])
            cur = gauges.get(k)
            if cur is None:
                gauges[k] = {"name": g["name"],
                             "labels": dict(g.get("labels") or {}),
                             "sum": v, "min": v,
                             "max": float(g.get("max", v)), "n": 1}
            else:
                cur["sum"] += v
                cur["min"] = min(cur["min"], v)
                cur["max"] = max(cur["max"], float(g.get("max", v)))
                cur["n"] += 1
        for h in snap.get("histograms", []):
            k = _key(h)
            cur = hists.get(k)
            if cur is None:
                hists[k] = {"name": h["name"],
                            "labels": dict(h.get("labels") or {}),
                            "buckets": list(h["buckets"]),
                            "counts": list(h["counts"]),
                            "sum": float(h["sum"])}
            elif list(h["buckets"]) == cur["buckets"]:
                cur["counts"] = [a + b for a, b in
                                 zip(cur["counts"], h["counts"])]
                cur["sum"] += float(h["sum"])
            # mismatched bucket layouts are skipped (schema rule flags them)
    for h in hists.values():
        # cross-rank latency quantiles ride the merged buckets — the
        # same estimator the adaptive edge-health policy runs per rank
        for q, key in ((0.5, "p50"), (0.99, "p99")):
            v = quantile_from_buckets(h["buckets"], h["counts"], q)
            h[key] = None if v != v else v  # NaN -> null for JSON
    merged = {
        "schema": MERGED_SCHEMA,
        "ranks": sorted(ranks),
        "jobs": jobs,
        "counters": [counters[k] for k in sorted(counters)],
        "gauges": [gauges[k] for k in sorted(gauges)],
        "histograms": [hists[k] for k in sorted(hists)],
    }
    merged["ledger"] = ledger_balance(merged)
    return merged


def _counter_total(merged: dict, name: str) -> float:
    return sum(c["value"] for c in merged.get("counters", [])
               if c["name"] == name)


def ledger_balance(merged: dict) -> dict:
    """Evaluate the mailbox conservation identity over a merged summary."""
    deposits = _counter_total(merged, LEDGER_DEPOSITS)
    collected = _counter_total(merged, LEDGER_COLLECTED)
    drained = _counter_total(merged, LEDGER_DRAINED)
    pending = _counter_total(merged, LEDGER_PENDING)
    return {
        "deposits": deposits,
        "collected": collected,
        "drained": drained,
        "pending": pending,
        "balanced": deposits == collected + drained + pending,
    }


def _prom_name(name: str) -> str:
    out = "".join(ch if ch.isalnum() or ch == "_" else "_" for ch in name)
    return f"bftpu_{out}"


def _prom_labels(labels: Dict[str, object], extra: str = "") -> str:
    items = [f'{k}="{v}"' for k, v in sorted(labels.items())]
    if extra:
        items.append(extra)
    return "{" + ",".join(items) + "}" if items else ""


def to_prometheus(merged: dict) -> str:
    """Prometheus text exposition (0.0.4) of a merged summary."""
    lines: List[str] = []
    typed = set()

    def _type(name: str, kind: str):
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for c in merged.get("counters", []):
        n = _prom_name(c["name"])
        _type(n, "counter")
        lines.append(f"{n}{_prom_labels(c['labels'])} {c['value']}")
    for g in merged.get("gauges", []):
        n = _prom_name(g["name"])
        _type(n, "gauge")
        base = dict(g["labels"])
        for agg in ("sum", "min", "max"):
            extra = 'agg="%s"' % agg
            lines.append(f"{n}{_prom_labels(base, extra)} {g[agg]}")
    for h in merged.get("histograms", []):
        n = _prom_name(h["name"])
        _type(n, "histogram")
        cum = 0
        for le, cnt in zip(h["buckets"], h["counts"]):
            cum += cnt
            extra = 'le="%s"' % le
            lines.append(f"{n}_bucket{_prom_labels(h['labels'], extra)} {cum}")
        cum += h["counts"][-1]
        inf = 'le="+Inf"'
        lines.append(f"{n}_bucket{_prom_labels(h['labels'], inf)} {cum}")
        lines.append(f"{n}_sum{_prom_labels(h['labels'])} {h['sum']}")
        lines.append(f"{n}_count{_prom_labels(h['labels'])} {cum}")
    return "\n".join(lines) + "\n"


def merge_job_snapshots(dir_value: Optional[str], job: str) -> Optional[str]:
    """Launcher-side collection: merge ``telemetry-<job>-r*.json`` under
    the telemetry dir into ``telemetry-<job>-merged.json`` (plus a
    ``.prom`` text exposition next to it).  Returns the merged path, or
    None when telemetry was off or no rank wrote a snapshot."""
    if not dir_value or dir_value == "0":
        return None
    from bluefog_tpu.telemetry.registry import _DEFAULT_DIR

    d = _DEFAULT_DIR if dir_value == "1" else dir_value
    pattern = os.path.join(d, f"telemetry-{_safe_name(job)}-r*.json")
    snaps = []
    for p in sorted(glob.glob(pattern)):
        try:
            snap = load_snapshot(p)
        except (OSError, ValueError):
            continue
        if snap is not None:
            snaps.append(snap)
    if not snaps:
        return None
    merged = merge_snapshots(snaps)
    out = os.path.join(d, f"telemetry-{_safe_name(job)}-merged.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(merged, f, indent=2)
    with open(out[:-len(".json")] + ".prom", "w", encoding="utf-8") as f:
        f.write(to_prometheus(merged))
    return out


# -- request-level journals: SLO windows joined to causes -------------------

def find_journals(paths: Iterable[str]) -> List[str]:
    """Expand files/directories into event-journal paths.  A directory
    yields every ``telemetry-*.events.jsonl`` in it plus rotated ``.1``
    generations; explicit files pass through when they look like
    journals."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                glob.glob(os.path.join(p, "telemetry-*.events.jsonl"))))
            out.extend(sorted(
                glob.glob(os.path.join(p, "telemetry-*.events.jsonl.1"))))
        elif ".events.jsonl" in os.path.basename(p):
            out.append(p)
    return out


def read_journal(path: str) -> List[dict]:
    """Parsed event records from one journal.  Corrupt lines are skipped
    (a SIGKILLed rank tears at most the line in flight), as is an
    unreadable file — survivors' journals still merge."""
    events: List[dict] = []
    try:
        fh = open(path, "r", encoding="utf-8")
    except OSError:
        return events
    with fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                events.append(rec)
    return events


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    return f if f == f and f not in (float("inf"), float("-inf")) else None


def slo_report(paths: Iterable[str], margin_s: float = 2.0) -> dict:
    """Join SLO violation windows to the cause events that explain them.

    Reads every journal under ``paths``, collects ``slo_violation``
    windows (written by the per-replica SLO monitor with wall-clock
    bounds) and :data:`SLO_CAUSE_KINDS` events, and attributes each
    window to every cause whose universal ``ts`` falls within
    ``[t0_wall - margin_s, t1_wall + margin_s]`` — wall clock is the one
    timebase journals from different processes share.  A window no cause
    overlaps counts as *unattributed*: in a chaos run those are the
    unexplained violations the acceptance gate requires to be zero.
    """
    journals = find_journals(paths)
    windows: List[dict] = []
    causes: List[dict] = []
    requests = 0
    for path in journals:
        name = os.path.basename(path)
        for rec in read_journal(path):
            kind = rec.get("event")
            if kind == "slo_violation":
                w = dict(rec)
                w["_journal"] = name
                windows.append(w)
            elif kind in SLO_CAUSE_KINDS:
                causes.append(rec)
            elif kind == "serve_request":
                requests += 1
    causes.sort(key=lambda r: _num(r.get("ts")) or 0.0)
    out_windows: List[dict] = []
    unattributed = 0
    for w in sorted(windows, key=lambda r: _num(r.get("t0_wall")) or 0.0):
        t0 = _num(w.get("t0_wall"))
        t1 = _num(w.get("t1_wall"))
        joined = []
        if t0 is not None:
            lo, hi = t0 - margin_s, (t1 if t1 is not None else t0) + margin_s
            for c in causes:
                ts = _num(c.get("ts"))
                if ts is None or not (lo <= ts <= hi):
                    continue
                cause = {"kind": c.get("event"), "ts": ts,
                         "rank": c.get("rank"), "dt_s": ts - t0}
                for k in ("replica", "win", "version", "group"):
                    if k in c:
                        cause[k] = c[k]
                joined.append(cause)
        if not joined:
            unattributed += 1
        out_windows.append({
            "replica": w.get("replica"),
            "t0_wall": w.get("t0_wall"),
            "t1_wall": w.get("t1_wall"),
            "duration_s": (t1 - t0 if t0 is not None and t1 is not None
                           else None),
            "requests": w.get("requests"),
            "worst_ms": w.get("worst_ms"),
            "kinds": w.get("kinds"),
            "journal": w.get("_journal"),
            "causes": joined,
        })
    return {
        "schema": SLO_REPORT_SCHEMA,
        "journals": [os.path.basename(p) for p in journals],
        "margin_s": float(margin_s),
        "requests": requests,
        "windows": out_windows,
        "total_windows": len(out_windows),
        "unattributed": unattributed,
    }


#: serve_request fields every writer (Replica.note_request and the
#: loadgen's registry fallback) must journal as finite numbers.
_REQUEST_NUM_FIELDS = ("send_mono", "start_mono", "done_mono", "latency_ms")


def check_request_records(paths: Iterable[str]) -> List[str]:
    """Validate ``serve_request`` journal records; one error string per
    malformed record.  The schema is what downstream joins rely on:
    finite monotonic timestamps ordered send <= done, a latency
    consistent with them on the open-loop basis (charged from the
    *scheduled* send), and a non-empty outcome label."""
    errors: List[str] = []
    for path in find_journals(paths):
        name = os.path.basename(path)
        for i, rec in enumerate(read_journal(path)):
            if rec.get("event") != "serve_request":
                continue
            where = f"{name}: serve_request #{i}"
            nums = {}
            bad = False
            for fld in _REQUEST_NUM_FIELDS:
                v = _num(rec.get(fld))
                if v is None:
                    errors.append(f"{where}: field {fld!r} missing or "
                                  f"not a finite number: "
                                  f"{rec.get(fld)!r}")
                    bad = True
                nums[fld] = v
            if not bad:
                if nums["done_mono"] < nums["send_mono"]:
                    errors.append(f"{where}: done_mono precedes send_mono "
                                  f"({nums['done_mono']} < "
                                  f"{nums['send_mono']})")
                else:
                    want = (nums["done_mono"] - nums["send_mono"]) * 1e3
                    if abs(nums["latency_ms"] - want) > 0.5:
                        errors.append(
                            f"{where}: latency_ms={nums['latency_ms']:.3f} "
                            f"inconsistent with done-send="
                            f"{want:.3f} ms (open-loop basis)")
            out = rec.get("outcome")
            if not isinstance(out, str) or not out:
                errors.append(f"{where}: outcome missing or not a "
                              f"non-empty string: {out!r}")
            if "replica" not in rec:
                errors.append(f"{where}: replica missing")
    return errors
