"""CLI: merge per-rank telemetry snapshots into one cross-rank summary.

    python -m bluefog_tpu.telemetry SNAP_OR_DIR [...] [--format json|prom|both]
                                    [--out PATH] [--check]
                                    [--slo-report] [--slo-margin-s S]

Positional arguments are snapshot files or directories (directories are
globbed for ``telemetry-*.json``; previously merged summaries are
skipped by schema tag).  With no arguments the default telemetry dir
(``$BFTPU_TELEMETRY`` when it names a dir, else /tmp/bftpu_telemetry)
is scanned.

``--check`` runs the telemetry analysis rules (snapshot schema +
conservation invariant) over the corpus, plus the ``serve_request``
journal-record schema when event journals sit alongside the snapshots,
and exits non-zero on findings.

``--slo-report`` switches to the request-level journals instead: SLO
violation windows (journaled by the per-replica monitor) are joined to
the cause events that explain them (publishes, swaps, staleness
retries, tree churn) on the shared wall clock.  Exits non-zero when any
window has no overlapping cause — an *unexplained* violation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from bluefog_tpu.telemetry.merge import (
    check_request_records,
    find_snapshots,
    load_snapshot,
    merge_snapshots,
    slo_report,
    to_prometheus,
)
from bluefog_tpu.telemetry.registry import _DEFAULT_DIR, telemetry_dir


def _default_paths() -> List[str]:
    d = telemetry_dir() or _DEFAULT_DIR
    return [d] if os.path.isdir(d) else []


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.telemetry",
        description="Merge per-rank telemetry snapshots into one summary.")
    ap.add_argument("paths", nargs="*",
                    help="snapshot files or directories "
                         "(default: the telemetry dir)")
    ap.add_argument("--format", choices=("json", "prom", "both"),
                    default="json", help="output format (default: json)")
    ap.add_argument("--out", default=None,
                    help="write output to PATH instead of stdout "
                         "(with --format both, PATH and PATH.prom)")
    ap.add_argument("--check", action="store_true",
                    help="run telemetry analysis rules over the corpus "
                         "(snapshots + serve_request journal schema); "
                         "exit non-zero on findings")
    ap.add_argument("--slo-report", action="store_true",
                    help="join SLO violation windows in the event "
                         "journals to their cause events; exit non-zero "
                         "on unattributed windows")
    ap.add_argument("--slo-margin-s", type=float, default=2.0,
                    help="cause-join slack around each violation window "
                         "(seconds, default: 2.0)")
    args = ap.parse_args(argv)

    if args.slo_report:
        report = slo_report(args.paths or _default_paths(),
                            margin_s=args.slo_margin_s)
        text = json.dumps(report, indent=2)
        if args.out:
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(text + "\n")
        else:
            print(text)
        if not report["journals"]:
            print("error: no event journals found (run with "
                  "BFTPU_TELEMETRY=1, or pass journal paths)",
                  file=sys.stderr)
            return 2
        print(f"slo report: {report['total_windows']} violation "
              f"window(s) over {report['requests']} request(s) in "
              f"{len(report['journals'])} journal(s), "
              f"{report['unattributed']} unattributed",
              file=sys.stderr)
        return 1 if report["unattributed"] else 0

    paths = find_snapshots(args.paths or _default_paths())
    snaps = []
    skipped = []
    for p in paths:
        try:
            snap = load_snapshot(p)
        except (OSError, ValueError) as e:
            # a SIGKILLed rank leaves a truncated/partial snapshot:
            # merge what the survivors wrote instead of dying mid-merge
            print(f"warning: skipping {p}: {e}", file=sys.stderr)
            skipped.append(p)
            continue
        if snap is not None:
            snaps.append(snap)
    if not snaps:
        print("error: no telemetry snapshots found "
              "(run with BFTPU_TELEMETRY=1, or pass snapshot paths)",
              file=sys.stderr)
        return 2

    merged = merge_snapshots(snaps)
    json_text = json.dumps(merged, indent=2)
    prom_text = to_prometheus(merged)

    if args.out:
        if args.format in ("json", "both"):
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(json_text + "\n")
        if args.format == "prom":
            with open(args.out, "w", encoding="utf-8") as f:
                f.write(prom_text)
        elif args.format == "both":
            with open(args.out + ".prom", "w", encoding="utf-8") as f:
                f.write(prom_text)
        print(f"merged {len(snaps)} snapshot(s) "
              f"(ranks {merged['ranks']}) -> {args.out}", file=sys.stderr)
    else:
        if args.format in ("json", "both"):
            print(json_text)
        if args.format in ("prom", "both"):
            print(prom_text, end="")

    rc = 0
    if args.check:
        from bluefog_tpu.analysis import telemetry_rules

        findings = telemetry_rules.check_snapshot_corpus(snaps)
        for f in findings:
            print(f"CHECK {f.severity}: [{f.rule}] {f.subject}: {f.message}",
                  file=sys.stderr)
        req_errors = check_request_records(args.paths or _default_paths())
        for msg in req_errors:
            print(f"CHECK error: [telemetry.request-journal] {msg}",
                  file=sys.stderr)
        if skipped:
            # an unreadable rank means the corpus (and thus the ledger
            # verdict) is incomplete — note it and fail the check
            print(f"CHECK warning: [telemetry.merge-skipped] "
                  f"{len(skipped)} snapshot(s) unreadable/truncated: "
                  f"{', '.join(skipped)}", file=sys.stderr)
        if findings or req_errors or skipped:
            rc = 1
        else:
            led = merged["ledger"]
            print(f"check ok: {len(snaps)} snapshots, ledger balanced "
                  f"(deposits={led['deposits']:.0f} = "
                  f"collected={led['collected']:.0f} + "
                  f"drained={led['drained']:.0f} + "
                  f"pending={led['pending']:.0f})", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
