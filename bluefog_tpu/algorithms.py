"""Exact decentralized algorithms: gradient tracking, EXTRA, Push-DIGing.

Plain gossip SGD (ATC/AWC, ``optim.py``) converges to a *neighborhood* of
the optimum when ranks hold heterogeneous data: each rank's gradient pulls
toward its local minimizer and the gossip only averages the iterates, so a
bias of order ``alpha * heterogeneity`` persists.  The reference
demonstrates the exact-method family on decentralized logistic regression
in ``examples/pytorch_optimization.py`` [U] (push-sum / EXTRA-style
methods, SURVEY.md §2.2 examples row); these are the TPU-native optax
siblings (r3 verdict next-round #4).

All three are SPMD transforms in the ``optim.py`` convention: they run
inside a jitted/shard_mapped train step where the mesh axis carries the
gossip, and communicate pytrees in ONE fused program per round (the x- and
y-exchanges ride the same ``ppermute`` classes).

- :func:`gradient_tracking_spmd` — DIGing/ATC-GT: a tracker ``y``
  estimates the GLOBAL average gradient (``y^k = W y^{k-1} + g^k -
  g^{k-1}``), and the iterate descends along the tracker through the same
  mixing (``x^{k+1} = W(x^k - lr * y^k)``).  Needs a doubly-stochastic
  mixing matrix: the built-in undirected topologies qualify (uniform
  weights on regular graphs; Metropolis-Hastings on irregular ones are
  symmetric).
- :func:`extra_spmd` — EXTRA (Shi et al., SIAM J. Optim. 2015):
  ``x^{k+1} = 2 Wt x^k - Wt x^{k-1} - lr (g^k - g^{k-1})`` with
  ``Wt = (I + W)/2``, ``x^1 = Wt x^0 - lr g^0``.  One comm round per
  step; same doubly-stochastic requirement.
- :func:`push_diging_spmd` — Push-DIGing (Nedic, Olshevsky, Shi, SIAM J.
  Optim. 2017) for DIRECTED graphs where no doubly-stochastic matrix
  exists: column-stochastic mixing ``C`` (each sender splits its mass,
  :func:`column_stochastic_plan`) plus a push-sum weight ``v`` that
  de-biases the iterate (``x = u / v``).

All converge to the CENTRALIZED optimum at constant step size on smooth
strongly-convex objectives — the property the heterogeneous-shard test
(tests/test_algorithms.py) asserts and plain ATC measurably lacks.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

from bluefog_tpu import ops_spmd
from bluefog_tpu.core.basics import NODES_AXIS
from bluefog_tpu.core.plan import CommPlan, plan_from_neighbor_lists

__all__ = [
    "gradient_tracking_spmd",
    "extra_spmd",
    "push_diging_spmd",
    "column_stochastic_plan",
    "DistributedGradientTrackingOptimizer",
    "DistributedEXTRAOptimizer",
    "DistributedPushDIGingOptimizer",
]


def column_stochastic_plan(topology) -> CommPlan:
    """Column-stochastic mixing plan from a (directed) networkx graph:
    sender s splits mass uniformly over its out-neighbors and itself
    (``C[d, s] = 1 / (out_deg(s) + 1)``), so columns sum to 1 — the
    push-sum weight convention [U, pytorch_optimization.py push-sum demo].
    """
    size = topology.number_of_nodes()
    out_deg = {s: 0 for s in range(size)}
    src_lists = [[] for _ in range(size)]
    for s, d in topology.edges():
        if s == d:
            continue
        out_deg[int(s)] += 1
        src_lists[int(d)].append(int(s))
    src_weights = [
        {s: 1.0 / (out_deg[s] + 1) for s in src_lists[d]} for d in range(size)
    ]
    self_weights = [1.0 / (out_deg[s] + 1) for s in range(size)]
    return plan_from_neighbor_lists(
        size, [sorted(s) for s in src_lists],
        src_weights=src_weights, self_weights=self_weights,
    )


class _GTState(NamedTuple):
    cy: Any  # W @ y from the previous round (zeros before the first)
    prev_g: Any
    step: jnp.ndarray


def gradient_tracking_spmd(
    learning_rate: float,
    plan: CommPlan,
    axis_name: str = NODES_AXIS,
) -> optax.GradientTransformation:
    """ATC gradient tracking (DIGing family).  ``plan`` must mix with a
    doubly-stochastic matrix (built-in undirected topologies qualify)."""
    lr = float(learning_rate)

    def comm(tree):
        return ops_spmd.neighbor_allreduce(tree, plan, axis_name, fuse=True)

    def init(params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _GTState(cy=z, prev_g=z, step=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("gradient tracking requires params")
        # y^k = W y^{k-1} + g^k - g^{k-1}   (y^0 = g^0)
        y = jax.tree_util.tree_map(
            lambda c, g, pg: c + g - pg, state.cy, grads, state.prev_g)
        # one fused comm round: x-descent and the tracker share the plan
        x_new, cy = comm((
            jax.tree_util.tree_map(lambda p, yy: p - lr * yy, params, y),
            y,
        ))
        updates = jax.tree_util.tree_map(
            lambda xn, p: (xn - p).astype(p.dtype), x_new, params)
        return updates, _GTState(cy=cy, prev_g=grads, step=state.step + 1)

    return optax.GradientTransformation(init, update)


class _ExtraState(NamedTuple):
    prev_wtx: Any  # Wt x^{k-1}
    prev_g: Any
    step: jnp.ndarray


def extra_spmd(
    learning_rate: float,
    plan: CommPlan,
    axis_name: str = NODES_AXIS,
) -> optax.GradientTransformation:
    """EXTRA with ``Wt = (I + W)/2``; one comm round per step."""
    lr = float(learning_rate)

    def wt(tree):
        mixed = ops_spmd.neighbor_allreduce(tree, plan, axis_name, fuse=True)
        return jax.tree_util.tree_map(lambda m, t: 0.5 * (m + t), mixed, tree)

    def init(params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _ExtraState(prev_wtx=z, prev_g=z,
                           step=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("EXTRA requires params")
        wtx = wt(params)

        def first(_):
            # x^1 = Wt x^0 - lr g^0
            return jax.tree_util.tree_map(
                lambda w, g: w - lr * g, wtx, grads)

        def later(_):
            # x^{k+1} = 2 Wt x^k - Wt x^{k-1} - lr (g^k - g^{k-1})
            return jax.tree_util.tree_map(
                lambda w, pw, g, pg: 2.0 * w - pw - lr * (g - pg),
                wtx, state.prev_wtx, grads, state.prev_g)

        x_new = jax.lax.cond(state.step == 0, first, later, None)
        updates = jax.tree_util.tree_map(
            lambda xn, p: (xn - p).astype(p.dtype), x_new, params)
        return updates, _ExtraState(
            prev_wtx=wtx, prev_g=grads, step=state.step + 1)

    return optax.GradientTransformation(init, update)


class _PushDigingState(NamedTuple):
    u: Any  # raw (biased) iterate; params hold x = u / v
    v: jnp.ndarray  # push-sum weight, shape (1,)
    cy: Any  # C @ y from the previous round
    prev_g: Any
    step: jnp.ndarray


def push_diging_spmd(
    learning_rate: float,
    plan: CommPlan,
    axis_name: str = NODES_AXIS,
) -> optax.GradientTransformation:
    """Push-DIGing over a COLUMN-stochastic plan
    (:func:`column_stochastic_plan`): gradient tracking + push-sum
    de-biasing for directed graphs.  Gradients are evaluated at the
    de-biased iterate ``x = u / v``, which is what ``params`` hold."""
    lr = float(learning_rate)

    def comm(tree):
        return ops_spmd.neighbor_allreduce(tree, plan, axis_name, fuse=True)

    def init(params):
        z = jax.tree_util.tree_map(jnp.zeros_like, params)
        return _PushDigingState(
            u=jax.tree_util.tree_map(jnp.asarray, params),
            v=jnp.ones((1,), jnp.float32),
            cy=z, prev_g=z, step=jnp.zeros((), jnp.int32))

    def update(grads, state, params=None):
        if params is None:
            raise ValueError("Push-DIGing requires params")
        # y^k = C y^{k-1} + g^k - g^{k-1}   (y^0 = g^0)
        y = jax.tree_util.tree_map(
            lambda c, g, pg: c + g - pg, state.cy, grads, state.prev_g)
        # one fused push round: u-descent, the weight v, and the tracker
        u_new, v_new, cy = comm((
            jax.tree_util.tree_map(lambda u, yy: u - lr * yy, state.u, y),
            state.v,
            y,
        ))
        x_new = jax.tree_util.tree_map(lambda u: u / v_new[0], u_new)
        updates = jax.tree_util.tree_map(
            lambda xn, p: (xn - p).astype(p.dtype), x_new, params)
        return updates, _PushDigingState(
            u=u_new, v=v_new, cy=cy, prev_g=grads, step=state.step + 1)

    return optax.GradientTransformation(init, update)


# --------------------------------------------------------------------------
# Parity classes — eager, rank-major (the optim.py convention)
# --------------------------------------------------------------------------


class _EagerExactOptimizer:
    """Rank-major eager wrapper over an exact SPMD transform.

    Unlike ``optim._EagerDistributedOptimizer``, ``init`` also runs inside
    ``shard_map``: Push-DIGing's push-sum weight ``v`` is per-rank state
    with no rank-major params leaf to mirror, so the per-shard init is the
    only correct way to lay it out."""

    def __init__(self, learning_rate: float):
        self.learning_rate = float(learning_rate)
        self._cache = {}

    def _plan(self, ctx):
        return ctx.plan

    def _make_tx(self, plan):
        raise NotImplementedError

    def _tx(self):
        from bluefog_tpu.core import basics

        ctx = basics.context()
        plan = self._plan(ctx)
        key = ("tx", plan)
        if self._cache.get("tx_key") != key:
            self._cache["tx"] = self._make_tx(plan)
            self._cache["tx_key"] = key
            self._cache.pop("step_fn", None)
            self._cache.pop("init_fn", None)
        return self._cache["tx"], ctx

    def init(self, params):
        from jax.sharding import PartitionSpec as P

        tx, ctx = self._tx()
        spec = P(NODES_AXIS)

        def per_rank(p):
            local = jax.tree_util.tree_map(lambda a: a[0], p)
            st = tx.init(local)
            return jax.tree_util.tree_map(
                lambda a: a[None] if getattr(a, "ndim", 0) >= 1 else a, st)

        shapes = jax.eval_shape(per_rank,
                                jax.tree_util.tree_map(
                                    lambda a: jax.ShapeDtypeStruct(
                                        (1,) + a.shape[1:], a.dtype), params))
        out_spec = jax.tree_util.tree_map(
            lambda s: spec if s.ndim >= 1 else P(), shapes)
        f = jax.jit(jax.shard_map(per_rank, mesh=ctx.mesh,
                                  in_specs=P(NODES_AXIS), out_specs=out_spec))
        return f(params)

    def step(self, params, grads, state):
        import optax as _optax
        from jax.sharding import PartitionSpec as P

        tx, ctx = self._tx()
        spec = P(NODES_AXIS)
        key = jax.tree_util.tree_structure(state)
        if "step_fn" not in self._cache or self._cache["step_key"] != key:
            state_spec = jax.tree_util.tree_map(
                lambda a: spec
                if getattr(a, "ndim", 0) >= 1 and a.shape[0] == ctx.size
                else P(), state)

            def whole(params, grads, state):
                p = jax.tree_util.tree_map(lambda a: a[0], params)
                g = jax.tree_util.tree_map(lambda a: a[0], grads)
                st = jax.tree_util.tree_map(
                    lambda a: a[0] if getattr(a, "ndim", 0) >= 1 else a, state)
                updates, new_st = tx.update(g, st, p)
                new_p = _optax.apply_updates(p, updates)
                expand = lambda t: jax.tree_util.tree_map(
                    lambda a: a[None] if getattr(a, "ndim", 0) >= 1 else a, t)
                # re-expand exactly the leaves that were stripped (inside
                # shard_map, sharded state leaves carry a leading 1)
                return expand(new_p), jax.tree_util.tree_map(
                    lambda new, old: new[None]
                    if getattr(old, "ndim", 0) >= 1 else new,
                    new_st, state)

            self._cache["step_fn"] = jax.jit(
                jax.shard_map(whole, mesh=ctx.mesh,
                              in_specs=(spec, spec, state_spec),
                              out_specs=(spec, state_spec)))
            self._cache["step_key"] = key
        return self._cache["step_fn"](params, grads, state)


class DistributedGradientTrackingOptimizer(_EagerExactOptimizer):
    """Gradient tracking (DIGing) on the installed (undirected) topology."""

    def _make_tx(self, plan):
        return gradient_tracking_spmd(self.learning_rate, plan)


class DistributedEXTRAOptimizer(_EagerExactOptimizer):
    """EXTRA on the installed (undirected) topology."""

    def _make_tx(self, plan):
        return extra_spmd(self.learning_rate, plan)


class DistributedPushDIGingOptimizer(_EagerExactOptimizer):
    """Push-DIGing: column-stochastic push weights derived from the
    installed topology (which may be a directed graph)."""

    def _plan(self, ctx):
        return column_stochastic_plan(ctx.topology)

    def _make_tx(self, plan):
        return push_diging_spmd(self.learning_rate, plan)
