"""Interactive/notebook support — sibling of the reference's ``ibfrun``.

The reference needs ``ibfrun`` (``bluefog/run/interactive_run.py`` [U],
SURVEY.md §2.2) to keep persistent MPI worker daemons alive so Jupyter
cells can issue collective ops.  Under single-controller JAX the need
dissolves: one process drives every rank, so a notebook only has to build
the mesh.  ``setup_interactive`` does that — optionally simulating an
n-rank CPU mesh inside the running kernel (the notebook twin of
``bftpu-run --simulate``).
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["setup_interactive"]


def setup_interactive(simulate_ranks: Optional[int] = None, **init_kwargs):
    """Initialize bluefog_tpu for interactive use and return the context.

    simulate_ranks: force an n-device virtual CPU mesh (must be called
    before jax initializes its backends — i.e. first thing in the notebook).
    """
    if simulate_ranks:
        flags = os.environ.get("XLA_FLAGS", "")
        token = f"--xla_force_host_platform_device_count={simulate_ranks}"
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + token).strip()
        import jax

        if jax.default_backend() != "cpu":
            try:
                jax.config.update("jax_platforms", "cpu")
            except Exception as e:  # backends already initialized
                raise RuntimeError(
                    "setup_interactive(simulate_ranks=...) must run before "
                    "any jax computation in this kernel"
                ) from e

    import bluefog_tpu as bf

    bf.init(**init_kwargs)
    from bluefog_tpu.core import basics

    return basics.context()
