"""Interactive multi-rank island sessions — the ``ibfrun`` twin for the
TRUE multi-process runtime (round-3 verdict #9 / round-2 missing #3).

The reference's ``ibfrun`` (``bluefog/run/interactive_run.py`` [U],
SURVEY.md §2.2) keeps persistent MPI daemons alive so Jupyter cells can
drive a live multi-rank job.  ``run/interactive.py`` covers the
single-controller case (where the daemons dissolve); THIS module covers
the islands case: N persistent OS processes, each owning its island
runtime (windows, mailboxes, mutexes stay ALIVE between cells), driven
from the notebook one task at a time.

    from bluefog_tpu.run.interactive_islands import IslandSession

    sess = IslandSession(4)                    # cell 1: spawn the workers
    sess.run(lambda rank, size: islands_setup(...))
    sess.run(step_fn, lr=0.1)                  # cell 2..n: live gossip
    sess.shutdown()                            # last cell

Functions are shipped with cloudpickle, so notebook-defined closures
work.  Each ``run`` broadcasts one callable ``fn(rank, size, *args,
**kwargs)`` to every worker and returns the per-rank results in rank
order; exceptions on any rank are re-raised in the driving kernel with
the worker traceback attached.
"""

from __future__ import annotations

import itertools
import os
from typing import Any, List, Optional

__all__ = ["IslandSession"]

_session_counter = itertools.count()


def _worker_loop(rank: int, size: int, job: str, conn) -> None:
    """One persistent island worker: init once, serve tasks until the
    shutdown sentinel, then tear down collectively."""
    try:
        # inside the try: a missing cloudpickle must surface as an
        # ('error', ...) reply, not a silent driver-side timeout
        import cloudpickle

        from bluefog_tpu import islands

        islands.init(rank, size, job)
        conn.send(("ready", rank))
    except Exception as e:  # noqa: BLE001
        import traceback

        conn.send(("error", f"{e}\n{traceback.format_exc()}"))
        return
    while True:
        try:
            msg = conn.recv()
        except EOFError:
            # driver died or the session was GC'd without shutdown():
            # treat like the sentinel so teardown/unlink still runs
            msg = None
        if msg is None:  # shutdown sentinel
            break
        try:
            fn, args, kwargs = cloudpickle.loads(msg)
            out = fn(rank, size, *args, **kwargs)
            conn.send(("ok", out))
        except Exception as e:  # noqa: BLE001
            import traceback

            conn.send(("error", f"{e}\n{traceback.format_exc()}"))
    try:
        islands.barrier()
        islands.shutdown(unlink=(rank == 0))
    except Exception:  # noqa: BLE001 — peers may already be gone
        pass
    conn.send(("bye", rank))


class IslandSession:
    """N persistent island processes driven from this (notebook) process.

    State persists across ``run`` calls: a window created in one cell is
    live in the next — the property ``ibfrun`` exists for.
    """

    def __init__(self, nranks: int, job: Optional[str] = None,
                 timeout: float = 300.0):
        import multiprocessing as mp

        self.nranks = nranks
        self.timeout = timeout
        self.job = job or (
            f"ibf{os.getpid()}_{next(_session_counter)}"
        )
        ctx = mp.get_context("spawn")  # fresh interpreters (own JAX runtime)
        self._conns = []
        self._procs = []
        for r in range(nranks):
            parent, child = ctx.Pipe()
            p = ctx.Process(
                target=_worker_loop, args=(r, nranks, self.job, child),
                daemon=True,
            )
            p.start()
            self._conns.append(parent)
            self._procs.append(p)
        for r, conn in enumerate(self._conns):
            self._expect(conn, r, ("ready",))
        self._alive = True

    def _expect(self, conn, rank, kinds):
        if not conn.poll(self.timeout):
            self.terminate()
            raise TimeoutError(
                f"island worker {rank} did not answer within "
                f"{self.timeout:g}s"
            )
        kind, payload = conn.recv()
        if kind == "error":
            self.terminate()
            raise RuntimeError(f"island worker {rank} failed:\n{payload}")
        if kind not in kinds:
            self.terminate()
            raise RuntimeError(
                f"island worker {rank}: unexpected reply {kind!r}")
        return payload

    def _collect(self, kinds) -> List[Any]:
        """One reply per rank, polled ACROSS ranks: a failure on any rank
        surfaces immediately with its real traceback, even while other
        ranks block in a collective waiting for the failed one."""
        import time as _time

        results: dict = {}
        deadline = _time.monotonic() + self.timeout
        while len(results) < self.nranks:
            progressed = False
            for r, conn in enumerate(self._conns):
                if r in results or not conn.poll(0.02):
                    continue
                progressed = True
                kind, payload = conn.recv()
                if kind == "error":
                    self.terminate()
                    raise RuntimeError(
                        f"island worker {r} failed:\n{payload}")
                if kind not in kinds:
                    self.terminate()
                    raise RuntimeError(
                        f"island worker {r}: unexpected reply {kind!r}")
                results[r] = payload
            if not progressed and _time.monotonic() > deadline:
                missing = sorted(set(range(self.nranks)) - set(results))
                self.terminate()
                raise TimeoutError(
                    f"island worker(s) {missing} did not answer within "
                    f"{self.timeout:g}s"
                )
        return [results[r] for r in range(self.nranks)]

    def _send_all(self, payloads) -> None:
        """Broadcast with dead-worker detection: a broken pipe (worker
        OOM-killed/segfaulted between cells) tears the session down
        instead of leaving it half-alive with segments unreclaimed."""
        try:
            for conn, blob in zip(self._conns, payloads):
                conn.send(blob)
        except (BrokenPipeError, OSError) as e:
            self.terminate()
            raise RuntimeError(
                "an island worker died between cells (broken pipe); "
                "session terminated and segments reclaimed"
            ) from e

    def run(self, fn, *args, **kwargs) -> List[Any]:
        """Run ``fn(rank, size, *args, **kwargs)`` on EVERY rank; returns
        per-rank results in rank order.  Collective ops inside ``fn`` are
        fine — all ranks execute the same cell."""
        if not self._alive:
            raise RuntimeError("session is shut down")
        import cloudpickle

        blob = cloudpickle.dumps((fn, args, kwargs))
        self._send_all([blob] * self.nranks)
        return self._collect(("ok",))

    def shutdown(self) -> None:
        """Collective teardown: windows freed, segments unlinked."""
        if not self._alive:
            return
        self._send_all([None] * self.nranks)
        self._collect(("bye",))
        for p in self._procs:
            p.join(self.timeout)
        self._alive = False

    def terminate(self) -> None:
        """Hard kill (error paths); reclaims the job's shm segments.

        Workers are joined (then killed) BEFORE the unlink: SIGTERM is
        asynchronous, and a worker mid win_create could re-create a
        segment after the unlink, leaking it (same ordering as
        ``islands.spawn``)."""
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(10.0)
            if p.is_alive():
                p.kill()
                p.join(10.0)
        from bluefog_tpu.native import shm_native

        shm_native.unlink_all(self.job)
        self._alive = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._alive:
            try:
                self.shutdown()
            except Exception:  # noqa: BLE001
                self.terminate()
