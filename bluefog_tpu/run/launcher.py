"""``bftpu-run`` — TPU-slice launcher, sibling of the reference's ``bfrun``.

The reference's ``bfrun`` (``bluefog/run/run.py`` [U], SURVEY.md §3.5)
assembles and execs an ``mpirun`` command: host list parsing, NIC probing,
env forwarding, one process per rank, ssh to remote hosts.  On TPU pods the
platform already provides the process-per-host convention and rendezvous
(``jax.distributed.initialize`` auto-configures from the TPU environment),
so for the single-host cases the launcher's job shrinks to: validate the
environment, set Bluefog env vars, optionally configure a multi-process CPU
simulation, and exec the training script.  For multi-machine runs it does
what ``bfrun -H`` does: spawn ranks on each listed host (ssh for remote
hosts, fork for local ones), forward the env whitelist, and propagate the
first failure to every sibling.

Usage:
  bftpu-run python train.py                    # on a TPU host/pod worker
  bftpu-run --simulate 8 python train.py       # 8 virtual CPU devices
  bftpu-run -np 4 --coordinator host:port --process-id K python train.py
                                               # explicit multi-host bootstrap
  bftpu-run -np 2 -H hostA:1,hostB:1 python train.py
                                               # ssh-spawned multi-machine run
  bftpu-run --islands 4 python async_train.py  # N async island processes
  bftpu-run --islands 4 -H a:2,b:2 python async_train.py
                                               # islands across machines
                                               # (shm intra-host, TCP inter)
  bftpu-run --islands 4 --self-heal python async_train.py
                                               # elastic fleet: signal-killed
                                               # ranks respawn as joiners
  bftpu-run --islands 4 --serve-replicas 2 python async_train.py
                                               # + 2 inference replicas
                                               # hot-swapping published
                                               # weight snapshots
  bftpu-run --attach JOB scale +2              # resize a running islands job
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

__all__ = ["main", "build_env", "parse_hosts", "ssh_command", "env_whitelist",
           "control_sock_path"]

# Env forwarded to ssh-spawned ranks, by prefix (the reference forwards an
# explicit whitelist plus every ``-x NAME``; prefixes cover our namespaced
# config the same way).
_FORWARD_PREFIXES = ("BLUEFOG_", "BFTPU_", "JAX_", "XLA_", "PYTHONPATH",
                     "LIBTPU_", "TPU_")


def build_env(args, base_env=None) -> dict:
    """Compute the child environment (separated from exec for testability)."""
    env = dict(os.environ if base_env is None else base_env)
    if args.simulate:
        flags = env.get("XLA_FLAGS", "")
        token = f"--xla_force_host_platform_device_count={args.simulate}"
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " " + token).strip()
        env["JAX_PLATFORMS"] = "cpu"
    if args.verbose:
        env["BLUEFOG_LOG_LEVEL"] = "debug"
    if args.timeline:
        env["BLUEFOG_TIMELINE"] = args.timeline
    if getattr(args, "adaptive", False):
        # islands mode: straggler-aware gossip (resilience/adaptive.py);
        # plain env spelling BFTPU_ADAPTIVE=1 is forwarded anyway
        env["BFTPU_ADAPTIVE"] = "1"
    if getattr(args, "lab_probe", False):
        # islands mode: per-rank convergence probe (lab/probe.py); plain
        # env spelling BFTPU_LAB_PROBE=1 is forwarded anyway
        env["BFTPU_LAB_PROBE"] = "1"
    if getattr(args, "monitor", False):
        # islands mode: spawn the passive fleet monitor next to the
        # workers (monitor/scraper.py); plain env spelling
        # BFTPU_MONITOR=1 is forwarded anyway
        env["BFTPU_MONITOR"] = "1"
    # Multi-host bootstrap: forwarded to jax.distributed.initialize via env
    # (JAX reads these standard variables).
    if args.coordinator:
        env["JAX_COORDINATOR_ADDRESS"] = args.coordinator
    if args.np is not None:
        env["JAX_NUM_PROCESSES"] = str(args.np)
    if args.process_id is not None:
        env["JAX_PROCESS_ID"] = str(args.process_id)
    return env


def parse_hosts(spec: str) -> list:
    """``"hostA:2,hostB:4"`` -> ``[("hostA", 2), ("hostB", 4)]`` (the
    reference's ``-H``/``--hosts`` slot syntax [U]; a bare host means 1)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, slots = part.partition(":")
        if not host:
            raise ValueError(f"bad -H entry {part!r}: empty host")
        try:
            n = int(slots) if slots else 1
        except ValueError:
            raise ValueError(f"bad -H entry {part!r}: slots must be an int")
        if n < 1:
            raise ValueError(f"bad -H entry {part!r}: slots must be >= 1")
        out.append((host, n))
    if not out:
        raise ValueError(f"-H {spec!r} lists no hosts")
    return out


import functools


@functools.lru_cache(maxsize=None)
def _local_names() -> frozenset:
    # computed once: getfqdn can touch DNS
    return frozenset({"localhost", "127.0.0.1", "::1",
                      socket.gethostname(), socket.getfqdn()})


def _is_local_host(host: str) -> bool:
    """True for every name this machine answers to — including its FQDN,
    so `-H thismachine.example.com:4,...` forks locally instead of
    ssh-ing to itself."""
    return host in _local_names()


def env_whitelist(env: dict) -> dict:
    """The subset of ``env`` forwarded across ssh (prefix whitelist)."""
    return {k: v for k, v in env.items()
            if k.startswith(_FORWARD_PREFIXES)}


def ssh_command(host: str, cmd, env: dict, cwd: str,
                pidfile: str = None) -> list:
    """The ssh invocation for one remote rank: non-interactive, forwards
    the env whitelist inline (sshd's AcceptEnv cannot be assumed), recreates
    the working directory, and execs the user command.  ``pidfile`` records
    the remote shell's pid (kept by ``exec``) so teardown can kill the real
    remote process — killing the local ssh client alone would orphan it."""
    pid = f"echo $$ > {shlex.quote(pidfile)}; " if pidfile else ""
    inner = "{}cd {} && exec env {} {}".format(
        pid,
        shlex.quote(cwd),
        " ".join(f"{k}={shlex.quote(v)}" for k, v in sorted(env.items())),
        " ".join(shlex.quote(c) for c in cmd),
    )
    return ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no",
            host, inner]


class _Rank:
    """One spawned rank: the local Popen (the rank itself, or its ssh
    client) plus what remote teardown needs."""

    __slots__ = ("proc", "host", "pidfile")

    def __init__(self, proc, host, pidfile=None):
        self.proc = proc
        self.host = host
        self.pidfile = pidfile

    @property
    def remote(self):
        return self.pidfile is not None


def _spawn_rank(host: str, cmd, child_env: dict, tag: str, r: int) -> _Rank:
    """Spawn one rank: fork locally, ssh for a remote host.  Each child is
    its own process group so a launcher timeout can kill the whole tree."""
    if _is_local_host(host):
        proc = subprocess.Popen(cmd, env=child_env, start_new_session=True)
        return _Rank(proc, host)
    pidfile = f"/tmp/{tag}-r{r}.pid"
    full = ssh_command(host, cmd, env_whitelist(child_env), os.getcwd(),
                       pidfile=pidfile)
    return _Rank(subprocess.Popen(full, start_new_session=True), host, pidfile)


def _ssh_best_effort(host: str, script: str, timeout: float = 15.0):
    """Run a teardown/cleanup snippet on a remote host; failures are
    reported but never raised (the host may be unreachable already)."""
    try:
        subprocess.run(
            ["ssh", "-o", "BatchMode=yes", "-o", "StrictHostKeyChecking=no",
             "-o", "ConnectTimeout=5", host, script],
            timeout=timeout, capture_output=True,
        )
    except Exception as e:  # noqa: BLE001
        print(f"bftpu-run: remote cleanup on {host} failed: {e!r}",
              file=sys.stderr)


def _kill_local(ranks, sig=signal.SIGTERM):
    for rk in ranks:
        if rk.proc.poll() is None:
            try:
                os.killpg(rk.proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                rk.proc.send_signal(sig)


def _kill_remote(ranks, sig="TERM"):
    """Kill the real remote processes via their pidfiles (once, on
    teardown — the local ssh client's death does not reach them)."""
    for rk in ranks:
        if rk.remote and rk.proc.poll() is None:
            pf = shlex.quote(rk.pidfile)
            _ssh_best_effort(
                rk.host,
                f"test -f {pf} && kill -{sig} $(cat {pf}); rm -f {pf}",
            )


def _launch_grace_s() -> float:
    """How long surviving ranks get to finish after a sibling dies
    (``BFTPU_LAUNCH_GRACE_S``, default 5).  With the resilience layer a
    survivor can heal the topology and run to completion — killing it the
    instant a sibling fails would forfeit that; 0 restores the old
    immediate teardown."""
    try:
        return max(0.0, float(os.environ.get("BFTPU_LAUNCH_GRACE_S", "5")))
    except ValueError:
        return 5.0


def _supervise(ranks, timeout: float) -> int:
    """Poll ALL children until done: rank k can die while rank 0 blocks in
    the distributed rendezvous waiting for it — an in-order wait would only
    report the failure after jax's multi-minute init timeout.  On the first
    nonzero exit the survivors get a grace period (they may heal and
    finish — see docs/RESILIENCE.md), then the rest are torn down,
    including the REAL processes behind ssh clients; the FIRST failing
    exit code is what propagates.  ``--timeout`` expiry tears down
    immediately."""
    code = 0
    deadline = time.monotonic() + timeout if timeout else None
    grace_deadline = None
    live = list(ranks)

    def teardown(sig=signal.SIGTERM):
        _kill_remote(ranks)
        _kill_local(ranks, sig)

    try:
        while live:
            for rk in list(live):
                rc = rk.proc.poll()
                if rc is None:
                    continue
                live.remove(rk)
                if rc != 0 and code == 0:
                    code = rc
                    grace = _launch_grace_s()
                    if grace > 0 and live:
                        grace_deadline = time.monotonic() + grace
                        print(
                            f"bftpu-run: a rank failed (exit {rc}); "
                            f"giving {len(live)} surviving rank(s) "
                            f"{grace:g}s to finish", file=sys.stderr)
                    else:
                        teardown()
            if live and grace_deadline is not None \
                    and time.monotonic() > grace_deadline:
                print(f"bftpu-run: grace expired; killing {len(live)} "
                      f"surviving rank(s)", file=sys.stderr)
                grace_deadline = None
                teardown()
            if live and deadline is not None and time.monotonic() > deadline:
                print(f"bftpu-run: timeout after {timeout:g}s; killing "
                      f"{len(live)} live rank(s)", file=sys.stderr)
                teardown()
                time.sleep(2.0)
                _kill_local(ranks, signal.SIGKILL)
                return 124
            if live:
                time.sleep(0.05)
    except KeyboardInterrupt:
        teardown(signal.SIGINT)
        code = 130
    finally:
        # _kill_remote's rm -f only reaches STILL-LIVE ranks, so cleanly
        # exited remote ranks leaked their pidfiles on every return path
        # (incl. timeout).  Collect them here, one ssh per host (idempotent;
        # finally covers the early `return 124` too).
        by_host = {}
        for rk in ranks:
            if rk.remote:
                by_host.setdefault(rk.host, []).append(rk.pidfile)
        for host, pfs in sorted(by_host.items()):
            _ssh_best_effort(
                host, "rm -f " + " ".join(shlex.quote(p) for p in pfs))
    return code


def _supervise_islands(ranks, timeout: float, spawn_joiner, self_heal: bool,
                       state: dict) -> int:
    """:func:`_supervise`, plus the elastic behaviors of an islands run:
    a control-socket ``scale`` request spawns extra JOINER ranks
    mid-run, and with ``--self-heal`` a rank that dies BY SIGNAL
    (SIGKILL'd mid-``win_put``, OOM-killed, ...) is replaced by a fresh
    joiner — never its old global rank, per the monotone dead-set
    contract — while the survivors heal around the corpse.  A rank that
    exits nonzero on its own still fails the run (user-code bugs must
    not loop forever through respawns); the respawn budget
    (``BFTPU_MAX_RESPAWNS``) bounds the healing too."""
    code = 0
    deadline = time.monotonic() + timeout if timeout else None
    grace_deadline = None
    live = list(ranks)
    respawns_left = _respawn_budget()

    def teardown(sig=signal.SIGTERM):
        _kill_remote(ranks)
        _kill_local(ranks, sig)

    try:
        while live:
            with state["lock"]:
                todo = state["scale_requests"]
                state["scale_requests"] = 0
            for _ in range(todo):
                rk = spawn_joiner()
                ranks.append(rk)
                live.append(rk)
                with state["lock"]:
                    state["joiners"] += 1
                print(f"bftpu-run: scale request — spawned joiner "
                      f"(pid {rk.proc.pid})", file=sys.stderr)
            for rk in list(live):
                rc = rk.proc.poll()
                if rc is None:
                    continue
                live.remove(rk)
                if rc < 0 and self_heal and code == 0:
                    if respawns_left > 0:
                        respawns_left -= 1
                        nk = spawn_joiner()
                        ranks.append(nk)
                        live.append(nk)
                        with state["lock"]:
                            state["joiners"] += 1
                        print(
                            f"bftpu-run: rank died on signal {-rc}; "
                            f"self-heal spawned replacement joiner "
                            f"(pid {nk.proc.pid}, "
                            f"{respawns_left} respawn(s) left)",
                            file=sys.stderr)
                        continue
                    print("bftpu-run: respawn budget exhausted "
                          "(BFTPU_MAX_RESPAWNS)", file=sys.stderr)
                if rc != 0 and code == 0:
                    code = rc
                    grace = _launch_grace_s()
                    if grace > 0 and live:
                        grace_deadline = time.monotonic() + grace
                        print(
                            f"bftpu-run: a rank failed (exit {rc}); "
                            f"giving {len(live)} surviving rank(s) "
                            f"{grace:g}s to finish", file=sys.stderr)
                    else:
                        teardown()
            with state["lock"]:
                state["live"] = len(live)
            if live and grace_deadline is not None \
                    and time.monotonic() > grace_deadline:
                print(f"bftpu-run: grace expired; killing {len(live)} "
                      f"surviving rank(s)", file=sys.stderr)
                grace_deadline = None
                teardown()
            if live and deadline is not None and time.monotonic() > deadline:
                print(f"bftpu-run: timeout after {timeout:g}s; killing "
                      f"{len(live)} live rank(s)", file=sys.stderr)
                teardown()
                time.sleep(2.0)
                _kill_local(ranks, signal.SIGKILL)
                return 124
            if live:
                time.sleep(0.05)
    except KeyboardInterrupt:
        teardown(signal.SIGINT)
        code = 130
    return code


def control_sock_path(job: str) -> str:
    """The supervisor's control socket for an islands run — what
    ``bftpu-run --attach JOB`` dials to resize the fleet without a
    restart."""
    return os.path.join(tempfile.gettempdir(), f"bftpu-run-{job}.sock")


def _respawn_budget() -> int:
    """How many signal-killed ranks a ``--self-heal`` run will replace
    (``BFTPU_MAX_RESPAWNS``, default 2) — a budget, not a loop: a rank
    that keeps getting killed should eventually fail the run."""
    try:
        return max(0, int(os.environ.get("BFTPU_MAX_RESPAWNS", "2")))
    except ValueError:
        return 2


class _Control:
    """Line-JSON control server on a unix socket: ``scale`` enqueues
    extra joiner ranks, ``status``/``top`` report the fleet, ``trace``
    publishes the runtime trace-control word.  Handlers only
    enqueue/read — the supervisor loop owns all process state."""

    def __init__(self, job: str, state: dict):
        self.job = job
        self.path = control_sock_path(job)
        try:
            os.unlink(self.path)
        except OSError:
            pass
        self.state = state  # {"lock", "scale_requests", "live", "joiners"}
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.bind(self.path)
        self.sock.listen(4)
        self.thread = threading.Thread(target=self._loop, daemon=True)
        self.thread.start()

    def _handle(self, req: dict) -> dict:
        cmd = req.get("cmd")
        st = self.state
        if cmd == "scale":
            n = int(req.get("n", 1))
            if n < 1:
                return {"ok": False, "error": f"scale n must be >= 1, got {n}"}
            with st["lock"]:
                st["scale_requests"] += n
            return {"ok": True, "queued": n}
        if cmd == "status":
            with st["lock"]:
                return {"ok": True, "live": st["live"],
                        "joiners": st["joiners"],
                        "pending_scale": st["scale_requests"]}
        if cmd == "top":
            # launcher-side half of the bftpu-top view; the client merges
            # this with the shm status pages it reads directly
            with st["lock"]:
                return {"ok": True, "job": self.job, "live": st["live"],
                        "joiners": st["joiners"],
                        "pending_scale": st["scale_requests"]}
        if cmd == "trace":
            from bluefog_tpu.introspect import statuspage as _sp

            mode = {"on": _sp.TRACE_ON, "off": _sp.TRACE_OFF,
                    "default": _sp.TRACE_DEFAULT}.get(req.get("mode"))
            if mode is None:
                return {"ok": False,
                        "error": f"trace mode must be on|off|default, "
                                 f"got {req.get('mode')!r}"}
            gen = _sp.publish_trace_control(self.job, mode)
            return {"ok": True, "mode": req["mode"], "generation": gen}
        return {"ok": False, "error": f"unknown command {cmd!r}"}

    def _loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            try:
                line = conn.makefile("r").readline()
                rep = self._handle(json.loads(line))
            except Exception as e:  # noqa: BLE001 — report, don't die
                rep = {"ok": False, "error": repr(e)}
            try:
                conn.sendall((json.dumps(rep) + "\n").encode())
            except OSError:
                pass
            conn.close()

    def stop(self):
        try:
            self.sock.close()
        except OSError:
            pass
        try:
            os.unlink(self.path)
        except OSError:
            pass


def attach_main(job: str, command) -> int:
    """``bftpu-run --attach JOB [scale +K | status | top .. | trace ..]``
    — the client side of the control socket (``top`` additionally reads
    the shm status pages directly; see ``python -m
    bluefog_tpu.introspect``)."""
    if not command:
        command = ["status"]
    if command[0] == "top":
        from bluefog_tpu.introspect.__main__ import main as top_main

        return top_main(["--job", job] + list(command[1:]))
    if command[0] == "monitor":
        # fleet monitor: scrape daemon / store export / attribution
        # report, all over shm + journals — no control socket needed
        from bluefog_tpu.monitor.__main__ import main as mon_main

        rest = list(command[1:])
        if not any(a in ("--daemon", "--export", "--serve", "--report")
                   for a in rest):
            rest = ["--daemon"] + rest
        return mon_main(["--job", job] + rest)
    if command[0] == "trace":
        if len(command) < 2 or command[1] not in ("on", "off", "default"):
            print("bftpu-run: trace needs a mode: trace on|off|default",
                  file=sys.stderr)
            return 2
        req = {"cmd": "trace", "mode": command[1]}
        path = control_sock_path(job)
        try:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.connect(path)
            s.sendall((json.dumps(req) + "\n").encode())
            line = s.makefile("r").readline()
            s.close()
            print(line.strip())
            return 0 if json.loads(line).get("ok") else 1
        except (OSError, ValueError):
            # no launcher (e.g. the job was spawned in-process): publish
            # the trace-control word directly — workers poll the word,
            # not the socket
            from bluefog_tpu.introspect import statuspage as _sp

            mode = {"on": _sp.TRACE_ON, "off": _sp.TRACE_OFF,
                    "default": _sp.TRACE_DEFAULT}[command[1]]
            gen = _sp.publish_trace_control(job, mode)
            print(json.dumps({"ok": True, "mode": command[1],
                              "generation": gen, "via": "word"}))
            return 0
    if command[0] == "scale":
        if len(command) < 2:
            print("bftpu-run: scale needs a count: scale +K",
                  file=sys.stderr)
            return 2
        try:
            n = int(command[1].lstrip("+"))
        except ValueError:
            print(f"bftpu-run: bad scale count {command[1]!r}",
                  file=sys.stderr)
            return 2
        req = {"cmd": "scale", "n": n}
    elif command[0] == "status":
        req = {"cmd": "status"}
    else:
        print(f"bftpu-run: unknown control command {command[0]!r} "
              "(expected: scale +K, status, top, monitor, "
              "trace on|off|default)",
              file=sys.stderr)
        return 2
    path = control_sock_path(job)
    try:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.connect(path)
        s.sendall((json.dumps(req) + "\n").encode())
        line = s.makefile("r").readline()
        s.close()
    except OSError as e:
        print(f"bftpu-run: cannot reach {path} — is the islands run "
              f"still up? ({e})", file=sys.stderr)
        return 1
    print(line.strip())
    try:
        return 0 if json.loads(line).get("ok") else 1
    except ValueError:
        return 1


def _pick_port() -> int:
    """An ephemeral port for the rendezvous.  Bind-then-close is a TOCTOU
    (another process may grab it before the children bind), and for a
    REMOTE head host the probe says nothing at all — both launch paths
    therefore retry once with a fresh port when every child dies
    immediately (the observable signature of a rendezvous bind failure)."""
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _head_address(by_rank) -> str:
    """The rendezvous host every rank can reach.  Loopback only works when
    all ranks share this machine; a locally-spelled first host must be
    replaced with this machine's externally reachable name when any rank
    is remote."""
    if all(_is_local_host(h) for h in by_rank):
        return "127.0.0.1"
    head = by_rank[0]
    return socket.getfqdn() if _is_local_host(head) else head


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bftpu-run",
        description="Launch a bluefog_tpu training script on a TPU slice "
        "(or a simulated CPU mesh).",
    )
    parser.add_argument(
        "-np",
        type=int,
        default=None,
        help="total number of processes (multi-host; maps to JAX_NUM_PROCESSES)",
    )
    parser.add_argument(
        "-H", "--hosts",
        default=None,
        metavar="HOST:SLOTS,...",
        help="host list with slot counts (reference bfrun -H [U]): ranks "
        "are spawned host-major, over ssh for remote hosts.  Works with "
        "-np (counts must agree) and with --islands (sets the hostmap so "
        "window traffic rides shm intra-host and TCP inter-host)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="coordinator address host:port for multi-host rendezvous",
    )
    parser.add_argument(
        "--process-id", type=int, default=None, help="this process's index"
    )
    parser.add_argument(
        "--timeout",
        type=float,
        default=0.0,
        help="kill the whole launch after this many seconds (0 = no limit); "
        "guards against a child hanging in the distributed rendezvous",
    )
    parser.add_argument(
        "--simulate",
        type=int,
        default=0,
        metavar="N",
        help="run on N virtual CPU devices instead of TPU (testing)",
    )
    parser.add_argument(
        "--islands",
        type=int,
        default=0,
        metavar="N",
        help="spawn N asynchronous island processes (bluefog_tpu.islands): "
        "each gets BLUEFOG_ISLAND_RANK/SIZE/JOB and steps independently — "
        "the direct analogue of the reference's `bfrun -np N` process model",
    )
    parser.add_argument(
        "--job",
        default=None,
        help="island job name (shared-memory namespace); default: pid-derived",
    )
    parser.add_argument(
        "--self-heal",
        action="store_true",
        help="islands mode: replace a signal-killed rank with a fresh "
        "joiner process (up to BFTPU_MAX_RESPAWNS) instead of failing "
        "the run — the survivors heal, the replacement rejoins under a "
        "new global rank",
    )
    parser.add_argument(
        "--adaptive",
        action="store_true",
        help="islands mode: enable the adaptive edge-health control loop "
        "(BFTPU_ADAPTIVE=1) — deadline-missed edges are absorbed per "
        "round and a persistently slow rank is demoted to one anchor "
        "edge instead of convoying the fleet (docs/RESILIENCE.md, "
        "'Adaptive topology')",
    )
    parser.add_argument(
        "--lab-probe",
        action="store_true",
        help="islands mode: stream the per-rank convergence probe "
        "(BFTPU_LAB_PROBE=1) — each win_update publishes the debiased "
        "consensus error to telemetry and the status page's CONV "
        "column (docs/OBSERVABILITY.md, 'Convergence observatory')",
    )
    parser.add_argument(
        "--monitor",
        action="store_true",
        help="islands mode: spawn the passive fleet monitor "
        "(BFTPU_MONITOR=1) — a scrape daemon polling every rank's "
        "status page, retaining time series in an mmap'd store and "
        "raising declarative alerts (docs/OBSERVABILITY.md, "
        "'Fleet monitor'); attach later with "
        "bftpu-run --attach JOB monitor",
    )
    parser.add_argument(
        "--serve-replicas",
        type=int,
        default=0,
        metavar="K",
        help="islands mode: spawn K inference replica processes "
        "(python -m bluefog_tpu.serve) subscribed to the job's snapshot "
        "region — each hot-swaps to every version the training fleet "
        "publishes via islands.serve_publish, with zero serving "
        "downtime; replicas are torn down when the fleet exits "
        "(docs/SERVING.md)",
    )
    parser.add_argument(
        "--serve-remote",
        default=None,
        metavar="HOST:PORT",
        help="islands mode, with --serve-replicas: attach the replicas "
        "over TCP through the snapshot distribution tree rooted at the "
        "given publisher feed address, instead of the local shm region "
        "— the cross-host read path (each replica joins the tree, "
        "feeds off its assigned parent, and relays to its children; "
        "docs/SERVING.md, 'Cross-host distribution')",
    )
    parser.add_argument(
        "--attach",
        default=None,
        metavar="JOB",
        help="dial a running islands job's control socket instead of "
        "launching: `bftpu-run --attach JOB scale +K` admits K extra "
        "ranks, `... status` reports the fleet, `... top` opens the "
        "live bftpu-top view, `... trace on|off` toggles tracing at "
        "runtime",
    )
    parser.add_argument("--timeline", default=None, help="write a Chrome trace here")
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER, help="program to run")
    args = parser.parse_args(argv)

    if args.attach:
        cmd = args.command
        if cmd and cmd[0] == "--":
            cmd = cmd[1:]
        return attach_main(args.attach, cmd)
    if not args.command:
        parser.error("no command given; usage: bftpu-run [options] python train.py")
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]

    hosts = parse_hosts(args.hosts) if args.hosts else None
    if hosts is not None:
        total = sum(s for _, s in hosts)
        if args.islands:
            if args.islands != total:
                parser.error(f"--islands {args.islands} but -H lists {total} slots")
        elif args.np is None:
            args.np = total
        elif args.np != total:
            parser.error(f"-np {args.np} but -H lists {total} slots")

    if args.serve_replicas and not args.islands:
        parser.error("--serve-replicas requires --islands (the snapshot "
                     "region is published by an islands fleet)")
    if args.monitor and not args.islands:
        parser.error("--monitor requires --islands (the scraper polls "
                     "the fleet's per-rank status pages)")
    if args.serve_remote and not args.serve_replicas:
        parser.error("--serve-remote requires --serve-replicas (it "
                     "selects how those replicas attach)")
    env = build_env(args)
    if args.islands:
        return _run_islands(cmd, env, args.islands, args.job, hosts,
                            args.timeout, self_heal=args.self_heal,
                            serve_replicas=args.serve_replicas,
                            serve_remote=args.serve_remote)
    if args.np is not None and args.np > 1 and args.process_id is None:
        # `-np N` with no explicit process id: WE are the process launcher
        # (the reference's `bfrun -np N` execs mpirun which forks the ranks
        # [U]; here each child is one jax.distributed process)
        return _run_multiprocess(cmd, env, args.np, args.coordinator, hosts,
                                 args.timeout)
    try:
        os.execvpe(cmd[0], cmd, env)
    except FileNotFoundError:
        print(f"bftpu-run: command not found: {cmd[0]}", file=sys.stderr)
        return 127


def _rank_hosts(hosts, nprocs: int) -> list:
    """Host of each rank, host-major (``-H a:2,b:2`` -> a,a,b,b)."""
    if hosts is None:
        return ["localhost"] * nprocs
    out = []
    for host, slots in hosts:
        out.extend([host] * slots)
    return out


def _run_multiprocess(cmd, env, nprocs: int, coordinator, hosts,
                      timeout: float) -> int:
    """Spawn ``nprocs`` jax.distributed processes: locally (the CPU-mesh
    integration mode) or across machines with ``-H`` (ssh for remote
    hosts, the reference's mpirun shape [U])."""
    by_rank = _rank_hosts(hosts, nprocs)
    tag = f"bfrun-{os.getpid()}-{int(time.time())}"
    code = 1
    for attempt in (0, 1):
        coord = coordinator
        if coord is None:
            coord = f"{_head_address(by_rank)}:{_pick_port()}"
        t0 = time.monotonic()
        ranks = []
        for r in range(nprocs):
            child_env = dict(env)
            child_env["JAX_COORDINATOR_ADDRESS"] = coord
            child_env["JAX_NUM_PROCESSES"] = str(nprocs)
            child_env["JAX_PROCESS_ID"] = str(r)
            ranks.append(_spawn_rank(by_rank[r], cmd, child_env, tag, r))
        code = _supervise(ranks, timeout)
        if (code not in (0, 124) and coordinator is None and attempt == 0
                and time.monotonic() - t0 < 20.0):
            # every child died almost immediately: the classic signature of
            # a rendezvous bind failure (local _pick_port TOCTOU, or the
            # probed port not being free on a remote head) — retry once
            print("bftpu-run: launch failed fast; retrying with a fresh "
                  "rendezvous port", file=sys.stderr)
            continue
        return code
    return code


def _cleanup_island_segments(job: str, by_rank) -> None:
    """Reclaim the job's shm segments on EVERY host: a later run reusing
    the job name must never attach to stale mailboxes/barrier state.
    Remote hosts get a best-effort ssh cleanup (same env whitelist, so
    PYTHONPATH reaches the package)."""
    from bluefog_tpu.native import shm_native

    shm_native.unlink_all(job)
    pypath = os.environ.get("PYTHONPATH", "")
    snippet = (
        "from bluefog_tpu.native import shm_native; "
        f"shm_native.unlink_all({job!r})"
    )
    for host in sorted({h for h in by_rank if not _is_local_host(h)}):
        _ssh_best_effort(
            host,
            "env PYTHONPATH={} {} -c {}".format(
                shlex.quote(pypath), shlex.quote(sys.executable or "python3"),
                shlex.quote(snippet),
            ),
        )


def _collect_telemetry(env: dict, job: str) -> None:
    """Best-effort cross-rank aggregation: merge the per-rank snapshot
    files the ranks wrote at exit into one summary (JSON + Prometheus
    text).  No-op when BFTPU_TELEMETRY is off; never fails the run."""
    try:
        from bluefog_tpu.telemetry.merge import merge_job_snapshots

        out = merge_job_snapshots(env.get("BFTPU_TELEMETRY"), job)
        if out:
            print(f"bftpu-run: telemetry merged -> {out}", file=sys.stderr)
    except Exception as e:  # telemetry must never mask the run's exit code
        print(f"bftpu-run: telemetry merge failed: {e}", file=sys.stderr)


def _collect_traces(env: dict, job: str) -> None:
    """Best-effort trace post-processing: convert flight rings left by
    ranks that died without dumping (SIGKILL), then stitch the per-rank
    span buffers into one merged Chrome trace.  No-op when BFTPU_TRACING
    is off; never fails the run."""
    raw = env.get("BFTPU_TRACING", "")
    if not raw or raw == "0":
        return
    try:
        from bluefog_tpu import tracing as _tracing
        from bluefog_tpu.tracing.tracer import _DEFAULT_DIR

        d = _DEFAULT_DIR if raw == "1" else raw
        if not d or not os.path.isdir(d):
            return
        converted = _tracing.convert_flight_rings(job, d, reason="launcher")
        for p in converted:
            print(f"bftpu-run: flight ring recovered -> {p}",
                  file=sys.stderr)
        traces = []
        for p in _tracing.find_traces([d]):
            try:
                t = _tracing.load_trace(p)
            except (OSError, ValueError):
                continue
            if t is not None and t.get("job") == job:
                traces.append(t)
        if not traces:
            return
        merged = _tracing.merge_traces(traces)
        out = os.path.join(d, f"merged-trace-{job}.json")
        with open(out, "w", encoding="utf-8") as f:
            json.dump(merged, f)
            f.write("\n")
        print(f"bftpu-run: traces merged ({len(traces)} ranks) -> {out}",
              file=sys.stderr)
    except Exception as e:  # tracing must never mask the run's exit code
        print(f"bftpu-run: trace merge failed: {e}", file=sys.stderr)


def _run_islands(cmd, env, nranks: int, job, hosts, timeout: float,
                 self_heal: bool = False, serve_replicas: int = 0,
                 serve_remote=None) -> int:
    """Fork N island processes (the `mpirun -np N` shape of the reference's
    launcher [U]).  With ``-H``, ranks spawn on their hosts over ssh and
    the hostmap/coordinator env is set so window traffic rides shared
    memory intra-host and TCP inter-host (routed transport).  Returns the
    first nonzero child exit code, tearing the others down on failure.

    Single-host runs are ELASTIC: a control socket
    (:func:`control_sock_path`) accepts ``scale`` requests from
    ``bftpu-run --attach JOB scale +K``, and ``--self-heal`` replaces
    signal-killed ranks with fresh joiner processes
    (``BLUEFOG_ISLAND_JOINER=1`` routes ``islands.init`` to
    ``islands.join``).  Multi-host fleets keep the fixed-size
    supervisor — cross-host respawn placement is not implemented."""
    job = job or f"bfrun{os.getpid()}"
    by_rank = _rank_hosts(hosts, nranks)
    multi_host = hosts is not None and len(set(by_rank)) > 1
    tag = f"bfrun-{os.getpid()}-{int(time.time())}"
    code = 1

    def spawn_joiner() -> _Rank:
        jc = dict(env)
        jc.pop("BLUEFOG_ISLAND_RANK", None)
        jc["BLUEFOG_ISLAND_JOINER"] = "1"
        jc["BLUEFOG_ISLAND_SIZE"] = str(nranks)
        jc["BLUEFOG_ISLAND_JOB"] = job
        spawn_joiner.idx += 1
        return _spawn_rank("localhost", cmd, jc, tag,
                           10000 + spawn_joiner.idx)

    spawn_joiner.idx = 0

    for attempt in (0, 1):
        coord = (f"{_head_address(by_rank)}:{_pick_port()}"
                 if multi_host else None)
        t0 = time.monotonic()
        ranks = []
        for r in range(nranks):
            child_env = dict(env)
            child_env["BLUEFOG_ISLAND_RANK"] = str(r)
            child_env["BLUEFOG_ISLAND_SIZE"] = str(nranks)
            child_env["BLUEFOG_ISLAND_JOB"] = job
            if multi_host:
                child_env["BLUEFOG_ISLAND_HOSTMAP"] = ",".join(by_rank)
                child_env["BLUEFOG_ISLAND_COORD"] = coord
                # EVERY rank must advertise an address its remote peers
                # can dial: remote ranks their host name, locally-forked
                # ranks this machine's reachable name — never the
                # loopback the transport would otherwise default to
                child_env["BLUEFOG_ISLAND_HOST"] = (
                    socket.getfqdn() if _is_local_host(by_rank[r])
                    else by_rank[r])
            ranks.append(_spawn_rank(by_rank[r], cmd, child_env, tag, r))
        # serving fleet: local replica processes subscribed to the
        # job's snapshot region.  They poll until the first publish
        # lands, hot-swap each version, and are torn down with the
        # fleet — a replica exiting never fails the training run.
        serve_procs = []
        for i in range(serve_replicas):
            rc = dict(env)
            rc["BFTPU_SERVE_REPLICAS"] = str(serve_replicas)
            serve_cmd = [sys.executable, "-m", "bluefog_tpu.serve",
                         "--job", job, "--replica-id", str(i)]
            if serve_remote:
                # cross-host attach: feed through the distribution
                # tree rooted at the publisher's feed address instead
                # of the local shm region
                rc["BFTPU_SERVE_REMOTE"] = serve_remote
                serve_cmd += ["--remote", serve_remote]
            serve_procs.append(subprocess.Popen(serve_cmd, env=rc))
        # fleet monitor: one passive scrape daemon per job.  It only
        # reads seqlock'd pages and journals, exits on its own once the
        # fleet's pages are reclaimed, and is SIGTERMed with the serve
        # procs — a monitor dying never fails the training run.
        if env.get("BFTPU_MONITOR", "0") not in ("", "0"):
            mc = dict(env)
            mc["BLUEFOG_ISLAND_JOB"] = job
            serve_procs.append(subprocess.Popen(
                [sys.executable, "-m", "bluefog_tpu.monitor",
                 "--job", job, "--daemon"], env=mc))
        control = None
        try:
            if multi_host:
                code = _supervise(ranks, timeout)
            else:
                state = {"lock": threading.Lock(), "scale_requests": 0,
                         "live": len(ranks), "joiners": 0}
                try:
                    control = _Control(job, state)
                except OSError as e:
                    print(f"bftpu-run: control socket unavailable ({e}); "
                          "run is not resizable", file=sys.stderr)
                code = _supervise_islands(ranks, timeout, spawn_joiner,
                                          self_heal, state)
        finally:
            if control is not None:
                control.stop()
            for p in serve_procs:
                if p.poll() is None:
                    p.terminate()
            for p in serve_procs:
                try:
                    p.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    p.kill()
            _cleanup_island_segments(job, by_rank)
            _collect_telemetry(env, job)
            _collect_traces(env, job)
        if (code not in (0, 124, 130) and multi_host and attempt == 0
                and time.monotonic() - t0 < 20.0):
            # same fast-failure signature as _run_multiprocess: the TCP
            # rendezvous port may not have been free on the head host
            print("bftpu-run: islands launch failed fast; retrying with a "
                  "fresh rendezvous port", file=sys.stderr)
            continue
        return code
    return code


if __name__ == "__main__":
    sys.exit(main())
