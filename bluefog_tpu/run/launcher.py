"""``bftpu-run`` — TPU-slice launcher, sibling of the reference's ``bfrun``.

The reference's ``bfrun`` (``bluefog/run/run.py`` [U], SURVEY.md §3.5)
assembles and execs an ``mpirun`` command: NIC probing, env forwarding,
one process per rank.  On TPU pods the platform already provides the
process-per-host convention and rendezvous (``jax.distributed.initialize``
auto-configures from the TPU environment), so the launcher's job shrinks
to: validate the environment, set Bluefog env vars, optionally configure a
multi-process CPU simulation, and exec the training script.

Usage:
  bftpu-run python train.py                    # on a TPU host/pod worker
  bftpu-run --simulate 8 python train.py       # 8 virtual CPU devices
  bftpu-run -np 4 --coordinator host:port --process-id K python train.py
                                               # explicit multi-host bootstrap
  bftpu-run --islands 4 python async_train.py  # N async island processes
                                               # (bluefog_tpu.islands jobs —
                                               # the ``mpirun -np N`` shape)
"""

from __future__ import annotations

import argparse
import os
import sys

__all__ = ["main", "build_env"]


def build_env(args, base_env=None) -> dict:
    """Compute the child environment (separated from exec for testability)."""
    env = dict(os.environ if base_env is None else base_env)
    if args.simulate:
        flags = env.get("XLA_FLAGS", "")
        token = f"--xla_force_host_platform_device_count={args.simulate}"
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (flags + " " + token).strip()
        env["JAX_PLATFORMS"] = "cpu"
    if args.verbose:
        env["BLUEFOG_LOG_LEVEL"] = "debug"
    if args.timeline:
        env["BLUEFOG_TIMELINE"] = args.timeline
    # Multi-host bootstrap: forwarded to jax.distributed.initialize via env
    # (JAX reads these standard variables).
    if args.coordinator:
        env["JAX_COORDINATOR_ADDRESS"] = args.coordinator
    if args.np is not None:
        env["JAX_NUM_PROCESSES"] = str(args.np)
    if args.process_id is not None:
        env["JAX_PROCESS_ID"] = str(args.process_id)
    return env


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bftpu-run",
        description="Launch a bluefog_tpu training script on a TPU slice "
        "(or a simulated CPU mesh).",
    )
    parser.add_argument(
        "-np",
        type=int,
        default=None,
        help="total number of processes (multi-host; maps to JAX_NUM_PROCESSES)",
    )
    parser.add_argument(
        "--coordinator",
        default=None,
        help="coordinator address host:port for multi-host rendezvous",
    )
    parser.add_argument(
        "--process-id", type=int, default=None, help="this process's index"
    )
    parser.add_argument(
        "--simulate",
        type=int,
        default=0,
        metavar="N",
        help="run on N virtual CPU devices instead of TPU (testing)",
    )
    parser.add_argument(
        "--islands",
        type=int,
        default=0,
        metavar="N",
        help="spawn N asynchronous island processes (bluefog_tpu.islands): "
        "each gets BLUEFOG_ISLAND_RANK/SIZE/JOB and steps independently — "
        "the direct analogue of the reference's `bfrun -np N` process model",
    )
    parser.add_argument(
        "--job",
        default=None,
        help="island job name (shared-memory namespace); default: pid-derived",
    )
    parser.add_argument("--timeline", default=None, help="write a Chrome trace here")
    parser.add_argument("-v", "--verbose", action="store_true")
    parser.add_argument("command", nargs=argparse.REMAINDER, help="program to run")
    args = parser.parse_args(argv)

    if not args.command:
        parser.error("no command given; usage: bftpu-run [options] python train.py")
    cmd = args.command
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    env = build_env(args)
    if args.islands:
        return _run_islands(cmd, env, args.islands, args.job)
    if args.np is not None and args.np > 1 and args.process_id is None:
        # `-np N` with no explicit process id: WE are the process launcher
        # (the reference's `bfrun -np N` execs mpirun which forks the ranks
        # [U]; here each child is one jax.distributed process)
        return _run_multiprocess(cmd, env, args.np, args.coordinator)
    try:
        os.execvpe(cmd[0], cmd, env)
    except FileNotFoundError:
        print(f"bftpu-run: command not found: {cmd[0]}", file=sys.stderr)
        return 127


def _run_multiprocess(cmd, env, nprocs: int, coordinator: str | None) -> int:
    """Spawn ``nprocs`` local jax.distributed processes (single-host
    multi-process: the CPU-mesh integration mode, and one-host-many-
    processes TPU debugging).  Real multi-host runs invoke bftpu-run once
    per host with an explicit ``--process-id`` instead."""
    import socket
    import subprocess

    if coordinator is None:
        # pick a free port for the rendezvous on this host
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coordinator = f"127.0.0.1:{s.getsockname()[1]}"
    import time

    procs = []
    for r in range(nprocs):
        child_env = dict(env)
        child_env["JAX_COORDINATOR_ADDRESS"] = coordinator
        child_env["JAX_NUM_PROCESSES"] = str(nprocs)
        child_env["JAX_PROCESS_ID"] = str(r)
        procs.append(subprocess.Popen(cmd, env=child_env))
    code = 0
    # poll ALL children: rank k can die while rank 0 blocks in the
    # distributed rendezvous waiting for it — an in-order wait would only
    # report the failure after jax's multi-minute init timeout
    live = list(procs)
    while live:
        for p in list(live):
            rc = p.poll()
            if rc is None:
                continue
            live.remove(p)
            if rc != 0 and code == 0:
                code = rc
                for q in procs:
                    if q.poll() is None:
                        q.terminate()
        if live:
            time.sleep(0.05)
    return code


def _run_islands(cmd, env, nranks: int, job: str | None) -> int:
    """Fork N child processes, one island each (the `mpirun -np N` shape of
    the reference's launcher [U], minus ssh/NIC plumbing: islands on one
    host talk through shared memory).  Returns the first nonzero child exit
    code, and tears the others down on failure."""
    import signal
    import subprocess

    job = job or f"bfrun{os.getpid()}"
    procs = []
    for r in range(nranks):
        child_env = dict(env)
        child_env["BLUEFOG_ISLAND_RANK"] = str(r)
        child_env["BLUEFOG_ISLAND_SIZE"] = str(nranks)
        child_env["BLUEFOG_ISLAND_JOB"] = job
        procs.append(subprocess.Popen(cmd, env=child_env))
    code = 0
    try:
        # poll ALL children: a rank can fail while its siblings are blocked
        # in the shm barrier, so waiting in rank order would hang forever
        import time as _time

        live = list(procs)
        while live:
            for p in list(live):
                rc = p.poll()
                if rc is None:
                    continue
                live.remove(p)
                if rc != 0 and code == 0:
                    code = rc
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
            if live:
                _time.sleep(0.05)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGINT)
        code = 130
    finally:
        # reclaim the job's segments on EVERY path: a later run reusing the
        # job name must never attach to stale mailboxes/barrier state
        from bluefog_tpu.native import shm_native

        shm_native.unlink_all(job)
    return code


if __name__ == "__main__":
    sys.exit(main())
