"""mmap'd time-series store: per-(series, subject) ring buffers with
downsampling tiers.

The scraper is a separate process that can die (or be killed) without
taking history with it, so the store lives in an mmap'd segment under
the job's shm namespace (``bf_<job>_monitor``) — the same fallback-
segment machinery the status pages use.  Anyone can re-attach later
(``python -m bluefog_tpu.monitor --export``) and read what the dead
monitor retained; :func:`bluefog_tpu.native.shm_native.unlink_all`
reclaims it with the rest of the job's segments because it rides the
``seg_name`` prefix.

Layout (little-endian, all offsets fixed by the header so readers of a
different build can still walk it):

* header — magic ``BFMN``, layout version, one global u64 seqlock,
  slot count and the three tier capacities;
* slot directory — ``nslots`` entries of (48-byte key, three u64
  append counters), key = ``"<series>|<subject>"``, zero key = free;
* data — per slot, three contiguous rings of ``(t_wall, value)`` f64
  pairs: **raw** (every sample), **mid** (mean of every 10 raw), and
  **coarse** (mean of every 10 mid) — so with a 1 s scrape cadence the
  default 240/120/60 rings retain 4 minutes at full rate, 20 minutes
  at 10×, and 100 minutes at 100×.

Downsample accumulators are writer-process state, not persisted: a
monitor death loses at most one partial mean bucket per tier, never a
committed point.  Writers bump the seqlock odd around every append;
readers double-read and retry, exactly the status-page discipline —
one writer, any number of passive readers, no locks held while a
reader is looking.

Sizing comes from ``BFTPU_MON_SLOTS`` (distinct (series, subject)
pairs, default 256) and ``BFTPU_MON_RING`` (raw ring capacity, default
240; mid/coarse derive as /2 and /4).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict, List, Optional, Tuple

from bluefog_tpu.native import shm_native

__all__ = ["MonitorStore", "STORE_MAGIC", "STORE_VERSION", "STORE_SCHEMA"]

STORE_MAGIC = 0x42464D4E  # "BFMN"
STORE_VERSION = 1
STORE_SCHEMA = "bftpu-monitor/1"

_HEAD = struct.Struct("<IIQIIII")  # magic, version, seq, nslots, caps x3
_DIR = struct.Struct("<48sQQQ")    # key, append counters raw/mid/coarse
_POINT = struct.Struct("<dd")      # (t_wall, value)

TIERS = ("raw", "mid", "coarse")
_BUCKET = 10  # raw→mid and mid→coarse downsample factor


def _env_int(key: str, default: int, lo: int, hi: int) -> int:
    try:
        v = int(os.environ.get(key, "") or default)
    except ValueError:
        v = default
    return max(lo, min(hi, v))


def store_path(job: str) -> str:
    return os.path.join(shm_native._FALLBACK_DIR,
                        shm_native.seg_name(job, "monitor")[1:])


class MonitorStore:
    """One writer (the scraper / sim twin), many passive readers.

    ``create=True`` initializes the header (idempotent: an existing
    compatible segment is adopted, counters intact, so a respawned
    monitor continues the same history).  ``create=False`` attaches
    read-only semantics — raises ``FileNotFoundError`` when no monitor
    ever ran for the job.
    """

    def __init__(self, job: str, *, create: bool = False,
                 nslots: Optional[int] = None,
                 cap_raw: Optional[int] = None):
        self.job = job
        self.path = store_path(job)
        if not create and not os.path.exists(self.path):
            raise FileNotFoundError(
                f"no monitor store for job {job!r} ({self.path})")
        nslots = (_env_int("BFTPU_MON_SLOTS", 256, 8, 65536)
                  if nslots is None else int(nslots))
        cap_raw = (_env_int("BFTPU_MON_RING", 240, 8, 1 << 20)
                   if cap_raw is None else int(cap_raw))
        caps = (cap_raw, max(4, cap_raw // 2), max(2, cap_raw // 4))
        size = (_HEAD.size + nslots * _DIR.size
                + nslots * sum(caps) * _POINT.size)
        self._seg = shm_native._FallbackSegment(self.path, max(
            size, os.path.getsize(self.path) if os.path.exists(self.path)
            else 0))
        magic, version, _, n, c0, c1, c2 = _HEAD.unpack_from(self._seg._mm, 0)
        if magic == STORE_MAGIC and version == STORE_VERSION:
            # Adopt the existing geometry — it wins over env/args.
            self.nslots, self.caps = n, (c0, c1, c2)
        elif create and magic == 0:
            self.nslots, self.caps = nslots, caps
            _HEAD.pack_into(self._seg._mm, 0, STORE_MAGIC, STORE_VERSION,
                            0, nslots, *caps)
        else:
            self._seg.close()
            raise ValueError(
                f"monitor store {self.path} has foreign magic/version "
                f"{magic:#x}/{version}")
        self._dir_off = _HEAD.size
        self._data_off = self._dir_off + self.nslots * _DIR.size
        self._slot_bytes = sum(self.caps) * _POINT.size
        self._slots: Dict[str, int] = {}
        self._accum: Dict[Tuple[int, int], List[float]] = {}
        for i in range(self.nslots):
            key = self._key_at(i)
            if key:
                self._slots[key] = i

    # -- geometry ---------------------------------------------------------

    def _key_at(self, slot: int) -> str:
        raw = _DIR.unpack_from(self._seg._mm,
                               self._dir_off + slot * _DIR.size)[0]
        return raw.rstrip(b"\x00").decode("utf-8", "replace")

    def _counts_at(self, slot: int) -> Tuple[int, int, int]:
        e = _DIR.unpack_from(self._seg._mm, self._dir_off + slot * _DIR.size)
        return e[1], e[2], e[3]

    def _ring_off(self, slot: int, tier: int) -> int:
        return (self._data_off + slot * self._slot_bytes
                + sum(self.caps[:tier]) * _POINT.size)

    # -- seqlock ----------------------------------------------------------

    def _seq(self) -> int:
        return struct.unpack_from("<Q", self._seg._mm, 8)[0]

    def _bump(self) -> None:
        struct.pack_into("<Q", self._seg._mm, 8, self._seq() + 1)

    # -- writer -----------------------------------------------------------

    def append(self, series: str, subject, t_wall: float,
               value: float) -> None:
        """Append one raw point (and any downsampled means it completes)
        under a single odd/even seqlock bump."""
        key = f"{series}|{subject}"[:47]
        slot = self._slots.get(key)
        self._bump()  # odd: writers in flight
        try:
            if slot is None:
                slot = self._alloc(key)
                if slot is None:
                    return  # directory full: drop newest series, keep run
            self._push(slot, 0, float(t_wall), float(value))
            self._downsample(slot, 0, float(t_wall), float(value))
        finally:
            self._bump()  # even: quiescent

    def _alloc(self, key: str) -> Optional[int]:
        for i in range(self.nslots):
            if not self._key_at(i):
                _DIR.pack_into(self._seg._mm,
                               self._dir_off + i * _DIR.size,
                               key.encode("utf-8")[:48], 0, 0, 0)
                self._slots[key] = i
                return i
        return None

    def _push(self, slot: int, tier: int, t: float, v: float) -> None:
        off = self._dir_off + slot * _DIR.size
        entry = list(_DIR.unpack_from(self._seg._mm, off))
        count = entry[1 + tier]
        idx = count % self.caps[tier]
        _POINT.pack_into(self._seg._mm,
                         self._ring_off(slot, tier) + idx * _POINT.size,
                         t, v)
        entry[1 + tier] = count + 1
        _DIR.pack_into(self._seg._mm, off, *entry)

    def _downsample(self, slot: int, tier: int, t: float, v: float) -> None:
        if tier + 1 >= len(self.caps):
            return
        acc = self._accum.setdefault((slot, tier), [0.0, 0.0, 0.0])
        acc[0] += t
        acc[1] += v
        acc[2] += 1.0
        if acc[2] >= _BUCKET:
            mt, mv = acc[0] / acc[2], acc[1] / acc[2]
            self._accum[(slot, tier)] = [0.0, 0.0, 0.0]
            self._push(slot, tier + 1, mt, mv)
            self._downsample(slot, tier + 1, mt, mv)

    # -- reader -----------------------------------------------------------

    def _read_ring(self, slot: int, tier: int, count: int) -> List[
            Tuple[float, float]]:
        cap = self.caps[tier]
        n = min(count, cap)
        start = count - n
        out = []
        base = self._ring_off(slot, tier)
        for k in range(start, count):
            t, v = _POINT.unpack_from(self._seg._mm,
                                      base + (k % cap) * _POINT.size)
            out.append((t, v))
        return out

    def snapshot(self, retries: int = 8) -> Dict[str, Dict[str, list]]:
        """Consistent read of every slot: ``{key: {tier: [(t, v), ...]}}``.
        Retries on seqlock motion; a persistently-busy writer degrades
        to a best-effort read rather than raising (monitoring must not
        wedge on monitoring)."""
        out: Dict[str, Dict[str, list]] = {}
        for _ in range(max(1, retries)):
            s0 = self._seq()
            if s0 & 1:
                continue
            out = {}
            for i in range(self.nslots):
                key = self._key_at(i)
                if not key:
                    continue
                counts = self._counts_at(i)
                out[key] = {tier: self._read_ring(i, t, counts[t])
                            for t, tier in enumerate(TIERS)}
            if self._seq() == s0:
                return out
        return out

    # -- export -----------------------------------------------------------

    def to_json(self) -> dict:
        snap = self.snapshot()
        series = []
        for key in sorted(snap):
            name, _, subject = key.partition("|")
            series.append({"series": name, "subject": subject,
                           "tiers": snap[key]})
        return {"schema": STORE_SCHEMA, "job": self.job,
                "nslots": self.nslots, "caps": list(self.caps),
                "series": series}

    def to_prometheus(self) -> str:
        """Prometheus text exposition: latest raw point per slot as a
        gauge, plus the sample count as a counter."""
        snap = self.snapshot()
        lines: List[str] = []
        seen_help = set()
        for key in sorted(snap):
            name, _, subject = key.partition("|")
            metric = "bftpu_mon_" + "".join(
                c if c.isalnum() or c == "_" else "_" for c in name)
            raw = snap[key]["raw"]
            if not raw:
                continue
            if metric not in seen_help:
                lines.append(f"# TYPE {metric} gauge")
                seen_help.add(metric)
            t, v = raw[-1]
            label = subject.replace("\\", "\\\\").replace('"', '\\"')
            lines.append(f'{metric}{{subject="{label}"}} {v:.17g} '
                         f"{int(t * 1000)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def close(self, unlink: bool = False) -> None:
        self._seg.close(unlink=unlink)


def export_json(job: str) -> dict:
    store = MonitorStore(job)
    try:
        return store.to_json()
    finally:
        store.close()


def export_prometheus(job: str) -> str:
    store = MonitorStore(job)
    try:
        return store.to_prometheus()
    finally:
        store.close()
