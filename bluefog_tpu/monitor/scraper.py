"""The passive fleet scraper: status pages → time series → alerts.

One daemon per job (spawned by ``bftpu-run --monitor`` /
``BFTPU_MONITOR=1``, or attached after the fact with ``bftpu-run
--attach JOB monitor``) polls every rank's seqlock'd status page on a
``BFTPU_MON_SCRAPE_S`` cadence.  It carries the same passive-read
guarantee as ``bftpu-top``: seqlock double-reads only, no locks, no
writes into any rank's segments — the < 2% ``monitor_overhead_pct``
bench gate holds the line.

Each scrape derives the monitor series from the raw pages
(:class:`FleetSampler` keeps the between-scrape state — last step
progress, previous suspect set, per-rank convergence bests), appends
every point to the mmap'd :class:`~bluefog_tpu.monitor.store
.MonitorStore` (history survives monitor death), and feeds the batch
to the :class:`~bluefog_tpu.monitor.rules.AlertEngine`, journaling
each gap-closed window as an ``alert`` event when telemetry is on.

The scraper also publishes its OWN v8 status page at rank
``MONITOR_RANK`` (2000 — above the 1000+ replica band) carrying the
alert lamp (``alert_state``: -1 none / 0 quiet / 1 firing) and the
last-alert word, so ``bftpu-top`` shows the fleet's alarm state with
zero extra plumbing.

Lifecycle: the daemon waits for pages to appear, follows them while
the job lives, and exits on its own once every page has been reclaimed
for ``BFTPU_MON_LINGER`` consecutive scrapes (default 10) — or
immediately on SIGTERM from the launcher's teardown, flushing open
alert windows either way.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from bluefog_tpu.introspect import statuspage
from bluefog_tpu.monitor.rules import AlertEngine
from bluefog_tpu.monitor.store import MonitorStore

__all__ = ["FleetSampler", "MonitorDaemon", "MONITOR_RANK",
           "scrape_interval"]

#: The scraper's own status-page rank: above the 1000+ serve-replica
#: band so it can never collide with a real rank or replica.
MONITOR_RANK = 2000

Point = Tuple[str, str, float]


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


def scrape_interval() -> float:
    """``BFTPU_MON_SCRAPE_S``: seconds between scrapes (default 1.0,
    floored at 10 ms so a typo cannot busy-spin the box)."""
    return max(0.01, _env_float("BFTPU_MON_SCRAPE_S", 1.0))


class FleetSampler:
    """Derive monitor series from one ``read_fleet`` snapshot.

    Stateless rules need stateful series — a stall is *time since*
    progress, a storm is a *rate* — so the sampler carries the small
    between-scrape memory and emits plain ``(series, subject, value)``
    points the engine and store consume.  Subjects are ``fleet`` for
    whole-job series and ``r<rank>`` for per-rank ones.
    """

    def __init__(self):
        self._last_step: Optional[int] = None
        self._last_step_t: Optional[float] = None
        self._prev_suspects: Optional[frozenset] = None
        self._prev_t: Optional[float] = None
        self._conv_best: Dict[int, float] = {}
        self._conv_best_t: Dict[int, float] = {}

    def sample(self, fleet: Dict[int, dict], t_mono: float) -> List[Point]:
        points: List[Point] = []
        pages = {r: p for r, p in fleet.items() if "error" not in p}
        if not pages:
            return points
        # mass ledger: only NET OVER-COLLECTION alarms.  A positive
        # fleet balance is legitimate in-flight mass mid-window; more
        # collected+drained than was ever deposited never is.
        balance = sum(p["ledger"]["balance"] for p in pages.values())
        points.append(("mass_err", "fleet", max(0.0, -balance)))
        # step progress → stall seconds
        step = max(int(p.get("step", 0)) for p in pages.values())
        if self._last_step is None or step > self._last_step:
            self._last_step, self._last_step_t = step, t_mono
        points.append(("epoch_stall_s", "fleet",
                       t_mono - (self._last_step_t or t_mono)))
        # suspect transitions per minute
        suspects = frozenset(
            (r, e["peer"]) for r, p in pages.items()
            for e in p.get("edges", ()) if e.get("state") == "suspect")
        if self._prev_suspects is not None and self._prev_t is not None:
            dt = max(1e-9, t_mono - self._prev_t)
            fresh = len(suspects - self._prev_suspects)
            points.append(("suspect_rate", "fleet", fresh / dt * 60.0))
        self._prev_suspects, self._prev_t = suspects, t_mono
        # dead edges (kill observed, heal not yet committed)
        dead = sum(1 for p in pages.values()
                   for e in p.get("edges", ()) if e.get("state") == "dead")
        points.append(("dead_edges", "fleet", float(dead)))
        # committed demotions vs the minority cap
        nranks = max(int(p.get("nranks", 1)) for p in pages.values())
        demoted = len({e["peer"] for p in pages.values()
                       for e in p.get("edges", ())
                       if e.get("state") == "demoted"})
        points.append(("demote_excess", "fleet",
                       float(demoted - (max(1, nranks) - 1) // 2)))
        for r, p in sorted(pages.items()):
            sub = f"r{r}"
            points.append(("orphan", sub, 1.0 if p.get("orphan") else 0.0))
            serve = p.get("serve", {})
            if serve.get("version", -1) >= 0 and serve.get("lag", -1) >= 0:
                points.append(("serve_lag", sub, float(serve["lag"])))
                if p.get("distrib", {}).get("slot", -1) >= 0:
                    # tree-fed replica: its lag IS its staleness
                    points.append(("distrib_staleness", sub,
                                   float(serve["lag"])))
            if serve.get("slo_state", -1) >= 0:
                points.append(("request_slo", sub,
                               1.0 if serve["slo_state"] == 1 else 0.0))
            conv = p.get("conv", {})
            if conv.get("round", -1) >= 0 and conv.get("err", -1.0) >= 0.0:
                err = float(conv["err"])
                best = self._conv_best.get(r)
                if best is None or err < best:
                    self._conv_best[r] = err
                    self._conv_best_t[r] = t_mono
                    best = err
                if best > 0.0:
                    points.append(("conv_ratio", sub, err / best))
                points.append(("conv_plateau_s", sub,
                               t_mono - self._conv_best_t[r]))
        return points


class MonitorDaemon:
    """The scrape loop: pages → sampler → store + engine → lamp page."""

    def __init__(self, job: str, *, interval: Optional[float] = None,
                 journal_fn=None, lamp: bool = True):
        self.job = str(job)
        self.interval = scrape_interval() if interval is None else max(
            0.01, float(interval))
        self.linger = max(1, int(_env_float("BFTPU_MON_LINGER", 10)))
        self.sampler = FleetSampler()
        self.store = MonitorStore(self.job, create=True)
        self._registry = None
        if journal_fn is None:
            journal_fn = self._default_journal()
        # gap must outlast the scrape cadence or every incident shreds
        # into one window per scrape (the flapping-alert fixture)
        from bluefog_tpu.monitor.rules import mon_gap_s
        gap = max(mon_gap_s(), 2.5 * self.interval)
        self.engine = AlertEngine(gap_s=gap, journal_fn=journal_fn)
        self._page = (statuspage.StatusPage(self.job, MONITOR_RANK)
                      if lamp else None)
        self._seen_pages = False
        self._misses = 0
        self.scrapes = 0
        self.stop = False

    def _default_journal(self):
        """Journal alerts like any rank journals events — through a
        Registry at MONITOR_RANK — when telemetry is on; silent no-op
        otherwise (the in-process ``engine.windows`` list still fills)."""
        from bluefog_tpu.telemetry import registry as _reg

        out_dir = _reg.telemetry_dir()
        if out_dir is None:
            return None
        self._registry = _reg.Registry(out_dir=out_dir, rank=MONITOR_RANK,
                                       job=self.job)
        return self._registry.journal

    def step(self) -> bool:
        """One scrape; returns False once the daemon should exit."""
        # chaos seam: BFTPU_CHAOS_MON_DROP_SCRAPE=N drops every Nth
        # scrape (reads nothing, feeds nothing) — the chaos e2e uses it
        # to prove the engine's gap-closing rides out scrape loss
        drop = int(_env_float("BFTPU_CHAOS_MON_DROP_SCRAPE", 0))
        if drop > 0 and self.scrapes > 0 and self.scrapes % drop == 0:
            self.scrapes += 1
            return not self.stop
        fleet = {r: p for r, p in statuspage.read_fleet(self.job).items()
                 if r != MONITOR_RANK}
        live = [p for p in fleet.values() if "error" not in p]
        if live:
            self._seen_pages = True
            self._misses = 0
        elif self._seen_pages:
            self._misses += 1
            if self._misses >= self.linger:
                return False
        t_mono = time.monotonic()
        t_wall = time.time()
        points = self.sampler.sample(fleet, t_mono)
        for series, subject, value in points:
            self.store.append(series, subject, t_wall, value)
        self.engine.feed(t_mono, points, wall=t_wall)
        self.scrapes += 1
        if self._page is not None:
            epoch = max((int(p.get("epoch", 0)) for p in live), default=0)
            self._page.publish(
                nranks=len(live), step=self.scrapes, epoch=epoch,
                op_id=self.engine.firings, last_op="monitor",
                alert_state=self.engine.state,
                last_alert=self.engine.last_alert)
        return not self.stop

    def run(self) -> int:
        """Blocking scrape loop; returns the count of alert windows."""
        try:
            while self.step():
                time.sleep(self.interval)
        finally:
            self.close()
        return len(self.engine.windows)

    def close(self) -> None:
        self.engine.close()
        if self._page is not None:
            self._page.close(unlink=True)
            self._page = None
        if self._registry is not None:
            self._registry.close()
            self._registry = None
        self.store.close()
