"""Declarative alert rules + the gap-closed alert engine.

The fleet monitor's rule table names the standing conditions an
operator pages on — the same conditions the analysis corpus audits
post-mortem, compiled down to threshold checks over sampled series:

==================  =========================  ==============================
rule                series it consumes         fires when
==================  =========================  ==============================
mass_imbalance      ``mass_err``               ledger residual beyond
                                               ``BFTPU_MON_MASS_TOL``
epoch_stall         ``epoch_stall_s``          no rank made step progress for
                                               ``BFTPU_MON_EPOCH_STALL_S``
epoch_fork          ``epoch_fork``             two live member groups commit
                                               the same epoch (split brain)
suspect_storm       ``suspect_rate``           edge-state demotion/suspect
                                               transitions per minute above
                                               ``BFTPU_MON_SUSPECT_RATE``
demote_storm        ``demote_excess``          committed demotions exceed the
                                               minority cap ``(n-1)//2``
edge_dead           ``dead_edges``             a live page reports a DEAD
                                               edge (kill observed, heal
                                               not yet committed)
orphan              ``orphan``                 a rank entered quorum-lost
                                               ORPHAN quiesce
serve_lag           ``serve_lag``              a replica trails the committed
                                               head past
                                               ``BFTPU_MON_SERVE_MAX_LAG``
distrib_staleness   ``distrib_staleness``      a tree-fed replica lags past
                                               ``BFTPU_MON_DISTRIB_STALENESS``
request_slo         ``request_slo``            a replica is inside an open
                                               request-SLO violation window
                                               (or, in the sim, holds
                                               overdue unserved requests)
conv_divergence     ``conv_ratio``             ``lab.conv_err`` grew past
                                               ``BFTPU_MON_CONV_DIVERGE`` ×
                                               its best value (divergence)
conv_plateau        ``conv_plateau_s``         ``lab.conv_err`` stopped
                                               improving for
                                               ``BFTPU_MON_CONV_PLATEAU_S``
==================  =========================  ==============================

A rule only ever fires on a series the sampler actually produced, so a
plane that is not armed (no serve replicas, probe off) cannot false-
alarm — the same "absent = disarmed" convention the status page uses.

Individual firing samples are noise; the engine folds them into
**gap-closed alert windows** exactly like the serve SLO monitor
(:mod:`bluefog_tpu.serve.loadgen.slo`): a window stays open while the
rule keeps firing and closes once it has been quiet for more than
``gap_s``.  Each closed window is journaled as one ``alert`` event with
monotonic *and* wall-clock bounds, which is what lets ``python -m
bluefog_tpu.monitor --report`` join it to the cause events
(kill/heal/join/demote/publish/reparent/resync) other processes
journaled inside it.  Without the gap hysteresis one incident shreds
into a window per scrape — the ``monitor-flapping-alert`` fixture
keeps that property honest.

Thresholds come from the env (``BFTPU_MON_*``), individually
overridable — and wholesale configurable — via ``BFTPU_MON_RULES``:
either inline JSON or a path to a JSON file mapping rule name to
``{"threshold": x}`` / ``{"disabled": true}``.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, replace
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "AlertRule",
    "AlertEngine",
    "default_rules",
    "load_rules",
    "mon_gap_s",
    "ALERT_STATE_NONE",
    "ALERT_STATE_OK",
    "ALERT_STATE_FIRING",
]

#: statuspage v8 alert-lamp encoding (mirrors the slo_state lamp):
#: -1 = no monitor attached / no samples yet, 0 = sampled and quiet,
#: 1 = at least one alert window currently open.
ALERT_STATE_NONE = -1
ALERT_STATE_OK = 0
ALERT_STATE_FIRING = 1


def _env_float(key: str, default: float) -> float:
    try:
        return float(os.environ.get(key, "") or default)
    except ValueError:
        return default


def mon_gap_s(default: float = 0.25) -> float:
    """``BFTPU_MON_GAP_S``: the window-close hysteresis in seconds."""
    return max(0.0, _env_float("BFTPU_MON_GAP_S", default))


@dataclass(frozen=True)
class AlertRule:
    """One declarative rule: fire when ``series`` crosses ``threshold``
    under ``op`` (``gt`` = value > threshold, ``nonzero`` = value != 0)."""

    name: str
    series: str
    op: str = "gt"
    threshold: float = 0.0
    doc: str = ""

    def fires(self, value: float) -> bool:
        if self.op == "nonzero":
            return bool(value)
        return float(value) > self.threshold


def default_rules() -> Tuple[AlertRule, ...]:
    """The built-in table with env-resolved thresholds (read at call
    time, so a harness's monkeypatched env is honored)."""
    return (
        AlertRule("mass_imbalance", "mass_err", "gt",
                  _env_float("BFTPU_MON_MASS_TOL", 1e-6),
                  "mass-ledger residual beyond tolerance"),
        AlertRule("epoch_stall", "epoch_stall_s", "gt",
                  _env_float("BFTPU_MON_EPOCH_STALL_S", 30.0),
                  "no rank made step progress for this many seconds"),
        AlertRule("epoch_fork", "epoch_fork", "nonzero", 0.0,
                  "two member groups committed the same epoch "
                  "(split brain)"),
        AlertRule("suspect_storm", "suspect_rate", "gt",
                  _env_float("BFTPU_MON_SUSPECT_RATE", 30.0),
                  "suspect/demote edge transitions per minute"),
        AlertRule("demote_storm", "demote_excess", "gt", 0.0,
                  "committed demotions exceed the minority cap"),
        AlertRule("edge_dead", "dead_edges", "nonzero", 0.0,
                  "a live page reports a DEAD edge"),
        AlertRule("orphan", "orphan", "nonzero", 0.0,
                  "a rank entered quorum-lost ORPHAN quiesce"),
        AlertRule("serve_lag", "serve_lag", "gt",
                  _env_float("BFTPU_MON_SERVE_MAX_LAG",
                             _env_float("BFTPU_SERVE_MAX_LAG", 8.0)),
                  "a replica trails the committed head"),
        AlertRule("distrib_staleness", "distrib_staleness", "gt",
                  _env_float("BFTPU_MON_DISTRIB_STALENESS", 8.0),
                  "a tree-fed replica lags its staleness SLO"),
        AlertRule("request_slo", "request_slo", "nonzero", 0.0,
                  "open request-SLO violation window / overdue "
                  "unserved requests"),
        AlertRule("conv_divergence", "conv_ratio", "gt",
                  _env_float("BFTPU_MON_CONV_DIVERGE", 50.0),
                  "conv_err grew this many times past its best"),
        AlertRule("conv_plateau", "conv_plateau_s", "gt",
                  _env_float("BFTPU_MON_CONV_PLATEAU_S", 60.0),
                  "conv_err stopped improving for this many seconds"),
    )


def load_rules(spec: Optional[str] = None) -> Tuple[AlertRule, ...]:
    """The effective rule table: :func:`default_rules` with
    ``BFTPU_MON_RULES`` overrides applied.  ``spec`` (inline JSON or a
    file path) wins over the env when given; unknown rule names are
    ignored (a newer config against an older build must not crash the
    monitor)."""
    raw = spec if spec is not None else os.environ.get("BFTPU_MON_RULES", "")
    rules = default_rules()
    if not raw:
        return rules
    text = raw.strip()
    if not text.startswith("{"):
        try:
            with open(text, "r", encoding="utf-8") as f:
                text = f.read()
        except OSError:
            return rules
    try:
        overrides = json.loads(text)
    except ValueError:
        return rules
    if not isinstance(overrides, dict):
        return rules
    out: List[AlertRule] = []
    for rule in rules:
        ov = overrides.get(rule.name)
        if not isinstance(ov, dict):
            out.append(rule)
            continue
        if ov.get("disabled"):
            continue
        if "threshold" in ov:
            try:
                rule = replace(rule, threshold=float(ov["threshold"]))
            except (TypeError, ValueError):
                pass
        out.append(rule)
    return tuple(out)


class AlertEngine:
    """Fold per-sample rule firings into gap-closed alert windows.

    Feed it one batch of ``(series, subject, value)`` points per scrape
    via :meth:`feed` and :meth:`close` it at teardown.  Windows are
    kept in-process (``self.windows``, flush order) *and* journaled
    through ``journal_fn`` when given, mirroring
    :class:`~bluefog_tpu.serve.loadgen.slo.SLOMonitor` — tests assert
    on the list, the attribution CLI joins the journal.

    The engine is clock-agnostic: the caller passes each sample's
    monotonic instant (and optionally its wall twin), so the SAME
    engine runs against ``time.monotonic()`` under the scraper and
    against the virtual clock inside ``SimConfig(monitor=True)`` —
    which is what makes "seeded bug ⇒ alert" a deterministic,
    bit-identical sim invariant.
    """

    def __init__(self, rules: Optional[Iterable[AlertRule]] = None, *,
                 gap_s: Optional[float] = None, journal_fn=None):
        self.rules: Tuple[AlertRule, ...] = tuple(
            rules if rules is not None else load_rules())
        self.gap_s = mon_gap_s() if gap_s is None else max(0.0, float(gap_s))
        self.journal_fn = journal_fn
        self.samples = 0
        self.firings = 0
        self.windows: List[dict] = []
        self._open: Dict[Tuple[str, str], dict] = {}
        self._by_series: Dict[str, List[AlertRule]] = {}
        for r in self.rules:
            self._by_series.setdefault(r.series, []).append(r)

    @property
    def state(self) -> int:
        """The statuspage v8 alert lamp for this engine."""
        if self.samples == 0:
            return ALERT_STATE_NONE
        return ALERT_STATE_FIRING if self._open else ALERT_STATE_OK

    @property
    def last_alert(self) -> str:
        """Rule name of the newest open (preferred) or closed window."""
        if self._open:
            w = max(self._open.values(), key=lambda w: w["t1_mono"])
            return w["rule"]
        return self.windows[-1]["rule"] if self.windows else ""

    def feed(self, t_mono: float,
             points: Iterable[Tuple[str, str, float]],
             wall: Optional[float] = None) -> List[dict]:
        """One sample batch; returns the windows it closed (if any)."""
        self.samples += 1
        t = float(t_mono)
        off = (time.time() - time.monotonic() if wall is None
               else float(wall) - t)
        firing: Dict[Tuple[str, str], Tuple[AlertRule, float]] = {}
        for series, subject, value in points:
            for rule in self._by_series.get(series, ()):
                if rule.fires(value):
                    key = (rule.name, str(subject))
                    prev = firing.get(key)
                    if prev is None or abs(value) > abs(prev[1]):
                        firing[key] = (rule, float(value))
        for key in sorted(firing):
            rule, value = firing[key]
            self.firings += 1
            w = self._open.get(key)
            if w is not None and t - w["t1_mono"] <= self.gap_s:
                w["t1_mono"] = max(w["t1_mono"], t)
                w["t1_wall"] = w["t1_mono"] + off
                w["samples"] += 1
                if abs(value) > abs(w["worst"]):
                    w["worst"] = value
            else:
                if w is not None:
                    self._flush(key)
                self._open[key] = {
                    "rule": rule.name,
                    "subject": key[1],
                    "series": rule.series,
                    "threshold": rule.threshold,
                    "t0_mono": t, "t1_mono": t,
                    "t0_wall": t + off, "t1_wall": t + off,
                    "samples": 1,
                    "worst": value,
                }
        closed: List[dict] = []
        for key in sorted(self._open):
            if key not in firing and t - self._open[key]["t1_mono"] > self.gap_s:
                closed.append(self._flush(key))
        return closed

    def _flush(self, key: Tuple[str, str]) -> dict:
        w = self._open.pop(key)
        self.windows.append(w)
        if self.journal_fn is not None:
            self.journal_fn("alert", **w)
        return w

    def close(self) -> List[dict]:
        """Flush every open window (monitor teardown)."""
        return [self._flush(key) for key in sorted(self._open)]
