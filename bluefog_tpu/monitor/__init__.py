"""Always-on fleet monitor: scrape, retain, alert, attribute.

The monitoring layer on top of the observability planes the repo
already has (docs/OBSERVABILITY.md "Fleet monitor"):

* :mod:`~bluefog_tpu.monitor.scraper` — a passive daemon polling every
  rank's seqlock'd status page on a ``BFTPU_MON_SCRAPE_S`` cadence,
  never perturbing the run (same guarantee as ``bftpu-top``);
* :mod:`~bluefog_tpu.monitor.store` — mmap'd ring-buffer time series
  with raw → 10× → 100× downsampling tiers, attachable post-mortem,
  exported as Prometheus text or JSON;
* :mod:`~bluefog_tpu.monitor.rules` — declarative alert rules compiled
  from the standing invariants the analysis corpus names, folded into
  gap-closed alert windows and journaled as ``alert`` events;
* :mod:`~bluefog_tpu.monitor.tail` — a rotation-safe incremental
  journal tailer (survives the ``BFTPU_JOURNAL_MAX_MB`` ``.1`` flip);
* :mod:`~bluefog_tpu.monitor.report` — incident attribution joining
  every alert window to the cause events inside it.

The same rule engine runs against the virtual clock inside
``SimConfig(monitor=True)``, where "seeded bug ⇒ matching alert" and
"clean campaign ⇒ zero alerts" are standing, bit-identical invariants
(``analysis --family monitor``).
"""

from bluefog_tpu.monitor.rules import (  # noqa: F401
    ALERT_STATE_FIRING,
    ALERT_STATE_NONE,
    ALERT_STATE_OK,
    AlertEngine,
    AlertRule,
    default_rules,
    load_rules,
)
from bluefog_tpu.monitor.scraper import (  # noqa: F401
    MONITOR_RANK,
    FleetSampler,
    MonitorDaemon,
    scrape_interval,
)
from bluefog_tpu.monitor.store import MonitorStore  # noqa: F401
from bluefog_tpu.monitor.tail import JournalTailer  # noqa: F401
from bluefog_tpu.monitor.report import (  # noqa: F401
    MON_CAUSE_KINDS,
    format_report,
    monitor_report,
)

__all__ = [
    "AlertEngine",
    "AlertRule",
    "default_rules",
    "load_rules",
    "ALERT_STATE_NONE",
    "ALERT_STATE_OK",
    "ALERT_STATE_FIRING",
    "FleetSampler",
    "MonitorDaemon",
    "MONITOR_RANK",
    "scrape_interval",
    "MonitorStore",
    "JournalTailer",
    "MON_CAUSE_KINDS",
    "monitor_report",
    "format_report",
]
