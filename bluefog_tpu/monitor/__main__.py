"""The fleet-monitor CLI.

    python -m bluefog_tpu.monitor --job JOB --daemon        # scrape loop
    python -m bluefog_tpu.monitor --job JOB --export        # JSON dump
    python -m bluefog_tpu.monitor --job JOB --export --prom # Prometheus
    python -m bluefog_tpu.monitor --job JOB --serve 9099    # HTTP /metrics
    python -m bluefog_tpu.monitor --report DIR [DIR...]     # attribution
    bftpu-run --attach JOB monitor [...]                    # same thing

``--export``/``--serve`` attach to the mmap'd store read-only and work
even after the monitor (or the whole job) died — the history is in the
segment, not the process.  ``--report`` joins journaled ``alert``
windows to cause events and exits nonzero when any window is
unattributed, which is the machine-checkable "every incident
explained" gate the chaos e2e relies on.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys

from bluefog_tpu.monitor import report as report_mod
from bluefog_tpu.monitor import store as store_mod
from bluefog_tpu.monitor.scraper import MonitorDaemon


def _serve(job: str, port: int) -> int:
    """Minimal stdlib exporter: ``/metrics`` (Prometheus text) and
    ``/json`` over the job's store segment."""
    from http.server import BaseHTTPRequestHandler, HTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib naming)
            try:
                if self.path.startswith("/json"):
                    body = json.dumps(store_mod.export_json(job),
                                      indent=2).encode()
                    ctype = "application/json"
                else:
                    body = store_mod.export_prometheus(job).encode()
                    ctype = "text/plain; version=0.0.4"
            except FileNotFoundError:
                self.send_error(404, f"no monitor store for job {job!r}")
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet: scrape targets are chatty
            pass

    httpd = HTTPServer(("", port), Handler)
    print(f"monitor exporter for job {job!r} on :{port} "
          f"(/metrics, /json)", file=sys.stderr)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="bftpu-monitor",
        description="Always-on fleet monitor: passive scraper, mmap'd "
        "time-series store, declarative alerts, incident attribution.")
    parser.add_argument("--job", default=None,
                        help="island job name (BLUEFOG_ISLAND_JOB)")
    parser.add_argument("--daemon", action="store_true",
                        help="run the scrape loop until the job's pages "
                        "disappear (or SIGTERM)")
    parser.add_argument("--interval", type=float, default=None,
                        help="scrape interval in seconds "
                        "(default BFTPU_MON_SCRAPE_S, 1.0)")
    parser.add_argument("--export", action="store_true",
                        help="dump the job's retained time series and exit")
    parser.add_argument("--prom", action="store_true",
                        help="with --export: Prometheus text format "
                        "instead of JSON")
    parser.add_argument("--serve", type=int, metavar="PORT", default=None,
                        help="serve /metrics and /json over HTTP")
    parser.add_argument("--report", nargs="+", metavar="PATH", default=None,
                        help="attribution report over journal files/dirs; "
                        "exits nonzero on unattributed alert windows")
    parser.add_argument("--margin", type=float, default=2.0,
                        help="attribution join margin in seconds")
    parser.add_argument("--json", action="store_true",
                        help="with --report: machine-readable JSON "
                        "(schema bftpu-monitor-report/1)")
    args = parser.parse_args(argv)

    if args.report is not None:
        rep = report_mod.monitor_report(args.report, margin_s=args.margin)
        print(json.dumps(rep, indent=2) if args.json
              else report_mod.format_report(rep))
        return 1 if rep["unattributed"] else 0

    if args.job is None:
        parser.error("--job is required (except with --report)")

    if args.export:
        try:
            if args.prom:
                sys.stdout.write(store_mod.export_prometheus(args.job))
            else:
                print(json.dumps(store_mod.export_json(args.job), indent=2))
        except FileNotFoundError as e:
            print(f"bftpu-monitor: {e}", file=sys.stderr)
            return 1
        return 0

    if args.serve is not None:
        return _serve(args.job, args.serve)

    if args.daemon:
        daemon = MonitorDaemon(args.job, interval=args.interval)

        def _term(signum, frame):
            daemon.stop = True

        signal.signal(signal.SIGTERM, _term)
        try:
            windows = daemon.run()
        except KeyboardInterrupt:
            daemon.close()
            windows = len(daemon.engine.windows)
        print(f"monitor: {daemon.scrapes} scrape(s), "
              f"{windows} alert window(s)", file=sys.stderr)
        return 0

    parser.error("pick one of --daemon / --export / --serve / --report")
    return 2


if __name__ == "__main__":
    sys.exit(main())
