"""Rotation-safe incremental journal tailer.

The scraper follows every rank's event journal live, but
``BFTPU_JOURNAL_MAX_MB`` rotation swaps the file out from under a
naive tailer: :meth:`Registry.journal` closes the live file,
``os.replace``\\ s it to ``<path>.1`` and reopens a fresh ``<path>``.
A tailer that only tracks a byte offset then either re-reads the new
file from its stale offset (dropping everything before it) or rewinds
to zero (double-counting what it already consumed from the old
generation).

:class:`JournalTailer` tracks ``(st_ino, offset)`` instead.  On each
poll it stats the live path; when the inode changed, the bytes it was
tailing now live at ``<path>.1`` (that is the *same* inode — rename
does not copy), so it drains the remainder of the rotated file from
the saved offset first, then switches to the new live file at offset
0.  Exactly-once within each generation is preserved because a torn
final line (a writer mid-append) is buffered, not parsed, until its
newline arrives — and after a rotation the held fragment is completed
from the rotated generation, never glued onto the new file's first
line.

Only one rotated generation exists by design (the registry keeps
``.1`` only), so a tailer that polls at the scrape cadence can lose
records only if a rank writes a full ``BFTPU_JOURNAL_MAX_MB`` *twice*
between polls — at which point the journals themselves have dropped
that history too.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Tuple

__all__ = ["JournalTailer"]


class JournalTailer:
    """Incrementally yield parsed events from one rank's journal,
    surviving ``.1`` rotation without double-counting or dropping."""

    def __init__(self, path: str):
        self.path = path
        self._ino: Optional[int] = None
        self._offset = 0
        self._carry = b""
        self.events_read = 0
        self.bad_lines = 0
        self.rotations = 0

    # -- internals --------------------------------------------------------

    def _read_from(self, path: str, offset: int) -> Tuple[bytes, int]:
        try:
            with open(path, "rb") as f:
                f.seek(offset)
                data = f.read()
        except OSError:
            return b"", offset
        return data, offset + len(data)

    def _parse(self, data: bytes, final: bool) -> List[dict]:
        """Split ``carry + data`` on newlines; an unterminated tail is
        carried unless ``final`` (end of a rotated generation, where the
        writer is gone and the fragment is all there will ever be)."""
        buf = self._carry + data
        if final:
            chunks, self._carry = buf.split(b"\n"), b""
        else:
            chunks = buf.split(b"\n")
            self._carry = chunks.pop()
        out: List[dict] = []
        for line in chunks:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                self.bad_lines += 1
                continue
            if isinstance(ev, dict):
                out.append(ev)
            else:
                self.bad_lines += 1
        self.events_read += len(out)
        return out

    # -- API --------------------------------------------------------------

    def poll(self) -> List[dict]:
        """All events appended since the last poll, across at most one
        rotation flip."""
        out: List[dict] = []
        try:
            st = os.stat(self.path)
        except OSError:
            return out  # not created yet (or already reaped)
        if self._ino is None:
            self._ino = st.st_ino
        elif st.st_ino != self._ino:
            # The file we were tailing was renamed to <path>.1 and a
            # fresh live file took its place: drain the old generation
            # from our saved offset, then restart on the new inode.
            self.rotations += 1
            data, _ = self._read_from(self.path + ".1", self._offset)
            out.extend(self._parse(data, final=True))
            self._ino = st.st_ino
            self._offset = 0
        data, self._offset = self._read_from(self.path, self._offset)
        out.extend(self._parse(data, final=False))
        return out

    def drain(self) -> List[dict]:
        """Final poll that also flushes a trailing unterminated line
        (teardown: the writers have exited, nothing more is coming)."""
        out = self.poll()
        if self._carry:
            out.extend(self._parse(b"", final=True))
        return out
