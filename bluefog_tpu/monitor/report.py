"""Incident attribution: alert windows joined to their cause events.

This generalizes the serve plane's ``--slo-report`` join
(:func:`bluefog_tpu.telemetry.merge.slo_report`) to *every* alert kind
the monitor raises.  The scraper journals one ``alert`` event per
gap-closed window with wall-clock bounds; every other process journals
the things that *happen* — kills declared, heals, epoch switches,
demotions, joins, snapshot publishes, tree reparents, resyncs.  Wall
time is the one timebase those journals share, so the join is the same
interval overlap: a cause explains a window when its ``ts`` lands in
``[t0_wall - margin, t1_wall + margin]``.

A window no cause overlaps is **unattributed** — in a chaos run those
are the unexplained incidents, and ``python -m bluefog_tpu.monitor
--report`` exits nonzero when any exist (the acceptance gate for the
np=4 kill/respawn e2e is a report with every window attributed).
"""

from __future__ import annotations

import os
from typing import Iterable, List

from bluefog_tpu.telemetry.merge import (
    SLO_CAUSE_KINDS,
    _num,
    find_journals,
    read_journal,
)

__all__ = ["MON_CAUSE_KINDS", "MON_REPORT_SCHEMA", "monitor_report",
           "format_report"]

MON_REPORT_SCHEMA = "bftpu-monitor-report/1"

#: Everything that can explain an alert window: the serve-plane causes
#: the SLO report already joins, plus the resilience plane (failure
#: detection, heal, membership churn, orphan quiesce, demotion votes)
#: and the progress engine's quiesce/resume brackets.
MON_CAUSE_KINDS = SLO_CAUSE_KINDS + (
    "death_declared",
    "heal",
    "epoch_switch",
    "edge_state",
    "peer_timeout",
    "deadline_exhausted",
    "orphan_entered",
    "orphan_merged",
    "quorum_denied",
    "join_requested_seen",
    "join_granted",
    "join_admitted",
    "join_mass_admitted",
    "distrib_join",
    "progress_quiesce",
    "progress_resume",
)


def monitor_report(paths: Iterable[str], margin_s: float = 2.0) -> dict:
    """Join every journaled ``alert`` window to its overlapping cause
    events; count the windows nothing explains."""
    journals = find_journals(paths)
    windows: List[dict] = []
    causes: List[dict] = []
    for path in journals:
        name = os.path.basename(path)
        for rec in read_journal(path):
            kind = rec.get("event")
            if kind == "alert":
                w = dict(rec)
                w["_journal"] = name
                windows.append(w)
            elif kind in MON_CAUSE_KINDS:
                causes.append(rec)
    causes.sort(key=lambda r: _num(r.get("ts")) or 0.0)
    out_windows: List[dict] = []
    unattributed = 0
    for w in sorted(windows, key=lambda r: _num(r.get("t0_wall")) or 0.0):
        t0 = _num(w.get("t0_wall"))
        t1 = _num(w.get("t1_wall"))
        joined = []
        if t0 is not None:
            lo, hi = t0 - margin_s, (t1 if t1 is not None else t0) + margin_s
            for c in causes:
                ts = _num(c.get("ts"))
                if ts is None or not (lo <= ts <= hi):
                    continue
                cause = {"kind": c.get("event"), "ts": ts,
                         "rank": c.get("rank"), "dt_s": ts - t0}
                for k in ("replica", "peer", "state", "epoch", "version",
                          "slot", "group", "win"):
                    if k in c:
                        cause[k] = c[k]
                joined.append(cause)
        if not joined:
            unattributed += 1
        out_windows.append({
            "rule": w.get("rule"),
            "subject": w.get("subject"),
            "series": w.get("series"),
            "t0_wall": w.get("t0_wall"),
            "t1_wall": w.get("t1_wall"),
            "duration_s": (t1 - t0 if t0 is not None and t1 is not None
                           else None),
            "samples": w.get("samples"),
            "worst": w.get("worst"),
            "journal": w.get("_journal"),
            "causes": joined,
        })
    return {
        "schema": MON_REPORT_SCHEMA,
        "journals": [os.path.basename(p) for p in journals],
        "margin_s": float(margin_s),
        "windows": out_windows,
        "total_windows": len(out_windows),
        "unattributed": unattributed,
    }


def format_report(report: dict) -> str:
    """Human-readable one-window-per-block rendering (the JSON is the
    machine interface; this is what lands on an operator's terminal)."""
    lines = [f"monitor report: {report['total_windows']} alert window(s), "
             f"{report['unattributed']} unattributed "
             f"(margin {report['margin_s']:.1f}s, "
             f"{len(report['journals'])} journal(s))"]
    for w in report["windows"]:
        dur = w.get("duration_s")
        lines.append(
            f"  [{w.get('rule')}] subject={w.get('subject')} "
            f"dur={dur:.2f}s worst={w.get('worst')}"
            if dur is not None else
            f"  [{w.get('rule')}] subject={w.get('subject')} "
            f"worst={w.get('worst')}")
        if w["causes"]:
            for c in w["causes"][:8]:
                extra = "".join(
                    f" {k}={c[k]}" for k in ("peer", "state", "epoch",
                                             "replica", "version", "slot")
                    if k in c)
                lines.append(f"      <- {c['kind']} rank={c.get('rank')} "
                             f"dt={c['dt_s']:+.2f}s{extra}")
            if len(w["causes"]) > 8:
                lines.append(f"      ... {len(w['causes']) - 8} more")
        else:
            lines.append("      <- UNATTRIBUTED")
    return "\n".join(lines)
