"""Rule family 3a: exhaustive model checking of the shm-mailbox protocol.

``native/shm_mailbox.cc`` implements a seqlock mailbox (writers per-slot
spinlocked with an odd/even sequence publish; readers wait-free with a
bracketed retry copy), an atomic read+zero ``collect``, and a
sense-reversing barrier.  MPI gives the reference this machinery for
free; here it is 449 lines of hand-rolled C++ that had never been model
checked.  This module mirrors each protocol as a small explicit-state
machine and exhaustively enumerates ALL interleavings at small bounds
(1-2 writers x 1-2 deposits, 2-word payloads, 2-3 ranks x 2 barrier
episodes), proving within those bounds:

- **no torn read**: every payload a completed reader returns is a single
  deposit's value, never a mix of two (seqlock safety);
- **no lost deposit**: ``collect``'s read+zero critical section conserves
  mass against a concurrent accumulating writer;
- **no lost wakeup / deadlock**: the barrier's reset-then-release order
  can never strand a rank spinning on a generation bump that already
  happened.

The step orders are imported from ``native/shm_native.py``'s protocol
spec constants and asserted to match, so the model cannot silently drift
from the implementation it vouches for.  Seeded-bug variants (writer
skips the odd phase; collect splits read and zero; barrier releases
before resetting) are exported for the fixture corpus — each must make
the checker fire (tests/test_analysis.py).

The model assumes sequential consistency.  The fences in shm_mailbox.cc
(seq_cst store-store before the payload mutation, release before the
even publish, acquire-bracketed reads) are exactly what collapses the
hardware's weaker orders to the interleaving semantics checked here;
the comments at ``slot_write``/``slot_read`` document that mapping.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from bluefog_tpu.native.shm_native import (
    BARRIER_RESET_BEFORE_RELEASE,
    CHUNK_COMMIT_IN_ORDER,
    CHUNK_READER_STEPS,
    CHUNK_WRITER_STEPS,
    COLLECT_IS_ATOMIC,
    DEAD_WRITER_DRAIN_STEPS,
    DEPOSIT_COMMITS_AFTER_PAYLOAD,
    DRAINED_COLLECT_IS_ATOMIC,
    SEQLOCK_READER_STEPS,
    SEQLOCK_WRITER_STEPS,
)

from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "Model",
    "explore",
    "seqlock_model",
    "collect_model",
    "barrier_model",
    "chunk_ring_model",
    "drained_collect_model",
    "dead_writer_drain_model",
    "check_model",
]


# ---------------------------------------------------------------------------
# tiny explicit-state explorer
# ---------------------------------------------------------------------------
#
# A process is a list of *steps*.  A step is
#     step(shared: dict, regs: dict) -> list[(shared', regs', next_pc)]
# returning every successor from this state (deterministic steps return
# one; a blocked spin returns none).  Steps must treat their inputs as
# immutable and may set shared["_bad"] to a message to flag a safety
# violation at that transition.


@dataclasses.dataclass
class Model:
    name: str
    shared: Dict
    programs: List[List[Callable]]
    final_check: Optional[Callable[[Dict], Optional[str]]] = None


def _freeze(d: Dict) -> Tuple:
    return tuple(sorted(d.items()))


def _thaw(t: Tuple) -> Dict:
    return dict(t)


def explore(model: Model, max_states: int = 1_000_000) -> List[str]:
    """DFS over every interleaving; returns violation messages.

    Detects three failure shapes: a step-flagged safety violation
    (``shared["_bad"]``), a deadlock (some process unfinished, no process
    can move — the lost-wakeup signature), and a failed ``final_check``
    on a fully-terminated state.
    """
    programs = model.programs
    init = (_freeze(model.shared),
            tuple((0, ()) for _ in programs))
    seen = {init}
    stack = [init]
    violations: List[str] = []
    flagged = set()

    def flag(msg: str) -> None:
        if msg not in flagged:
            flagged.add(msg)
            violations.append(msg)

    while stack:
        shared_t, procs = stack.pop()
        shared = _thaw(shared_t)
        any_move = False
        all_done = True
        for i, (pc, regs_t) in enumerate(procs):
            prog = programs[i]
            if pc >= len(prog):
                continue
            all_done = False
            regs = _thaw(regs_t)
            for sh2, rg2, pc2 in prog[pc](shared, regs):
                any_move = True
                bad = sh2.pop("_bad", None)
                if bad is not None:
                    flag(f"{model.name}: {bad}")
                    continue  # prune past the violation
                nxt = (_freeze(sh2),
                       procs[:i] + ((pc2, _freeze(rg2)),) + procs[i + 1:])
                if nxt not in seen:
                    if len(seen) >= max_states:
                        raise RuntimeError(
                            f"{model.name}: state space exceeded "
                            f"{max_states} states — tighten the bounds")
                    seen.add(nxt)
                    stack.append(nxt)
        if all_done:
            if model.final_check is not None:
                msg = model.final_check(shared)
                if msg:
                    flag(f"{model.name}: {msg}")
        elif not any_move:
            stuck = [i for i, (pc, _) in enumerate(procs)
                     if pc < len(programs[i])]
            flag(f"{model.name}: deadlock — process(es) {stuck} blocked "
                 "forever (lost wakeup)")
    return violations


def _s(shared, regs, pc, **updates):
    """One successor with shared-var updates applied."""
    sh = dict(shared)
    sh.update(updates)
    return [(sh, regs, pc)]


def _r(shared, regs, pc, **updates):
    """One successor with register updates applied."""
    rg = dict(regs)
    rg.update(updates)
    return [(shared, rg, pc)]


# ---------------------------------------------------------------------------
# model 1: seqlock write/read (torn-read safety)
# ---------------------------------------------------------------------------


def _writer_program(writer_id: int, deposits: int, words: int,
                    use_lock: bool, odd_phase: bool,
                    early_publish: bool) -> Tuple[List[Callable], Tuple[str, ...]]:
    """One writer: ``deposits`` sequential slot_write calls, each writing
    the deposit's unique value to every payload word, one word per step
    (the memcpy is not atomic — that is the whole point)."""
    prog: List[Callable] = []
    steps: List[str] = []

    for dep in range(deposits):
        value = writer_id * 100 + dep + 1

        # Each closure captures its own next-pc at construction time.
        def mk_acquire(next_pc):
            def step(sh, rg):
                if sh["lock"]:
                    return []
                return _s(sh, rg, next_pc, lock=1)
            return step

        def mk_seq_bump(next_pc):
            def step(sh, rg):
                return _s(sh, rg, next_pc, seq=sh["seq"] + 1)
            return step

        def mk_write_word(w, v, next_pc):
            def step(sh, rg):
                return _s(sh, rg, next_pc, **{f"w{w}": v})
            return step

        def mk_release(next_pc):
            def step(sh, rg):
                return _s(sh, rg, next_pc, lock=0)
            return step

        base = len(prog)
        seq_bumps = ([("seq_to_odd", mk_seq_bump)] if odd_phase else [])
        publish = [("seq_to_even", mk_seq_bump)]
        body: List[Tuple[str, Callable]] = []
        if use_lock:
            body.append(("acquire_lock", mk_acquire))
        body.extend(seq_bumps)
        if early_publish:
            body.extend(publish)
        body.extend(("mutate_payload", lambda nxt, w=w, v=value:
                     mk_write_word(w, v, nxt)) for w in range(words))
        if not early_publish:
            body.extend(publish)
        if use_lock:
            body.append(("release_lock", mk_release))
        for k, (name, maker) in enumerate(body):
            prog.append(maker(base + k + 1))
            steps.append(name)
    return prog, tuple(steps)


def _reader_program(words: int, check_after: bool = True) -> List[Callable]:
    """slot_read: bracketed retry copy, no lock.  Registers: ``before``
    and one ``r<w>`` per word.  On completion the snapshot must be a
    single deposit's value."""
    pc_start = 0

    def read_before(sh, rg):
        if sh["seq"] & 1:
            return [(sh, rg, pc_start)]  # odd: retry (self-loop via state)
        return _r(sh, rg, 1, before=sh["seq"])

    prog: List[Callable] = [read_before]

    def mk_copy(w, next_pc):
        def step(sh, rg):
            return _r(sh, rg, next_pc, **{f"r{w}": sh[f"w{w}"]})
        return step

    for w in range(words):
        prog.append(mk_copy(w, len(prog) + 1))

    def read_after(sh, rg):
        if check_after and sh["seq"] != rg["before"]:
            return [(sh, {}, pc_start)]  # retry from scratch
        vals = {rg[f"r{w}"] for w in range(words)}
        if len(vals) > 1:
            sh2 = dict(sh)
            sh2["_bad"] = (f"torn read: completed snapshot mixes deposits "
                           f"{sorted(vals)}")
            return [(sh2, rg, len(prog))]
        return [(sh, rg, len(prog))]

    prog.append(read_after)
    return prog


def seqlock_model(n_writers: int = 1, deposits: int = 2, words: int = 2,
                  use_lock: bool = True, odd_phase: bool = True,
                  early_publish: bool = False,
                  reader_checks_after: bool = True) -> Model:
    """The mailbox slot under concurrent writers and one wait-free reader.

    Default parameters mirror ``slot_write``/``slot_read`` exactly (order
    asserted against the shm_native protocol spec); the keyword knobs
    produce the seeded-bug variants for the fixture corpus."""
    shared = {"lock": 0, "seq": 0}
    for w in range(words):
        shared[f"w{w}"] = 0
    programs = []
    for i in range(n_writers):
        prog, steps = _writer_program(i, deposits, words, use_lock,
                                      odd_phase, early_publish)
        if (use_lock and odd_phase and not early_publish):
            # one deposit's step-name sequence must equal the impl spec
            per_dep = steps[:len(steps) // deposits]
            collapsed = tuple(
                name for k, name in enumerate(per_dep)
                if name != "mutate_payload" or
                (k == 0 or per_dep[k - 1] != "mutate_payload"))
            assert collapsed == SEQLOCK_WRITER_STEPS, (
                f"model drifted from shm_native.SEQLOCK_WRITER_STEPS: "
                f"{collapsed}")
        programs.append(prog)
    programs.append(_reader_program(words, check_after=reader_checks_after))
    assert len(SEQLOCK_READER_STEPS) == 3  # spec sync (retry-bracketed copy)
    return Model(name="seqlock", shared=shared, programs=programs)


# ---------------------------------------------------------------------------
# model 2: collect vs concurrent accumulate (mass conservation)
# ---------------------------------------------------------------------------


def collect_model(deposits: int = 2, atomic_collect: bool = COLLECT_IS_ATOMIC
                  ) -> Model:
    """One accumulating writer (``bf_shm_win_write`` mode 1) racing one
    ``collect`` drain (``bf_shm_win_read`` collect=1).  Mass conservation:
    every deposited unit is either collected or still in the slot when
    both finish.  ``atomic_collect=False`` models the seeded bug — a
    seqlock *read* followed by a separate locked zero — which loses any
    deposit that lands in between."""
    shared = {"lock": 0, "seq": 0, "m": 0, "collected": 0}

    writer: List[Callable] = []
    for dep in range(deposits):
        base = len(writer)

        def mk(step_idx):
            def acquire(sh, rg):
                if sh["lock"]:
                    return []
                return _s(sh, rg, step_idx + 1, lock=1)
            return acquire

        writer.append(mk(base))

        def mk_read(nxt):
            def step(sh, rg):
                return _r(sh, rg, nxt, tmp=sh["m"])
            return step

        writer.append(mk_read(base + 2))

        def mk_addback(nxt):
            def step(sh, rg):
                return _s(sh, rg, nxt, m=rg["tmp"] + 1)
            return step

        writer.append(mk_addback(base + 3))

        def mk_release(nxt):
            def step(sh, rg):
                return _s(sh, rg, nxt, lock=0)
            return step

        writer.append(mk_release(base + 4))

    if atomic_collect:
        def c_acquire(sh, rg):
            if sh["lock"]:
                return []
            return _s(sh, rg, 1, lock=1)

        def c_read_zero(sh, rg):
            sh2 = dict(sh)
            sh2["collected"] = sh["collected"] + sh["m"]
            sh2["m"] = 0
            return [(sh2, rg, 2)]

        def c_release(sh, rg):
            return _s(sh, rg, 3, lock=0)

        collector = [c_acquire, c_read_zero, c_release]
    else:
        # seeded bug: read outside the critical section, zero inside
        def c_read(sh, rg):
            return _r(sh, rg, 1, got=sh["m"])

        def c_acquire(sh, rg):
            if sh["lock"]:
                return []
            return _s(sh, rg, 2, lock=1)

        def c_zero(sh, rg):
            sh2 = dict(sh)
            sh2["collected"] = sh["collected"] + rg["got"]
            sh2["m"] = 0
            return [(sh2, rg, 3)]

        def c_release(sh, rg):
            return _s(sh, rg, 4, lock=0)

        collector = [c_read, c_acquire, c_zero, c_release]

    def conserved(sh) -> Optional[str]:
        if sh["collected"] + sh["m"] != deposits:
            return (f"lost deposit: {deposits} deposited but "
                    f"collected={sh['collected']} + remaining={sh['m']}")
        return None

    return Model(name="collect", shared=shared,
                 programs=[writer, collector], final_check=conserved)


# ---------------------------------------------------------------------------
# model 2b: chunk-ring commit protocol (protocol v2 — torn chunk /
# reordered commit / missing commit fence)
# ---------------------------------------------------------------------------
#
# slot_deposit in the v2 transport splits the payload into chunks, each
# guarded by its OWN seqlock, committed in ascending index order:
#     for c in chunks: cs[c] -> odd; fence; write chunk c; release; cs[c] -> even
# Two consumer shapes depend on different halves of that contract:
#   * the per-chunk bracketed reader (slot_read, probe's drain leg) needs
#     each chunk's odd/even bracket to actually cover the chunk's bytes —
#     a commit published before the payload lands (missing fence) lets a
#     bracket with before == after return a half-written chunk;
#   * the pipelined frontier reader (bf_shm_win_probe's consumer chasing
#     the commit frontier) additionally needs the ASCENDING commit order:
#     observing chunk LAST committed at episode d must imply every earlier
#     chunk already carries episode >= d.
# Both are modeled below; the seeded-bug knobs break exactly one promise
# each and must make the corresponding reader fire.


def _chunk_writer_program(nchunks: int, deposits: int, words: int,
                          in_order_commit: bool, commit_fence: bool
                          ) -> List[Callable]:
    """One depositing writer: per episode e (value e+1), commit every
    chunk under its own seqlock.  ``in_order_commit=False`` commits in
    DESCENDING index order (the reordered-commit bug); ``commit_fence=
    False`` publishes the even value BEFORE the chunk's words are written
    (the missing release-fence bug, modeled at SC as the reordered
    publish it permits on hardware)."""
    prog: List[Callable] = []

    def mk_seq_bump(c, next_pc):
        def step(sh, rg):
            return _s(sh, rg, next_pc, **{f"cs{c}": sh[f"cs{c}"] + 1})
        return step

    def mk_write_word(c, w, v, next_pc):
        def step(sh, rg):
            return _s(sh, rg, next_pc, **{f"c{c}w{w}": v})
        return step

    spec_names: List[str] = []
    for dep in range(deposits):
        value = dep + 1
        order = range(nchunks) if in_order_commit else \
            range(nchunks - 1, -1, -1)
        for c in order:
            body: List[Tuple[str, Callable]] = []
            body.append(("chunk_seq_to_odd", lambda nxt, c=c:
                         mk_seq_bump(c, nxt)))
            mutate = [("mutate_chunk", lambda nxt, c=c, w=w, v=value:
                       mk_write_word(c, w, v, nxt)) for w in range(words)]
            publish = [("chunk_seq_to_even", lambda nxt, c=c:
                        mk_seq_bump(c, nxt))]
            if commit_fence:
                body.extend(mutate + publish)
            else:
                body.extend(publish + mutate)
            base = len(prog)
            for k, (name, maker) in enumerate(body):
                prog.append(maker(base + k + 1))
                if dep == 0 and c == (0 if in_order_commit else nchunks - 1):
                    spec_names.append(name)
    if in_order_commit and commit_fence:
        collapsed = tuple(
            name for k, name in enumerate(spec_names)
            if name != "mutate_chunk"
            or (k == 0 or spec_names[k - 1] != "mutate_chunk"))
        assert collapsed == CHUNK_WRITER_STEPS, (
            f"model drifted from shm_native.CHUNK_WRITER_STEPS: {collapsed}")
    return prog


def _chunk_reader_program(nchunks: int, words: int) -> List[Callable]:
    """Per-chunk bracketed consumer: for each chunk, retry-bracketed copy
    under that chunk's seqlock; a completed bracket whose words mix two
    episodes is a torn chunk."""
    prog: List[Callable] = []
    for c in range(nchunks):
        pc_start = len(prog)

        def read_before(sh, rg, c=c, pc_start=pc_start):
            if sh[f"cs{c}"] & 1:
                return [(sh, rg, pc_start)]  # odd: retry
            return _r(sh, rg, pc_start + 1, before=sh[f"cs{c}"])

        prog.append(read_before)

        def mk_copy(c, w, next_pc):
            def step(sh, rg):
                return _r(sh, rg, next_pc, **{f"r{w}": sh[f"c{c}w{w}"]})
            return step

        for w in range(words):
            prog.append(mk_copy(c, w, len(prog) + 1))

        def read_after(sh, rg, c=c, pc_start=pc_start, end=pc_start + words + 2):
            if sh[f"cs{c}"] != rg["before"]:
                return [(sh, {}, pc_start)]  # changed: retry from scratch
            vals = {rg[f"r{w}"] for w in range(words)}
            if len(vals) > 1:
                sh2 = dict(sh)
                sh2["_bad"] = (f"torn chunk {c}: completed bracket mixes "
                               f"episodes {sorted(vals)}")
                return [(sh2, rg, end)]
            return [(sh, {}, end)]

        prog.append(read_after)
    assert len(CHUNK_READER_STEPS) == 3  # spec sync (retry-bracketed copy)
    return prog


def _frontier_reader_program(nchunks: int, words: int) -> List[Callable]:
    """Pipelined consumer chasing the commit frontier: once the LAST
    chunk's seqlock shows d completed commits (even, >= 2), ascending
    commit order guarantees every chunk already carries episode >= d —
    in every word, even mid-write (older words are episode >= d, newer
    ones are > d).  This is what lets bf_shm_win_probe's reader start
    draining chunk 0 while the writer is still depositing chunk k."""
    last = nchunks - 1

    def observe_frontier(sh, rg):
        s = sh[f"cs{last}"]
        if (s & 1) or s < 2:
            return [(sh, rg, 0)]  # spin until a commit of the last chunk
        return _r(sh, rg, 1, d=s // 2)

    prog: List[Callable] = [observe_frontier]
    for c in range(nchunks):
        def check_chunk(sh, rg, c=c, next_pc=len(prog) + 1):
            lo = min(sh[f"c{c}w{w}"] for w in range(words))
            if lo < rg["d"]:
                sh2 = dict(sh)
                sh2["_bad"] = (
                    f"commit frontier violated: chunk {nchunks - 1} shows "
                    f"episode {rg['d']} committed but chunk {c} still "
                    f"carries episode {lo}")
                return [(sh2, rg, next_pc)]
            return [(sh, rg, next_pc)]

        prog.append(check_chunk)
    return prog


def chunk_ring_model(nchunks: int = 2, deposits: int = 2, words: int = 2,
                     in_order_commit: bool = CHUNK_COMMIT_IN_ORDER,
                     commit_fence: bool = True,
                     frontier_reader: bool = False) -> Model:
    """The v2 chunk-ring slot under one depositing writer and one
    consumer.  Defaults mirror ``slot_deposit`` (order asserted against
    the shm_native protocol spec); ``commit_fence=False`` and
    ``in_order_commit=False`` are the seeded-bug variants, caught by the
    bracketed and frontier readers respectively."""
    shared: Dict = {}
    for c in range(nchunks):
        shared[f"cs{c}"] = 0
        for w in range(words):
            shared[f"c{c}w{w}"] = 0
    writer = _chunk_writer_program(nchunks, deposits, words,
                                   in_order_commit, commit_fence)
    reader = (_frontier_reader_program(nchunks, words) if frontier_reader
              else _chunk_reader_program(nchunks, words))
    return Model(name="chunk-ring", shared=shared,
                 programs=[writer, reader])


# ---------------------------------------------------------------------------
# model 2c: drained-marker collect (protocol v2 — O(1) drain)
# ---------------------------------------------------------------------------


def drained_collect_model(deposits: int = 2,
                          atomic_collect: bool = DRAINED_COLLECT_IS_ATOMIC
                          ) -> Model:
    """The v2 drain: collect stores ``drained = version`` under the slot
    lock instead of zeroing the payload — a slot whose ``drained ==
    version`` READS as zero, and an accumulating deposit into it degrades
    to a copy (``add = drained != version``).  Mass conservation: with
    one accumulating writer racing one collector, every deposited unit is
    either collected or still logically in the slot.  The seeded bug
    (``atomic_collect=False``) samples ``m``/``version`` OUTSIDE the
    critical section and only takes the lock to store the marker — a
    deposit landing in between is marked drained without ever being read."""
    shared = {"lock": 0, "m": 0, "version": 0, "drained": 0, "collected": 0}

    def logical(sh) -> int:
        return 0 if sh["drained"] == sh["version"] else sh["m"]

    writer: List[Callable] = []
    for dep in range(deposits):
        base = len(writer)

        def w_acquire(sh, rg, nxt=base + 1):
            if sh["lock"]:
                return []
            return _s(sh, rg, nxt, lock=1)

        def w_deposit(sh, rg, nxt=base + 2):
            # add = (drained != version): accumulate into a drained slot
            # restarts from zero — the marker makes stale mass invisible
            return _s(sh, rg, nxt, m=logical(sh) + 1,
                      version=sh["version"] + 1)

        def w_release(sh, rg, nxt=base + 3):
            return _s(sh, rg, nxt, lock=0)

        writer.extend([w_acquire, w_deposit, w_release])

    if atomic_collect:
        def c_acquire(sh, rg):
            if sh["lock"]:
                return []
            return _s(sh, rg, 1, lock=1)

        def c_drain(sh, rg):
            return _s(sh, rg, 2, collected=sh["collected"] + logical(sh),
                      drained=sh["version"])

        def c_release(sh, rg):
            return _s(sh, rg, 3, lock=0)

        collector = [c_acquire, c_drain, c_release]
    else:
        # seeded bug: sample the logical mass lock-free, then only take
        # the lock to store the drained marker
        def c_sample(sh, rg):
            return _r(sh, rg, 1, got=logical(sh))

        def c_acquire(sh, rg):
            if sh["lock"]:
                return []
            return _s(sh, rg, 2, lock=1)

        def c_mark(sh, rg):
            return _s(sh, rg, 3, collected=sh["collected"] + rg["got"],
                      drained=sh["version"])

        def c_release(sh, rg):
            return _s(sh, rg, 4, lock=0)

        collector = [c_sample, c_acquire, c_mark, c_release]

    def conserved(sh) -> Optional[str]:
        if sh["collected"] + logical(sh) != deposits:
            return (f"lost deposit: {deposits} deposited but "
                    f"collected={sh['collected']} + "
                    f"logical-remaining={logical(sh)} "
                    f"(drained marker {sh['drained']} vs version "
                    f"{sh['version']})")
        return None

    return Model(name="drained-collect", shared=shared,
                 programs=[writer, collector], final_check=conserved)


# ---------------------------------------------------------------------------
# model 2d: dead-writer force-drain (resilience — no deposited mass lost)
# ---------------------------------------------------------------------------


def dead_writer_drain_model(deposits: int = 2, collects: int = 1,
                            commits_after_payload: bool =
                            DEPOSIT_COMMITS_AFTER_PAYLOAD,
                            account_wiped: bool = True) -> Model:
    """A writer that may DIE at any protocol step (SIGKILL: no cleanup,
    lock possibly held mid-deposit) against the slot owner, who collects
    normally until the failure detector fires and then applies the
    force-drain rule (``bf_shm_win_force_drain``: mark the slot drained,
    then break the dead writer's lock — DEAD_WRITER_DRAIN_STEPS).

    Proves, over every death point and interleaving:

    - **no unbacked mass**: every unit that ever becomes visible
      (``version``/``m`` committed) has its payload fully written first —
      the reason ``slot_deposit`` commits AFTER the chunk writes
      (DEPOSIT_COMMITS_AFTER_PAYLOAD).  Seeded bug
      ``commits_after_payload=False``: a writer dying between commit and
      payload makes the owner collect a unit that was never deposited.
    - **no lost deposit**: every committed unit is collected, wiped by
      the accounted force-drain, or still logically in the slot —
      ``collected + wiped + logical == committed`` in every final state.
      Seeded bug ``account_wiped=False``: the drain marks the slot
      drained without accounting the in-transit mass to the dead rank's
      excised ledger, silently destroying deposits that had committed.
    - **no stranded survivor**: the owner never deadlocks on the dead
      writer's lock (the drain breaks it) — the built-in deadlock check.

    A writer that dies BEFORE committing leaves ``paid`` > ``committed``:
    that mass died with the writer and is charged to the dead rank by the
    healing rules, not to this slot — the model deliberately does not
    count it.
    """
    shared = {"lock": 0, "m": 0, "version": 0, "drained": 0,
              "dead": 0, "paid": 0, "committed": 0, "collected": 0,
              "wiped": 0}

    def logical(sh) -> int:
        return 0 if sh["drained"] == sh["version"] else sh["m"]

    def dying(step):
        """Wrap a writer step: at every pc the writer may also die in
        place — pc jumps past the program end, shared state (including a
        held lock) frozen as-is."""
        def wrapped(sh, rg):
            succ = list(step(sh, rg))
            succ.extend(_s(sh, rg, 10_000, dead=1))
            return succ
        return wrapped

    def w_acquire(sh, rg, nxt):
        if sh["lock"]:
            return []
        return _s(sh, rg, nxt, lock=1)

    def w_payload(sh, rg, nxt):
        return _s(sh, rg, nxt, paid=sh["paid"] + 1)

    def w_commit(sh, rg, nxt):
        return _s(sh, rg, nxt, m=logical(sh) + 1,
                  version=sh["version"] + 1,
                  committed=sh["committed"] + 1)

    def w_release(sh, rg, nxt):
        return _s(sh, rg, nxt, lock=0)

    order = ([w_acquire, w_payload, w_commit, w_release]
             if commits_after_payload
             # seeded bug: visibility before the payload lands
             else [w_acquire, w_commit, w_payload, w_release])
    writer: List[Callable] = []
    for _dep in range(deposits):
        base = len(writer)
        for i, s in enumerate(order):
            def pinned(sh, rg, s=s, nxt=base + i + 1):
                return s(sh, rg, nxt)
            writer.append(dying(pinned))

    owner: List[Callable] = []
    for _c in range(collects):
        nxt = len(owner) + 1

        def c_try_collect(sh, rg, nxt=nxt):
            # atomic read+mark under the lock (the v2 locked collect,
            # coarsened: the lock serializes it against the writer), or
            # skip this round — both orders are explored
            succ = _s(sh, rg, nxt)  # skip
            if not sh["lock"]:
                got = logical(sh)
                succ += _s(sh, rg, nxt,
                           collected=sh["collected"] + got,
                           drained=sh["version"])
            return succ
        owner.append(c_try_collect)

    base = len(owner)

    def o_detect(sh, rg, base=base):
        # the failure detector: fires only once the writer is truly dead;
        # the no-failure path skips the drain entirely
        succ = _s(sh, rg, base + 3)  # no drain (detector never fired)
        if sh["dead"]:
            succ += _s(sh, rg, base + 1)
        return succ

    def o_wipe(sh, rg, base=base):
        # mark_drained: in-transit mass is charged to the dead rank's
        # excised ledger (account_wiped) and the slot reads as zero
        got = logical(sh)
        return _s(sh, rg, base + 2, drained=sh["version"],
                  wiped=sh["wiped"] + (got if account_wiped else 0))

    def o_break_lock(sh, rg, base=base):
        # clear_lock comes LAST in DEAD_WRITER_DRAIN_STEPS: nobody can
        # slip into a half-drained slot
        return _s(sh, rg, base + 3, lock=0)

    owner.extend([o_detect, o_wipe, o_break_lock])

    # spec sync: the drain rule this model vouches for must mark the
    # drained slot before clearing the dead writer's lock
    assert DEAD_WRITER_DRAIN_STEPS.index("mark_drained") \
        < DEAD_WRITER_DRAIN_STEPS.index("clear_lock"), \
        "model drifted from shm_native.DEAD_WRITER_DRAIN_STEPS"

    def conserved(sh) -> Optional[str]:
        if sh["committed"] > sh["paid"]:
            return (f"unbacked mass: {sh['committed']} unit(s) committed "
                    f"but only {sh['paid']} payload(s) fully written — a "
                    "torn deposit became visible (commit must follow the "
                    "payload)")
        if sh["collected"] + sh["wiped"] + logical(sh) != sh["committed"]:
            return (f"lost deposit: committed={sh['committed']} but "
                    f"collected={sh['collected']} + wiped={sh['wiped']} + "
                    f"logical-remaining={logical(sh)} — the drain rule "
                    "destroyed committed mass without accounting it")
        return None

    return Model(name="dead-writer-drain", shared=shared,
                 programs=[writer, owner], final_check=conserved)


# ---------------------------------------------------------------------------
# model 3: sense-reversing barrier (lost wakeup)
# ---------------------------------------------------------------------------


def barrier_model(nranks: int = 2, episodes: int = 2,
                  reset_before_release: bool = BARRIER_RESET_BEFORE_RELEASE
                  ) -> Model:
    """``bf_shm_job_barrier`` at small bounds.  The last arriver resets
    ``arrived`` then bumps ``generation``; every other rank spins on the
    bump.  ``reset_before_release=False`` is the seeded bug — releasing
    first lets a fast rank enter the next episode and have its arrival
    wiped by the late reset, deadlocking everyone (the lost wakeup)."""
    shared = {"arrived": 0, "generation": 0}

    def make_rank() -> List[Callable]:
        prog: List[Callable] = []
        for _ in range(episodes):
            base = len(prog)
            # pcs within one episode: base+0 read gen / fetch_add,
            # base+1 reset-or-spin, base+2 release (last arriver only)
            def arrive(sh, rg, base=base):
                a = sh["arrived"] + 1
                rg2 = dict(rg)
                rg2["gen"] = sh["generation"]
                rg2["last"] = 1 if a == nranks else 0
                return [(dict(sh, arrived=a), rg2, base + 1)]

            def reset_or_spin(sh, rg, base=base):
                if rg["last"]:
                    if reset_before_release:
                        return _s(sh, rg, base + 2, arrived=0)
                    return _s(sh, rg, base + 2,
                              generation=sh["generation"] + 1)
                if sh["generation"] == rg["gen"]:
                    return []  # spin on the bump
                return [(sh, rg, base + 3)]

            def release(sh, rg, base=base):
                if not rg["last"]:
                    return [(sh, rg, base + 3)]
                if reset_before_release:
                    return _s(sh, rg, base + 3,
                              generation=sh["generation"] + 1)
                return _s(sh, rg, base + 3, arrived=0)

            prog.extend([arrive, reset_or_spin, release])
        return prog

    return Model(name="barrier", shared=shared,
                 programs=[make_rank() for _ in range(nranks)])


# ---------------------------------------------------------------------------
# report plumbing + registration
# ---------------------------------------------------------------------------


def check_model(model: Model, report: Optional[Report] = None,
                rule: str = "protocol.model") -> Report:
    report = report if report is not None else Report()
    report.subjects_checked += 1
    for msg in explore(model):
        report.add(Finding(rule, model.name, msg))
    return report


@registry.rule("protocol.seqlock-torn-read", "protocol",
               "no interleaving of seqlock writers with a wait-free "
               "reader yields a torn snapshot")
def _run_seqlock(report: Report) -> None:
    for n_writers, deposits in ((1, 2), (2, 1), (2, 2)):
        check_model(
            seqlock_model(n_writers=n_writers, deposits=deposits),
            report, rule="protocol.seqlock-torn-read")


@registry.rule("protocol.collect-mass-conservation", "protocol",
               "collect's read+zero critical section loses no concurrent "
               "deposit")
def _run_collect(report: Report) -> None:
    for deposits in (1, 2, 3):
        check_model(collect_model(deposits=deposits), report,
                    rule="protocol.collect-mass-conservation")


@registry.rule("protocol.barrier-lost-wakeup", "protocol",
               "the sense-reversing barrier can never strand a rank")
def _run_barrier(report: Report) -> None:
    for nranks, episodes in ((2, 2), (3, 2)):
        check_model(barrier_model(nranks=nranks, episodes=episodes),
                    report, rule="protocol.barrier-lost-wakeup")


@registry.rule("protocol.chunk-ring-commit", "protocol",
               "v2 chunk-ring deposits: no bracketed reader returns a "
               "torn chunk and no frontier reader overtakes an ascending "
               "commit")
def _run_chunk_ring(report: Report) -> None:
    # bracketed per-chunk consumer: torn-chunk safety (words > 1 so a
    # half-written chunk is representable)
    for nchunks, deposits in ((2, 2), (3, 1)):
        check_model(
            chunk_ring_model(nchunks=nchunks, deposits=deposits, words=2),
            report, rule="protocol.chunk-ring-commit")
    # pipelined frontier consumer: ascending commit order (one word per
    # chunk — ordering, not tearing, is what this reader depends on)
    for nchunks, deposits in ((2, 2), (3, 2)):
        check_model(
            chunk_ring_model(nchunks=nchunks, deposits=deposits, words=1,
                             frontier_reader=True),
            report, rule="protocol.chunk-ring-commit")


@registry.rule("protocol.chunk-drained-mass-conservation", "protocol",
               "the v2 O(1) drained-marker drain loses no concurrent "
               "accumulating deposit")
def _run_drained_collect(report: Report) -> None:
    for deposits in (1, 2, 3):
        check_model(drained_collect_model(deposits=deposits), report,
                    rule="protocol.chunk-drained-mass-conservation")


@registry.rule("resilience.dead-writer-drain", "resilience",
               "a writer dying at ANY protocol step: the force-drain "
               "rule neither loses committed mass nor surfaces a torn "
               "deposit nor strands the surviving slot owner")
def _run_dead_writer_drain(report: Report) -> None:
    for deposits, collects in ((1, 1), (2, 1), (2, 2), (3, 1)):
        check_model(
            dead_writer_drain_model(deposits=deposits, collects=collects),
            report, rule="resilience.dead-writer-drain")
