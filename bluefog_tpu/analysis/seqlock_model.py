"""Rule family 3a: exhaustive model checking of the shm-mailbox protocol.

``native/shm_mailbox.cc`` implements a seqlock mailbox (writers per-slot
spinlocked with an odd/even sequence publish; readers wait-free with a
bracketed retry copy), an atomic read+zero ``collect``, and a
sense-reversing barrier.  MPI gives the reference this machinery for
free; here it is 449 lines of hand-rolled C++ that had never been model
checked.  This module mirrors each protocol as a small explicit-state
machine and exhaustively enumerates ALL interleavings at small bounds
(1-2 writers x 1-2 deposits, 2-word payloads, 2-3 ranks x 2 barrier
episodes), proving within those bounds:

- **no torn read**: every payload a completed reader returns is a single
  deposit's value, never a mix of two (seqlock safety);
- **no lost deposit**: ``collect``'s read+zero critical section conserves
  mass against a concurrent accumulating writer;
- **no lost wakeup / deadlock**: the barrier's reset-then-release order
  can never strand a rank spinning on a generation bump that already
  happened.

The step orders are imported from ``native/shm_native.py``'s protocol
spec constants and asserted to match, so the model cannot silently drift
from the implementation it vouches for.  Seeded-bug variants (writer
skips the odd phase; collect splits read and zero; barrier releases
before resetting) are exported for the fixture corpus — each must make
the checker fire (tests/test_analysis.py).

The model assumes sequential consistency.  The fences in shm_mailbox.cc
(seq_cst store-store before the payload mutation, release before the
even publish, acquire-bracketed reads) are exactly what collapses the
hardware's weaker orders to the interleaving semantics checked here;
the comments at ``slot_write``/``slot_read`` document that mapping.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from bluefog_tpu.native.shm_native import (
    BARRIER_RESET_BEFORE_RELEASE,
    COLLECT_IS_ATOMIC,
    SEQLOCK_READER_STEPS,
    SEQLOCK_WRITER_STEPS,
)

from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "Model",
    "explore",
    "seqlock_model",
    "collect_model",
    "barrier_model",
    "check_model",
]


# ---------------------------------------------------------------------------
# tiny explicit-state explorer
# ---------------------------------------------------------------------------
#
# A process is a list of *steps*.  A step is
#     step(shared: dict, regs: dict) -> list[(shared', regs', next_pc)]
# returning every successor from this state (deterministic steps return
# one; a blocked spin returns none).  Steps must treat their inputs as
# immutable and may set shared["_bad"] to a message to flag a safety
# violation at that transition.


@dataclasses.dataclass
class Model:
    name: str
    shared: Dict
    programs: List[List[Callable]]
    final_check: Optional[Callable[[Dict], Optional[str]]] = None


def _freeze(d: Dict) -> Tuple:
    return tuple(sorted(d.items()))


def _thaw(t: Tuple) -> Dict:
    return dict(t)


def explore(model: Model, max_states: int = 1_000_000) -> List[str]:
    """DFS over every interleaving; returns violation messages.

    Detects three failure shapes: a step-flagged safety violation
    (``shared["_bad"]``), a deadlock (some process unfinished, no process
    can move — the lost-wakeup signature), and a failed ``final_check``
    on a fully-terminated state.
    """
    programs = model.programs
    init = (_freeze(model.shared),
            tuple((0, ()) for _ in programs))
    seen = {init}
    stack = [init]
    violations: List[str] = []
    flagged = set()

    def flag(msg: str) -> None:
        if msg not in flagged:
            flagged.add(msg)
            violations.append(msg)

    while stack:
        shared_t, procs = stack.pop()
        shared = _thaw(shared_t)
        any_move = False
        all_done = True
        for i, (pc, regs_t) in enumerate(procs):
            prog = programs[i]
            if pc >= len(prog):
                continue
            all_done = False
            regs = _thaw(regs_t)
            for sh2, rg2, pc2 in prog[pc](shared, regs):
                any_move = True
                bad = sh2.pop("_bad", None)
                if bad is not None:
                    flag(f"{model.name}: {bad}")
                    continue  # prune past the violation
                nxt = (_freeze(sh2),
                       procs[:i] + ((pc2, _freeze(rg2)),) + procs[i + 1:])
                if nxt not in seen:
                    if len(seen) >= max_states:
                        raise RuntimeError(
                            f"{model.name}: state space exceeded "
                            f"{max_states} states — tighten the bounds")
                    seen.add(nxt)
                    stack.append(nxt)
        if all_done:
            if model.final_check is not None:
                msg = model.final_check(shared)
                if msg:
                    flag(f"{model.name}: {msg}")
        elif not any_move:
            stuck = [i for i, (pc, _) in enumerate(procs)
                     if pc < len(programs[i])]
            flag(f"{model.name}: deadlock — process(es) {stuck} blocked "
                 "forever (lost wakeup)")
    return violations


def _s(shared, regs, pc, **updates):
    """One successor with shared-var updates applied."""
    sh = dict(shared)
    sh.update(updates)
    return [(sh, regs, pc)]


def _r(shared, regs, pc, **updates):
    """One successor with register updates applied."""
    rg = dict(regs)
    rg.update(updates)
    return [(shared, rg, pc)]


# ---------------------------------------------------------------------------
# model 1: seqlock write/read (torn-read safety)
# ---------------------------------------------------------------------------


def _writer_program(writer_id: int, deposits: int, words: int,
                    use_lock: bool, odd_phase: bool,
                    early_publish: bool) -> Tuple[List[Callable], Tuple[str, ...]]:
    """One writer: ``deposits`` sequential slot_write calls, each writing
    the deposit's unique value to every payload word, one word per step
    (the memcpy is not atomic — that is the whole point)."""
    prog: List[Callable] = []
    steps: List[str] = []

    for dep in range(deposits):
        value = writer_id * 100 + dep + 1

        # Each closure captures its own next-pc at construction time.
        def mk_acquire(next_pc):
            def step(sh, rg):
                if sh["lock"]:
                    return []
                return _s(sh, rg, next_pc, lock=1)
            return step

        def mk_seq_bump(next_pc):
            def step(sh, rg):
                return _s(sh, rg, next_pc, seq=sh["seq"] + 1)
            return step

        def mk_write_word(w, v, next_pc):
            def step(sh, rg):
                return _s(sh, rg, next_pc, **{f"w{w}": v})
            return step

        def mk_release(next_pc):
            def step(sh, rg):
                return _s(sh, rg, next_pc, lock=0)
            return step

        base = len(prog)
        seq_bumps = ([("seq_to_odd", mk_seq_bump)] if odd_phase else [])
        publish = [("seq_to_even", mk_seq_bump)]
        body: List[Tuple[str, Callable]] = []
        if use_lock:
            body.append(("acquire_lock", mk_acquire))
        body.extend(seq_bumps)
        if early_publish:
            body.extend(publish)
        body.extend(("mutate_payload", lambda nxt, w=w, v=value:
                     mk_write_word(w, v, nxt)) for w in range(words))
        if not early_publish:
            body.extend(publish)
        if use_lock:
            body.append(("release_lock", mk_release))
        for k, (name, maker) in enumerate(body):
            prog.append(maker(base + k + 1))
            steps.append(name)
    return prog, tuple(steps)


def _reader_program(words: int, check_after: bool = True) -> List[Callable]:
    """slot_read: bracketed retry copy, no lock.  Registers: ``before``
    and one ``r<w>`` per word.  On completion the snapshot must be a
    single deposit's value."""
    pc_start = 0

    def read_before(sh, rg):
        if sh["seq"] & 1:
            return [(sh, rg, pc_start)]  # odd: retry (self-loop via state)
        return _r(sh, rg, 1, before=sh["seq"])

    prog: List[Callable] = [read_before]

    def mk_copy(w, next_pc):
        def step(sh, rg):
            return _r(sh, rg, next_pc, **{f"r{w}": sh[f"w{w}"]})
        return step

    for w in range(words):
        prog.append(mk_copy(w, len(prog) + 1))

    def read_after(sh, rg):
        if check_after and sh["seq"] != rg["before"]:
            return [(sh, {}, pc_start)]  # retry from scratch
        vals = {rg[f"r{w}"] for w in range(words)}
        if len(vals) > 1:
            sh2 = dict(sh)
            sh2["_bad"] = (f"torn read: completed snapshot mixes deposits "
                           f"{sorted(vals)}")
            return [(sh2, rg, len(prog))]
        return [(sh, rg, len(prog))]

    prog.append(read_after)
    return prog


def seqlock_model(n_writers: int = 1, deposits: int = 2, words: int = 2,
                  use_lock: bool = True, odd_phase: bool = True,
                  early_publish: bool = False,
                  reader_checks_after: bool = True) -> Model:
    """The mailbox slot under concurrent writers and one wait-free reader.

    Default parameters mirror ``slot_write``/``slot_read`` exactly (order
    asserted against the shm_native protocol spec); the keyword knobs
    produce the seeded-bug variants for the fixture corpus."""
    shared = {"lock": 0, "seq": 0}
    for w in range(words):
        shared[f"w{w}"] = 0
    programs = []
    for i in range(n_writers):
        prog, steps = _writer_program(i, deposits, words, use_lock,
                                      odd_phase, early_publish)
        if (use_lock and odd_phase and not early_publish):
            # one deposit's step-name sequence must equal the impl spec
            per_dep = steps[:len(steps) // deposits]
            collapsed = tuple(
                name for k, name in enumerate(per_dep)
                if name != "mutate_payload" or
                (k == 0 or per_dep[k - 1] != "mutate_payload"))
            assert collapsed == SEQLOCK_WRITER_STEPS, (
                f"model drifted from shm_native.SEQLOCK_WRITER_STEPS: "
                f"{collapsed}")
        programs.append(prog)
    programs.append(_reader_program(words, check_after=reader_checks_after))
    assert len(SEQLOCK_READER_STEPS) == 3  # spec sync (retry-bracketed copy)
    return Model(name="seqlock", shared=shared, programs=programs)


# ---------------------------------------------------------------------------
# model 2: collect vs concurrent accumulate (mass conservation)
# ---------------------------------------------------------------------------


def collect_model(deposits: int = 2, atomic_collect: bool = COLLECT_IS_ATOMIC
                  ) -> Model:
    """One accumulating writer (``bf_shm_win_write`` mode 1) racing one
    ``collect`` drain (``bf_shm_win_read`` collect=1).  Mass conservation:
    every deposited unit is either collected or still in the slot when
    both finish.  ``atomic_collect=False`` models the seeded bug — a
    seqlock *read* followed by a separate locked zero — which loses any
    deposit that lands in between."""
    shared = {"lock": 0, "seq": 0, "m": 0, "collected": 0}

    writer: List[Callable] = []
    for dep in range(deposits):
        base = len(writer)

        def mk(step_idx):
            def acquire(sh, rg):
                if sh["lock"]:
                    return []
                return _s(sh, rg, step_idx + 1, lock=1)
            return acquire

        writer.append(mk(base))

        def mk_read(nxt):
            def step(sh, rg):
                return _r(sh, rg, nxt, tmp=sh["m"])
            return step

        writer.append(mk_read(base + 2))

        def mk_addback(nxt):
            def step(sh, rg):
                return _s(sh, rg, nxt, m=rg["tmp"] + 1)
            return step

        writer.append(mk_addback(base + 3))

        def mk_release(nxt):
            def step(sh, rg):
                return _s(sh, rg, nxt, lock=0)
            return step

        writer.append(mk_release(base + 4))

    if atomic_collect:
        def c_acquire(sh, rg):
            if sh["lock"]:
                return []
            return _s(sh, rg, 1, lock=1)

        def c_read_zero(sh, rg):
            sh2 = dict(sh)
            sh2["collected"] = sh["collected"] + sh["m"]
            sh2["m"] = 0
            return [(sh2, rg, 2)]

        def c_release(sh, rg):
            return _s(sh, rg, 3, lock=0)

        collector = [c_acquire, c_read_zero, c_release]
    else:
        # seeded bug: read outside the critical section, zero inside
        def c_read(sh, rg):
            return _r(sh, rg, 1, got=sh["m"])

        def c_acquire(sh, rg):
            if sh["lock"]:
                return []
            return _s(sh, rg, 2, lock=1)

        def c_zero(sh, rg):
            sh2 = dict(sh)
            sh2["collected"] = sh["collected"] + rg["got"]
            sh2["m"] = 0
            return [(sh2, rg, 3)]

        def c_release(sh, rg):
            return _s(sh, rg, 4, lock=0)

        collector = [c_read, c_acquire, c_zero, c_release]

    def conserved(sh) -> Optional[str]:
        if sh["collected"] + sh["m"] != deposits:
            return (f"lost deposit: {deposits} deposited but "
                    f"collected={sh['collected']} + remaining={sh['m']}")
        return None

    return Model(name="collect", shared=shared,
                 programs=[writer, collector], final_check=conserved)


# ---------------------------------------------------------------------------
# model 3: sense-reversing barrier (lost wakeup)
# ---------------------------------------------------------------------------


def barrier_model(nranks: int = 2, episodes: int = 2,
                  reset_before_release: bool = BARRIER_RESET_BEFORE_RELEASE
                  ) -> Model:
    """``bf_shm_job_barrier`` at small bounds.  The last arriver resets
    ``arrived`` then bumps ``generation``; every other rank spins on the
    bump.  ``reset_before_release=False`` is the seeded bug — releasing
    first lets a fast rank enter the next episode and have its arrival
    wiped by the late reset, deadlocking everyone (the lost wakeup)."""
    shared = {"arrived": 0, "generation": 0}

    def make_rank() -> List[Callable]:
        prog: List[Callable] = []
        for _ in range(episodes):
            base = len(prog)
            # pcs within one episode: base+0 read gen / fetch_add,
            # base+1 reset-or-spin, base+2 release (last arriver only)
            def arrive(sh, rg, base=base):
                a = sh["arrived"] + 1
                rg2 = dict(rg)
                rg2["gen"] = sh["generation"]
                rg2["last"] = 1 if a == nranks else 0
                return [(dict(sh, arrived=a), rg2, base + 1)]

            def reset_or_spin(sh, rg, base=base):
                if rg["last"]:
                    if reset_before_release:
                        return _s(sh, rg, base + 2, arrived=0)
                    return _s(sh, rg, base + 2,
                              generation=sh["generation"] + 1)
                if sh["generation"] == rg["gen"]:
                    return []  # spin on the bump
                return [(sh, rg, base + 3)]

            def release(sh, rg, base=base):
                if not rg["last"]:
                    return [(sh, rg, base + 3)]
                if reset_before_release:
                    return _s(sh, rg, base + 3,
                              generation=sh["generation"] + 1)
                return _s(sh, rg, base + 3, arrived=0)

            prog.extend([arrive, reset_or_spin, release])
        return prog

    return Model(name="barrier", shared=shared,
                 programs=[make_rank() for _ in range(nranks)])


# ---------------------------------------------------------------------------
# report plumbing + registration
# ---------------------------------------------------------------------------


def check_model(model: Model, report: Optional[Report] = None,
                rule: str = "protocol.model") -> Report:
    report = report if report is not None else Report()
    report.subjects_checked += 1
    for msg in explore(model):
        report.add(Finding(rule, model.name, msg))
    return report


@registry.rule("protocol.seqlock-torn-read", "protocol",
               "no interleaving of seqlock writers with a wait-free "
               "reader yields a torn snapshot")
def _run_seqlock(report: Report) -> None:
    for n_writers, deposits in ((1, 2), (2, 1), (2, 2)):
        check_model(
            seqlock_model(n_writers=n_writers, deposits=deposits),
            report, rule="protocol.seqlock-torn-read")


@registry.rule("protocol.collect-mass-conservation", "protocol",
               "collect's read+zero critical section loses no concurrent "
               "deposit")
def _run_collect(report: Report) -> None:
    for deposits in (1, 2, 3):
        check_model(collect_model(deposits=deposits), report,
                    rule="protocol.collect-mass-conservation")


@registry.rule("protocol.barrier-lost-wakeup", "protocol",
               "the sense-reversing barrier can never strand a rank")
def _run_barrier(report: Report) -> None:
    for nranks, episodes in ((2, 2), (3, 2)):
        check_model(barrier_model(nranks=nranks, episodes=episodes),
                    report, rule="protocol.barrier-lost-wakeup")
