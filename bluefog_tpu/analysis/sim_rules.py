"""Rule family: the deterministic fleet simulator as a verifier.

The sim (bluefog_tpu/sim/) runs the REAL protocol state machines —
``FailureDetector``, ``EdgeHealth``/``AdaptivePolicy``, the healing
planners, ``MembershipBoard.grant``/``commit_reweight`` — against an
in-memory transport on a virtual clock, auditing the standing
invariants after every protocol event (mass conservation, doubly
stochastic plans, monotone epochs, no majority demotion, push-sum
consensus at quiesce).  That makes a seeded campaign itself a static
check: no subprocesses, no wall-clock, same seed → same event log bit
for bit.  Three rule groups:

- **campaign-clean** — pinned-seed fault campaigns (kills, slowdowns,
  suspensions, joins over exp2) finish with zero violations, a
  balanced count ledger, and consensus within tolerance;
- **determinism** — the same seed run twice yields the identical
  event-log digest (the property every repro file leans on);
- **shrink-minimal** — a seeded invariant bug (``mass_leak``) is
  caught, and the ddmin shrinker reduces its schedule to the true
  minimum (the empty schedule: a code bug needs no faults to fire).

The heavyweight pinned campaigns (N=64/128/256, the acceptance sizes)
run under the CLI's ``--self-test`` arm via
:func:`selftest_campaigns`, not in the default corpus — the CI gate
stays fast.
"""

from __future__ import annotations

from typing import Iterable, List, Tuple

from bluefog_tpu.analysis.engine import Finding, Report, registry

__all__ = [
    "campaign_findings",
    "iter_pinned_campaigns",
    "selftest_campaigns",
    "SELFTEST_PINS",
]

#: The --self-test pinned campaigns: (ranks, rounds, seed).  These are
#: the acceptance sizes — a 256-rank seeded campaign must finish clean
#: in well under a minute single-process.
SELFTEST_PINS: Tuple[Tuple[int, int, int], ...] = (
    (64, 50, 42),
    (128, 50, 7),
    (256, 50, 7),
)


def _config(ranks: int, rounds: int, seed: int, **kw):
    from bluefog_tpu.sim.campaign import SimConfig

    kw.setdefault("quiesce_rounds", max(20, rounds * 4 // 5))
    return SimConfig(ranks=ranks, rounds=rounds, seed=seed, **kw)


def campaign_findings(result, label: str) -> List[Finding]:
    """Map a :class:`CampaignResult`'s violations onto findings (one
    per distinct violation name, with the first occurrence's detail —
    a broken invariant fires on every subsequent event, and one
    finding per event would drown the report)."""
    out: List[Finding] = []
    seen = set()
    for v in result.violations:
        if v["name"] in seen:
            continue
        seen.add(v["name"])
        out.append(Finding(f"sim.{v['name']}", label,
                           f"t={v['t']:.3f} rank {v['rank']}: "
                           f"{v['detail']}"))
    return out


def iter_pinned_campaigns() -> Iterable[Tuple[str, object]]:
    """The default-corpus campaigns: small enough for the CI gate,
    still exercising kill→heal, slow→demote→promote, suspend→fence,
    and join→grant→enter."""
    from bluefog_tpu.sim.campaign import run_campaign

    for ranks, rounds, seed in ((32, 30, 0), (32, 30, 7)):
        cfg = _config(ranks, rounds, seed,
                      faults=("kill", "suspend", "slow", "join"))
        label = f"campaign[n={ranks},rounds={rounds},seed={seed}]"
        yield label, run_campaign(cfg)


@registry.rule("sim.campaign-clean", "sim",
               "pinned-seed fault campaigns over the real protocol "
               "state machines finish with zero invariant violations, "
               "a balanced count ledger, and push-sum consensus")
def _run_campaign_clean(report: Report) -> None:
    for label, res in iter_pinned_campaigns():
        report.subjects_checked += 1
        report.extend(campaign_findings(res, label))
        led = res.final.get("ledger") or {}
        if not led.get("balanced"):
            report.add(Finding("sim.campaign-clean", label,
                               f"count ledger unbalanced: {led}"))
        report.metrics[f"sim.events/{label}"] = float(res.events)


@registry.rule("sim.determinism", "sim",
               "the same (seed, config) campaign run twice yields the "
               "identical event-log digest — the property every "
               "shrink-to-seed repro file leans on")
def _run_determinism(report: Report) -> None:
    from bluefog_tpu.sim.campaign import run_campaign

    cfg = _config(32, 30, 3)
    report.subjects_checked += 1
    a = run_campaign(cfg)
    b = run_campaign(cfg)
    if a.digest != b.digest:
        report.add(Finding(
            "sim.determinism", "campaign[n=32,seed=3]",
            f"two same-seed runs diverged: {a.digest[:16]} != "
            f"{b.digest[:16]} — replay and repro files are broken"))


@registry.rule("sim.shrink-minimal", "sim",
               "a seeded mass-leak bug is caught by the continuous "
               "mass audit and ddmin-shrinks to the empty schedule "
               "(a code bug needs no faults to reproduce)")
def _run_shrink_minimal(report: Report) -> None:
    from bluefog_tpu.sim.campaign import run_campaign, shrink_schedule

    cfg = _config(8, 15, 3, quiesce_rounds=5,
                  debug_bugs=("mass_leak",))
    label = "campaign[n=8,seed=3,bug=mass_leak]"
    report.subjects_checked += 1
    res = run_campaign(cfg)
    if res.ok:
        report.add(Finding(
            "sim.shrink-minimal", label,
            "the seeded mass_leak bug was NOT caught — the continuous "
            "mass audit is not actually auditing"))
        return
    minimal, viol, _runs = shrink_schedule(cfg, res.schedule)
    if viol is None or viol["name"] != "mass-conservation":
        report.add(Finding(
            "sim.shrink-minimal", label,
            f"shrinker lost the violation (got {viol!r})"))
    if len(minimal) != 0:
        report.add(Finding(
            "sim.shrink-minimal", label,
            f"shrunk schedule still holds {len(minimal)} fault(s); a "
            "pure code bug must shrink to the empty schedule"))


def selftest_campaigns() -> List[Tuple[str, object, List[Finding]]]:
    """The ``--self-test`` arm: the acceptance-size pinned campaigns
    (N=64/128/256, seeded kills+slowdowns+joins) each run once and
    must come back clean.  Returns ``(label, result, findings)``."""
    from bluefog_tpu.sim.campaign import run_campaign

    out = []
    for ranks, rounds, seed in SELFTEST_PINS:
        cfg = _config(ranks, rounds, seed, quiesce_rounds=40)
        label = f"campaign[n={ranks},rounds={rounds},seed={seed}]"
        res = run_campaign(cfg)
        findings = campaign_findings(res, label)
        led = res.final.get("ledger") or {}
        if not led.get("balanced"):
            findings.append(Finding("sim.campaign-clean", label,
                                    f"count ledger unbalanced: {led}"))
        out.append((label, res, findings))
    return out
