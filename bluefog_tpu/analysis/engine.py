"""Rule-engine core for the static verifier (`bluefog_tpu.analysis`).

Every rule family (plan/topology, HLO lint, protocol model checking,
win-op epoch ordering) produces the same currency — :class:`Finding` —
so the CLI, the pytest integration, and future CI gates share one
severity model and one exit-code policy.  A *rule* is any callable
returning a list of findings; families register their rules in a
:class:`Registry` so the CLI can enumerate and select them by name.

Design note: the checker is deliberately *static* — it inspects compiled
plans, HLO text, and abstract protocol models, never live device state —
so a full default-corpus run is cheap enough to gate every PR (the
ROADMAP's "every future perf/refactor PR is safe to land" goal).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, Iterable, List, Optional

__all__ = [
    "Severity",
    "Finding",
    "Report",
    "Rule",
    "Registry",
    "registry",
]


class Severity:
    ERROR = "error"      # contract violation: CLI exits nonzero
    WARNING = "warning"  # suspicious but not proven wrong
    INFO = "info"        # reported metric (e.g. spectral gap)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule firing on one subject."""

    rule: str      # dotted rule id, e.g. "plan.class-permutation"
    subject: str   # what was checked, e.g. "exp2@8 class 1"
    message: str
    severity: str = Severity.ERROR

    def __str__(self) -> str:
        return f"[{self.severity}] {self.rule} ({self.subject}): {self.message}"


class Report:
    """Accumulated findings plus reported metrics for one verifier run."""

    def __init__(self) -> None:
        self.findings: List[Finding] = []
        self.metrics: Dict[str, float] = {}
        self.subjects_checked = 0

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def extend(self, findings: Iterable[Finding]) -> None:
        self.findings.extend(findings)

    def metric(self, name: str, value: float) -> None:
        self.metrics[name] = value

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == Severity.ERROR]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def summary(self) -> str:
        n_err = len(self.errors())
        n_warn = sum(f.severity == Severity.WARNING for f in self.findings)
        verdict = "OK" if self.ok else "FAIL"
        return (f"{verdict}: {self.subjects_checked} subjects checked, "
                f"{n_err} errors, {n_warn} warnings")

    def to_dict(self) -> dict:
        return {
            "ok": self.ok,
            "subjects_checked": self.subjects_checked,
            "findings": [dataclasses.asdict(f) for f in self.findings],
            "metrics": self.metrics,
        }


@dataclasses.dataclass(frozen=True)
class Rule:
    """A named check: ``run()`` yields findings over the default corpus.

    ``check``-style helpers (pure functions over one subject) live in the
    family modules and are what tests call directly; the Rule wrapper is
    the CLI-facing registration that binds a helper to its corpus.
    """

    name: str     # dotted id, e.g. "plan.edge-cover"
    family: str   # "plan" | "hlo" | "protocol" | "epoch"
    doc: str
    run: Callable[[Report], None]


class Registry:
    """Rule registry keyed by family; the CLI's source of truth."""

    def __init__(self) -> None:
        self._rules: Dict[str, Rule] = {}

    def register(self, rule: Rule) -> Rule:
        if rule.name in self._rules:
            raise ValueError(f"duplicate rule name {rule.name!r}")
        self._rules[rule.name] = rule
        return rule

    def rule(self, name: str, family: str, doc: str = ""):
        """Decorator: register ``fn(report) -> None`` as a corpus rule."""

        def deco(fn):
            self.register(Rule(name=name, family=family,
                               doc=doc or (fn.__doc__ or "").strip(),
                               run=fn))
            return fn

        return deco

    def families(self) -> List[str]:
        return sorted({r.family for r in self._rules.values()})

    def select(self, families: Optional[Iterable[str]] = None) -> List[Rule]:
        fams = set(families) if families is not None else None
        return [r for _, r in sorted(self._rules.items())
                if fams is None or r.family in fams]

    def run(self, families: Optional[Iterable[str]] = None,
            report: Optional[Report] = None,
            verbose: bool = False) -> Report:
        report = report if report is not None else Report()
        for rule in self.select(families):
            t0 = time.perf_counter()
            rule.run(report)
            if verbose:
                dt = (time.perf_counter() - t0) * 1e3
                print(f"  {rule.name:<40s} {dt:7.1f} ms")
        return report


#: Process-wide registry the family modules register into on import.
registry = Registry()
