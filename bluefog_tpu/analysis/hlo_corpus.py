"""Registered HLO rules: compile the seed's hot programs and lint them.

The pure rule objects live in :mod:`bluefog_tpu.analysis.hlo_rules`; this
module binds them to a REAL compiled corpus — ``neighbor_allreduce`` over
each named topology at n=8 on the forced-8-device CPU mesh, plus the
fused window exchange — and registers the result with the engine, so
``python -m bluefog_tpu.analysis`` checks the same O(deg) contract the
pytest suite pins (tests/test_hlo_contract.py), from the same rule
objects.

Compiling costs seconds per program (it runs GSPMD + the CPU backend),
so this family is the slow one; the CLI's ``--no-hlo`` flag and the CI
gate skip it while the full run and the pytest suite keep it honest.
Everything here imports jax lazily — the plan/protocol families must
stay runnable without touching a backend.
"""

from __future__ import annotations

from typing import List

from bluefog_tpu.analysis.engine import Finding, Report, Severity, registry
from bluefog_tpu.analysis.hlo_rules import (
    CollectiveBudget,
    NoFullAxisAllGather,
    NoReplicatedLargeBuffer,
    check_program,
)

SIZE = 8

#: topology label -> (constructor, expected number of shift classes at n=8)
GOSSIP_CORPUS = {
    "exp2": ("ExponentialTwoGraph", 3),
    "ring": ("RingGraph", 2),
    "ring_uni": (None, 1),  # built inline (connect_style=1)
    "full": ("FullyConnectedGraph", 7),
}

# any single collective result bigger than this on the n=8 toy shapes
# means a buffer got replicated across the axis
MAX_RESULT_BYTES = 1 << 20


def _ensure_devices() -> bool:
    import jax

    return len(jax.devices()) >= SIZE


def _gossip_text(topo):
    """(post-partitioner text, #shift classes) of one rank-major
    neighbor_allreduce."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import bluefog_tpu as bf
    from bluefog_tpu import ops_spmd
    from bluefog_tpu.core import basics
    from bluefog_tpu.core.basics import NODES_AXIS

    bf.set_topology(topo)
    ctx = basics.context()
    fn = jax.shard_map(
        functools.partial(ops_spmd.neighbor_allreduce, plan=ctx.plan,
                          axis_name=NODES_AXIS),
        mesh=ctx.mesh, in_specs=P(NODES_AXIS), out_specs=P(NODES_AXIS))
    x = jnp.zeros((SIZE, 4))
    return jax.jit(fn).lower(x).compile().as_text(), len(ctx.plan.classes)


def check_gossip_corpus(report: Report) -> None:
    from bluefog_tpu import topology_util as tu

    for label in GOSSIP_CORPUS:
        if label == "ring_uni":
            topo = tu.RingGraph(SIZE, connect_style=1)
        else:
            topo = getattr(tu, GOSSIP_CORPUS[label][0])(SIZE)
        text, nclasses = _gossip_text(topo)
        expect = GOSSIP_CORPUS[label][1]
        subject = f"neighbor_allreduce/{label}@{SIZE}"
        if nclasses != expect:
            report.add(Finding(
                "hlo.gossip-contract", subject,
                f"plan compiled to {nclasses} shift classes (expected "
                f"{expect})"))
        rules = [
            CollectiveBudget({"collective-permute": nclasses},
                             subject=subject),
            NoFullAxisAllGather(axis_size=SIZE, subject=subject),
            NoReplicatedLargeBuffer(MAX_RESULT_BYTES, subject=subject),
        ]
        report.subjects_checked += 1
        report.extend(check_program(text, rules))


def check_window_exchange(report: Report) -> None:
    import jax
    import jax.numpy as jnp

    import bluefog_tpu as bf
    from bluefog_tpu import topology_util as tu
    from bluefog_tpu.core import basics
    from bluefog_tpu.windows import _build_exchange

    bf.set_topology(tu.ExponentialTwoGraph(SIZE))
    ctx = basics.context()
    plan = ctx.plan
    nclasses = len(plan.classes)
    maxd = plan.max_in_degree
    x = jnp.zeros((SIZE, 4), jnp.float32)
    mail = jnp.zeros((SIZE, maxd, 4), jnp.float32)
    ver = jnp.zeros((SIZE, maxd), jnp.int32)
    p_self = jnp.ones((SIZE,), jnp.float32)
    p_mail = jnp.ones((SIZE, maxd), jnp.float32)
    scales = jnp.ones((nclasses, SIZE), jnp.float32)
    active = jnp.ones((nclasses, SIZE), jnp.float32)
    f = _build_exchange(plan, accumulate=False, with_p=False, donate=False)
    text = f.lower(x, mail, ver, p_self, p_mail, scales, active) \
            .compile().as_text()
    subject = f"win_exchange/exp2@{SIZE}"
    rules = [
        CollectiveBudget({"collective-permute": nclasses}, subject=subject),
        NoFullAxisAllGather(axis_size=SIZE, subject=subject),
        NoReplicatedLargeBuffer(MAX_RESULT_BYTES, subject=subject),
    ]
    report.subjects_checked += 1
    report.extend(check_program(text, rules))


def _with_context(report: Report, body) -> None:
    import bluefog_tpu as bf
    from bluefog_tpu.core import basics

    if not _ensure_devices():
        report.add(Finding(
            "hlo.environment", "devices",
            f"only {len(__import__('jax').devices())} devices visible "
            f"(need {SIZE}); run via `python -m bluefog_tpu.analysis`, "
            "which forces an 8-device CPU mesh", Severity.WARNING))
        return
    owned = not basics.is_initialized()
    if owned:
        bf.init(local_size=2)
    try:
        body(report)
    finally:
        if owned:
            bf.shutdown()


@registry.rule("hlo.gossip-contract", "hlo",
               "neighbor_allreduce compiles to one permute per shift "
               "class, no gathers, no replicated buffers")
def _run_gossip(report: Report) -> None:
    _with_context(report, check_gossip_corpus)


@registry.rule("hlo.window-exchange", "hlo",
               "the fused window exchange moves data only via one permute "
               "per shift class")
def _run_window(report: Report) -> None:
    _with_context(report, check_window_exchange)
